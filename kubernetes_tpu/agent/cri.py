"""CRI boundary: the container-runtime interface the kubelet drives, plus the
fake runtime used by the hollow/test node agent.

reference: staging/src/k8s.io/cri-api/pkg/apis/runtime/v1/api.proto — the 34
RuntimeService/ImageService rpcs; the subset modeled here is the pod/container
lifecycle the kubelet's syncPod path exercises (RunPodSandbox, CreateContainer,
StartContainer, StopContainer, StopPodSandbox, RemovePodSandbox,
ListPodSandbox, ListContainers, ContainerStatus, PullImage). The fake mirrors
pkg/kubelet/container/testing.FakeRuntime / kubemark's containertest.FakeOS:
state transitions without a kernel.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

# container states (api.proto ContainerState)
CONTAINER_CREATED = "CONTAINER_CREATED"
CONTAINER_RUNNING = "CONTAINER_RUNNING"
CONTAINER_EXITED = "CONTAINER_EXITED"

SANDBOX_READY = "SANDBOX_READY"
SANDBOX_NOTREADY = "SANDBOX_NOTREADY"

_ids = itertools.count(1)


@dataclass
class ContainerStatus:
    id: str
    name: str
    image: str
    state: str = CONTAINER_CREATED
    exit_code: int = 0
    started_at: float = 0.0
    finished_at: float = 0.0
    restart_count: int = 0


@dataclass
class PodSandboxStatus:
    id: str
    pod_key: str  # "ns/name"
    uid: str
    state: str = SANDBOX_READY
    containers: Dict[str, ContainerStatus] = field(default_factory=dict)  # by name


class CRIRuntime:
    """The RuntimeService surface the kubelet calls (gRPC in the reference)."""

    def version(self) -> str:
        raise NotImplementedError

    def run_pod_sandbox(self, pod_key: str, uid: str) -> str:
        raise NotImplementedError

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        raise NotImplementedError

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        raise NotImplementedError

    def list_pod_sandboxes(self) -> List[PodSandboxStatus]:
        raise NotImplementedError

    def create_container(self, sandbox_id: str, name: str, image: str) -> str:
        raise NotImplementedError

    def start_container(self, sandbox_id: str, name: str) -> None:
        raise NotImplementedError

    def stop_container(self, sandbox_id: str, name: str) -> None:
        raise NotImplementedError

    def pull_image(self, image: str) -> None:
        raise NotImplementedError

    def exec_sync(self, pod_key: str, container: str, command: List[str],
                  stdin: bytes = b"") -> "Tuple[bytes, bytes, int]":
        """Run a command in the container (CRI ExecSync rpc,
        cri-api api.proto). Returns (stdout, stderr, exit_code)."""
        raise NotImplementedError

    def port_data(self, pod_key: str, port: int, data: bytes) -> bytes:
        """One port-forward connection round: bytes in, bytes out (the data
        channel of the CRI PortForward stream)."""
        raise NotImplementedError


class FakeRuntime(CRIRuntime):
    """In-memory runtime. Containers run until `exit_container` is called or
    their image's configured `run_duration` elapses on `tick(now)` — which is
    how tests/hollow clusters simulate Jobs finishing."""

    def __init__(self, clock=None):
        from ..utils import Clock

        self.clock = clock or Clock()
        self._lock = threading.RLock()
        self.sandboxes: Dict[str, PodSandboxStatus] = {}
        self.pulled_images: List[str] = []
        self.run_durations: Dict[str, float] = {}  # image -> seconds until exit 0
        self.fail_images: Dict[str, int] = {}  # image -> exit code on completion
        self.calls: List[str] = []  # rpc log (FakeRuntime.CalledFunctions)
        self._exec_handler: Optional[Callable] = None
        self._port_handlers: Dict[int, Callable[[bytes], bytes]] = {}
        # (pod_key, path) -> bytes: the fake container filesystem cat/tee
        # (and therefore `ktl cp`) operate on
        self._files: Dict[tuple, bytes] = {}

    # -- RuntimeService --------------------------------------------------------

    def version(self) -> str:
        return "0.1.0-faker"

    def run_pod_sandbox(self, pod_key: str, uid: str) -> str:
        with self._lock:
            self.calls.append("RunPodSandbox")
            sid = f"sandbox-{next(_ids)}"
            self.sandboxes[sid] = PodSandboxStatus(id=sid, pod_key=pod_key, uid=uid)
            return sid

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        with self._lock:
            self.calls.append("StopPodSandbox")
            sb = self.sandboxes.get(sandbox_id)
            if sb is None:
                return
            sb.state = SANDBOX_NOTREADY
            for c in sb.containers.values():
                if c.state == CONTAINER_RUNNING:
                    c.state = CONTAINER_EXITED
                    c.exit_code = 137  # SIGKILL
                    c.finished_at = self.clock.now()

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        with self._lock:
            self.calls.append("RemovePodSandbox")
            sb = self.sandboxes.get(sandbox_id)
            if sb is not None:
                # pod filesystems are ephemeral: a recreated same-name pod
                # must NOT inherit the dead pod's files
                self._files = {k: v for k, v in self._files.items()
                               if k[0] != sb.pod_key}
            self.sandboxes.pop(sandbox_id, None)

    def list_pod_sandboxes(self) -> List[PodSandboxStatus]:
        with self._lock:
            self.calls.append("ListPodSandbox")
            return list(self.sandboxes.values())

    def create_container(self, sandbox_id: str, name: str, image: str) -> str:
        with self._lock:
            self.calls.append("CreateContainer")
            sb = self.sandboxes[sandbox_id]
            prev = sb.containers.get(name)
            c = ContainerStatus(id=f"container-{next(_ids)}", name=name, image=image,
                                restart_count=prev.restart_count + 1 if prev else 0)
            sb.containers[name] = c
            return c.id

    def start_container(self, sandbox_id: str, name: str) -> None:
        with self._lock:
            self.calls.append("StartContainer")
            c = self.sandboxes[sandbox_id].containers[name]
            c.state = CONTAINER_RUNNING
            c.started_at = self.clock.now()

    def stop_container(self, sandbox_id: str, name: str) -> None:
        with self._lock:
            self.calls.append("StopContainer")
            c = self.sandboxes[sandbox_id].containers[name]
            if c.state == CONTAINER_RUNNING:
                c.state = CONTAINER_EXITED
                c.exit_code = 137
                c.finished_at = self.clock.now()

    def pull_image(self, image: str) -> None:
        with self._lock:
            self.calls.append("PullImage")
            self.pulled_images.append(image)

    def exec_sync(self, pod_key: str, container: str, command: List[str],
                  stdin: bytes = b"") -> Tuple[bytes, bytes, int]:
        """Emulated ExecSync: a handful of real shell semantics (echo, cat,
        true/false, env) so exec round-trips carry meaningful bytes; tests
        override per-command behavior with `set_exec_handler`."""
        with self._lock:
            self.calls.append("ExecSync")
            handler = self._exec_handler
        if handler is not None:
            return handler(pod_key, container, command, stdin)
        if not command:
            return b"", b"exec requires a command\n", 1
        prog = command[0]
        if prog == "echo":
            return (" ".join(command[1:]) + "\n").encode(), b"", 0
        if prog == "cat":
            if len(command) > 1:
                # per-pod in-memory filesystem (backs `ktl cp` reads)
                with self._lock:
                    data = self._files.get((pod_key, command[1]))
                if data is None:
                    return (b"", f"cat: {command[1]}: No such file or "
                            f"directory\n".encode(), 1)
                return data, b"", 0
            return stdin, b"", 0
        if prog == "tee":
            if len(command) > 1:
                with self._lock:
                    self._files[(pod_key, command[1])] = stdin
            return stdin, b"", 0
        if prog == "true":
            return b"", b"", 0
        if prog == "false":
            return b"", b"", 1
        if prog == "hostname":
            return (pod_key.split("/", 1)[-1] + "\n").encode(), b"", 0
        if prog == "env":
            return f"POD={pod_key}\nCONTAINER={container}\n".encode(), b"", 0
        return (f"exec: {' '.join(command)}\n").encode(), b"", 0

    def set_exec_handler(self, fn: Optional[Callable]) -> None:
        with self._lock:
            self._exec_handler = fn

    def port_data(self, pod_key: str, port: int, data: bytes) -> bytes:
        """Echo backend by default; tests register per-port servers with
        `set_port_handler` (e.g. a canned HTTP response)."""
        with self._lock:
            self.calls.append("PortForward")
            handler = self._port_handlers.get(port)
        if handler is not None:
            return handler(data)
        return b"ECHO:" + data

    def set_port_handler(self, port: int,
                         fn: Optional[Callable[[bytes], bytes]]) -> None:
        with self._lock:
            if fn is None:
                self._port_handlers.pop(port, None)
            else:
                self._port_handlers[port] = fn

    # -- test hooks ------------------------------------------------------------

    def exit_container(self, pod_key: str, name: str, exit_code: int = 0) -> None:
        with self._lock:
            for sb in self.sandboxes.values():
                if sb.pod_key == pod_key and name in sb.containers:
                    c = sb.containers[name]
                    if c.state == CONTAINER_RUNNING:
                        c.state = CONTAINER_EXITED
                        c.exit_code = exit_code
                        c.finished_at = self.clock.now()

    def tick(self) -> None:
        """Expire containers whose image has a configured run duration."""
        now = self.clock.now()
        with self._lock:
            for sb in self.sandboxes.values():
                for c in sb.containers.values():
                    dur = self.run_durations.get(c.image)
                    if (dur is not None and c.state == CONTAINER_RUNNING
                            and now - c.started_at >= dur):
                        c.state = CONTAINER_EXITED
                        c.exit_code = self.fail_images.get(c.image, 0)
                        c.finished_at = now

    def sandbox_for(self, pod_key: str) -> Optional[PodSandboxStatus]:
        with self._lock:
            for sb in self.sandboxes.values():
                if sb.pod_key == pod_key and sb.state == SANDBOX_READY:
                    return sb
            return None
