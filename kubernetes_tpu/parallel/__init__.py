"""Device mesh construction and sharded solvers (ICI-scale node/pod axes)."""

from __future__ import annotations


def mesh_context(mesh):
    """`jax.sharding.set_mesh(mesh)`-compatible context manager across jax
    versions (the ROADMAP env gap: this toolchain's jax build predates the
    public set_mesh). Every caller here device_puts its arrays with explicit
    NamedShardings, so on older builds the legacy `with mesh:` resource-env
    context is sufficient — GSPMD partitioning and replica groups come out
    identical (pinned by the sharded-parity tests)."""
    import jax

    set_mesh = getattr(jax.sharding, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    return mesh  # jax.sharding.Mesh is itself a context manager
