"""Device mesh construction and sharded solvers (ICI-scale node/pod axes)."""
