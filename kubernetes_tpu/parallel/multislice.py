"""Multi-slice / DCN-aware mesh construction and collective-locality checks.

The scale story (SURVEY.md §5 comm backend): one TPU slice is a set of chips
joined by ICI (terabit, microsecond); slices interconnect over DCN (gigabit,
millisecond). The reference spreads its scheduler fan-out over goroutines and
its HA over etcd/gRPC; the TPU-native equivalent is a HYBRID MESH whose outer
axis crosses slices (DCN) and whose inner axis stays inside a slice (ICI),
with shardings arranged so that:

  - the node axis — where every scan step runs segment-sums and a global
    argmax — lives on the INNER (ICI) axis: per-step collectives never leave
    a slice;
  - the pod/batch axis — embarrassingly parallel (one gather at the end) —
    lives on the OUTER (DCN) axis: DCN carries exactly one collective per
    batch, not one per scan step.

Axis names stay ("dp", "nodes") so every NamedSharding in sharded.py works
unchanged on a hybrid mesh; only the device placement underneath changes.

Multi-host bring-up: each host calls jax.distributed.initialize(...) and
jax.devices() then spans all slices; `make_hybrid_mesh()` groups by
`device.slice_index`. Single-host (and the CPU test rig) emulates slices by
folding the flat device list — the GSPMD partitioning and the collective
replica groups are identical either way, which is what the HLO locality test
asserts.
"""

from __future__ import annotations

import re
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def slice_topology(devices: Optional[Sequence] = None) -> Dict[int, List]:
    """Group devices by their slice (ICI domain). Real multi-slice TPU
    exposes `slice_index`; anything without one is a single ICI domain."""
    devices = list(devices if devices is not None else jax.devices())
    by_slice: Dict[int, List] = defaultdict(list)
    for d in devices:
        by_slice[getattr(d, "slice_index", 0) or 0].append(d)
    return dict(by_slice)


def make_hybrid_mesh(n_slices: Optional[int] = None,
                     devices: Optional[Sequence] = None) -> Mesh:
    """Mesh whose "dp" axis crosses slices (DCN) and "nodes" axis stays
    intra-slice (ICI). On hardware that reports slice_index the grouping is
    physical; otherwise `n_slices` folds the device list into emulated slices
    (the CPU rig and single-slice chips)."""
    devices = list(devices if devices is not None else jax.devices())
    groups = slice_topology(devices)
    if len(groups) > 1:
        sizes = {len(v) for v in groups.values()}
        if len(sizes) != 1:
            raise ValueError(f"uneven slices: { {k: len(v) for k, v in groups.items()} }")
        if n_slices is not None and n_slices != len(groups):
            raise ValueError(f"hardware has {len(groups)} slices, asked for {n_slices}")
        arr = np.array([groups[k] for k in sorted(groups)])
    else:
        n_slices = n_slices or 1
        if len(devices) % n_slices:
            raise ValueError(f"{len(devices)} devices do not fold into "
                             f"{n_slices} slices")
        arr = np.array(devices).reshape(n_slices, -1)
    return Mesh(arr, ("dp", "nodes"))


# ---- collective locality audit ------------------------------------------------

_OPS = r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
# v1 list format: replica_groups={{0,1,2,3},{4,5,6,7}}
_V1_RE = re.compile(_OPS + r"[^\n]*replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
# v2 iota format: replica_groups=[2,4]<=[8] or [4,2]<=[2,4]T(1,0)
_V2_RE = re.compile(
    _OPS + r"[^\n]*replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\]"
    r"(?:T\(([\d,]+)\))?")


def _iota_groups(g: int, s: int, dims: List[int],
                 perm: Optional[List[int]]) -> List[List[int]]:
    """Expand the v2 iota replica-group spec: devices = iota(prod(dims))
    .reshape(dims).transpose(perm).flatten(), split into g rows of s."""
    ids = np.arange(int(np.prod(dims))).reshape(dims)
    if perm is not None:
        ids = ids.transpose(perm)
    return ids.reshape(g, s).tolist()


def collective_replica_groups(compiled_text: str) -> List[Tuple[str, List[List[int]]]]:
    """Parse (op, replica_groups) out of compiled HLO text — both the literal
    {{...}} and the iota [g,s]<=[dims]T(perm) spellings."""
    out: List[Tuple[str, List[List[int]]]] = []
    for m in _V1_RE.finditer(compiled_text):
        groups = [[int(x) for x in g.strip("{}").split(",") if x.strip() != ""]
                  for g in re.findall(r"\{[^}]*\}", m.group(2))]
        out.append((m.group(1), groups))
    for m in _V2_RE.finditer(compiled_text):
        g, s = int(m.group(2)), int(m.group(3))
        dims = [int(x) for x in m.group(4).split(",")]
        perm = [int(x) for x in m.group(5).split(",")] if m.group(5) else None
        out.append((m.group(1), _iota_groups(g, s, dims, perm)))
    # replica_groups={} means "one group of everything" — report as a single
    # group of -1 so audit treats it as crossing
    for m in re.finditer(_OPS + r"[^\n]*replica_groups=\{\}", compiled_text):
        out.append((m.group(1), [[-1, -2]]))
    return out


def audit_collectives(fn, mesh: Mesh, *args, dcn_ok: Sequence[str] = (),
                      **kwargs) -> Dict[str, int]:
    """Compile `fn` under `mesh` and verify every collective's replica group
    stays inside one slice (one row of the mesh's device array). Collectives
    named in `dcn_ok` (by HLO op) may cross. Returns {"ici": n, "dcn": n}
    counts; raises AssertionError when a non-exempt collective crosses DCN.

    This is the profile-free version of "look at the xplane and check which
    collectives ride which fabric": replica groups are decided at compile
    time, so locality is checkable without hardware."""
    from . import mesh_context

    with mesh_context(mesh):
        compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    text = compiled.as_text()
    # device id -> slice row
    row_of: Dict[int, int] = {}
    for r, row in enumerate(mesh.devices):
        for d in row:
            row_of[d.id] = r
    counts = {"ici": 0, "dcn": 0}
    for op, groups in collective_replica_groups(text):
        # unknown ids (incl. the empty-replica_groups sentinel) keep their own
        # identity so a global collective reads as crossing, never as local
        crosses = any(len({row_of.get(i, i) for i in g}) > 1 for g in groups)
        if crosses:
            counts["dcn"] += 1
            if op not in dcn_ok:
                raise AssertionError(
                    f"{op} crosses slices (replica_groups={groups}); "
                    f"only {list(dcn_ok)} may ride DCN")
        else:
            counts["ici"] += 1
    return counts
