"""Mesh construction and sharded solver entry points.

The distributed design (SURVEY.md §2.4): the node axis of every cluster tensor
is sharded across the mesh's "nodes" axis (the tensor-parallel analog — the
direct replacement for the scheduler's 16-goroutine Parallelizer fan-out,
parallelize/parallelism.go:67), and the pod axis of batch matrices across "dp"
(data-parallel analog). Shardings are annotated with NamedSharding and XLA/GSPMD
inserts the collectives (segment-sum psums for topology counts, argmax
all-reduce for host selection) over ICI.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh_context
from ..ops.solver import SolverInputs, greedy_scan_solve
from ..scheduler.framework import MAX_NODE_SCORE


def make_mesh(n_devices: Optional[int] = None, dp: int = 1) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    assert n % dp == 0, f"dp={dp} must divide device count {n}"
    return Mesh(np.array(devices).reshape(dp, n // dp), ("dp", "nodes"))


# PartitionSpec per SolverInputs field: which axis is the node axis.
_SPECS = dict(
    alloc=P("nodes", None), used=P("nodes", None), used_nz=P("nodes", None),
    pod_count=P("nodes"), max_pods=P("nodes"),
    filter_ok=P(None, "nodes"), aff_ok=P(None, "nodes"),
    napref_raw=P(None, "nodes"), has_napref=P(),
    taint_cnt=P(None, "nodes"), img_score=P(None, "nodes"),
    class_ports=P(), node_ports=P("nodes", None),
    topo_id=P(None, "nodes"), selcls_count=P(None, "nodes"),
    class_matches_selcls=P(),
    ct_class=P(), ct_key=P(), ct_sel=P(), ct_max_skew=P(),
    ct_min_domains=P(), ct_self_match=P(),
    st_class=P(), st_key=P(), st_sel=P(), st_max_skew=P(), st_self_match=P(),
    ra_key=P(), ra_sel=P(),
    rn_key=P(), rn_sel=P(),
    pp_key=P(), pp_sel=P(), pp_weight=P(),
    grp_key=P(), grp_count=P(None, "nodes"), class_holds_grp=P(),
    ea_grp=P(),
    sym_grp=P(), sym_weight=P(),
    class_self_ok=P(), class_has_ra=P(),
    req=P(), req_nz=P(), class_of_pod=P(), balanced_active=P(),
    gang_bonus=P(None, "nodes"),
)


def _pad_nodes(inp: SolverInputs, multiple: int) -> Tuple[SolverInputs, int]:
    """Pad the node axis so it divides the mesh. Padding nodes are infeasible
    (filter_ok false, zero capacity) and can never be selected."""
    n = inp.alloc.shape[0]
    pad = (-n) % multiple
    if pad == 0:
        return inp, n
    def pad_node_axis(name, arr):
        if arr is None:  # optional field absent (e.g. gang_bonus)
            return None
        spec = _SPECS[name]
        axis = None
        for i, s in enumerate(spec):
            if s == "nodes":
                axis = i
        if axis is None:
            return arr
        widths = [(0, 0)] * arr.ndim
        widths[axis] = (0, pad)
        return jnp.pad(arr, widths)
    padded = SolverInputs(**{k: pad_node_axis(k, v) for k, v in inp._asdict().items()})
    # padded topo ids are 0 after padding — mark them missing (-1)
    if padded.topo_id.size:
        mask = jnp.arange(padded.topo_id.shape[1]) >= n
        padded = padded._replace(topo_id=jnp.where(mask[None, :], -1, padded.topo_id))
    return padded, n


def shard_inputs(inp: SolverInputs, mesh: Mesh) -> Tuple[SolverInputs, int]:
    """device_put every field with its NamedSharding (node axis over the mesh)."""
    inp, n = _pad_nodes(inp, mesh.shape["nodes"])
    placed = {
        k: (v if v is None
            else jax.device_put(v, NamedSharding(mesh, _SPECS[k])))
        for k, v in inp._asdict().items()
    }
    return SolverInputs(**placed), n


def sharded_greedy_solve(inp: SolverInputs, d_max: int, mesh: Mesh):
    """greedy_scan_solve with node-axis-sharded inputs: GSPMD partitions the
    per-step filter/score over the mesh and inserts the argmax/segment-sum
    collectives. Assignment indices refer to the padded node axis; callers must
    treat idx >= true_n as unschedulable (cannot happen: padding is infeasible)."""
    with mesh_context(mesh):
        return greedy_scan_solve(inp, d_max)


def feasibility_cost_matrices(inp: SolverInputs, d_max: int):
    """F[P,N], C[P,N] against the *initial* snapshot state (no intra-batch
    dynamics) — the batch-extender surface (ExtenderArgs -> filtered nodes +
    HostPriority lists, reference: extender/v1/types.go) and the 2D (dp x nodes)
    sharded kernel. Scores use the same default-weight composition as the
    solver."""
    from ..ops.solver import pod_row_feasibility_score

    def per_pod(req, req_nz, cls, bal_active):
        return pod_row_feasibility_score(inp, req, req_nz, cls, bal_active)

    return jax.vmap(per_pod)(inp.req, inp.req_nz, inp.class_of_pod, inp.balanced_active)


def sharded_feasibility_cost(inp: SolverInputs, d_max: int, mesh: Mesh):
    """2D-sharded F/C: pods over 'dp', nodes over 'nodes'."""
    fn = jax.jit(feasibility_cost_matrices, static_argnames=("d_max",),
                 out_shardings=(NamedSharding(mesh, P("dp", "nodes")),
                                NamedSharding(mesh, P("dp", "nodes"))))
    with mesh_context(mesh):
        return fn(inp, d_max)


# PartitionSpec per GroupProblem field (models/transport.py): the node axis
# of the [G, N] transportation problem shards over the mesh — BASELINE.json
# ladder #4 "Sinkhorn relaxation node-sharded". The group axis stays
# replicated (G is small after class collapse); GSPMD inserts the node-axis
# reductions (sinkhorn row-logsumexp, auction top-k/argmax) over ICI.
_GP_SPECS = dict(
    utility=P(None, "nodes"), feasible=P(None, "nodes"),
    jcap=P(None, "nodes"), supply=P(), slots=P("nodes"), req=P(),
    alloc=P("nodes", None), used=P("nodes", None),
)


def shard_group_problem(problem, mesh: Mesh):
    """Pad the node axis to the mesh multiple (padding is infeasible: zero
    capacity/slots, -inf utility) and device_put every field with its
    NamedSharding. Returns (sharded problem, true node count)."""
    from ..models.transport import NEG_INF

    n = problem.utility.shape[1]
    mult = mesh.shape["nodes"]
    pad = (-n) % mult
    if pad:
        # spec-driven (same pattern as _pad_nodes): every field whose spec
        # names the nodes axis pads along it — a new field added to
        # _GP_SPECS is padded automatically or device_put fails loudly
        padded = {}
        for k, spec in _GP_SPECS.items():
            arr = getattr(problem, k)
            axis = next((i for i, s in enumerate(spec) if s == "nodes"), None)
            if axis is None:
                continue
            widths = [(0, 0)] * arr.ndim
            widths[axis] = (0, pad)
            fill = float(NEG_INF) if k == "utility" else 0
            padded[k] = jnp.pad(arr, widths, constant_values=fill)
        problem = problem._replace(**padded)
    placed = {k: jax.device_put(getattr(problem, k),
                                NamedSharding(mesh, _GP_SPECS[k]))
              for k in _GP_SPECS}
    return problem._replace(**placed), n
