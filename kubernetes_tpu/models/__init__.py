"""End-to-end solver models (greedy scan / auction / sinkhorn assignment)."""
