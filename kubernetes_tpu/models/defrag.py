"""Slice defragmentation kernel (ISSUE 17, ROADMAP direction 3).

The background rebalancer (scheduler/rebalance.py) periodically re-solves
the whole allocation as one batched tensor problem — the CvxCluster insight
that granular cluster allocation re-solves orders of magnitude faster as a
structured batched program, applied to the one decision this orchestrator
repeats forever: which movable pods leave a fragmented ICI slice so a whole
slice's worth of contiguous room reappears. Two pieces live here:

  fragmentation score — per resource r with nonzero total free capacity,
      frag_r = 1 - max_slice_free_r / total_free_r: 0 when every free unit
      sits on one slice (a gang admits without eviction), approaching 1 as
      free capacity smears evenly across slices (the state where arriving
      gangs can only be admitted by destroying work through preemption).
      The cycle score is the max over resources — computed host-side from
      the cluster tensors alone, so the steady-state probe allocates no pod
      objects.

  defrag assignment — given the candidate victims of a donor slice (in
      caller-supplied drain order) and the free/headroom tensors of the
      candidate target nodes, greedily re-place each victim on the
      tightest-fitting eligible node (best-fit: minimize the summed free
      capacity remaining after placement, ties to the lowest node index).
      One lax.scan over the victim axis carries the (free, headroom) state
      so every step sees the capacity its predecessors consumed — the
      waterfill idiom (models/waterfill.py) with a placement argmin instead
      of a water level. defrag_assign_host is the numpy oracle (bit-parity
      pinned by tests/test_rebalance.py) and the fallback when the padded
      tensors would not be worth uploading.

The kernel takes only batch-stable statics (pow2 buckets over both padded
axes) and does no host sync inside the traced body (JT001/JT002,
schedlint-enforced). Everything is int32 on device (this project runs jax
in 32-bit mode): quantized resource magnitudes (millicores / MiB) keep a
per-node dim sum far below 2^31, and the sentinel below stays in range.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# victims considered per rebalance cycle before the plan budget even
# applies; the rebalancer publishes a candidates_capped stat when it clips —
# never a silent truncation
DEFRAG_MAX_VICTIMS = 1024
# above this padded-tensor size the per-step [V, N, R] fit masks are not
# worth building on device; the numpy oracle computes the same plan
_DEFRAG_KERNEL_MAX_ELEMS = 4_000_000

_INT32_BIG = 2**30  # "no eligible target" sentinel for the best-fit argmin


# -- fragmentation score ------------------------------------------------------


def slice_fragmentation(free: np.ndarray, slice_of_node: np.ndarray,
                        active: Optional[np.ndarray] = None,
                        ) -> Tuple[float, np.ndarray]:
    """(score, per_slice_free [S, R]) from the cluster free tensor
    (alloc - used, [N, R]) and the per-node slice ids (scheduler/gang.py
    node_slice_ids; -1 = unlabeled, excluded). Score is the max over
    resources with nonzero total free of 1 - max_slice_free / total_free:
    0 on a zero-frag (or single-slice, or fully-packed) cluster.

    active ([R] bool) restricts the score to resources the cluster actually
    CONSUMES (the rebalancer passes used.sum(axis=0) > 0): a dim nothing
    requests has its free capacity spread evenly by construction — scoring
    it would read a permanent ~1-1/S "fragmentation" no migration can ever
    change, and the no-op steady state would never be reached."""
    free = np.maximum(np.asarray(free, dtype=np.int64), 0)
    sl = np.asarray(slice_of_node, dtype=np.int64)
    labeled = sl >= 0
    if not labeled.any():
        return 0.0, np.zeros((0, free.shape[1]), dtype=np.int64)
    s = int(sl[labeled].max()) + 1
    per_slice = np.zeros((s, free.shape[1]), dtype=np.int64)
    np.add.at(per_slice, sl[labeled], free[labeled])
    if s < 2:
        return 0.0, per_slice
    total = per_slice.sum(axis=0)
    nz = total > 0
    if active is not None:
        nz &= np.asarray(active, dtype=bool)
    if not nz.any():
        return 0.0, per_slice
    frag = 1.0 - per_slice[:, nz].max(axis=0) / total[nz]
    return float(frag.max()), per_slice


# -- defrag assignment --------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_slots", "v_max"))
def defrag_assign(free, headroom, target_ok, v_req, v_valid,
                  n_slots: int, v_max: int):
    """Target node per victim (-1 = no eligible target; the victim stays).
    All arrays padded by the caller: free [n_slots, R] int32, headroom
    [n_slots] int32 (remaining pod-count slots), target_ok [n_slots] bool
    (schedulable AND not on a donor slice), v_req [v_max, R] int32 in drain
    order, v_valid [v_max] bool (False pads). Statics are pow2 buckets only.
    One scan step per victim: the carry is the live (free, headroom), so a
    wave of placements never double-books a node."""

    def step(carry, xs):
        fr, hd = carry
        vr, valid = xs
        fits = (fr >= vr[None, :]).all(axis=1) & (hd > 0) & target_ok
        # best-fit key: free capacity REMAINING after placement, summed
        # across dims — the tightest bin wins, ties to the lowest index
        waste = jnp.sum(fr - vr[None, :], axis=1)
        key = jnp.where(fits, waste, jnp.int32(_INT32_BIG))
        tgt = jnp.argmin(key).astype(jnp.int32)
        place = (key[tgt] < jnp.int32(_INT32_BIG)) & valid
        fr = fr.at[tgt].add(-vr * place)
        hd = hd.at[tgt].add(-place.astype(hd.dtype))
        return (fr, hd), jnp.where(place, tgt, jnp.int32(-1))

    (_fr, _hd), out = jax.lax.scan(
        step, (free, headroom), (v_req, v_valid), length=v_max)
    return out


def defrag_assign_host(free: np.ndarray, headroom: np.ndarray,
                       target_ok: np.ndarray,
                       v_req: np.ndarray) -> np.ndarray:
    """Numpy oracle of defrag_assign (unpadded): the parity target and the
    fallback when the padded tensors exceed the device budget. Same greedy,
    same best-fit key, same first-min tie-break."""
    free = np.asarray(free, dtype=np.int64).copy()
    headroom = np.asarray(headroom, dtype=np.int64).copy()
    target_ok = np.asarray(target_ok, dtype=bool)
    v_req = np.asarray(v_req, dtype=np.int64)
    out = np.full(len(v_req), -1, dtype=np.int64)
    for k in range(len(v_req)):
        vr = v_req[k]
        fits = (free >= vr[None, :]).all(axis=1) & (headroom > 0) & target_ok
        if not fits.any():
            continue
        waste = np.sum(free - vr[None, :], axis=1)
        key = np.where(fits, waste, np.int64(_INT32_BIG))
        tgt = int(np.argmin(key))
        out[k] = tgt
        free[tgt] -= vr
        headroom[tgt] -= 1
    return out


def defrag_plan(free: np.ndarray, headroom: np.ndarray, target_ok: np.ndarray,
                v_req: np.ndarray) -> np.ndarray:
    """Dispatch wrapper: pads to pow2 buckets and runs the jitted scan, or
    the numpy oracle when the padded tensors would blow the device budget.
    Returns the [V] target node index vector as numpy int64 (-1 = stay)."""
    v = len(v_req)
    ns, r = free.shape
    # pow2 buckets key the jit (JT001 discipline, models/waterfill.py idiom)
    n_slots = 1 << max(0, ns - 1).bit_length()
    v_max = 1 << max(0, v - 1).bit_length()
    if v == 0:
        return np.zeros(0, dtype=np.int64)
    if v_max * n_slots * r > _DEFRAG_KERNEL_MAX_ELEMS:
        return defrag_assign_host(free, headroom, target_ok, v_req)
    free_p = np.zeros((n_slots, r), dtype=np.int32)
    free_p[:ns] = free
    head_p = np.zeros(n_slots, dtype=np.int32)
    head_p[:ns] = headroom
    ok_p = np.zeros(n_slots, dtype=bool)
    ok_p[:ns] = target_ok
    vr_p = np.zeros((v_max, r), dtype=np.int32)
    vr_p[:v] = v_req
    valid_p = np.zeros(v_max, dtype=bool)
    valid_p[:v] = True
    out = np.asarray(defrag_assign(free_p, head_p, ok_p, vr_p, valid_p,
                                   n_slots=n_slots, v_max=v_max))
    return out[:v].astype(np.int64)
