"""Global batch solvers: auction and Sinkhorn on the group-level transportation
problem — the north-star replacement for prioritizeNodes() when the objective
is *joint* placement quality rather than serial-greedy emulation (reference:
pkg/scheduler/schedule_one.go:754; BASELINE.json north_star "JAX auction/
Sinkhorn over a dense feasibility/cost tensor").

Formulation. Batch pods collapse into G equivalence groups (identical class +
resource vector — snapshot/class_compiler.py); the problem becomes a
transportation problem on a [G, N] utility matrix:

    max Σ x_gn · C_gn      s.t.  Σ_n x_gn ≤ supply_g   (place each pod ≤ once)
                                 Σ_g x_gn ≤ slots_n    (node pod-count headroom)
                                 0 ≤ x_gn ≤ jcap_gn    (per-cell multi-resource fit)

`jcap_gn` bounds how many g-pods fit on n alone; cross-group resource coupling
is NOT in the relaxation — `repair_plan` enforces it exactly afterwards, and
pods it cannot seat return -1 (the batch driver re-runs them serially, so the
end-to-end result never violates a Filter).

Both solvers carry their duals across calls (`TransportState`): under churn the
next batch warm-starts from the previous prices/potentials re-mapped by node
name — the incremental re-solve of the north star (mirrors the generation-diff
snapshot stream, reference cache.go:186).

Solvers:
  auction_solve  — Bertsekas-style parallel forward auction with eps-scaling.
                   Holders + new bids per node are merged and the top slots_n
                   unit-levels are retained per round (a [2G, N] sort — node
                   axis shardable over the mesh). Integer-optimal to within
                   G·eps_final on the relaxation.
  sinkhorn_solve — log-domain entropic OT with inequality column marginals
                   (iterative Bregman projections; col update g += min(0,
                   eps·log(cap/colsum))). Returns a fractional plan that
                   `round_plan` converts to integers (floor + largest
                   remainder under column capacity).
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.solver import SolverInputs, pod_row_feasibility_score

NEG_INF = jnp.float32(-1e30)


class GroupProblem(NamedTuple):
    """The [G, N] transportation problem (all device arrays except members)."""

    utility: jnp.ndarray  # [G, N] float32 (int scores cast)
    feasible: jnp.ndarray  # [G, N] bool
    jcap: jnp.ndarray  # [G, N] int32 — per-cell max placements (single group)
    supply: jnp.ndarray  # [G] int32
    slots: jnp.ndarray  # [N] int32 — pod-count headroom
    req: jnp.ndarray  # [G, R] int32
    alloc: jnp.ndarray  # [N, R] int32
    used: jnp.ndarray  # [N, R] int32
    members: Tuple[np.ndarray, ...]  # per-group pod indices (queue order), host


class TransportState(NamedTuple):
    """Warm-startable duals. price doubles as the Sinkhorn node potential -g."""

    price: np.ndarray  # [N] float32
    node_names: Tuple[str, ...]
    iterations: int  # iterations spent by the last solve (observability)


def _group_rows(inp: SolverInputs, groups) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """F[G,N], C[G,N] from each group's representative pod."""
    reps = np.array([int(m[0]) for m, _ in groups])
    reqs = inp.req[reps]
    req_nzs = inp.req_nz[reps]
    clss = inp.class_of_pod[reps]
    bals = inp.balanced_active[reps]

    def row(req, req_nz, cls, bal):
        return pod_row_feasibility_score(inp, req, req_nz, cls, bal)

    return jax.vmap(row)(reqs, req_nzs, clss, bals)


def build_group_problem(inp: SolverInputs, groups) -> Optional[GroupProblem]:
    """groups: make_groups(batch) output. Returns None when any group's class
    declares host ports (per-port exclusion isn't in the transport relaxation;
    callers fall back to waterfill/scan)."""
    if not groups:
        return None
    for _, cls in groups:
        if bool(np.asarray(inp.class_ports[cls]).any()):
            return None
    feas, util = _group_rows(inp, groups)
    reps = np.array([int(m[0]) for m, _ in groups])
    req = inp.req[reps]  # [G, R]
    free = inp.alloc[None, :, :] - inp.used[None, :, :]  # [1, N, R]
    per_res = jnp.where(
        req[:, None, :] > 0,
        free // jnp.maximum(req[:, None, :], 1),
        jnp.int32(2**30),
    )
    jcap = jnp.min(per_res, axis=2).astype(jnp.int32)  # [G, N]
    slots = (inp.max_pods - inp.pod_count).astype(jnp.int32)
    jcap = jnp.minimum(jcap, slots[None, :])
    jcap = jnp.where(feas, jnp.maximum(jcap, 0), 0)
    supply = jnp.asarray([len(m) for m, _ in groups], dtype=jnp.int32)
    return GroupProblem(
        utility=util.astype(jnp.float32),
        feasible=feas,
        jcap=jcap,
        supply=supply,
        slots=jnp.maximum(slots, 0),
        req=jnp.asarray(req),
        alloc=inp.alloc,
        used=inp.used,
        members=tuple(np.asarray(m) for m, _ in groups),
    )


# ---------------------------------------------------------------------------
# auction
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("max_rounds",))
def _auction_phase(utility, jcap, supply, slots, req, free, x0, price0, level0,
                   eps, max_rounds: int):
    """One eps-phase of the forward auction. Returns (x, price, level, rounds).

    State: x[G,N] units held, level[G,N] the bid level units in the cell were
    acquired at (cell granularity — mixed-level cells keep the min, which only
    makes holders easier to evict, never violates feasibility), price[N].

    Acceptance is **resource-exact**: per node, holder+bid units are taken in
    level order while the cumulative multi-resource usage still fits
    (free = alloc − used) and the pod-count slot bound holds — the knapsack
    step is a lax.scan over the 2G sorted candidate rows carrying the running
    [N,R] usage. So the auction never produces a cross-group over-commit; the
    relaxation gap the repair pass has to fix is only supply clamping.
    """
    g, n = utility.shape
    r = req.shape[1]
    req2 = jnp.concatenate([req, req], axis=0)  # [2G, R] rows for both halves
    big = jnp.int32(2**30)

    def cond(state):
        x, price, level, rounds, progress = state
        unassigned = supply - jnp.sum(x, axis=1)
        return (jnp.any(unassigned > 0) & progress) & (rounds < max_rounds)

    def body(state):
        x, price, level, rounds, _ = state
        unassigned = supply - jnp.sum(x, axis=1)
        # value of acquiring one more unit of node n for group g; a pod prefers
        # any feasible node over staying unassigned (utility floor -inf only
        # for truly infeasible cells)
        v = jnp.where(jcap > x, utility - price[None, :], NEG_INF)
        # MULTI-NODE bids: each group bids its top-K nodes per round,
        # spreading unassigned units across them in value order. With one
        # node per round a single huge group (G=1, supply 50k) could place
        # only jcap units per round — 400 rounds capped it at ~13k pods.
        k = min(16, n)
        vk, jk = jax.lax.top_k(v, k)  # [G, K]
        v1 = vk[:, 0]
        # the marginal competing value: the best node OUTSIDE the top-K
        # (or the K-th best when nothing else is feasible) — every bid in
        # the wave uses it, which only raises bids above the minimum
        # Bertsekas increment (aggressive bids stay eps-CS-valid)
        rows = jnp.arange(g)[:, None].repeat(k, axis=1)
        v_next = jnp.max(v.at[rows, jk].set(NEG_INF), axis=1)
        v_next = jnp.where(v_next <= NEG_INF / 2,
                           jnp.where(vk[:, k - 1] > NEG_INF / 2,
                                     vk[:, k - 1], v1),
                           v_next)
        bidding = (unassigned > 0) & (v1 > NEG_INF / 2)
        avail = jnp.clip(
            jnp.take_along_axis(jcap, jk, axis=1)
            - jnp.take_along_axis(x, jk, axis=1), 0, None)  # [G, K]
        avail = jnp.where(vk > NEG_INF / 2, avail, 0)
        prefix = jnp.cumsum(avail, axis=1) - avail  # exclusive prefix
        units_k = jnp.clip(unassigned[:, None] - prefix, 0, avail)
        units_k = jnp.where(bidding[:, None], units_k, 0)
        beta_k = jnp.take_along_axis(utility, jk, axis=1) - v_next[:, None] + eps
        bids = jnp.zeros_like(x).at[rows, jk].add(units_k)
        bid_level = jnp.full_like(level, NEG_INF).at[rows, jk].max(
            jnp.where(units_k > 0, beta_k, NEG_INF))

        # merge holders + bids per node; greedy knapsack acceptance by level
        units = jnp.concatenate([x, bids], axis=0)  # [2G, N]
        levels = jnp.concatenate([
            jnp.where(x > 0, level, NEG_INF),
            jnp.where(bids > 0, bid_level, NEG_INF),
        ], axis=0)
        order = jnp.argsort(-levels, axis=0)  # [2G, N] rows by level desc
        u_sorted = jnp.take_along_axis(units, order, axis=0)
        l_sorted = jnp.take_along_axis(levels, order, axis=0)
        req_sorted = req2[order]  # [2G, N, R]

        def accept(carry, row):
            used_acc, cnt_acc = carry  # [N, R], [N]
            u_row, l_row, rq = row  # [N], [N], [N, R]
            room = free - used_acc  # [N, R]
            fit = jnp.min(
                jnp.where(rq > 0, room // jnp.maximum(rq, 1), big), axis=1
            )  # [N]
            fit = jnp.minimum(fit, slots - cnt_acc)
            k = jnp.clip(fit, 0, u_row)
            k = jnp.where(l_row > NEG_INF / 2, k, 0)
            used_acc = used_acc + k[:, None] * rq
            cnt_acc = cnt_acc + k
            return (used_acc, cnt_acc), k

        (_, _), keep = jax.lax.scan(
            accept,
            (jnp.zeros((n, r), jnp.int32), jnp.zeros((n,), jnp.int32)),
            (u_sorted, l_sorted, req_sorted),
        )  # keep: [2G, N]

        # price rises to the highest rejected level (the (cap+1)-th bid)
        rejected = u_sorted - keep
        any_rej = jnp.any(rejected > 0, axis=0)
        top_rej_level = jnp.max(
            jnp.where(rejected > 0, l_sorted, NEG_INF), axis=0
        )
        new_price = jnp.where(
            any_rej, jnp.maximum(price, top_rej_level), price
        )
        # scatter kept units back to [2G, N] then fold the two halves
        kept = jnp.zeros_like(units).at[
            order, jnp.arange(n)[None, :].repeat(2 * g, axis=0)
        ].set(keep)
        kept_levels = jnp.where(kept > 0, levels, -NEG_INF)
        x_new = kept[:g] + kept[g:]
        level_new = jnp.minimum(kept_levels[:g], kept_levels[g:])
        level_new = jnp.where(x_new > 0, level_new, NEG_INF)
        progress = jnp.any(units_k > 0)
        return x_new, new_price, level_new, rounds + 1, progress

    x, price, level, rounds, _ = jax.lax.while_loop(
        cond, body, (x0, price0, level0, jnp.int32(0), jnp.bool_(True))
    )
    return x, price, level, rounds


def auction_solve(
    problem: GroupProblem,
    state: Optional[TransportState] = None,
    node_names: Optional[List[str]] = None,
    eps_start: Optional[float] = None,
    eps_final: float = 0.9,
    scale: float = 4.0,
    max_rounds: int = 400,
) -> Tuple[np.ndarray, TransportState]:
    """eps-scaling forward auction. Returns (x[G,N] int counts, state).

    Scores are integers, so eps_final < 1 yields a relaxation-optimal
    assignment up to per-node ties; warm prices from `state` skip most of the
    price discovery under churn."""
    g, n = problem.utility.shape
    price0 = np.zeros(n, np.float32)
    if state is not None and node_names is not None:
        remapped = _remap_price(state, node_names)
        price0[:len(remapped)] = remapped  # node axis may be mesh-padded
    util_range = float(jnp.max(jnp.where(problem.feasible, problem.utility, 0)))
    eps = eps_start if eps_start is not None else max(util_range / 8.0, eps_final)
    price = jnp.asarray(price0)
    free = problem.alloc - problem.used
    total_rounds = 0
    while True:
        x, price, level, rounds = _auction_phase(
            problem.utility, problem.jcap, problem.supply, problem.slots,
            problem.req, free,
            jnp.zeros((g, n), jnp.int32), price, jnp.full((g, n), NEG_INF),
            jnp.float32(eps), max_rounds,
        )
        total_rounds += int(rounds)
        if eps <= eps_final:
            break
        eps = max(eps / scale, eps_final)
    names = tuple(node_names) if node_names else tuple(str(i) for i in range(n))
    new_state = TransportState(
        price=np.asarray(price)[:len(names)],
        node_names=names,
        iterations=total_rounds,
    )
    return np.asarray(x), new_state


def _remap_price(state: TransportState, node_names: List[str]) -> np.ndarray:
    """Carry duals across snapshots by node name (churn: nodes come and go)."""
    idx = {nm: i for i, nm in enumerate(state.node_names)}
    out = np.zeros(len(node_names), np.float32)
    for j, nm in enumerate(node_names):
        i = idx.get(nm)
        if i is not None:
            out[j] = state.price[i]
    return out


# ---------------------------------------------------------------------------
# sinkhorn
# ---------------------------------------------------------------------------


def _effective_cap(problem: GroupProblem) -> jnp.ndarray:
    """Scalarized per-node capacity for the Sinkhorn column marginal: the
    pod-count slot bound tightened by each resource's headroom divided by the
    supply-weighted mean request — so the fractional plan roughly respects the
    multi-resource budget the rounding/repair passes then enforce exactly."""
    supply = problem.supply.astype(jnp.float32)  # [G]
    total = jnp.maximum(jnp.sum(supply), 1.0)
    mean_req = jnp.sum(problem.req.astype(jnp.float32) * supply[:, None], axis=0) / total
    free = (problem.alloc - problem.used).astype(jnp.float32)  # [N, R]
    per_res = jnp.where(
        mean_req[None, :] > 0, free / jnp.maximum(mean_req[None, :], 1e-9), jnp.inf
    )
    cap = jnp.minimum(jnp.min(per_res, axis=1), problem.slots.astype(jnp.float32))
    return jnp.maximum(cap, 0.0)


@functools.partial(jax.jit, static_argnames=("iters",))
def _sinkhorn_iters(utility, feasible, supply, cap, f0, g0, eps, iters: int):
    """Log-domain scaling for  max ⟨C,x⟩ + eps·H(x)  s.t. rows ≤ supply,
    cols ≤ cap, x ≥ 0.  KKT: x = exp((C − f − g)/eps) with duals f,g ≥ 0 and
    complementary slackness, so each update is a clamped-at-zero exact solve:
        f = max(0, eps·(lse_n((C−g)/eps) − log supply))
        g = max(0, eps·(lse_g((C−f)/eps) − log cap))
    """
    logmask = jnp.where(feasible, 0.0, NEG_INF)
    logs = jnp.log(jnp.maximum(supply.astype(jnp.float32), 1e-9))
    logc = jnp.log(jnp.maximum(cap.astype(jnp.float32), 1e-9))
    z = (utility + logmask) / eps  # [G, N]

    def one(i, fg):
        f, g = fg
        row_lse = jax.scipy.special.logsumexp(z - g[None, :] / eps, axis=1)
        f = jnp.maximum(0.0, eps * (row_lse - logs))
        col_lse = jax.scipy.special.logsumexp(z - f[:, None] / eps, axis=0)
        g = jnp.maximum(0.0, eps * (col_lse - logc))
        return f, g

    f, g = jax.lax.fori_loop(0, iters, one, (f0, g0))
    plan = jnp.exp((utility + logmask - f[:, None] - g[None, :]) / eps)
    return f, g, plan


def sinkhorn_solve(
    problem: GroupProblem,
    state: Optional[TransportState] = None,
    node_names: Optional[List[str]] = None,
    eps: float = 2.0,
    iters: int = 60,
) -> Tuple[np.ndarray, TransportState]:
    """Entropic relaxation; returns (fractional plan [G,N], state). The node
    dual g (a price: ≥ 0, rises on contended nodes) is carried in
    TransportState.price — interchangeable with the auction's price vector."""
    gdim, n = problem.utility.shape
    g0 = np.zeros(n, np.float32)
    if state is not None and node_names is not None:
        remapped = np.maximum(_remap_price(state, node_names), 0.0)
        g0[:len(remapped)] = remapped  # node axis may be mesh-padded
    f0 = jnp.zeros(gdim, jnp.float32)
    f, g, plan = _sinkhorn_iters(
        problem.utility, problem.feasible, problem.supply, _effective_cap(problem),
        f0, jnp.asarray(g0), jnp.float32(eps), iters,
    )
    names = tuple(node_names) if node_names else tuple(str(i) for i in range(n))
    new_state = TransportState(
        price=np.asarray(g)[:len(names)],
        node_names=names,
        iterations=iters,
    )
    return np.asarray(plan), new_state


def round_plan(problem: GroupProblem, frac: np.ndarray) -> np.ndarray:
    """Fractional [G,N] → integer counts: floor, then largest-remainder fill
    per group under remaining column capacity and cell caps."""
    jcap = np.asarray(problem.jcap)
    frac = np.minimum(frac, jcap)
    x = np.floor(frac).astype(np.int32)
    # column headroom after floors
    col_room = np.asarray(problem.slots) - x.sum(axis=0)
    supply = np.asarray(problem.supply)
    rema = frac - x
    for gi in range(x.shape[0]):
        want = int(supply[gi] - x[gi].sum())
        if want <= 0:
            continue
        order = np.argsort(-rema[gi])
        for n_i in order:
            if want == 0:
                break
            if rema[gi, n_i] <= 0:
                break
            if col_room[n_i] > 0 and x[gi, n_i] < jcap[gi, n_i]:
                x[gi, n_i] += 1
                col_room[n_i] -= 1
                want -= 1
    return x


def repair_plan(problem: GroupProblem, x: np.ndarray) -> np.ndarray:
    """Enforce the exact multi-resource constraint Σ_g x_gn·req_g ≤ alloc−used
    and the pod-count slot bound, dropping units from lowest-utility cells
    first. Returns a feasible integer plan (reference semantics: a batch
    assignment must never violate Filter — fit.go:499)."""
    x = np.minimum(np.asarray(x, np.int64), np.asarray(problem.jcap))
    req = np.asarray(problem.req, np.int64)  # [G, R]
    free = np.asarray(problem.alloc, np.int64) - np.asarray(problem.used, np.int64)
    slots = np.asarray(problem.slots, np.int64)
    util = np.asarray(problem.utility)
    # clamp supply per group (defensive)
    supply = np.asarray(problem.supply, np.int64)
    for gi in range(x.shape[0]):
        over = int(x[gi].sum() - supply[gi])
        if over > 0:
            order = np.argsort(util[gi])  # drop worst first
            for n_i in order:
                if over <= 0:
                    break
                d = min(over, int(x[gi, n_i]))
                x[gi, n_i] -= d
                over -= d
    node_used = x.T @ req  # [N, R]
    node_cnt = x.sum(axis=0)
    bad = np.nonzero(
        (node_used > free).any(axis=1) | (node_cnt > slots)
    )[0]
    for n_i in bad:
        order = np.argsort(util[:, n_i])  # worst utility first
        for gi in order:
            while x[gi, n_i] > 0 and (
                (node_used[n_i] > free[n_i]).any() or node_cnt[n_i] > slots[n_i]
            ):
                x[gi, n_i] -= 1
                node_used[n_i] -= req[gi]
                node_cnt[n_i] -= 1
            if not (node_used[n_i] > free[n_i]).any() and node_cnt[n_i] <= slots[n_i]:
                break
    return x.astype(np.int32)


def assignment_from_plan(problem: GroupProblem, x: np.ndarray, n_pods: int) -> np.ndarray:
    """Integer plan → per-pod node index (queue order within each group);
    -1 for units the plan couldn't seat (batch driver retries them serially)."""
    out = np.full(n_pods, -1, np.int32)
    for gi, members in enumerate(problem.members):
        nodes = np.repeat(np.arange(x.shape[1]), x[gi])
        k = min(len(nodes), len(members))
        out[members[:k]] = nodes[:k].astype(np.int32)
    return out


def transport_solve(
    inp: SolverInputs,
    groups,
    method: str = "auction",
    state: Optional[TransportState] = None,
    node_names: Optional[List[str]] = None,
    mesh=None,
) -> Optional[Tuple[np.ndarray, TransportState]]:
    """End-to-end: build → solve → round → repair → per-pod assignment.
    Returns None when the batch isn't transport-eligible (host ports).

    With `mesh`, the [G, N] problem's node axis shards over the mesh's
    "nodes" axis (parallel/sharded.py shard_group_problem) and the solver
    runs under it — GSPMD inserts the node-axis collectives over ICI;
    padded nodes are infeasible and never receive units. Warm duals carry
    by node name either way."""
    import contextlib

    problem = build_group_problem(inp, groups)
    if problem is None:
        return None
    ctx = contextlib.nullcontext()
    if mesh is not None:
        from ..parallel.sharded import shard_group_problem

        true_n = problem.utility.shape[1]
        problem, _ = shard_group_problem(problem, mesh)
        if node_names is None:
            # duals must map to TRUE nodes, never mesh padding
            node_names = [str(i) for i in range(true_n)]
        from ..parallel import mesh_context

        ctx = mesh_context(mesh)
    with ctx:
        if method == "sinkhorn":
            frac, new_state = sinkhorn_solve(problem, state, node_names)
            x = round_plan(problem, frac)
        else:
            x, new_state = auction_solve(problem, state, node_names)
            x = np.asarray(x)
    x = repair_plan(problem, x)
    n_pods = inp.req.shape[0]
    return assignment_from_plan(problem, x, n_pods), new_state
