"""Propose-and-repair constraint solver — constrained batches on the fast path.

Before this module, every batch carrying a topology-spread or inter-pod
affinity term fell back to the one-pod-per-step scan kernel: 150-1100 pods/s
against the waterfill path's 23k (BENCH_r07/r08) — a ~100x scenario-coverage
gap, and exactly the shape the structured-solver literature attacks (Priority
Matters, arxiv 2511.08373; CvxCluster, arxiv 2605.01614): keep the constraints
as dense tensors and solve with a batched method instead of sequential steps.

Three phases, each reusing an existing layer:

  compile  — per-class hard masks and soft penalty rows are derived from the
             SAME count tensors the scan consumes (selcls_count / grp_count /
             the PTS tables of snapshot/ipa.py + tensorizer), evaluated
             against the LIVE counts as groups commit. A mask zeroes nodes
             whose topology domain already violates a required term
             (anti-affinity holder present, affinity target absent, spread
             skew at max); a penalty folds preferred terms and ScheduleAnyway
             spread into the waterfill static score. The class-axis dedup
             (the admission-primed pod_class_signature memo) makes this
             per-CLASS work, not per-pod.
  propose  — each identical-pod group runs the UNMODIFIED waterfill_group
             kernel with its mask ANDed into the filter row and the penalty
             added to the image row; a self-anti class (its own required
             anti term matches itself — ipa.class_rn_self) rides the
             host-port cap so at most one member lands per node. Counts are
             re-read between groups, so cross-class dynamics (group A's
             placements masking group B) are exact; only coarse-domain
             collisions within one call survive to repair.
  repair   — a jitted final-state violation check (repair_check, static
             `has_affinity`/`has_ct` gates + a pow2-bucketed pod axis — the
             JT001 discipline) marks violators; up to REPAIR_MAX_ROUNDS
             rip-and-repropose rounds re-route them through the masked
             waterfill; whatever still violates joins the residual, which
             the exact scan solver — still in tree as the semantics oracle —
             places against the committed counts.

Parity contract: the repair path never commits a hard-constraint violation
(the check runs on final state, which is STRICTER than the scan's
placement-time semantics for anti-affinity and spread), and it never
invents unschedulability — if the residual scan leaves any pod unplaced,
the whole batch re-solves with the full scan oracle, so unschedulable
verdicts are always the oracle's own (identical unschedulable sets by
construction). tests/test_repair.py pins both properties, property-based.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.solver import (
    SolverInputs,
    greedy_scan_solve,
    pts_counts,
    pts_domain_valid,
)
from .waterfill import bucket_j_max, make_groups, waterfill_group

# rip-and-repropose rounds before the residual goes to the scan oracle
REPAIR_MAX_ROUNDS = 4
# sort-key slot budget: base score 800 + soft penalty 200 + gang bonus 100
# must keep max_total_score * slots < 2^31 (waterfill.py sort-key encoding)
REPAIR_MAX_SLOTS = 1_900_000

# violation kinds (scheduler_constraint_violations_total{kind} label values)
KIND_ANTI = "anti_affinity"
KIND_EXISTING_ANTI = "existing_anti_affinity"
KIND_AFFINITY = "affinity"
KIND_SPREAD = "topology_spread"
_KINDS = (KIND_ANTI, KIND_EXISTING_ANTI, KIND_AFFINITY, KIND_SPREAD)


@dataclass
class RepairStats:
    """One batch's trip through the repair pipeline (flight record +
    sched_stats + the scheduler_constraint_* metrics)."""

    rounds: int = 0  # rip-and-repropose rounds executed
    proposed: int = 0  # pods placed by the masked waterfill propose
    repaired: int = 0  # pods re-placed by a repair round
    residual: int = 0  # pods handed to the scan oracle
    full_scan: bool = False  # residual scan left pods unplaced -> full oracle
    groups: int = 0  # identical-pod groups in the batch
    propose_calls: int = 0  # waterfill_group dispatches (merged runs)
    violations: Dict[str, int] = field(default_factory=dict)

    def as_dict(self) -> Dict:
        return {
            "rounds": self.rounds,
            "proposed": self.proposed,
            "repaired": self.repaired,
            "residual": self.residual,
            "full_scan": self.full_scan,
            "groups": self.groups,
            "propose_calls": self.propose_calls,
            "violations": {k: v for k, v in self.violations.items() if v},
        }


def _dom_view(counts: np.ndarray, topo_row: np.ndarray, d_max: int) -> np.ndarray:
    """Per-node view of each node's topology-domain total of `counts` [N]
    (nodes missing the key read 0) — the host mirror of the scan kernel's
    _dom_node_count."""
    valid = topo_row >= 0
    if not valid.any():
        return np.zeros(topo_row.shape[0], dtype=np.int64)
    dom = np.bincount(topo_row[valid], weights=counts[valid],
                      minlength=d_max).astype(np.int64)
    out = np.zeros(topo_row.shape[0], dtype=np.int64)
    out[valid] = dom[topo_row[valid]]
    return out


@functools.partial(jax.jit, static_argnames=("d_max", "has_affinity", "has_ct"))
def repair_check(node_of, cls_of, dyn_selcls, dyn_grp, topo_id,
                 rn_key, rn_sel, ea_grp, ra_key, ra_sel,
                 class_matches, class_holds, grp_key, aff_ok,
                 ct_class, ct_key, ct_sel, ct_max_skew, ct_min_domains,
                 d_max: int, has_affinity: bool = True, has_ct: bool = True):
    """Vectorized FINAL-STATE violation check over one placed batch.

    node_of [Pb] is the assignment padded to a pow2 bucket (-1 rows — the
    padding and unplaced pods — never violate); dyn_selcls / dyn_grp are the
    committed count tensors INCLUDING every placed pod, so each pod's own
    contribution (class_matches / class_holds of its class) is subtracted
    before the anti-affinity zero-tests. Final state is stricter than the
    scan's placement-time semantics (counts only grow within a batch), so a
    clean report proves the scan would have accepted this assignment in
    commit order; a violation only costs a repair round, never correctness.

    `has_affinity` / `has_ct` are STATIC gates like the scan kernel's: a
    spread-only batch compiles no IPA gathers and vice versa (JT001: bool
    gates + the caller's pow2 pod-axis bucket keep the jit cache stable
    across mixed constrained/unconstrained batch sequences —
    tests/test_retrace.py)."""
    pb = node_of.shape[0]
    placed = node_of >= 0
    nn = jnp.maximum(node_of, 0)
    cc = jnp.maximum(cls_of, 0)
    false_row = jnp.zeros(pb, dtype=bool)
    v_rn = v_ea = v_ra = v_ct = false_row

    if has_affinity:
        def dom_tot(counts):
            """[M, N] counts -> [Kk, M, N] per-node domain totals."""
            def per_k(trow):
                seg = jnp.where(trow >= 0, trow, d_max)

                def one(row):
                    dom = jax.ops.segment_sum(
                        jnp.where(trow >= 0, row, 0), seg,
                        num_segments=d_max + 1)
                    return jnp.where(trow >= 0,
                                     dom[jnp.clip(trow, 0, d_max - 1)], 0)

                return jax.vmap(one)(counts)

            return jax.vmap(per_k)(topo_id)

        sel_tot = dom_tot(dyn_selcls)
        grp_tot = dom_tot(dyn_grp)

        def per_pod(n_, c_):
            def rn_j(k, s):
                act = k >= 0
                k0 = jnp.maximum(k, 0)
                s0 = jnp.maximum(s, 0)
                other = sel_tot[k0, s0, n_] - class_matches[c_, s0]
                return act & (topo_id[k0, n_] >= 0) & (other > 0)

            def ea_j(g):
                act = g >= 0
                g0 = jnp.maximum(g, 0)
                k0 = grp_key[g0]
                other = grp_tot[k0, g0, n_] - class_holds[c_, g0]
                return act & (topo_id[k0, n_] >= 0) & (other > 0)

            def ra_j(k, s):
                # final-state affinity counts INCLUDE the pod itself: a
                # legal first-pod-exception seed satisfies its own term
                act = k >= 0
                k0 = jnp.maximum(k, 0)
                s0 = jnp.maximum(s, 0)
                return act & ((topo_id[k0, n_] < 0)
                              | (sel_tot[k0, s0, n_] <= 0))

            return (jnp.any(jax.vmap(rn_j)(rn_key[c_], rn_sel[c_])),
                    jnp.any(jax.vmap(ea_j)(ea_grp[c_])),
                    jnp.any(jax.vmap(ra_j)(ra_key[c_], ra_sel[c_])))

        p_rn, p_ea, p_ra = jax.vmap(per_pod)(nn, cc)
        v_rn = placed & p_rn
        v_ea = placed & p_ea
        v_ra = placed & p_ra

    if has_ct:
        def ct_row(tc, tk, ts, tskew, tmind):
            act = tc >= 0
            c0 = jnp.maximum(tc, 0)
            trow = topo_id[tk]
            arow = aff_ok[c0]
            dc = pts_counts(arow, dyn_selcls, trow, ts, d_max)
            valid = pts_domain_valid(arow, trow, d_max)
            n_valid = jnp.sum(valid.astype(jnp.int32))
            mmn = jnp.min(jnp.where(valid, dc, 2**30))
            mmn = jnp.where((tmind > 0) & (tmind > n_valid), 0, mmn)
            mmn = jnp.where(n_valid == 0, 0, mmn)
            node_dc = jnp.where(trow >= 0, dc[jnp.clip(trow, 0, d_max - 1)], 0)
            # the pod itself is in dc already — no + self term here
            bad = (trow < 0) | (node_dc - mmn > tskew)
            return jnp.where(act, bad, False), c0

        bad_rows, row_cls = jax.vmap(ct_row)(
            ct_class, ct_key, ct_sel, ct_max_skew, ct_min_domains)

        def pod_ct(n_, c_):
            return jnp.any((ct_class >= 0) & (row_cls == c_)
                           & bad_rows[:, n_])

        v_ct = placed & jax.vmap(pod_ct)(nn, cc)

    return v_rn, v_ea, v_ra, v_ct


class _RepairContext:
    """Host-side dynamic state + per-class compile products for one batch:
    the live count tensors (selcls / holder groups), the class tables the
    masks read, and the device-resident node state the propose kernel
    updates. All count math is numpy (the arrays came from the tensorizer
    before upload); only the per-group kernel calls and the violation check
    touch the device."""

    def __init__(self, inp: SolverInputs, batch, d_max: int, has_gang: bool):
        self.inp = inp
        self.d_max = d_max
        self.has_gang = has_gang
        self.n = int(inp.alloc.shape[0])
        ipa = batch.ipa
        # live counts, PADDED to the device shapes (make_inputs pads empty
        # selcls/grp tables to one row; the -1-clipped gathers then read
        # the zero row — mirror that exactly so indices line up)
        self.selcls = np.asarray(inp.selcls_count).astype(np.int64).copy()
        self.grp = np.asarray(inp.grp_count).astype(np.int64).copy()
        self.topo = np.asarray(inp.topo_id)
        # class tables (host numpy, pre-upload — no device readbacks)
        t = batch.tables
        self.filter_np = t.filter_ok
        self.aff_np = t.aff_ok
        self.class_ports_np = t.class_ports
        self.cm = batch.class_matches_selcls  # [C, max(SC,1)] int32
        self.chg = ipa.class_holds_grp  # [C, max(G,1)] int32
        self.rn_key, self.rn_sel = ipa.rn_key, ipa.rn_sel
        self.ra_key, self.ra_sel = ipa.ra_key, ipa.ra_sel
        self.pp_key, self.pp_sel, self.pp_w = (ipa.pp_key, ipa.pp_sel,
                                               ipa.pp_weight)
        self.ea = ipa.ea_grp
        self.sym, self.sym_w = ipa.sym_grp, ipa.sym_weight
        self.grp_key = (ipa.grp_key if ipa.grp_key.size
                        else np.zeros(1, np.int32))
        self.rn_self = ipa.class_rn_self
        self.ct_class, self.ct_key, self.ct_sel = (batch.ct_class,
                                                   batch.ct_key, batch.ct_sel)
        self.ct_skew, self.ct_mind, self.ct_self = (
            batch.ct_max_skew, batch.ct_min_domains, batch.ct_self_match)
        self.st_class, self.st_key, self.st_sel = (batch.st_class,
                                                   batch.st_key, batch.st_sel)
        self.req_np = batch.req
        self.req_nz_np = batch.req_nz
        self.cls_np = np.asarray(batch.class_of_pod)
        self.bal_np = np.asarray(batch.balanced_active)
        self.tables_napref = t.napref_raw
        self.tables_taint = t.taint_cnt
        self.tables_img = t.img_score
        self.gang_bonus_np = (np.asarray(batch.gang_bonus)
                              if has_gang and batch.gang_bonus is not None
                              else None)
        # device-resident node state the propose kernel consumes/updates
        self.used = inp.used
        self.used_nz = inp.used_nz
        self.pod_count = inp.pod_count
        self.port_taken = inp.node_ports
        self.any_ports = bool(self.class_ports_np.any())
        # start-of-batch free capacity (host): upper-bounds how many copies
        # of any request can ever stack on one node THIS batch (commits only
        # shrink it), so per-run j_max buckets stay safe over-estimates
        self.free0 = np.maximum(
            np.asarray(inp.alloc).astype(np.int64)
            - np.asarray(inp.used).astype(np.int64), 0)

    # -- constraint compile: per-class masks + penalties against live counts

    def class_mask(self, c: int) -> np.ndarray:
        """Nodes where a pod of class c can be placed RIGHT NOW without
        violating any hard term — the placement-time feasibility row the
        scan computes per pod, evaluated once per class per propose pass."""
        ok = np.ones(self.n, dtype=bool)
        for j in range(self.rn_key.shape[1]):
            k = int(self.rn_key[c, j])
            if k < 0:
                continue
            trow = self.topo[k]
            cnt = _dom_view(self.selcls[self.rn_sel[c, j]], trow, self.d_max)
            ok &= (trow < 0) | (cnt == 0)
        for j in range(self.ea.shape[1]):
            g = int(self.ea[c, j])
            if g < 0:
                continue
            trow = self.topo[self.grp_key[g]]
            cnt = _dom_view(self.grp[g], trow, self.d_max)
            ok &= (trow < 0) | (cnt == 0)
        for j in range(self.ra_key.shape[1]):
            k = int(self.ra_key[c, j])
            if k < 0:
                continue
            trow = self.topo[k]
            cnt = _dom_view(self.selcls[self.ra_sel[c, j]], trow, self.d_max)
            # first-pod-exception classes see an all-False mask here and
            # land in the residual, where the scan owns the exception
            ok &= (trow >= 0) & (cnt > 0)
        for t in np.nonzero(self.ct_class == c)[0]:
            trow = self.topo[self.ct_key[t]]
            elig = self.aff_np[c] & (trow >= 0)
            if not elig.any():
                ok &= False
                continue
            dc = np.bincount(trow[elig],
                             weights=self.selcls[self.ct_sel[t]][elig],
                             minlength=self.d_max).astype(np.int64)
            n_valid = np.unique(trow[elig]).size
            mmn = dc[np.unique(trow[elig])].min() if n_valid else 0
            if self.ct_mind[t] > 0 and self.ct_mind[t] > n_valid:
                mmn = 0
            node_dc = np.zeros(self.n, dtype=np.int64)
            node_dc[trow >= 0] = dc[trow[trow >= 0]]
            ok &= (trow >= 0) & (node_dc + int(self.ct_self[t]) - mmn
                                 <= int(self.ct_skew[t]))
        return ok

    def soft_row(self, c: int, feas: np.ndarray) -> Optional[np.ndarray]:
        """Preferred terms + symmetric weights + ScheduleAnyway spread as ONE
        normalized 0..200 preference row (the scan's 2x weight on its 0..100
        normalized IPA/PTS scores), added to the waterfill image row.
        Approximate by design — soft scores steer, hard masks decide."""
        raw = np.zeros(self.n, dtype=np.int64)
        any_soft = False
        for j in range(self.pp_key.shape[1]):
            k = int(self.pp_key[c, j])
            if k < 0:
                continue
            any_soft = True
            raw += int(self.pp_w[c, j]) * _dom_view(
                self.selcls[self.pp_sel[c, j]], self.topo[k], self.d_max)
        for j in range(self.sym.shape[1]):
            g = int(self.sym[c, j])
            if g < 0:
                continue
            any_soft = True
            raw += int(self.sym_w[c, j]) * _dom_view(
                self.grp[g], self.topo[self.grp_key[g]], self.d_max)
        for t in np.nonzero(self.st_class == c)[0]:
            any_soft = True
            raw -= _dom_view(self.selcls[self.st_sel[t]],
                             self.topo[self.st_key[t]], self.d_max)
        if not any_soft or not feas.any():
            return None
        lo = int(raw[feas].min())
        hi = int(raw[feas].max())
        if hi <= lo:
            return None
        return ((raw - lo) * 200 // (hi - lo)).clip(0, 200).astype(np.int32)

    # -- dynamic count bookkeeping --------------------------------------------

    def bump(self, c: int, placed_per_node: np.ndarray, sign: int = 1) -> None:
        """Fold `placed_per_node` pods of class c into the live counts —
        the host mirror of the scan step's dyn_selcls/dyn_grp commit."""
        for s in np.nonzero(self.cm[c])[0]:
            self.selcls[s] += sign * int(self.cm[c, s]) * placed_per_node
        for g in np.nonzero(self.chg[c])[0]:
            self.grp[g] += sign * int(self.chg[c, g]) * placed_per_node

    def commit_resources(self, placed_j, req_row: int) -> None:
        placed_col = placed_j[:, None]
        self.used = self.used + placed_col * self.inp.req[req_row][None, :]
        self.used_nz = (self.used_nz
                        + placed_col * self.inp.req_nz[req_row][None, :])
        self.pod_count = self.pod_count + placed_j

    def _apply_resources(self, rows: np.ndarray, nodes: np.ndarray,
                         sign: int) -> None:
        """Vectorized resource/pod-count delta for `rows` at `nodes` — one
        device op per tensor, never per pod."""
        d_used = np.zeros((self.n, self.req_np.shape[1]), dtype=np.int64)
        d_used_nz = np.zeros_like(d_used)
        np.add.at(d_used, nodes, self.req_np[rows].astype(np.int64))
        np.add.at(d_used_nz, nodes, self.req_nz_np[rows].astype(np.int64))
        d_count = np.bincount(nodes, minlength=self.n)
        s = np.int32(sign)
        self.used = self.used + s * jnp.asarray(d_used.astype(np.int32))
        self.used_nz = (self.used_nz
                        + s * jnp.asarray(d_used_nz.astype(np.int32)))
        self.pod_count = self.pod_count + s * jnp.asarray(
            d_count.astype(np.int32))

    def rip(self, rows: np.ndarray, assignment: np.ndarray) -> None:
        """Remove placed pods (batch rows) from every piece of dynamic state:
        resources, pod counts, and the live count tensors."""
        nodes = assignment[rows]
        self._apply_resources(rows, nodes, -1)
        for c in np.unique(self.cls_np[rows]):
            per_node = np.bincount(nodes[self.cls_np[rows] == c],
                                   minlength=self.n).astype(np.int64)
            self.bump(int(c), per_node, sign=-1)
        assignment[rows] = -1

    def recommit(self, rows: np.ndarray, nodes: np.ndarray) -> None:
        """Restore reprieved pods' resource state in one vectorized pass
        (their count-tensor bumps already happened per keep decision)."""
        self._apply_resources(rows, nodes, 1)

    def rebuild_ports(self, assignment: np.ndarray) -> None:
        """Port rows can't be decremented (two placed pods of one class on a
        node share the row) — rebuild from surviving placements instead.
        Only called when the batch has port-claiming classes at all."""
        taken = np.asarray(self.inp.node_ports).copy()
        placed = np.nonzero(assignment >= 0)[0]
        for c in np.unique(self.cls_np[placed]):
            crow = self.class_ports_np[c]
            if not crow.any():
                continue
            nodes = np.unique(assignment[placed[self.cls_np[placed] == c]])
            taken[nodes] |= crow[None, :]
        self.port_taken = jnp.asarray(taken)


def _class_fingerprint(ctx: _RepairContext, c: int, req_bytes: bytes,
                       bal: bool) -> tuple:
    """Classes with byte-identical constraint rows, score rows, and request
    vectors propose identically and may share ONE kernel call (the
    AntiAffinityNSSelector shape: one anti-affine group split over N
    namespaces compiles to N classes that differ only in namespace — 500
    classes, 50 propose dispatches)."""
    score_rows = [ctx.tables_napref[c].tobytes(), ctx.tables_taint[c].tobytes(),
                  ctx.tables_img[c].tobytes(), ctx.class_ports_np[c].tobytes()]
    if ctx.gang_bonus_np is not None:
        score_rows.append(ctx.gang_bonus_np[c].tobytes())
    return (
        ctx.rn_key[c].tobytes(), ctx.rn_sel[c].tobytes(),
        ctx.ra_key[c].tobytes(), ctx.ra_sel[c].tobytes(),
        ctx.ea[c].tobytes(), ctx.pp_key[c].tobytes(),
        ctx.pp_sel[c].tobytes(), ctx.pp_w[c].tobytes(),
        ctx.sym[c].tobytes(), ctx.sym_w[c].tobytes(),
        ctx.cm[c].tobytes(), ctx.chg[c].tobytes(),
        tuple((int(ctx.ct_key[t]), int(ctx.ct_sel[t]), int(ctx.ct_skew[t]),
               int(ctx.ct_mind[t]), int(ctx.ct_self[t]))
              for t in np.nonzero(ctx.ct_class == c)[0]),
        tuple((int(ctx.st_key[t]), int(ctx.st_sel[t]))
              for t in np.nonzero(ctx.st_class == c)[0]),
        ctx.filter_np[c].tobytes(), ctx.aff_np[c].tobytes(),
        tuple(score_rows),
        req_bytes, bal, bool(ctx.rn_self[c]),
    )


def repair_solve(inp: SolverInputs, batch, d_max: int, *,
                 has_gang: bool = False,
                 max_rounds: int = REPAIR_MAX_ROUNDS
                 ) -> Optional[Tuple[np.ndarray, RepairStats]]:
    """Solve a constrained batch: masked-waterfill propose, bounded repair,
    scan residual. Returns (assignment [P] int32, RepairStats), or None when
    the problem shape exceeds the fast path's sort-key range (the caller
    falls back to the scan, exactly like waterfill_solve declining)."""
    p = int(inp.req.shape[0])
    if p == 0:
        return np.zeros(0, dtype=np.int32), RepairStats()
    groups = make_groups(batch)
    n = inp.alloc.shape[0]  # per-CLUSTER static (the waterfill_solve idiom)
    max_group = max(len(m) for m, _ in groups)
    j_max = bucket_j_max(inp.max_pods, inp.pod_count, n, REPAIR_MAX_SLOTS,
                         cap_hint=max_group)
    if j_max is None:
        return None

    ctx = _RepairContext(inp, batch, d_max, has_gang)
    stats = RepairStats(groups=len(groups),
                        violations={k: 0 for k in _KINDS})
    assignment = np.full(p, -1, dtype=np.int32)
    residual: List[int] = []

    def propose(members: np.ndarray, cls: int) -> None:
        """One masked waterfill_group dispatch for `members` (all of class
        cls, or of byte-identical classes — the fingerprint merge)."""
        mask = ctx.class_mask(cls)
        if not mask.any():
            residual.extend(int(i) for i in members)
            return
        soft = ctx.soft_row(cls, mask & ctx.filter_np[cls])
        has_port = bool(ctx.class_ports_np[cls].any())
        cap_one = has_port or bool(ctx.rn_self[cls])
        port_conflict = jnp.any(
            ctx.port_taken & inp.class_ports[cls][None, :], axis=1)
        frow = inp.filter_ok[cls] & jnp.asarray(mask)
        img = inp.img_score[cls]
        if soft is not None:
            img = img + jnp.asarray(soft)
        pi0 = int(members[0])
        # per-run slot depth: kernel cost is linear in j_max (the [N, J]
        # marginal-score matrix), so cap-one groups compile the J=1 variant
        # and everything else buckets to pow2(min(batch j_max, group size,
        # start-of-batch stack bound)). All pow2 (JT001), and a bounded
        # variant set: log2(j_max) compiled shapes at most.
        if cap_one:
            run_j = 1
        else:
            req_row = ctx.req_np[pi0].astype(np.int64)
            nz = req_row > 0
            # the stack bound only needs to cover nodes the kernel can
            # actually CHOOSE — frow is filter_ok & mask, so restricting the
            # max to eligible nodes is strictly tighter and still a safe
            # over-estimate (free0 never grows within the batch). This is
            # the PodAffinity propose lever (ISSUE 11 satellite): an
            # affinity group's eligible zone nodes hold the seed pods and
            # have far less headroom than the emptiest cluster node, and
            # kernel cost is linear in run_j.
            elig = mask & ctx.filter_np[cls]
            if nz.any():
                free_elig = ctx.free0[elig][:, nz]
                stack = (int((free_elig // req_row[nz]).min(axis=1)
                             .max(initial=0)) if free_elig.size else 0)
            else:
                stack = j_max
            run_j = 1 << (max(1, min(j_max, len(members), stack))
                          - 1).bit_length()
        k_slots = min(1 << (len(members) - 1).bit_length(), n * run_j)
        k_slots = max(k_slots, min(256, n * run_j))
        k_per_node, chosen_nodes = waterfill_group(
            inp.alloc, ctx.used, ctx.used_nz, ctx.pod_count, inp.max_pods,
            frow, port_conflict, cap_one,
            inp.napref_raw[cls], inp.has_napref[cls], inp.taint_cnt[cls],
            img,
            inp.req[pi0], inp.req_nz[pi0], inp.balanced_active[pi0],
            jnp.int32(len(members)),
            j_max=run_j, k_slots=k_slots,
            gang_row=(inp.gang_bonus[cls] if ctx.gang_bonus_np is not None
                      else None),
            has_gang=ctx.gang_bonus_np is not None,
        )
        stats.propose_calls += 1
        chosen = np.full(len(members), -1, dtype=np.int32)
        got = np.asarray(chosen_nodes)[:len(members)]
        chosen[:len(got)] = got
        assignment[np.asarray(members)] = chosen
        unplaced = np.asarray(members)[chosen < 0]
        residual.extend(int(i) for i in unplaced)
        placed_j = jnp.asarray(k_per_node)
        ctx.commit_resources(placed_j, pi0)
        placed_np = np.asarray(k_per_node).astype(np.int64)
        # members may span merged classes with identical cm/chg rows; any
        # one of them attributes the count bump correctly
        ctx.bump(cls, placed_np)
        if has_port:
            ctx.port_taken = ctx.port_taken | (
                (placed_j > 0)[:, None] & inp.class_ports[cls][None, :])

    # ---- propose: merged runs of byte-identical consecutive classes --------
    runs: List[Tuple[np.ndarray, int]] = []
    last_fp = None
    for members, cls in groups:
        pi0 = int(members[0])
        fp = _class_fingerprint(ctx, cls, ctx.req_np[pi0].tobytes(),
                                bool(np.asarray(batch.balanced_active)[pi0]))
        if runs and fp == last_fp:
            prev_m, prev_c = runs[-1]
            runs[-1] = (np.concatenate([prev_m, members]), prev_c)
        else:
            runs.append((np.asarray(members), cls))
            last_fp = fp
    for members, cls in runs:
        propose(members, cls)
    stats.proposed = int((assignment >= 0).sum())

    # ---- repair: check -> rip -> repropose, bounded ------------------------
    has_affinity = bool(batch.ipa.has_any)
    has_ct = bool(batch.ct_class.size)
    rounds = 0
    while has_affinity or has_ct:
        viol_rows = _check(ctx, inp, assignment, p, d_max,
                           has_affinity, has_ct, stats)
        if viol_rows.size == 0:
            break
        # reprieve pass (the preemption reprieve idiom): the final-state
        # check marks EVERY party to a collision, but usually one of them
        # may stay. Rip them all, then re-admit each violator in batch
        # (priority) order when its node is still feasible against the
        # survivors + already-reprieved — only the true excess re-routes.
        old_nodes = assignment[viol_rows].copy()
        ctx.rip(viol_rows, assignment)
        kept_rows: List[int] = []
        kept_nodes: List[int] = []
        # per-class mask cache: a candidate that is NOT kept performs no
        # bump, so the mask is bit-identical for the next same-class
        # candidate — only a keep's count bump invalidates (thousands of
        # violators over a handful of classes pay O(keeps) mask builds,
        # not O(violators))
        mask_cache: Dict[int, np.ndarray] = {}
        for pos, i in enumerate(viol_rows.tolist()):
            c = int(ctx.cls_np[i])
            node = int(old_nodes[pos])
            mask = mask_cache.get(c)
            if mask is None:
                mask = mask_cache[c] = ctx.class_mask(c)
            if mask[node]:
                assignment[i] = node
                one = np.zeros(ctx.n, dtype=np.int64)
                one[node] = 1
                ctx.bump(c, one)
                mask_cache.clear()  # counts moved: every mask is stale
                kept_rows.append(i)
                kept_nodes.append(node)
        if kept_rows:
            ctx.recommit(np.asarray(kept_rows),
                         np.asarray(kept_nodes, dtype=np.int64))
        if ctx.any_ports:
            ctx.rebuild_ports(assignment)
        still = viol_rows[assignment[viol_rows] < 0]
        if still.size == 0:
            # every violator was reprieved: the pass just certified a legal
            # placement order for a final-state-strict flag (the PTS
            # final-vs-placement-time gap) — nothing actually moves
            break
        if rounds >= max_rounds:
            residual.extend(int(i) for i in still)
            break
        rounds += 1
        # re-propose by the FULL make_groups key, never class alone: one
        # class can span different request vectors (pod_class_signature
        # excludes resources), and propose() sizes capacity and commits
        # resources with members[0]'s request — a class-only regroup would
        # overcommit nodes for the mixed-request members
        regroups: Dict[tuple, List[int]] = {}
        order: List[tuple] = []
        for i in still.tolist():
            k = (int(ctx.cls_np[i]), ctx.req_np[i].tobytes(),
                 ctx.req_nz_np[i].tobytes(), bool(ctx.bal_np[i]))
            if k not in regroups:
                regroups[k] = []
                order.append(k)
            regroups[k].append(i)
        for k in order:
            propose(np.asarray(regroups[k], dtype=np.int64), k[0])
        stats.repaired += int((assignment[still] >= 0).sum())
    stats.rounds = rounds

    # ---- residual: the scan oracle against the committed counts ------------
    residual = sorted(set(i for i in residual if assignment[i] < 0))
    if residual:
        stats.residual = len(residual)
        res = np.asarray(residual, dtype=np.int64)
        res_inp = inp._replace(
            used=ctx.used, used_nz=ctx.used_nz, pod_count=ctx.pod_count,
            selcls_count=jnp.asarray(
                ctx.selcls.astype(np.int32)),
            grp_count=jnp.asarray(ctx.grp.astype(np.int32)),
            node_ports=ctx.port_taken,
            req=inp.req[res], req_nz=inp.req_nz[res],
            class_of_pod=inp.class_of_pod[res],
            balanced_active=inp.balanced_active[res])
        res_assign, _, _ = greedy_scan_solve(
            res_inp, d_max, has_ipa=has_affinity, has_ct=has_ct,
            has_st=bool(batch.st_class.size),
            has_gang=ctx.gang_bonus_np is not None)
        ra = np.asarray(res_assign)
        assignment[res] = ra
        if (ra < 0).any():
            # parity with the oracle: repair never invents unschedulability.
            # If the residual can't fully place against the committed counts,
            # the WHOLE batch re-solves on the untouched oracle path — the
            # unschedulable set is then the scan's own verdict, bit for bit.
            stats.full_scan = True
            full, _, _ = greedy_scan_solve(
                inp, d_max, has_ipa=has_affinity, has_ct=has_ct,
                has_st=bool(batch.st_class.size),
                has_gang=ctx.gang_bonus_np is not None)
            return np.asarray(full).astype(np.int32), stats
    return assignment, stats


def _check(ctx: _RepairContext, inp: SolverInputs,
           assignment: np.ndarray, p: int, d_max: int,
           has_affinity: bool, has_ct: bool, stats: RepairStats) -> np.ndarray:
    """Run the jitted final-state check; returns violating batch rows."""
    pb = max(256, 1 << (p - 1).bit_length())
    node_pad = np.full(pb, -1, dtype=np.int32)
    node_pad[:p] = assignment
    cls_pad = np.zeros(pb, dtype=np.int32)
    cls_pad[:p] = ctx.cls_np
    v_rn, v_ea, v_ra, v_ct = repair_check(
        jnp.asarray(node_pad), jnp.asarray(cls_pad),
        jnp.asarray(ctx.selcls.astype(np.int32)),
        jnp.asarray(ctx.grp.astype(np.int32)),
        inp.topo_id,
        inp.rn_key, inp.rn_sel, inp.ea_grp, inp.ra_key, inp.ra_sel,
        inp.class_matches_selcls, inp.class_holds_grp,
        jnp.asarray(ctx.grp_key), inp.aff_ok,
        inp.ct_class, inp.ct_key, inp.ct_sel, inp.ct_max_skew,
        inp.ct_min_domains,
        d_max=d_max, has_affinity=has_affinity, has_ct=has_ct)
    v_rn = np.asarray(v_rn)[:p]
    v_ea = np.asarray(v_ea)[:p]
    v_ra = np.asarray(v_ra)[:p]
    v_ct = np.asarray(v_ct)[:p]
    stats.violations[KIND_ANTI] += int(v_rn.sum())
    stats.violations[KIND_EXISTING_ANTI] += int(v_ea.sum())
    stats.violations[KIND_AFFINITY] += int(v_ra.sum())
    stats.violations[KIND_SPREAD] += int(v_ct.sum())
    return np.nonzero(v_rn | v_ea | v_ra | v_ct)[0]
