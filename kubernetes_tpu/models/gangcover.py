"""Gang victim-cover + rank-adjacency kernels (ISSUE 14, ROADMAP direction 4).

Two batched-tensor problems the gang preemption subsystem
(scheduler/gangpreempt.py) and the rank-aware placement pass
(scheduler/batch.py) hand to this module:

  victim cover — for ONE ICI slice, the capacity curve of eviction: caps[k] =
      how many gang pods the slice can host after evicting the first k
      victims of a caller-ordered victim list. The preemptor picks the
      smallest k with caps[k] >= quorum (the min-cost cover) or vetoes when
      no k reaches it on any slice — the all-or-nothing discipline of the
      gang placement veto, applied to eviction (a partial eviction that
      strands a half-placed gang is the failure mode this module exists to
      make impossible). The curve is ONE fused pass over a [K+1, Ns, R]
      prefix-freed tensor (cover_curve, jitted) instead of K sequential
      evict-and-recount steps; cover_curve_host is the numpy oracle
      (bit-parity pinned by tests/test_gangpreempt.py) and the fallback when
      the padded tensor would not be worth uploading.

  rank alignment — the solver places a gang's identical members as an
      interchangeable group (waterfill water-fills, so greedy order
      interleaves across nodes); which MEMBER lands on which node is a free
      permutation. rank_align matches rank order to ring-position order per
      (gang, class, request) group — the monotone matching that minimizes the
      sum of consecutive-rank position gaps (sorted-to-sorted is optimal for
      line distance: any permutation of distinct positions pays at least
      max-min over consecutive hops) — so rank r and rank r+1 sit on
      ICI-adjacent nodes (the Tesserae / rank-aware-MPI placement policy:
      per-step collectives traverse neighbor links, not the whole slice).
      Jitted with a pow2-bucketed pod axis (repair_check's JT001 discipline);
      gang-free batches never call it, so they stay byte-identical.

Both kernels take only batch-stable statics (pow2 buckets) and do no host
sync inside traced bodies (JT001/JT002, schedlint-enforced). Everything is
int32 on device (this project runs jax in 32-bit mode): quantized resource
magnitudes (millicores / MiB) keep a 1024-victim prefix sum far below 2^31,
and the sentinels below are chosen to stay inside the range.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# victims considered per slice (ordered best-first by victim_order, so the
# cap drops only the WORST candidates); the preemptor publishes a
# victims_capped stat when it fires — never a silent truncation
COVER_MAX_VICTIMS = 1024
# above this padded-tensor size the [K+1, Ns, R] prefix tensor is not worth
# building on device; the numpy oracle computes the same curve
_COVER_KERNEL_MAX_ELEMS = 4_000_000

_INT32_BIG = 2**30  # "infinite" capacity / unplaced-position sentinel


# -- victim ordering ----------------------------------------------------------


def victim_order(prio: np.ndarray, freed_norm: np.ndarray) -> np.ndarray:
    """Eviction order for a candidate victim list: lowest priority first
    (cheapest disruption), then the victim freeing the MOST capacity
    (fewest victims reach the cover), then index for determinism. Shared
    ordering for the gang cover and any batched victim path that wants the
    same preference."""
    idx = np.arange(len(prio))
    return np.lexsort((idx, -np.asarray(freed_norm, dtype=np.int64),
                       np.asarray(prio, dtype=np.int64)))


# -- victim cover curve -------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_slots", "k_max"))
def cover_curve(free, headroom, eligible, v_node, v_req, req,
                n_slots: int, k_max: int):
    """caps[k] for k in 0..k_max: gang pods the slice fits after evicting the
    first k victims. All arrays padded by the caller: free [n_slots, R]
    int32, headroom [n_slots] int32 (remaining pod-count slots), eligible
    [n_slots] bool, v_node [k_max] slice-local node index (-1 pads), v_req
    [k_max, R] int32, req [R] int32 (the gang's per-member request). Statics
    are pow2 buckets only."""
    valid = v_node >= 0
    onehot = (v_node[:, None] == jnp.arange(n_slots)[None, :]) & valid[:, None]
    freed1 = jnp.cumsum(onehot[:, :, None] * v_req[:, None, :], axis=0)
    freed = jnp.concatenate(
        [jnp.zeros((1, n_slots, v_req.shape[1]), freed1.dtype), freed1],
        axis=0)  # [K+1, Ns, R]
    rel1 = jnp.cumsum(onehot.astype(jnp.int32), axis=0)
    released = jnp.concatenate(
        [jnp.zeros((1, n_slots), rel1.dtype), rel1], axis=0)  # [K+1, Ns]
    avail = free[None, :, :] + freed
    nz = req > 0
    per = jnp.where(nz[None, None, :],
                    avail // jnp.maximum(req, 1)[None, None, :],
                    jnp.int32(_INT32_BIG))
    cap = jnp.min(per, axis=2)
    cap = jnp.minimum(cap, headroom[None, :] + released)
    cap = jnp.where(eligible[None, :], jnp.maximum(cap, 0), 0)
    return jnp.sum(cap, axis=1)  # [K+1]


def cover_curve_host(free: np.ndarray, headroom: np.ndarray,
                     eligible: np.ndarray, v_node: np.ndarray,
                     v_req: np.ndarray, req: np.ndarray) -> np.ndarray:
    """Numpy oracle of cover_curve (unpadded): the parity target and the
    fallback for slices whose padded prefix tensor exceeds the device
    budget. One incremental pass — O(R) work per victim, not a recount."""
    free = np.asarray(free, dtype=np.int64).copy()
    headroom = np.asarray(headroom, dtype=np.int64).copy()
    eligible = np.asarray(eligible, dtype=bool)
    req = np.asarray(req, dtype=np.int64)
    nz = req > 0

    def node_cap(n: int) -> int:
        if not eligible[n]:
            return 0
        c = int(headroom[n])
        if nz.any():
            c = min(c, int((free[n, nz] // req[nz]).min()))
        return max(c, 0)

    caps = np.empty(len(v_node) + 1, dtype=np.int64)
    cap_by_node = np.array([node_cap(n) for n in range(free.shape[0])],
                           dtype=np.int64)
    total = int(cap_by_node.sum())
    caps[0] = total
    for k, n in enumerate(np.asarray(v_node, dtype=np.int64).tolist()):
        free[n] += np.asarray(v_req[k], dtype=np.int64)
        headroom[n] += 1
        new = node_cap(n)
        total += new - int(cap_by_node[n])
        cap_by_node[n] = new
        caps[k + 1] = total
    return caps


def cover_curves(free: np.ndarray, headroom: np.ndarray, eligible: np.ndarray,
                 v_node: np.ndarray, v_req: np.ndarray,
                 req: np.ndarray) -> np.ndarray:
    """Dispatch wrapper: pads to pow2 buckets and runs the jitted curve, or
    the numpy oracle when the padded tensor would blow the device budget.
    Returns caps[len(v_node) + 1] as numpy int64."""
    k = len(v_node)
    ns, r = free.shape
    # pow2 buckets key the jit (JT001 discipline, models/waterfill.py idiom)
    n_slots = 1 << max(0, ns - 1).bit_length()
    k_max = 1 << max(0, k - 1).bit_length()
    if (k_max + 1) * n_slots * r > _COVER_KERNEL_MAX_ELEMS or k == 0:
        return cover_curve_host(free, headroom, eligible, v_node, v_req, req)
    free_p = np.zeros((n_slots, r), dtype=np.int32)
    free_p[:ns] = free
    head_p = np.zeros(n_slots, dtype=np.int32)
    head_p[:ns] = headroom
    elig_p = np.zeros(n_slots, dtype=bool)
    elig_p[:ns] = eligible
    vn_p = np.full(k_max, -1, dtype=np.int32)
    vn_p[:k] = v_node
    vr_p = np.zeros((k_max, r), dtype=np.int32)
    vr_p[:k] = v_req
    caps = np.asarray(cover_curve(
        free_p, head_p, elig_p, vn_p, vr_p,
        np.asarray(req, dtype=np.int32), n_slots=n_slots, k_max=k_max))
    return caps[: k + 1].astype(np.int64)


# -- rank alignment -----------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("p_max",))
def rank_align_kernel(assignment, group_id, rank, pos_key, p_max: int):
    """Permute node assignments WITHIN each alignment group so rank order
    matches ring-position order: the i-th smallest rank gets the node of the
    i-th smallest position key (unplaced members carry the _INT32_BIG
    position sentinel, so the highest ranks stay unplaced). Non-members and
    padding carry unique group ids, making their permutation the identity.
    p_max is the pow2 pod-axis bucket (the caller pads); the two lexsorts
    enumerate each group contiguously in the same group order, so row i of
    both orders is the same group by construction."""
    idx = jnp.arange(p_max)
    order_rank = jnp.lexsort((idx, rank, group_id))
    order_pos = jnp.lexsort((idx, pos_key, group_id))
    return jnp.zeros_like(assignment).at[order_rank].set(
        assignment[order_pos])


def rank_align_host(assignment: np.ndarray, group_id: np.ndarray,
                    rank: np.ndarray, pos_key: np.ndarray) -> np.ndarray:
    """Numpy oracle of rank_align_kernel (parity pinned by tests)."""
    idx = np.arange(len(assignment))
    order_rank = np.lexsort((idx, rank, group_id))
    order_pos = np.lexsort((idx, pos_key, group_id))
    out = np.zeros_like(assignment)
    out[order_rank] = assignment[order_pos]
    return out


def rank_align(assignment: np.ndarray, group_id: np.ndarray,
               rank: np.ndarray, pos_key: np.ndarray) -> np.ndarray:
    """Pad to the pow2 pod bucket and run the jitted alignment. Padding rows
    get group ids beyond every real group (identity permutation). Inputs
    must already be int32-range (alignment_groups and the caller's position
    keys guarantee it)."""
    p = len(assignment)
    # pow2 pod-axis bucket (JT001 discipline, repair_check's pod axis)
    p_max = 1 << max(0, p - 1).bit_length()
    a = np.full(p_max, -1, dtype=np.int32)
    a[:p] = assignment
    # padding group ids: one singleton per pad row, above every real id
    g = np.arange(p_max, dtype=np.int32) + np.int32(_INT32_BIG)
    g[:p] = group_id
    r = np.zeros(p_max, dtype=np.int32)
    r[:p] = rank
    k = np.zeros(p_max, dtype=np.int32)
    k[:p] = pos_key
    out = np.asarray(rank_align_kernel(a, g, r, k, p_max=p_max))
    return out[:p].astype(assignment.dtype)


def alignment_groups(gang_of_pod: np.ndarray, class_of_pod: np.ndarray,
                     req: np.ndarray, req_nz: np.ndarray) -> np.ndarray:
    """Group ids for rank alignment: members are interchangeable ONLY within
    (gang, class, request vector) — the same key make_groups solves by — so
    a permutation can never move a pod onto a node that fits a different
    request or filter row. Non-members get unique singleton ids above the
    real groups (identity permutation), all int32-range. Vectorized (one
    np.unique over the stacked key columns): this runs on the solve path
    of every ranked-gang batch."""
    p = len(gang_of_pod)
    member = np.asarray(gang_of_pod) >= 0
    out = np.empty(p, dtype=np.int32)
    out[~member] = _INT32_BIG // 2 + np.nonzero(~member)[0].astype(np.int32)
    if member.any():
        rows = np.nonzero(member)[0]
        key = np.column_stack([
            np.asarray(gang_of_pod)[rows].astype(np.int64),
            np.asarray(class_of_pod)[rows].astype(np.int64),
            np.asarray(req)[rows].astype(np.int64),
            np.asarray(req_nz)[rows].astype(np.int64)])
        _uniq, inv = np.unique(key, axis=0, return_inverse=True)
        out[rows] = inv.astype(np.int32)
    return out


# -- adjacency metric ---------------------------------------------------------


def mean_neighbor_distance(group_id: Sequence[int], rank: Sequence[int],
                           slice_of: Sequence[int], pos: Sequence[int],
                           ring_len: Dict[int, int]) -> Optional[float]:
    """Mean ring distance between consecutive-rank placed members, the
    placement-quality column of the gang rungs: for ranks r and r+1 on the
    same slice it is the ICI ring hop count min(|dp|, L - |dp|); a
    cross-slice pair pays the worst ring length (the DCN hop the packing
    score exists to avoid). None when no gang has two placed members."""
    by_group: Dict[int, List[Tuple[int, int, int]]] = {}
    for g, r, s, p in zip(group_id, rank, slice_of, pos):
        if g < 0 or s < 0:
            continue
        by_group.setdefault(int(g), []).append((int(r), int(s), int(p)))
    worst = max(ring_len.values(), default=1)
    dists: List[float] = []
    for members in by_group.values():
        members.sort()
        for (r1, s1, p1), (r2, s2, p2) in zip(members, members[1:]):
            if s1 == s2:
                ln = max(ring_len.get(s1, 1), 1)
                d = abs(p2 - p1)
                dists.append(min(d, ln - d))
            else:
                dists.append(worst)
    if not dists:
        return None
    return float(np.mean(dists))
