"""Water-filling batch solver — the fast path for constraint-light batches.

Greedy scheduling of identical pods is a water-filling process: each placement
takes the current-best node, whose score then decreases. For a group of
identical pods (same equivalence class AND same resource vector), the j-th
placement on node n has a computable marginal score s[n, j] — so the whole
greedy sequence collapses into ONE top-k over the [N, J] marginal-score matrix
instead of P sequential steps. This replaces the per-pod loop with a handful of
fully-parallel device ops: the MXU/VPU-friendly formulation of
prioritizeNodes() (reference: schedule_one.go:754).

Exactness: scores are evaluated against group-start normalization and made
monotone by a running cummin, so selections have the prefix property (if slot
(n, j) is chosen, all (n, i<j) are too). For score compositions that are
monotone per node (LeastAllocated + static scores — the SchedulingBasic /
NodeAffinity / Taint workloads), this equals the serial greedy assignment
*counts* per node; BalancedAllocation's non-monotone hump is handled by the
cummin (pessimistic, may diverge from serial by small score-epsilon choices).
Filter correctness is exact: a selected slot always fits.

Batches containing PodTopologySpread or InterPodAffinity constraints are routed
to the exact scan solver by the driver (solver='auto').
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.solver import (
    SolverInputs,
    default_normalize,
    INT_MIN,
)
from ..scheduler.framework import MAX_NODE_SCORE


def bucket_j_max(max_pods, pod_count, n: int, max_slots: int,
                 cap_hint: Optional[int] = None) -> Optional[int]:
    """Pow2-bucketed per-node slot depth for the waterfill sort key.

    j_max must cover every node's remaining pod headroom, or schedulable pods
    would be silently clipped; the int32 sort key bounds total slots at
    `max_slots` (max_total_score * slots < 2^31 — each caller budgets its own
    score ceiling). Derived from STATIC capacity (max_pods) when it fits:
    headroom shrinks as the cluster fills and a headroom-derived bucket would
    recompile at every power-of-two boundary — each mid-run XLA compile costs
    tens of seconds on TPU. Only when the static bound blows the int32 key
    range does the tighter dynamic headroom (then a raw, unbucketed one) come
    in. cap_hint (the repair path's largest group size, itself pow2-bucketed
    by the shift below) tightens the depth when no group can ever fill a
    node. Returns None when the problem shape exceeds the key range entirely
    (callers fall back to the scan solver)."""
    cap = max(1, int(np.asarray(max_pods).max(initial=1)))
    if cap_hint is not None:
        cap = min(cap, max(1, int(cap_hint)))
    j_max = 1 << (cap - 1).bit_length()
    if n * j_max > max_slots:
        # documented last resort (docstring above): when the static pow2
        # bucket blows the int32 sort-key range, the raw dynamic headroom
        # keys the jit — recompiles are accepted there because the
        # alternative is no fast path at all
        headroom = max(1, int(np.asarray(max_pods - pod_count).max(initial=1)))
        if cap_hint is not None:
            headroom = min(headroom, max(1, int(cap_hint)))
        j_max = 1 << (headroom - 1).bit_length()
        if n * j_max > max_slots:
            if n * headroom > max_slots:
                return None
            j_max = headroom
    return j_max


@functools.partial(jax.jit, static_argnames=("j_max", "k_slots", "has_gang"))
def waterfill_group(
    alloc, used, used_nz, pod_count, max_pods,
    filter_ok_row, port_conflict_row, has_port,
    napref_row, has_napref, taint_row, img_row,
    req, req_nz, bal_active, group_size,
    j_max: int, k_slots: int,
    gang_row=None, has_gang: bool = False,
):
    """Place `group_size` (dynamic, <= k_slots) identical pods. k_slots is the
    static top-k width — bucketed to powers of two by the caller so batch-size
    changes don't recompile. Returns (k_per_node [N] int32, placement node ids
    [k_slots] int32 in greedy order, -1 beyond group_size)."""
    n = alloc.shape[0]
    # J_n: how many of this pod fit on node n right now
    free = alloc - used
    with_req = jnp.where(req[None, :] > 0, free // jnp.maximum(req[None, :], 1), j_max)
    j_cap = jnp.min(with_req, axis=1).astype(jnp.int32)
    j_cap = jnp.minimum(j_cap, max_pods - pod_count)
    j_cap = jnp.where(filter_ok_row, j_cap, 0)
    # a class with host ports can hold at most one pod per node, and zero where
    # the port is already taken
    j_cap = jnp.where(has_port, jnp.where(port_conflict_row, 0, jnp.minimum(j_cap, 1)), j_cap)
    j_cap = jnp.clip(j_cap, 0, j_max)

    # static (per-node) score components, normalized over the group-start
    # feasible set
    feas0 = j_cap > 0
    napref = jnp.where(has_napref, default_normalize(napref_row, feas0, reverse=False), 0)
    taint = default_normalize(taint_row, feas0, reverse=True)
    static = 2 * napref + 3 * taint + img_row  # int32 [N]
    if has_gang:
        # gang slice-packing bonus (scheduler/gang.py) — static per node like
        # img_row; the caller's slot guard budgets the extra score range
        static = static + gang_row

    # dynamic components as a function of j = pods already added (0..j_max-1),
    # via the SAME formula helpers the scan solver uses (one source of truth
    # for score parity), vmapped over the j axis
    from ..ops.solver import balanced_score, least_allocated_score

    js = jnp.arange(j_max, dtype=jnp.int32)  # [J]
    alloc2 = alloc[:, :2]  # cpu, memory — the configured scoring resources

    def at_j(j):
        least_j = least_allocated_score(alloc2, used_nz[:, :2] + j * req_nz[None, :2],
                                        req_nz[:2])
        bal_j = balanced_score(alloc2, used[:, :2] + j * req[None, :2], req[:2], bal_active)
        return least_j + bal_j

    score = jax.vmap(at_j)(js).T + static[:, None]  # [N, J]
    # prefix property: make marginal scores non-increasing in j
    score = jax.lax.associative_scan(jnp.minimum, score, axis=1)
    # mask slots beyond capacity
    score = jnp.where(js[None, :] < j_cap[:, None], score, INT_MIN)

    # greedy order = sort by (score desc, node asc, j asc). Encoded into one
    # int32 sort key: key = score * (n*j_max+1) - slot_rank. Valid while
    # max_score * slots < 2^31 — i.e. up to ~3M slots (scores are <= ~700);
    # callers cap j_max / shard nodes beyond that.
    slots = n * j_max
    flat_score = score.reshape(-1)
    # row-major flat index IS the (node asc, j asc) tie-break rank
    slot_rank = jnp.arange(slots, dtype=jnp.int32)
    sentinel = jnp.int32(-(2**31) + 1)
    key = flat_score * (slots + 1) - slot_rank
    key = jnp.where(flat_score <= INT_MIN, sentinel, key)
    top_keys, top_idx = jax.lax.top_k(key, k_slots)
    chosen = (top_keys > sentinel) & (jnp.arange(k_slots) < group_size)
    chosen_nodes = jnp.where(chosen, (top_idx // j_max).astype(jnp.int32), -1)

    k_per_node = jax.ops.segment_sum(
        chosen.astype(jnp.int32),
        jnp.where(chosen, top_idx // j_max, n).astype(jnp.int32),
        num_segments=n + 1,
    )[:n]
    return k_per_node, chosen_nodes


def waterfill_solve(inp: SolverInputs, groups: List[Tuple[np.ndarray, int]]):
    """Solve a batch as a sequence of identical-pod groups (few device calls).

    groups: list of (member_pod_indices (queue-ordered), class_id). Produces
    assignment[P] int32 like greedy_scan_solve, or None when the problem shape
    exceeds the fast path's int32 sort-key range (caller falls back to scan).
    """
    p = inp.req.shape[0]
    n = inp.alloc.shape[0]
    has_gang = inp.gang_bonus is not None
    # slot budget (bucket_j_max): max_total_score 800 * slots < 2^31 bounds
    # slots at ~2.6M; gang batches add GANG_SLICE_BONUS to the score range,
    # so their slot cap tightens to ~2.3M
    max_slots = 2_300_000 if has_gang else 2_600_000
    j_max = bucket_j_max(inp.max_pods, inp.pod_count, n, max_slots)
    if j_max is None:
        return None
    assignment = np.full(p, -1, dtype=np.int32)
    used = inp.used
    used_nz = inp.used_nz
    pod_count = inp.pod_count
    port_taken = inp.node_ports

    for members, cls in groups:
        pi0 = int(members[0])
        has_port = bool(np.asarray(inp.class_ports[cls]).any())
        port_conflict = jnp.any(port_taken & inp.class_ports[cls][None, :], axis=1)
        # pow2 bucket keeps the jit key stable across batch sizes; never wider
        # than the slot count (top_k requires k <= size). Floored at 256 so
        # trickles of small batches (requeues, churn) share ONE compiled shape
        # instead of compiling per power of two.
        k_slots = min(1 << (len(members) - 1).bit_length(), n * j_max)
        k_slots = max(k_slots, min(256, n * j_max))
        k_per_node, chosen_nodes = waterfill_group(
            inp.alloc, used, used_nz, pod_count, inp.max_pods,
            inp.filter_ok[cls], port_conflict, has_port,
            inp.napref_raw[cls], inp.has_napref[cls], inp.taint_cnt[cls],
            inp.img_score[cls],
            inp.req[pi0], inp.req_nz[pi0], inp.balanced_active[pi0],
            jnp.int32(len(members)),
            j_max=j_max, k_slots=k_slots,
            gang_row=inp.gang_bonus[cls] if has_gang else None,
            has_gang=has_gang,
        )
        chosen = np.full(len(members), -1, dtype=np.int32)
        got = np.asarray(chosen_nodes)[: len(members)]
        chosen[: len(got)] = got  # k_slots may be < group size: overflow stays -1
        assignment[np.asarray(members)] = chosen
        # commit group effects
        placed = jnp.asarray(k_per_node)
        used = used + placed[:, None] * inp.req[pi0][None, :]
        used_nz = used_nz + placed[:, None] * inp.req_nz[pi0][None, :]
        pod_count = pod_count + placed
        if has_port:
            port_taken = port_taken | ((placed > 0)[:, None] & inp.class_ports[cls][None, :])

    return assignment


def make_groups(batch) -> List[Tuple[np.ndarray, int]]:
    """Group batch pods by (class, resource vector), preserving queue order of
    first appearance (the fast path's priority approximation)."""
    keys = {}
    order = []
    for i in range(len(batch.pods)):
        k = (int(batch.class_of_pod[i]), batch.req[i].tobytes(), batch.req_nz[i].tobytes(),
             bool(batch.balanced_active[i]))
        if k not in keys:
            keys[k] = []
            order.append(k)
        keys[k].append(i)
    return [(np.array(keys[k], dtype=np.int64), k[0]) for k in order]
