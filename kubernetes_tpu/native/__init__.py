"""Native host runtime: C++ engines behind ctypes boundaries.

The compute path of this build is JAX/XLA on TPU; the host runtime around it
is native C++, compiled on first use with the toolchain's g++:

  hostsched.cpp  — pure array kernels loaded via ctypes CDLL, which RELEASES
                   the GIL for every call: the CPU-fallback batch engine
                   (greedy_assign) and the columnar-assume scatter-add
                   (commit_deltas). Never call these under a store/scheduler
                   lock (schedlint LK002; store/store.py NATIVE LOCK RULE).
  hostcommit.cpp — the C-API commit engine loaded via ctypes.PyDLL (GIL
                   HELD): bind/delete commit loops, the assume structural
                   loop, and build_pod_batch's fused row loop, byte-identical
                   to their Python oracles (tests/test_native_commit.py).

`native_available()` / `hostcommit.available()` gate callers; everything
degrades to the JAX/numpy/Python paths when no compiler is present.
"""

from . import hostcommit  # noqa: F401
from .hostsched import (  # noqa: F401
    native_available,
    native_commit_deltas,
    native_greedy_solve,
    native_solvable,
)
