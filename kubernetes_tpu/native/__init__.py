"""Native host runtime: C++ scheduler engine behind a ctypes boundary.

The compute path of this build is JAX/XLA on TPU; the host runtime around it —
here, the CPU-fallback batch engine mirroring ops/solver.py's scan solver —
is native C++ (hostsched.cpp), compiled on first use with the toolchain's g++
and loaded via ctypes. `native_available()` gates callers; everything degrades
to the JAX/numpy paths when no compiler is present.
"""

from .hostsched import (  # noqa: F401
    native_available,
    native_greedy_solve,
    native_solvable,
)
