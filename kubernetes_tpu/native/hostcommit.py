"""ctypes loader + wrappers for the C-API host commit engine (hostcommit.cpp).

Compiled on first use with the toolchain's g++ against the CPython headers
and loaded via ctypes.PyDLL — every entry point manipulates Python objects
and runs WITH the GIL held (the engine's speedup is fewer interpreter cycles
per pod inside the store/cache critical sections, not GIL release; the
GIL-releasing array kernels live in hostsched.py). Selection mirrors the
native solver: `available()` gates callers, everything degrades to the
Python oracles when the compile fails, and the HOSTSCHED_NATIVE_COMMIT env
var (0/false) forces the fallback — the knob the parity tests and the
BindCommit_20k bench's python-vs-native columns use.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional, Tuple

import numpy as np

from .hostsched import build_so

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "hostcommit.cpp")
_SO = os.path.join(_HERE, "_hostcommit.so")

_lock = threading.Lock()
_lib: Optional[ctypes.PyDLL] = None
_build_error: Optional[str] = None

_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")


def _env_disabled() -> bool:
    return os.environ.get("HOSTSCHED_NATIVE_COMMIT", "").lower() in (
        "0", "false")


def _load() -> Optional[ctypes.PyDLL]:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        err = build_so(_SRC, _SO, python_include=True)
        if err is not None:
            _build_error = err
            return None
        try:
            lib = ctypes.PyDLL(_SO)
            obj = ctypes.py_object
            lib.hc_init.restype = obj
            lib.hc_init.argtypes = [obj, obj, obj]
            lib.hc_bind_prepare.restype = obj
            lib.hc_bind_prepare.argtypes = [obj, obj, obj, obj]
            lib.hc_bind_commit.restype = obj
            lib.hc_bind_commit.argtypes = [
                obj, obj, obj, obj, ctypes.c_long, ctypes.c_int, obj, obj,
                obj]
            lib.hc_delete_commit.restype = obj
            lib.hc_delete_commit.argtypes = [
                obj, obj, obj, obj, ctypes.c_long, ctypes.c_int, obj, obj,
                obj]
            lib.hc_assume_structural.restype = obj
            lib.hc_assume_structural.argtypes = [obj, obj, obj, obj, obj]
            lib.hc_columnar_prepare.restype = obj
            lib.hc_columnar_prepare.argtypes = [obj, obj, obj, obj, obj, obj,
                                                _i32p, _i32p, _i32p]
            lib.hc_batch_rows.restype = obj
            lib.hc_batch_rows.argtypes = [obj, obj, obj, obj, obj, obj,
                                          _i32p, _i32p]
            # one-time type/string setup (the engine holds strong refs)
            from ..scheduler.framework import NodeInfo, PodInfo
            from ..store.store import Event

            lib.hc_init(Event, PodInfo, NodeInfo)
        except (OSError, AttributeError) as e:
            _build_error = f"load failed: {e}"
            return None
        _lib = lib
        return _lib


def available() -> bool:
    """True when the commit engine is loaded and not env-disabled. The env
    check is live (not cached) so tests can flip the fallback per-case."""
    if _env_disabled():
        return False
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_error


# -- store.bind_many ---------------------------------------------------------

def bind_prepare(pods: dict, bindings, prepared: list, errors: list) -> None:
    """Phase 1 (validate + ONE bind clone per pod; caller holds the pods
    shard). Appends (key, old, new, node_name) to prepared."""
    _lib.hc_bind_prepare(pods, bindings, prepared, errors)


def bind_commit(pods: dict, prepared: list, events: list, errors: list,
                rv: int, mode: int, commit_ts, cloner,
                etype: str) -> Tuple[int, int]:
    """Phase 2 (RV stamp + row swap + event append; caller holds global +
    shard). mode: 0 share / 1 lazy / 2 eager. Returns (final_rv, bound)."""
    return _lib.hc_bind_commit(pods, prepared, events, errors, rv, mode,
                               commit_ts, cloner, etype)


def delete_commit(pods: dict, keys, events: list, errors: list, rv: int,
                  mode: int, commit_ts, cloner,
                  etype: str) -> Tuple[int, int]:
    """Batched pod-delete commit (caller holds global + shard): pops rows,
    one structural clone per pod, DELETED events. Returns (final_rv, n)."""
    return _lib.hc_delete_commit(pods, keys, events, errors, rv, mode,
                                 commit_ts, cloner, etype)


def columnar_prepare(key2row: dict, bindings, node_ids: dict,
                     node_names: list, node_id_col: np.ndarray,
                     errors: list) -> Tuple[np.ndarray, np.ndarray, list]:
    """Columnar bind_many phase 1 (ISSUE 15; caller holds the pods shard):
    the validate/intern loop of store/columnar.py PodColumns.bind_prepare
    retargeted at the column arrays — key2row lookups + node_id[row] bound
    checks, no clones. Returns (rows int32[count], ids int32[count], keys
    list); mutates node_ids/node_names (the intern table) and errors exactly
    like the Python loop. bindings must be a sequence (the store normalizes
    iterables before calling)."""
    n = len(bindings)
    rows = np.empty(n, dtype=np.int32)
    ids = np.empty(n, dtype=np.int32)
    keys: list = []
    if n == 0:
        return rows, ids, keys
    count = _lib.hc_columnar_prepare(key2row, bindings, node_ids, node_names,
                                     errors, keys, node_id_col, rows, ids)
    return rows[:count], ids[:count], keys


# -- cache assume ------------------------------------------------------------

def assume_structural(pairs, pod_nodes: dict, assumed: dict, nodes: dict,
                      failed: list) -> None:
    """Cache.assume_pods_structural's loop (caller holds the cache lock;
    check_ports=False form only — host-port batches use the Python loop)."""
    _lib.hc_assume_structural(pairs, pod_nodes, assumed, nodes, failed)


# -- build_pod_batch ---------------------------------------------------------

def batch_rows(pods, sig_to_class: dict, rep_pods: list, req_cache: dict,
               sig_cb, entry_cb) -> Tuple[np.ndarray, np.ndarray]:
    """The fused per-pod loop of build_pod_batch: returns (class_of_pod
    int32[P], entry_rows int32[P]); mutates sig_to_class/rep_pods/req_cache
    exactly like the Python loop (misses call back into sig_cb/entry_cb)."""
    n = len(pods)
    if n == 0:
        z = np.zeros(0, dtype=np.int32)
        return z, z.copy()
    class_rows = np.empty(n, dtype=np.int32)
    entry_rows = np.empty(n, dtype=np.int32)
    _lib.hc_batch_rows(pods, sig_to_class, rep_pods, req_cache, sig_cb,
                       entry_cb, class_rows, entry_rows)
    return class_rows, entry_rows
