// Native host scheduler core: the CPU-fallback batch engine.
//
// The reference scheduler's hot loop (schedule_one.go:590 findNodesThatPass
// Filters + :754 prioritizeNodes) runs as compiled Go; this is the build's
// native equivalent for the host path: dense feasibility + score + sequential
// greedy commit over the same struct-of-arrays the TPU solver consumes
// (ops/solver.py SolverInputs). Array-in/array-out C ABI, loaded via ctypes.
//
// Formula parity with ops/solver.py greedy_scan_solve for batches without
// topology-spread constraints (those route to the device scan solver or the
// serial oracle): fit_feasible, dynamic NodePorts, least_allocated_score,
// balanced_score, default_normalize(napref/taint), ImageLocality bonus;
// sequential within batch, argmax score, lowest node index wins ties,
// capacity and ports committed before the next pod.

#include <cstdint>

namespace {

constexpr int32_t kMaxNodeScore = 100;  // framework/interface.go:255

// DefaultNormalizeScore (plugins/helper/normalize_score.go) over the feasible
// set: scaled = 100*raw//max; reverse flips to 100-scaled (100 when max==0).
inline int32_t normalize(int32_t raw, int64_t mx, bool reverse) {
  if (mx <= 0) return reverse ? kMaxNodeScore : 0;
  int32_t scaled = (int32_t)((int64_t)kMaxNodeScore * raw / mx);
  return reverse ? kMaxNodeScore - scaled : scaled;
}

}  // namespace

extern "C" {

// Sequential greedy batch assignment. Mutates used/used_nz/pod_count/
// node_ports (the virtual commit that makes pod p+1 see pod p's placement).
// Layouts (row-major): alloc/used/used_nz [N,R]; static_ok/napref_raw/
// taint_cnt/img_score [C,N]; class_ports [C,Pt]; node_ports [N,Pt];
// req/req_nz [P,R]. Returns number of pods placed; assignment[p] = node or -1.
int64_t greedy_assign(const int32_t* alloc, int32_t* used, int32_t* used_nz,
                      int32_t* pod_count, const int32_t* max_pods,
                      const uint8_t* static_ok, const int32_t* napref_raw,
                      const uint8_t* has_napref, const int32_t* taint_cnt,
                      const int32_t* img_score, const uint8_t* class_ports,
                      uint8_t* node_ports, const int32_t* class_of_pod,
                      const int32_t* req, const int32_t* req_nz,
                      const uint8_t* bal_active, int64_t p, int64_t n,
                      int64_t r, int64_t pt, uint8_t* feas_buf,
                      int32_t* assignment) {
  int64_t placed = 0;
  for (int64_t pi = 0; pi < p; ++pi) {
    const int64_t c = class_of_pod[pi];
    const uint8_t* ok_row = static_ok + c * n;
    const int32_t* napref_row = napref_raw + c * n;
    const int32_t* taint_row = taint_cnt + c * n;
    const int32_t* img_row = img_score + c * n;
    const uint8_t* cports = pt ? class_ports + c * pt : nullptr;
    const int32_t* preq = req + pi * r;
    const int32_t* preq_nz = req_nz + pi * r;

    // pass 1: feasibility (fit_feasible + class filter + dynamic ports) and
    // the normalization maxima over the feasible set
    int64_t napref_max = 0, taint_max = 0;
    for (int64_t i = 0; i < n; ++i) {
      uint8_t ok = ok_row[i];
      if (ok && pod_count[i] + 1 > max_pods[i]) ok = 0;
      if (ok) {
        const int32_t* a = alloc + i * r;
        const int32_t* u = used + i * r;
        for (int64_t k = 0; k < r; ++k) {
          if (preq[k] != 0 && preq[k] > a[k] - u[k]) { ok = 0; break; }
        }
      }
      if (ok && cports) {
        const uint8_t* nports = node_ports + i * pt;
        for (int64_t q = 0; q < pt; ++q) {
          if (nports[q] & cports[q]) { ok = 0; break; }
        }
      }
      feas_buf[i] = ok;
      if (ok) {
        if (napref_row[i] > napref_max) napref_max = napref_row[i];
        if (taint_row[i] > taint_max) taint_max = taint_row[i];
      }
    }

    // pass 2: score feasible nodes, track argmax (lowest index wins ties)
    int64_t best = -1;
    int64_t best_score = 0;
    for (int64_t i = 0; i < n; ++i) {
      if (!feas_buf[i]) continue;
      const int32_t* a = alloc + i * r;
      const int32_t* unz = used_nz + i * r;
      const int32_t* u = used + i * r;
      // leastResourceScorer over cpu+memory (least_allocated.go:30)
      int64_t least = 0, wsum = 0;
      for (int k = 0; k < 2 && k < r; ++k) {
        int64_t cap = a[k];
        if (cap <= 0) continue;
        ++wsum;
        int64_t want = (int64_t)unz[k] + preq_nz[k];
        if (want <= cap) least += (cap - want) * kMaxNodeScore / cap;
      }
      if (wsum == 0) wsum = 1;
      least /= wsum;
      // balancedResourceScorer 2-resource shortcut (balanced_allocation.go:145).
      // float (not double) on purpose: the scan solver computes this in
      // float32, and the truncation at the *100 boundary must round the same
      // way for bit parity.
      int64_t bal = 0;
      if (bal_active[pi]) {
        float frac[2] = {0.0f, 0.0f};
        int n_frac = 0;
        for (int k = 0; k < 2 && k < r; ++k) {
          float cap = (float)a[k];
          if (cap <= 0.0f) continue;
          ++n_frac;
          float want = (float)u[k] + (float)preq[k];
          float f = want / cap;
          frac[k] = f > 1.0f ? 1.0f : f;
        }
        float stdv = n_frac == 2 ? (frac[0] > frac[1] ? frac[0] - frac[1]
                                                      : frac[1] - frac[0]) / 2.0f
                                 : 0.0f;
        bal = (int64_t)(int32_t)((1.0f - stdv) * (float)kMaxNodeScore);
      }
      int64_t napref =
          has_napref[c] ? normalize(napref_row[i], napref_max, false) : 0;
      int64_t taint = normalize(taint_row[i], taint_max, true);
      int64_t total = least + bal + 2 * napref + 3 * taint + img_row[i];
      if (best < 0 || total > best_score) {
        best = i;
        best_score = total;
      }
    }

    assignment[pi] = (int32_t)best;
    if (best >= 0) {
      int32_t* u = used + best * r;
      int32_t* unz = used_nz + best * r;
      for (int64_t k = 0; k < r; ++k) {
        u[k] += preq[k];
        unz[k] += preq_nz[k];
      }
      pod_count[best] += 1;
      if (cports) {
        uint8_t* nports = node_ports + best * pt;
        for (int64_t q = 0; q < pt; ++q) nports[q] |= cports[q];
      }
      ++placed;
    }
  }
  return placed;
}

// Fused columnar-assume scatter-add (the _columnar_account hot block):
// d_used[nodes[i]] += raw_req[rows[i]], d_used_nz likewise, d_count bump,
// touched-node flags — ONE pass over the batch instead of two np.add.at
// dispatches + bincount + unique. Pure array math: called via ctypes CDLL,
// which RELEASES the GIL for the duration (the scheduling thread's commit
// accounting no longer steals interpreter time from the bind worker). Must
// therefore never run under a store/scheduler lock (schedlint LK002 lists
// the wrapper as a blocking call). Layouts: raw_req/raw_req_nz [p_all, R]
// int64 row-major; d_used/d_used_nz [N, R] int64 zeroed by the caller;
// d_count [N] int64 zeroed; touched [N] uint8 zeroed.
//
// Indices are VALIDATED (pass 1) before anything is written (pass 2): the
// numpy oracle surfaces a bad node/row as a catchable IndexError that the
// assume/dispatch failure-domain guard rolls back — a silent out-of-bounds
// heap write here would defeat that machinery. Returns 0, or (bad_index+1)
// negated for the first out-of-range entry; the wrapper raises IndexError.
int64_t commit_deltas(const int64_t* rows, const int64_t* nodes, int64_t p,
                      const int64_t* raw_req, const int64_t* raw_req_nz,
                      int64_t r, int64_t p_all, int64_t n, int64_t* d_used,
                      int64_t* d_used_nz, int64_t* d_count,
                      uint8_t* touched) {
  for (int64_t i = 0; i < p; ++i) {
    if (nodes[i] < 0 || nodes[i] >= n || rows[i] < 0 || rows[i] >= p_all)
      return -(i + 1);
  }
  for (int64_t i = 0; i < p; ++i) {
    const int64_t node = nodes[i];
    const int64_t row = rows[i];
    int64_t* du = d_used + node * r;
    int64_t* dz = d_used_nz + node * r;
    const int64_t* rq = raw_req + row * r;
    const int64_t* rz = raw_req_nz + row * r;
    for (int64_t k = 0; k < r; ++k) {
      du[k] += rq[k];
      dz[k] += rz[k];
    }
    d_count[node] += 1;
    touched[node] = 1;
  }
  return 0;
}

}  // extern "C"
