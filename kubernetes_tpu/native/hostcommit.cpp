// Native host COMMIT engine: the CPython-object half of the native host
// runtime (the array half lives in hostsched.cpp).
//
// The end-to-end NorthStar profile is GIL-bound interpreter work on the host
// commit path — bind/event commit, the assume structural loop, and the fused
// build_pod_batch row loop. Each of those loops is a long chain of tiny
// object operations (dict copies, instance allocation, dict inserts, list
// appends) whose cost is dominated by bytecode dispatch, not the operations
// themselves. This engine replays EXACTLY the same object operations through
// the C API, entered ONCE per batch/chunk, so the per-pod cost drops to the
// C-level primitives alone.
//
// Contract (enforced by tests/test_native_commit.py):
//   - Byte-identical results with the Python oracles in store/store.py,
//     scheduler/cache.py, and snapshot/tensorizer.py: same stored rows, same
//     RV sequence, same Event instances field-for-field (including the lazy
//     slot layout), same placements. The Python implementations stay in-tree
//     as the oracle and the no-g++ fallback.
//   - Every entry point here manipulates Python objects and therefore MUST
//     be called with the GIL HELD — the loader uses ctypes.PyDLL, which does
//     not release the GIL around calls. The win is fewer interpreter cycles
//     inside the store's critical sections (the GIL-held region per chunk
//     shrinks ~3-5x), which is what lets the bind worker's commit overlap
//     the scheduling thread's Python instead of starving it. The
//     GIL-RELEASING kernels (ctypes CDLL: greedy_assign, commit_deltas) live
//     in hostsched.cpp and must never be called under a store lock
//     (schedlint LK002 flags them; see the rule note in store/store.py).
//   - Errors: every path either completes or returns NULL with a Python
//     exception set (ctypes raises it); no partial hidden state beyond what
//     the equivalent Python loop would have committed before raising.

#include <Python.h>

namespace {

// interned key strings (hc_init)
PyObject* s_metadata;
PyObject* s_spec;
PyObject* s_status;
PyObject* s_node_name;
PyObject* s_resource_version;
PyObject* s_labels;
PyObject* s_annotations;
PyObject* s_owner_references;
PyObject* s_finalizers;
PyObject* s_conditions;
PyObject* s_type;
PyObject* s_kind;
PyObject* s_obj;
PyObject* s_prev;
PyObject* s_lazy;
PyObject* s_commit_ts;
PyObject* s_key;
PyObject* s_key_cache;
PyObject* s_req_cache;
PyObject* s_class_sig;
PyObject* s_req_sig;
PyObject* s_pods;
PyObject* s_pods_with_affinity;
PyObject* s_pods_with_req_anti;
PyObject* s_affinity;
PyObject* s_pod_aff_req;
PyObject* s_pod_anti_req;
PyObject* s_pod_aff_pref;
PyObject* s_pod_anti_pref;
PyObject* s_slot_pod;
PyObject* s_slot_request;
PyObject* s_slot_nz_request;
PyObject* s_slot_req_aff;
PyObject* s_slot_req_anti;
PyObject* s_slot_pref_aff;
PyObject* s_slot_pref_anti;
PyObject* s_kind_pods;

PyObject* g_event_type;     // store.store.Event
PyObject* g_podinfo_type;   // scheduler.framework.PodInfo
PyObject* g_nodeinfo_type;  // scheduler.framework.NodeInfo
PyObject* g_empty_tuple;
PyObject* g_zero_float;

bool g_ready = false;

inline PyObject** inst_dict_ptr(PyObject* obj) {
  return _PyObject_GetDictPtr(obj);
}

// Borrowed-ref instance-dict lookup with full-attribute fallback. On a dict
// hit returns the borrowed value (*own stays NULL); on fallback stores the
// new ref in *own and returns it (caller XDECREFs *own). NULL = error set.
PyObject* fast_attr(PyObject* obj, PyObject* name, PyObject** own) {
  *own = nullptr;
  PyObject** dp = inst_dict_ptr(obj);
  if (dp != nullptr && *dp != nullptr) {
    PyObject* v = PyDict_GetItemWithError(*dp, name);
    if (v != nullptr) return v;
    if (PyErr_Occurred()) return nullptr;
  }
  *own = PyObject_GetAttr(obj, name);
  return *own;
}

// _shallow's exact C equivalent: fresh instance of the same class whose
// __dict__ is a C-level copy of the source's. Only valid for plain classes
// with an instance dict (Pod/ObjectMeta/PodSpec/PodStatus/Event here).
PyObject* shallow_copy(PyObject* obj) {
  PyObject** sdp = inst_dict_ptr(obj);
  if (sdp == nullptr || *sdp == nullptr) {
    PyErr_SetString(PyExc_TypeError,
                    "hostcommit: shallow_copy needs an instance __dict__");
    return nullptr;
  }
  PyObject* d = PyDict_Copy(*sdp);
  if (d == nullptr) return nullptr;
  PyTypeObject* tp = Py_TYPE(obj);
  PyObject* neu = tp->tp_alloc(tp, 0);
  if (neu == nullptr) {
    Py_DECREF(d);
    return nullptr;
  }
  PyObject** ddp = inst_dict_ptr(neu);
  if (ddp == nullptr) {
    Py_DECREF(d);
    Py_DECREF(neu);
    PyErr_SetString(PyExc_TypeError,
                    "hostcommit: target class has no __dict__ slot");
    return nullptr;
  }
  // the slot is NULL after tp_alloc on 3.10; newer CPythons
  // (Py_TPFLAGS_MANAGED_DICT) may have materialized an empty dict when we
  // took the pointer — release it or every clone leaks one dict there
  Py_XSETREF(*ddp, d);
  return neu;
}

// Replace key in obj's (already private) __dict__ with a shallow copy of its
// current value; returns the borrowed new copy (owned by the dict) or NULL.
PyObject* privatize_member(PyObject* owner_dict, PyObject* key) {
  PyObject* cur = PyDict_GetItemWithError(owner_dict, key);
  if (cur == nullptr) {
    if (!PyErr_Occurred())
      PyErr_Format(PyExc_AttributeError, "hostcommit: missing %U", key);
    return nullptr;
  }
  PyObject* cp = shallow_copy(cur);
  if (cp == nullptr) return nullptr;
  if (PyDict_SetItem(owner_dict, key, cp) < 0) {
    Py_DECREF(cp);
    return nullptr;
  }
  Py_DECREF(cp);  // dict holds it
  return PyDict_GetItemWithError(owner_dict, key);
}

// store.store.pod_bind_clone, exactly: fresh Pod/ObjectMeta/PodSpec shells,
// everything else shared.
PyObject* bind_clone(PyObject* pod) {
  PyObject* neu = shallow_copy(pod);
  if (neu == nullptr) return nullptr;
  PyObject* nd = *inst_dict_ptr(neu);
  if (privatize_member(nd, s_metadata) == nullptr ||
      privatize_member(nd, s_spec) == nullptr) {
    Py_DECREF(neu);
    return nullptr;
  }
  return neu;
}

// list(x) equivalent (fresh list from any sequence/iterable)
PyObject* list_copy(PyObject* seq) { return PySequence_List(seq); }

// store.store.pod_structural_clone, exactly: private metadata (with own
// labels/annotations/owner_references/finalizers), private spec, private
// status (own conditions list).
PyObject* structural_clone(PyObject* pod) {
  PyObject* neu = shallow_copy(pod);
  if (neu == nullptr) return nullptr;
  PyObject* nd = *inst_dict_ptr(neu);
  PyObject* meta = privatize_member(nd, s_metadata);
  if (meta == nullptr) goto fail;
  {
    PyObject* md = *inst_dict_ptr(meta);
    PyObject* cur;
    PyObject* cp;
    if ((cur = PyDict_GetItemWithError(md, s_labels)) == nullptr) goto fail;
    if ((cp = PyDict_Copy(cur)) == nullptr) goto fail;
    if (PyDict_SetItem(md, s_labels, cp) < 0) { Py_DECREF(cp); goto fail; }
    Py_DECREF(cp);
    if ((cur = PyDict_GetItemWithError(md, s_annotations)) == nullptr)
      goto fail;
    if ((cp = PyDict_Copy(cur)) == nullptr) goto fail;
    if (PyDict_SetItem(md, s_annotations, cp) < 0) { Py_DECREF(cp); goto fail; }
    Py_DECREF(cp);
    if ((cur = PyDict_GetItemWithError(md, s_owner_references)) == nullptr)
      goto fail;
    if ((cp = list_copy(cur)) == nullptr) goto fail;
    if (PyDict_SetItem(md, s_owner_references, cp) < 0) {
      Py_DECREF(cp);
      goto fail;
    }
    Py_DECREF(cp);
    if ((cur = PyDict_GetItemWithError(md, s_finalizers)) == nullptr)
      goto fail;
    if ((cp = list_copy(cur)) == nullptr) goto fail;
    if (PyDict_SetItem(md, s_finalizers, cp) < 0) { Py_DECREF(cp); goto fail; }
    Py_DECREF(cp);
  }
  if (privatize_member(nd, s_spec) == nullptr) goto fail;
  {
    PyObject* status = privatize_member(nd, s_status);
    if (status == nullptr) goto fail;
    PyObject* sd = *inst_dict_ptr(status);
    PyObject* cur = PyDict_GetItemWithError(sd, s_conditions);
    if (cur == nullptr) goto fail;
    PyObject* cp = list_copy(cur);
    if (cp == nullptr) goto fail;
    if (PyDict_SetItem(sd, s_conditions, cp) < 0) { Py_DECREF(cp); goto fail; }
    Py_DECREF(cp);
  }
  return neu;
fail:
  Py_DECREF(neu);
  return nullptr;
}

// store.store._make_event, exactly (same dict insertion order).
PyObject* make_event(PyObject* etype, PyObject* kind, PyObject* obj,
                     PyObject* rv, PyObject* prev, PyObject* lazy,
                     PyObject* ts) {
  PyObject* d = PyDict_New();
  if (d == nullptr) return nullptr;
  if (PyDict_SetItem(d, s_type, etype) < 0 ||
      PyDict_SetItem(d, s_kind, kind) < 0 ||
      PyDict_SetItem(d, s_obj, obj) < 0 ||
      PyDict_SetItem(d, s_resource_version, rv) < 0 ||
      PyDict_SetItem(d, s_prev, prev) < 0 ||
      PyDict_SetItem(d, s_lazy, lazy) < 0 ||
      PyDict_SetItem(d, s_commit_ts, ts) < 0) {
    Py_DECREF(d);
    return nullptr;
  }
  PyTypeObject* tp = (PyTypeObject*)g_event_type;
  PyObject* ev = tp->tp_alloc(tp, 0);
  if (ev == nullptr) {
    Py_DECREF(d);
    return nullptr;
  }
  PyObject** ddp = inst_dict_ptr(ev);
  if (ddp == nullptr) {
    Py_DECREF(d);
    Py_DECREF(ev);
    PyErr_SetString(PyExc_TypeError, "hostcommit: Event has no __dict__");
    return nullptr;
  }
  Py_XSETREF(*ddp, d);  // see shallow_copy: 3.11+ may pre-materialize
  return ev;
}

// set clone.spec.node_name (clone's spec is private, plain dict write)
int set_node_name(PyObject* pod, PyObject* node_name) {
  PyObject* own = nullptr;
  PyObject* spec = fast_attr(pod, s_spec, &own);
  if (spec == nullptr) return -1;
  PyObject** sdp = inst_dict_ptr(spec);
  int rc;
  if (sdp != nullptr && *sdp != nullptr)
    rc = PyDict_SetItem(*sdp, s_node_name, node_name);
  else
    rc = PyObject_SetAttr(spec, s_node_name, node_name);
  Py_XDECREF(own);
  return rc;
}

// pod.key with the property's memo semantics (the property call on a miss
// computes AND caches — parity by construction)
PyObject* pod_key(PyObject* pod) {  // new ref
  PyObject** dp = inst_dict_ptr(pod);
  if (dp != nullptr && *dp != nullptr) {
    PyObject* k = PyDict_GetItemWithError(*dp, s_key_cache);
    if (k != nullptr) {
      Py_INCREF(k);
      return k;
    }
    if (PyErr_Occurred()) return nullptr;
  }
  return PyObject_GetAttr(pod, s_key);
}

int append_error(PyObject* errors, PyObject* key, PyObject* msg_owned) {
  if (msg_owned == nullptr) return -1;
  PyObject* t = PyTuple_Pack(2, key, msg_owned);
  Py_DECREF(msg_owned);
  if (t == nullptr) return -1;
  int rc = PyList_Append(errors, t);
  Py_DECREF(t);
  return rc;
}

int ensure_ready() {
  if (!g_ready) {
    PyErr_SetString(PyExc_RuntimeError, "hostcommit: hc_init not called");
    return -1;
  }
  return 0;
}

// Unpack one entry that is USUALLY a tuple but — like the Python oracles'
// `for a, b in pairs` — may be any sequence of the right arity. Fills out[]
// with refs borrowed from the entry (tuple fast path, *owned NULL) or from
// *owned (caller must Py_XDECREF it when done with the values). A
// wrong-arity entry raises, matching the oracle's unpack ValueError.
int unpack_entry(PyObject* item, Py_ssize_t want, PyObject** out,
                 PyObject** owned, const char* what) {
  *owned = nullptr;
  if (PyTuple_Check(item) && PyTuple_GET_SIZE(item) == want) {
    for (Py_ssize_t i = 0; i < want; ++i) out[i] = PyTuple_GET_ITEM(item, i);
    return 0;
  }
  PyObject* f = PySequence_Fast(item, what);
  if (f == nullptr) return -1;
  if (PySequence_Fast_GET_SIZE(f) != want) {
    Py_DECREF(f);
    PyErr_SetString(PyExc_ValueError, what);
    return -1;
  }
  PyObject** its = PySequence_Fast_ITEMS(f);
  for (Py_ssize_t i = 0; i < want; ++i) out[i] = its[i];
  *owned = f;
  return 0;
}

}  // namespace

extern "C" {

// One-time setup: type references + interned strings. Called by the loader
// (kubernetes_tpu/native/hostcommit.py) under its module lock.
PyObject* hc_init(PyObject* event_type, PyObject* podinfo_type,
                  PyObject* nodeinfo_type) {
  if (!g_ready) {
#define INTERN(var, lit)                     \
  var = PyUnicode_InternFromString(lit);     \
  if (var == nullptr) return nullptr
    INTERN(s_metadata, "metadata");
    INTERN(s_spec, "spec");
    INTERN(s_status, "status");
    INTERN(s_node_name, "node_name");
    INTERN(s_resource_version, "resource_version");
    INTERN(s_labels, "labels");
    INTERN(s_annotations, "annotations");
    INTERN(s_owner_references, "owner_references");
    INTERN(s_finalizers, "finalizers");
    INTERN(s_conditions, "conditions");
    INTERN(s_type, "type");
    INTERN(s_kind, "kind");
    INTERN(s_obj, "obj");
    INTERN(s_prev, "prev");
    INTERN(s_lazy, "lazy");
    INTERN(s_commit_ts, "commit_ts");
    INTERN(s_key, "key");
    INTERN(s_key_cache, "_key_cache");
    INTERN(s_req_cache, "_req_cache");
    INTERN(s_class_sig, "_class_sig");
    INTERN(s_req_sig, "_req_sig");
    INTERN(s_pods, "pods");
    INTERN(s_pods_with_affinity, "pods_with_affinity");
    INTERN(s_pods_with_req_anti, "pods_with_required_anti_affinity");
    INTERN(s_affinity, "affinity");
    INTERN(s_pod_aff_req, "pod_affinity_required");
    INTERN(s_pod_anti_req, "pod_anti_affinity_required");
    INTERN(s_pod_aff_pref, "pod_affinity_preferred");
    INTERN(s_pod_anti_pref, "pod_anti_affinity_preferred");
    INTERN(s_slot_pod, "pod");
    INTERN(s_slot_request, "request");
    INTERN(s_slot_nz_request, "non_zero_request");
    INTERN(s_slot_req_aff, "required_affinity_terms");
    INTERN(s_slot_req_anti, "required_anti_affinity_terms");
    INTERN(s_slot_pref_aff, "preferred_affinity_terms");
    INTERN(s_slot_pref_anti, "preferred_anti_affinity_terms");
    INTERN(s_kind_pods, "pods");
#undef INTERN
    g_empty_tuple = PyTuple_New(0);
    if (g_empty_tuple == nullptr) return nullptr;
    g_zero_float = PyFloat_FromDouble(0.0);
    if (g_zero_float == nullptr) return nullptr;
  }
  Py_XDECREF(g_event_type);
  Py_XDECREF(g_podinfo_type);
  Py_XDECREF(g_nodeinfo_type);
  Py_INCREF(event_type);
  Py_INCREF(podinfo_type);
  Py_INCREF(nodeinfo_type);
  g_event_type = event_type;
  g_podinfo_type = podinfo_type;
  g_nodeinfo_type = nodeinfo_type;
  g_ready = true;
  Py_RETURN_NONE;
}

// bind_many phase 1 (validate + clone, caller holds the pods shard):
// bindings = iterable of (namespace, name, node_name); appends
// (key, old stored pod, new clone, node_name) to `prepared` and
// (key, message) to `errors`. Returns None.
PyObject* hc_bind_prepare(PyObject* pods, PyObject* bindings,
                          PyObject* prepared, PyObject* errors) {
  if (ensure_ready() < 0) return nullptr;
  PyObject* fast = PySequence_Fast(bindings, "bindings must be iterable");
  if (fast == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject** items = PySequence_Fast_ITEMS(fast);
  // non-tuple entries' values borrow from this slot (unpack_entry); cleared
  // at every iteration boundary, released once more on the fail path
  PyObject* trip_owned = nullptr;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* trip[3];
    if (unpack_entry(items[i], 3, trip, &trip_owned,
                     "bindings must be (namespace, name, node) triples") < 0)
      goto fail;
    {
      PyObject* ns = trip[0];
      PyObject* name = trip[1];
      PyObject* node = trip[2];
      PyObject* key = PyUnicode_FromFormat("%S/%S", ns, name);
      if (key == nullptr) goto fail;
      PyObject* pod = PyDict_GetItemWithError(pods, key);
      if (pod == nullptr) {
        if (PyErr_Occurred()) {
          Py_DECREF(key);
          goto fail;
        }
        if (append_error(errors, key,
                         PyUnicode_FromFormat("pods %U not found", key)) < 0) {
          Py_DECREF(key);
          goto fail;
        }
        Py_DECREF(key);
        Py_CLEAR(trip_owned);
        continue;
      }
      PyObject* own = nullptr;
      PyObject* spec = fast_attr(pod, s_spec, &own);
      if (spec == nullptr) {
        Py_DECREF(key);
        goto fail;
      }
      PyObject* own2 = nullptr;
      PyObject* cur_node = fast_attr(spec, s_node_name, &own2);
      if (cur_node == nullptr) {
        Py_XDECREF(own);
        Py_DECREF(key);
        goto fail;
      }
      int bound = PyObject_IsTrue(cur_node);
      if (bound < 0) {
        Py_XDECREF(own2);
        Py_XDECREF(own);
        Py_DECREF(key);
        goto fail;
      }
      if (bound) {
        int rc = append_error(
            errors, key,
            PyUnicode_FromFormat("pod %U is already bound to %S", key,
                                 cur_node));
        Py_XDECREF(own2);
        Py_XDECREF(own);
        Py_DECREF(key);
        if (rc < 0) goto fail;
        Py_CLEAR(trip_owned);
        continue;
      }
      Py_XDECREF(own2);
      Py_XDECREF(own);
      PyObject* neu = bind_clone(pod);
      if (neu == nullptr) {
        Py_DECREF(key);
        goto fail;
      }
      if (set_node_name(neu, node) < 0) {
        Py_DECREF(neu);
        Py_DECREF(key);
        goto fail;
      }
      PyObject* entry = PyTuple_Pack(4, key, pod, neu, node);
      Py_DECREF(neu);
      Py_DECREF(key);
      if (entry == nullptr) goto fail;
      int rc = PyList_Append(prepared, entry);
      Py_DECREF(entry);
      if (rc < 0) goto fail;
    }
    Py_CLEAR(trip_owned);
  }
  Py_DECREF(fast);
  Py_RETURN_NONE;
fail:
  Py_XDECREF(trip_owned);
  Py_DECREF(fast);
  return nullptr;
}

// Columnar bind_many phase 1 (ISSUE 15; caller holds the pods shard):
// validate each (namespace, name, node) triple against the COLUMN ARRAYS —
// key2row lookup + node_id[row] bound check — and intern the node names,
// with NO clone and no object walk. Outputs: rows_out/ids_out (int32,
// caller-allocated at len(bindings); the first `count` entries are valid),
// keys_out (list, one key string per accepted entry), errors (list of
// (key, message), byte-identical to the Python loop in
// store/columnar.py PodColumns.bind_prepare). Returns count.
PyObject* hc_columnar_prepare(PyObject* key2row, PyObject* bindings,
                              PyObject* node_ids, PyObject* node_names,
                              PyObject* errors, PyObject* keys_out,
                              int32_t* node_id_col, int32_t* rows_out,
                              int32_t* ids_out) {
  if (ensure_ready() < 0) return nullptr;
  PyObject* fast = PySequence_Fast(bindings, "bindings must be iterable");
  if (fast == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject** items = PySequence_Fast_ITEMS(fast);
  PyObject* trip_owned = nullptr;
  long count = 0;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* trip[3];
    if (unpack_entry(items[i], 3, trip, &trip_owned,
                     "bindings must be (namespace, name, node) triples") < 0)
      goto fail;
    {
      PyObject* key = PyUnicode_FromFormat("%S/%S", trip[0], trip[1]);
      if (key == nullptr) goto fail;
      PyObject* row_obj = PyDict_GetItemWithError(key2row, key);
      if (row_obj == nullptr) {
        if (PyErr_Occurred()) {
          Py_DECREF(key);
          goto fail;
        }
        if (append_error(errors, key,
                         PyUnicode_FromFormat("pods %U not found", key)) < 0) {
          Py_DECREF(key);
          goto fail;
        }
        Py_DECREF(key);
        Py_CLEAR(trip_owned);
        continue;
      }
      long row = PyLong_AsLong(row_obj);
      if (row == -1 && PyErr_Occurred()) {
        Py_DECREF(key);
        goto fail;
      }
      int32_t cur = node_id_col[row];
      if (cur >= 0) {
        PyObject* cur_name = PyList_GetItem(node_names, (Py_ssize_t)cur);
        if (cur_name == nullptr) {
          Py_DECREF(key);
          goto fail;
        }
        int rc = append_error(
            errors, key,
            PyUnicode_FromFormat("pod %U is already bound to %S", key,
                                 cur_name));
        Py_DECREF(key);
        if (rc < 0) goto fail;
        Py_CLEAR(trip_owned);
        continue;
      }
      PyObject* node = trip[2];
      long nid;
      PyObject* nid_obj = PyDict_GetItemWithError(node_ids, node);
      if (nid_obj == nullptr) {
        if (PyErr_Occurred()) {
          Py_DECREF(key);
          goto fail;
        }
        nid = (long)PyList_GET_SIZE(node_names);
        PyObject* nid_new = PyLong_FromLong(nid);
        if (nid_new == nullptr) {
          Py_DECREF(key);
          goto fail;
        }
        // append BEFORE the dict insert: if the second step fails, the
        // shared intern table holds only a harmless orphan list entry —
        // the reverse order would leave a dict id past the table's end,
        // and a LATER bind of this node name would index out of range
        int rc = PyList_Append(node_names, node);
        if (rc == 0) rc = PyDict_SetItem(node_ids, node, nid_new);
        Py_DECREF(nid_new);
        if (rc < 0) {
          Py_DECREF(key);
          goto fail;
        }
      } else {
        nid = PyLong_AsLong(nid_obj);
        if (nid == -1 && PyErr_Occurred()) {
          Py_DECREF(key);
          goto fail;
        }
      }
      int rc = PyList_Append(keys_out, key);
      Py_DECREF(key);
      if (rc < 0) goto fail;
      rows_out[count] = (int32_t)row;
      ids_out[count] = (int32_t)nid;
      count += 1;
    }
    Py_CLEAR(trip_owned);
  }
  Py_DECREF(fast);
  return PyLong_FromLong(count);
fail:
  Py_XDECREF(trip_owned);
  Py_DECREF(fast);
  return nullptr;
}

// bind_many phase 2 (commit, caller holds global + shard): stamps a
// contiguous RV range, swaps rows, builds one event per bind. mode: 0 =
// share (store without isolation copies), 1 = lazy (event shares the stored
// object, lazy slot [None, cloner]), 2 = eager (event carries its own
// clone). Returns (final_rv, bound_count).
PyObject* hc_bind_commit(PyObject* pods, PyObject* prepared, PyObject* events,
                         PyObject* errors, long rv0, int mode,
                         PyObject* ts_obj, PyObject* cloner,
                         PyObject* etype) {
  if (ensure_ready() < 0) return nullptr;
  long rv = rv0;
  long bound = 0;
  Py_ssize_t n = PyList_GET_SIZE(prepared);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* entry = PyList_GET_ITEM(prepared, i);
    PyObject* key = PyTuple_GET_ITEM(entry, 0);
    PyObject* old = PyTuple_GET_ITEM(entry, 1);
    PyObject* neu = PyTuple_GET_ITEM(entry, 2);  // borrowed unless raced
    PyObject* node = PyTuple_GET_ITEM(entry, 3);
    PyObject* neu_owned = nullptr;
    PyObject* old_owned = nullptr;  // strong ref for the raced branch: the
    // row swap below drops the dict's (possibly sole) reference to cur,
    // and the event's prev must outlive it — same reason hc_delete_commit
    // INCREFs old (the Python oracle holds `old` in a strong local)
    PyObject* cur = PyDict_GetItemWithError(pods, key);
    if (cur == nullptr && PyErr_Occurred()) return nullptr;
    if (cur != old) {
      // raced between the phases: re-validate against the current row
      if (cur == nullptr) {
        if (append_error(errors, key,
                         PyUnicode_FromFormat("pods %U not found", key)) < 0)
          return nullptr;
        continue;
      }
      PyObject* own = nullptr;
      PyObject* spec = fast_attr(cur, s_spec, &own);
      if (spec == nullptr) return nullptr;
      PyObject* own2 = nullptr;
      PyObject* cur_node = fast_attr(spec, s_node_name, &own2);
      if (cur_node == nullptr) {
        Py_XDECREF(own);
        return nullptr;
      }
      int is_bound = PyObject_IsTrue(cur_node);
      if (is_bound < 0) {
        Py_XDECREF(own2);
        Py_XDECREF(own);
        return nullptr;
      }
      if (is_bound) {
        int rc = append_error(
            errors, key,
            PyUnicode_FromFormat("pod %U is already bound to %S", key,
                                 cur_node));
        Py_XDECREF(own2);
        Py_XDECREF(own);
        if (rc < 0) return nullptr;
        continue;
      }
      Py_XDECREF(own2);
      Py_XDECREF(own);
      Py_INCREF(cur);
      old_owned = cur;
      old = cur;
      neu_owned = bind_clone(cur);
      if (neu_owned == nullptr) {
        Py_DECREF(old_owned);
        return nullptr;
      }
      if (set_node_name(neu_owned, node) < 0) {
        Py_DECREF(neu_owned);
        Py_DECREF(old_owned);
        return nullptr;
      }
      neu = neu_owned;
    }
    rv += 1;
    PyObject* rv_obj = PyLong_FromLong(rv);
    if (rv_obj == nullptr) {
      Py_XDECREF(neu_owned);
      Py_XDECREF(old_owned);
      return nullptr;
    }
    // neu.metadata.resource_version = rv (metadata is the private clone)
    {
      PyObject* own = nullptr;
      PyObject* meta = fast_attr(neu, s_metadata, &own);
      if (meta == nullptr) {
        Py_DECREF(rv_obj);
        Py_XDECREF(neu_owned);
        Py_XDECREF(old_owned);
        return nullptr;
      }
      PyObject** mdp = inst_dict_ptr(meta);
      int rc = (mdp != nullptr && *mdp != nullptr)
                   ? PyDict_SetItem(*mdp, s_resource_version, rv_obj)
                   : PyObject_SetAttr(meta, s_resource_version, rv_obj);
      Py_XDECREF(own);
      if (rc < 0) {
        Py_DECREF(rv_obj);
        Py_XDECREF(neu_owned);
        Py_XDECREF(old_owned);
        return nullptr;
      }
    }
    if (PyDict_SetItem(pods, key, neu) < 0) {
      Py_DECREF(rv_obj);
      Py_XDECREF(neu_owned);
      Py_XDECREF(old_owned);
      return nullptr;
    }
    PyObject* ev = nullptr;
    if (mode == 1) {
      PyObject* lazy = PyList_New(2);
      if (lazy != nullptr) {
        Py_INCREF(Py_None);
        PyList_SET_ITEM(lazy, 0, Py_None);
        Py_INCREF(cloner);
        PyList_SET_ITEM(lazy, 1, cloner);
        ev = make_event(etype, s_kind_pods, neu, rv_obj, old, lazy, ts_obj);
        Py_DECREF(lazy);
      }
    } else if (mode == 2) {
      PyObject* evobj = bind_clone(neu);
      if (evobj != nullptr) {
        ev = make_event(etype, s_kind_pods, evobj, rv_obj, old, Py_None,
                        ts_obj);
        Py_DECREF(evobj);
      }
    } else {
      ev = make_event(etype, s_kind_pods, neu, rv_obj, old, Py_None, ts_obj);
    }
    Py_DECREF(rv_obj);
    Py_XDECREF(neu_owned);
    Py_XDECREF(old_owned);  // the event holds its own ref to prev now
    if (ev == nullptr) return nullptr;
    int rc = PyList_Append(events, ev);
    Py_DECREF(ev);
    if (rc < 0) return nullptr;
    bound += 1;
  }
  return Py_BuildValue("ll", rv, bound);
}

// Batched pod delete commit (caller holds global + shard): ONE structural
// clone per pod stamped at its post-delete RV, DELETED events in the same
// lazy/eager/share modes as bind. BUILD-THEN-POP: every clone and event is
// constructed BEFORE any row is removed, so a mid-batch failure (clone
// error, OOM) leaves the store untouched — no popped-but-never-narrated
// pods. A duplicate key in one batch errors like the pop it replaces
// ("not found" on the second occurrence). Returns (final_rv, deleted).
PyObject* hc_delete_commit(PyObject* pods, PyObject* keys, PyObject* events,
                           PyObject* errors, long rv0, int mode,
                           PyObject* ts_obj, PyObject* cloner,
                           PyObject* etype) {
  if (ensure_ready() < 0) return nullptr;
  PyObject* fast = PySequence_Fast(keys, "keys must be iterable");
  if (fast == nullptr) return nullptr;
  PyObject* found = PyList_New(0);  // keys to pop, in order
  if (found == nullptr) {
    Py_DECREF(fast);
    return nullptr;
  }
  PyObject* seen = PySet_New(nullptr);  // dup keys behave like the old pop
  if (seen == nullptr) {
    Py_DECREF(found);
    Py_DECREF(fast);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject** items = PySequence_Fast_ITEMS(fast);
  long rv = rv0;
  long deleted = 0;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* key = items[i];
    int dup = PySet_Contains(seen, key);
    if (dup < 0) goto fail;
    PyObject* old = dup ? nullptr : PyDict_GetItemWithError(pods, key);
    if (old == nullptr) {
      if (PyErr_Occurred()) goto fail;
      if (append_error(errors, key,
                       PyUnicode_FromFormat("pods %S not found", key)) < 0)
        goto fail;
      continue;
    }
    Py_INCREF(old);  // keep alive across the later row removal
    if (PySet_Add(seen, key) < 0 || PyList_Append(found, key) < 0) {
      Py_DECREF(old);
      goto fail;
    }
    rv += 1;
    {
      PyObject* obj;  // the stamped post-delete object
      if (mode == 0) {
        obj = old;
        Py_INCREF(obj);
      } else {
        obj = structural_clone(old);
      }
      if (obj == nullptr) {
        Py_DECREF(old);
        goto fail;
      }
      PyObject* rv_obj = PyLong_FromLong(rv);
      if (rv_obj == nullptr) {
        Py_DECREF(obj);
        Py_DECREF(old);
        goto fail;
      }
      PyObject* own = nullptr;
      PyObject* meta = fast_attr(obj, s_metadata, &own);
      int rc = -1;
      if (meta != nullptr) {
        PyObject** mdp = inst_dict_ptr(meta);
        rc = (mdp != nullptr && *mdp != nullptr)
                 ? PyDict_SetItem(*mdp, s_resource_version, rv_obj)
                 : PyObject_SetAttr(meta, s_resource_version, rv_obj);
      }
      Py_XDECREF(own);
      if (rc < 0) {
        Py_DECREF(rv_obj);
        Py_DECREF(obj);
        Py_DECREF(old);
        goto fail;
      }
      PyObject* ev = nullptr;
      if (mode == 1) {
        PyObject* lazy = PyList_New(2);
        if (lazy != nullptr) {
          Py_INCREF(Py_None);
          PyList_SET_ITEM(lazy, 0, Py_None);
          Py_INCREF(cloner);
          PyList_SET_ITEM(lazy, 1, cloner);
          ev = make_event(etype, s_kind_pods, obj, rv_obj, old, lazy, ts_obj);
          Py_DECREF(lazy);
        }
      } else if (mode == 2) {
        PyObject* evobj = structural_clone(obj);
        if (evobj != nullptr) {
          ev = make_event(etype, s_kind_pods, evobj, rv_obj, old, Py_None,
                          ts_obj);
          Py_DECREF(evobj);
        }
      } else {
        ev = make_event(etype, s_kind_pods, obj, rv_obj, old, Py_None, ts_obj);
      }
      Py_DECREF(rv_obj);
      Py_DECREF(obj);
      Py_DECREF(old);
      if (ev == nullptr) goto fail;
      rc = PyList_Append(events, ev);
      Py_DECREF(ev);
      if (rc < 0) goto fail;
      deleted += 1;
    }
  }
  // pop phase: everything narratable was built — removals cannot fail for
  // keys we just read under the lock the caller still holds
  for (Py_ssize_t i = 0; i < PyList_GET_SIZE(found); ++i) {
    if (PyDict_DelItem(pods, PyList_GET_ITEM(found, i)) < 0) goto fail;
  }
  Py_DECREF(seen);
  Py_DECREF(found);
  Py_DECREF(fast);
  return Py_BuildValue("ll", rv, deleted);
fail:
  Py_DECREF(seen);
  Py_DECREF(found);
  Py_DECREF(fast);
  return nullptr;
}

// Cache.assume_pods_structural's per-pod loop (caller holds the cache lock,
// check_ports=False form): pairs = [(pod, node_name)]. Mutates pod_nodes /
// assumed / nodes exactly like the Python loop; appends (index, message) to
// `failed`. Returns None.
PyObject* hc_assume_structural(PyObject* pairs, PyObject* pod_nodes,
                               PyObject* assumed, PyObject* nodes,
                               PyObject* failed) {
  if (ensure_ready() < 0) return nullptr;
  PyObject* fast = PySequence_Fast(pairs, "pairs must be iterable");
  if (fast == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject** items = PySequence_Fast_ITEMS(fast);
  // non-tuple entries' values borrow from this slot (unpack_entry; the
  // Python oracle's `for pod, node_name in pairs` unpacks any 2-sequence);
  // cleared at every iteration boundary, released once more on fail
  PyObject* pair_owned = nullptr;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* pr[2];
    if (unpack_entry(items[i], 2, pr, &pair_owned,
                     "pairs must be (pod, node_name) entries") < 0)
      goto fail;
    PyObject* pod = pr[0];
    PyObject* node_name = pr[1];
    PyObject* key = pod_key(pod);
    if (key == nullptr) goto fail;
    int has = PyDict_Contains(pod_nodes, key);
    if (has < 0) {
      Py_DECREF(key);
      goto fail;
    }
    if (has) {
      PyObject* msg =
          PyUnicode_FromFormat("pod %U is already in the cache", key);
      Py_DECREF(key);
      if (msg == nullptr) goto fail;
      PyObject* idx = PyLong_FromSsize_t(i);
      if (idx == nullptr) {
        Py_DECREF(msg);
        goto fail;
      }
      PyObject* t = PyTuple_Pack(2, idx, msg);
      Py_DECREF(idx);
      Py_DECREF(msg);
      if (t == nullptr) goto fail;
      int rc = PyList_Append(failed, t);
      Py_DECREF(t);
      if (rc < 0) goto fail;
      Py_CLEAR(pair_owned);
      continue;
    }
    if (set_node_name(pod, node_name) < 0) {
      Py_DECREF(key);
      goto fail;
    }
    PyObject* ni = PyDict_GetItemWithError(nodes, node_name);
    if (ni == nullptr) {
      if (PyErr_Occurred()) {
        Py_DECREF(key);
        goto fail;
      }
      PyObject* ni_new = PyObject_CallNoArgs(g_nodeinfo_type);
      if (ni_new == nullptr) {
        Py_DECREF(key);
        goto fail;
      }
      if (PyDict_SetItem(nodes, node_name, ni_new) < 0) {
        Py_DECREF(ni_new);
        Py_DECREF(key);
        goto fail;
      }
      Py_DECREF(ni_new);
      ni = PyDict_GetItemWithError(nodes, node_name);
      if (ni == nullptr) {
        Py_DECREF(key);
        goto fail;
      }
    }
    // PodInfo(pod), fast path when the request pair is memoized (the
    // tensorizer seeds it); cold pods take the Python constructor
    PyObject* pi = nullptr;
    int any_aff = 0;
    int req_anti = 0;
    {
      PyObject** pdp = inst_dict_ptr(pod);
      PyObject* cached = (pdp != nullptr && *pdp != nullptr)
                             ? PyDict_GetItemWithError(*pdp, s_req_cache)
                             : nullptr;
      if (cached == nullptr && PyErr_Occurred()) {
        Py_DECREF(key);
        goto fail;
      }
      if (cached == nullptr || !PyTuple_Check(cached) ||
          PyTuple_GET_SIZE(cached) != 2) {
        pi = PyObject_CallOneArg(g_podinfo_type, pod);
        if (pi == nullptr) {
          Py_DECREF(key);
          goto fail;
        }
        PyObject* t1 = PyObject_GetAttr(pi, s_slot_req_aff);
        PyObject* t2 = PyObject_GetAttr(pi, s_slot_req_anti);
        PyObject* t3 = PyObject_GetAttr(pi, s_slot_pref_aff);
        PyObject* t4 = PyObject_GetAttr(pi, s_slot_pref_anti);
        if (t1 == nullptr || t2 == nullptr || t3 == nullptr || t4 == nullptr) {
          Py_XDECREF(t1);
          Py_XDECREF(t2);
          Py_XDECREF(t3);
          Py_XDECREF(t4);
          Py_DECREF(pi);
          Py_DECREF(key);
          goto fail;
        }
        req_anti = PyObject_IsTrue(t2);
        any_aff = (PyObject_IsTrue(t1) || req_anti || PyObject_IsTrue(t3) ||
                   PyObject_IsTrue(t4));
        Py_DECREF(t1);
        Py_DECREF(t2);
        Py_DECREF(t3);
        Py_DECREF(t4);
      } else {
        PyTypeObject* tp = (PyTypeObject*)g_podinfo_type;
        pi = tp->tp_alloc(tp, 0);
        if (pi == nullptr) {
          Py_DECREF(key);
          goto fail;
        }
        if (PyObject_SetAttr(pi, s_slot_pod, pod) < 0 ||
            PyObject_SetAttr(pi, s_slot_request,
                             PyTuple_GET_ITEM(cached, 0)) < 0 ||
            PyObject_SetAttr(pi, s_slot_nz_request,
                             PyTuple_GET_ITEM(cached, 1)) < 0) {
          Py_DECREF(pi);
          Py_DECREF(key);
          goto fail;
        }
        PyObject* own = nullptr;
        PyObject* spec = fast_attr(pod, s_spec, &own);
        if (spec == nullptr) {
          Py_DECREF(pi);
          Py_DECREF(key);
          goto fail;
        }
        PyObject* own2 = nullptr;
        PyObject* aff = fast_attr(spec, s_affinity, &own2);
        Py_XDECREF(own);
        if (aff == nullptr) {
          Py_DECREF(pi);
          Py_DECREF(key);
          goto fail;
        }
        int truthy = (aff == Py_None) ? 0 : PyObject_IsTrue(aff);
        if (truthy < 0) {
          Py_XDECREF(own2);
          Py_DECREF(pi);
          Py_DECREF(key);
          goto fail;
        }
        if (!truthy) {
          if (PyObject_SetAttr(pi, s_slot_req_aff, g_empty_tuple) < 0 ||
              PyObject_SetAttr(pi, s_slot_req_anti, g_empty_tuple) < 0 ||
              PyObject_SetAttr(pi, s_slot_pref_aff, g_empty_tuple) < 0 ||
              PyObject_SetAttr(pi, s_slot_pref_anti, g_empty_tuple) < 0) {
            Py_XDECREF(own2);
            Py_DECREF(pi);
            Py_DECREF(key);
            goto fail;
          }
        } else {
          static PyObject** srcs[4] = {&s_pod_aff_req, &s_pod_anti_req,
                                       &s_pod_aff_pref, &s_pod_anti_pref};
          static PyObject** dsts[4] = {&s_slot_req_aff, &s_slot_req_anti,
                                       &s_slot_pref_aff, &s_slot_pref_anti};
          for (int j = 0; j < 4; ++j) {
            PyObject* src = PyObject_GetAttr(aff, *srcs[j]);
            if (src == nullptr) {
              Py_XDECREF(own2);
              Py_DECREF(pi);
              Py_DECREF(key);
              goto fail;
            }
            PyObject* t = PySequence_Tuple(src);
            Py_DECREF(src);
            if (t == nullptr) {
              Py_XDECREF(own2);
              Py_DECREF(pi);
              Py_DECREF(key);
              goto fail;
            }
            int truth = PyTuple_GET_SIZE(t) > 0;
            if (truth) any_aff = 1;
            if (j == 1 && truth) req_anti = 1;
            int rc = PyObject_SetAttr(pi, *dsts[j], t);
            Py_DECREF(t);
            if (rc < 0) {
              Py_XDECREF(own2);
              Py_DECREF(pi);
              Py_DECREF(key);
              goto fail;
            }
          }
        }
        Py_XDECREF(own2);
      }
    }
    // ni.pods.append(pi) (+ affinity sublists)
    {
      PyObject* lst = PyObject_GetAttr(ni, s_pods);
      if (lst == nullptr) {
        Py_DECREF(pi);
        Py_DECREF(key);
        goto fail;
      }
      int rc = PyList_Append(lst, pi);
      Py_DECREF(lst);
      if (rc == 0 && any_aff) {
        lst = PyObject_GetAttr(ni, s_pods_with_affinity);
        if (lst == nullptr)
          rc = -1;
        else {
          rc = PyList_Append(lst, pi);
          Py_DECREF(lst);
        }
        if (rc == 0 && req_anti) {
          lst = PyObject_GetAttr(ni, s_pods_with_req_anti);
          if (lst == nullptr)
            rc = -1;
          else {
            rc = PyList_Append(lst, pi);
            Py_DECREF(lst);
          }
        }
      }
      Py_DECREF(pi);
      if (rc < 0) {
        Py_DECREF(key);
        goto fail;
      }
    }
    if (PyDict_SetItem(pod_nodes, key, node_name) < 0 ||
        PyDict_SetItem(assumed, key, g_zero_float) < 0) {
      Py_DECREF(key);
      goto fail;
    }
    Py_DECREF(key);
    Py_CLEAR(pair_owned);
  }
  Py_DECREF(fast);
  Py_RETURN_NONE;
fail:
  Py_XDECREF(pair_owned);
  Py_DECREF(fast);
  return nullptr;
}

// build_pod_batch's fused per-pod loop (class signature + request-memo row):
// fills class_rows / entry_rows (int32[P], caller-allocated). Misses call
// back into the Python helpers (sig_cb = pod_class_signature, entry_cb =
// the batch-local _req_entry row closure) which own the memoization.
PyObject* hc_batch_rows(PyObject* pods, PyObject* sig_to_class,
                        PyObject* rep_pods, PyObject* req_cache,
                        PyObject* sig_cb, PyObject* entry_cb,
                        int32_t* class_rows, int32_t* entry_rows) {
  if (ensure_ready() < 0) return nullptr;
  PyObject* fast = PySequence_Fast(pods, "pods must be iterable");
  if (fast == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject** items = PySequence_Fast_ITEMS(fast);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* pod = items[i];
    PyObject** pdp = inst_dict_ptr(pod);
    if (pdp == nullptr || *pdp == nullptr) {
      PyErr_SetString(PyExc_TypeError, "pod without instance __dict__");
      goto fail;
    }
    PyObject* pdict = *pdp;
    PyObject* spec = PyDict_GetItemWithError(pdict, s_spec);
    if (spec == nullptr) {
      if (PyErr_Occurred()) goto fail;
      spec = Py_None;  // forces the memo miss path below
    }
    // ---- class signature (memo: (spec, labels, sig), identity-keyed) ----
    PyObject* sig = nullptr;
    PyObject* sig_own = nullptr;
    {
      PyObject* cs = PyDict_GetItemWithError(pdict, s_class_sig);
      if (cs == nullptr && PyErr_Occurred()) goto fail;
      if (cs != nullptr && PyTuple_Check(cs) && PyTuple_GET_SIZE(cs) == 3 &&
          PyTuple_GET_ITEM(cs, 0) == spec) {
        PyObject* meta = PyDict_GetItemWithError(pdict, s_metadata);
        if (meta == nullptr && PyErr_Occurred()) goto fail;
        PyObject* labels = nullptr;
        if (meta != nullptr) {
          PyObject** mdp = inst_dict_ptr(meta);
          if (mdp != nullptr && *mdp != nullptr) {
            labels = PyDict_GetItemWithError(*mdp, s_labels);
            if (labels == nullptr && PyErr_Occurred()) goto fail;
          }
        }
        if (labels != nullptr && PyTuple_GET_ITEM(cs, 1) == labels)
          sig = PyTuple_GET_ITEM(cs, 2);
      }
      if (sig == nullptr) {
        sig_own = PyObject_CallOneArg(sig_cb, pod);
        if (sig_own == nullptr) goto fail;
        sig = sig_own;
      }
    }
    {
      PyObject* ci_obj = PyDict_GetItemWithError(sig_to_class, sig);
      if (ci_obj == nullptr && PyErr_Occurred()) {
        Py_XDECREF(sig_own);
        goto fail;
      }
      long ci;
      if (ci_obj == nullptr) {
        ci = (long)PyList_GET_SIZE(rep_pods);
        PyObject* ci_new = PyLong_FromLong(ci);
        if (ci_new == nullptr) {
          Py_XDECREF(sig_own);
          goto fail;
        }
        int rc = PyDict_SetItem(sig_to_class, sig, ci_new);
        Py_DECREF(ci_new);
        if (rc < 0 || PyList_Append(rep_pods, pod) < 0) {
          Py_XDECREF(sig_own);
          goto fail;
        }
      } else {
        ci = PyLong_AsLong(ci_obj);
        if (ci == -1 && PyErr_Occurred()) {
          Py_XDECREF(sig_own);
          goto fail;
        }
      }
      class_rows[i] = (int32_t)ci;
      Py_XDECREF(sig_own);
    }
    // ---- request-memo row (memo: (spec, sig), identity-keyed) ----
    {
      long entry = -1;
      PyObject* rs = PyDict_GetItemWithError(pdict, s_req_sig);
      if (rs == nullptr && PyErr_Occurred()) goto fail;
      if (rs != nullptr && PyTuple_Check(rs) && PyTuple_GET_SIZE(rs) == 2 &&
          PyTuple_GET_ITEM(rs, 0) == spec) {
        PyObject* got =
            PyDict_GetItemWithError(req_cache, PyTuple_GET_ITEM(rs, 1));
        if (got == nullptr && PyErr_Occurred()) goto fail;
        if (got != nullptr) {
          entry = PyLong_AsLong(PyTuple_GET_ITEM(got, 0));
          if (entry == -1 && PyErr_Occurred()) goto fail;
          // seed the PodInfo request memo exactly like _req_entry does
          if (PyDict_SetDefault(pdict, s_req_cache,
                                PyTuple_GET_ITEM(got, 1)) == nullptr)
            goto fail;
        }
      }
      if (entry < 0) {
        PyObject* e = PyObject_CallOneArg(entry_cb, pod);
        if (e == nullptr) goto fail;
        entry = PyLong_AsLong(e);
        Py_DECREF(e);
        if (entry == -1 && PyErr_Occurred()) goto fail;
      }
      entry_rows[i] = (int32_t)entry;
    }
  }
  Py_DECREF(fast);
  Py_RETURN_NONE;
fail:
  Py_DECREF(fast);
  return nullptr;
}

}  // extern "C"
