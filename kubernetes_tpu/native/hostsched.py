"""ctypes loader + wrapper for the C++ host scheduler engine (hostsched.cpp).

Compiles the shared object on first use (g++ -O3, cached beside the source,
rebuilt when the source is newer) and exposes `native_greedy_solve`, which
matches ops/solver.py greedy_scan_solve's assignment semantics for batches
without topology-spread constraints (`native_solvable` checks that).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "hostsched.cpp")
_SO = os.path.join(_HERE, "_hostsched.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def build_so(src: str, so: str, python_include: bool = False) -> Optional[str]:
    """Compile src -> so if missing/stale (shared by this loader and the
    commit-engine loader in hostcommit.py). python_include adds the CPython
    headers for C-API translation units. Returns an error string or None."""
    try:
        if (os.path.exists(so)
                and os.path.getmtime(so) >= os.path.getmtime(src)):
            return None
        # per-process temp name: concurrent builds (pytest workers, daemon +
        # bench on a fresh checkout) must not interleave writes into one file
        tmp = f"{so}.tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src]
        if python_include:
            import sysconfig

            inc = sysconfig.get_paths().get("include")
            if not inc or not os.path.exists(os.path.join(inc, "Python.h")):
                return "Python.h not found (no CPython dev headers)"
            cmd.insert(1, f"-I{inc}")
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            return f"g++ failed: {proc.stderr[-500:]}"
        os.replace(tmp, so)  # atomic: a concurrent loader sees old or new
        return None
    except (OSError, subprocess.SubprocessError) as e:
        return str(e)


def _build() -> Optional[str]:
    return build_so(_SRC, _SO)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        err = _build()
        if err is not None:
            _build_error = err
            return None
        try:
            lib = ctypes.CDLL(_SO)
            i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
            lib.greedy_assign.restype = ctypes.c_int64
            lib.greedy_assign.argtypes = [
                i32p, i32p, i32p, i32p, i32p,  # alloc, used, used_nz, pod_count, max_pods
                u8p, i32p, u8p, i32p, i32p,  # static_ok, napref, has_napref, taint, img
                u8p, u8p,  # class_ports, node_ports
                i32p, i32p, i32p, u8p,  # class_of_pod, req, req_nz, bal_active
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                u8p, i32p,  # feas_buf, assignment
            ]
            lib.commit_deltas.restype = ctypes.c_int64
            lib.commit_deltas.argtypes = [
                i64p, i64p, ctypes.c_int64,  # rows, nodes, p
                i64p, i64p, ctypes.c_int64,  # raw_req, raw_req_nz, r
                ctypes.c_int64, ctypes.c_int64,  # p_all, n (bounds)
                i64p, i64p, i64p, u8p,  # d_used, d_used_nz, d_count, touched
            ]
        except (OSError, AttributeError) as e:
            # corrupt/incompatible .so: degrade, never raise from available()
            _build_error = f"load failed: {e}"
            return None
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def native_commit_deltas(rows, nodes, raw_req, raw_req_nz, n: int):
    """Fused columnar-assume scatter-add: one C pass over the solved batch
    computing (d_used [N,R] i64, d_used_nz [N,R] i64, d_count [N] i64,
    touched node indices, sorted). The ctypes CDLL call RELEASES the GIL for
    its duration — NEVER call this while holding a store or scheduler lock
    (schedlint LK002 enforces that; see store/store.py's lock-discipline
    note). Raises RuntimeError when the native library is unavailable."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native engine unavailable: {_build_error}")
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    nodes = np.ascontiguousarray(nodes, dtype=np.int64)
    raw_req = np.ascontiguousarray(raw_req, dtype=np.int64)
    raw_req_nz = np.ascontiguousarray(raw_req_nz, dtype=np.int64)
    r = raw_req.shape[1] if raw_req.ndim == 2 else 0
    d_used = np.zeros((n, r), dtype=np.int64)
    d_used_nz = np.zeros((n, r), dtype=np.int64)
    d_count = np.zeros(n, dtype=np.int64)
    touched = np.zeros(n, dtype=np.uint8)
    rc = lib.commit_deltas(rows, nodes, len(rows), raw_req, raw_req_nz, r,
                           len(raw_req), n, d_used, d_used_nz, d_count,
                           touched)
    if rc:
        # same failure surface as the np.add.at oracle: a catchable
        # IndexError the assume/dispatch failure-domain guard rolls back
        # (the kernel validates BEFORE writing, so the deltas are untouched)
        i = int(-rc - 1)
        raise IndexError(
            f"commit_deltas: entry {i} out of bounds "
            f"(node {int(nodes[i])} of {n}, row {int(rows[i])} of "
            f"{len(raw_req)})")
    return d_used, d_used_nz, d_count, np.nonzero(touched)[0]


def build_error() -> Optional[str]:
    _load()
    return _build_error


def native_solvable(batch) -> bool:
    """The native engine covers batches with no topology-spread constraints
    and no fallback-class pods (those carry semantics it does not model)."""
    return (batch.ct_class.size == 0 and batch.st_class.size == 0
            and not batch.fallback_class[batch.class_of_pod].any())


def native_greedy_solve(cluster, batch) -> Tuple[np.ndarray, int]:
    """Run the C++ engine on numpy ClusterTensors + PodBatchTensors.

    Returns (assignment[P] int32 with -1 for unschedulable, placed count).
    Raises RuntimeError when the native library is unavailable or the batch
    needs features the engine does not model (check native_solvable first).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native engine unavailable: {_build_error}")
    if not native_solvable(batch):
        raise RuntimeError("batch needs topology-spread/fallback semantics")
    t = batch.tables
    n = cluster.n
    p = batch.p
    r = len(cluster.resource_dims)
    used = np.ascontiguousarray(cluster.used, np.int32).copy()
    used_nz = np.ascontiguousarray(cluster.used_nz, np.int32).copy()
    pod_count = np.ascontiguousarray(cluster.pod_count, np.int32).copy()
    node_ports = np.ascontiguousarray(t.node_ports, np.uint8).copy()
    class_ports = np.ascontiguousarray(t.class_ports, np.uint8)
    pt = class_ports.shape[1] if class_ports.size else 0
    if pt == 0:
        class_ports = np.zeros((max(t.filter_ok.shape[0], 1), 1), np.uint8)
        node_ports = np.zeros((n, 1), np.uint8)
        pt = 0  # engine skips port checks when pt == 0
    assignment = np.full(p, -1, np.int32)
    feas_buf = np.zeros(n, np.uint8)
    placed = lib.greedy_assign(
        np.ascontiguousarray(cluster.alloc, np.int32), used, used_nz,
        pod_count, np.ascontiguousarray(cluster.max_pods, np.int32),
        np.ascontiguousarray(t.filter_ok, np.uint8),
        np.ascontiguousarray(t.napref_raw, np.int32),
        np.ascontiguousarray(t.has_napref, np.uint8),
        np.ascontiguousarray(t.taint_cnt, np.int32),
        np.ascontiguousarray(t.img_score, np.int32),
        class_ports, node_ports,
        np.ascontiguousarray(batch.class_of_pod, np.int32),
        np.ascontiguousarray(batch.req, np.int32),
        np.ascontiguousarray(batch.req_nz, np.int32),
        np.ascontiguousarray(batch.balanced_active, np.uint8),
        p, n, r, pt, feas_buf, assignment)
    return assignment, int(placed)
