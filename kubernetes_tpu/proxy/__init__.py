"""L7 — Service dataplane programming (reference: pkg/proxy)."""

from .proxier import (  # noqa: F401
    BoundedFrequencyRunner,
    FakeBackend,
    IptablesBackend,
    NftablesBackend,
    Proxier,
    RuleSet,
    ServicePortRule,
)
