"""kube-proxy equivalent: Services + EndpointSlices -> dataplane rules.

reference: pkg/proxy — ServiceChangeTracker/EndpointSliceCache feed a
full-state `syncProxyRules` (iptables/proxier.go:787, nftables/proxier.go:1166)
throttled by a BoundedFrequencyRunner (pkg/util/async). The proxier here
renders the same logical structure (per-service chains, per-endpoint DNAT
targets, uniform random balancing) through pluggable backends: an
iptables-save-style renderer, an nftables-style renderer, and a FakeBackend
for tests. No kernel is programmed — the rendered ruleset is the artifact, as
the reference's unit tests also assert on rendered rule text.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api.networking import EndpointSlice, Service
from ..controllers.base import Controller
from ..store import APIStore, NotFoundError
from ..utils import Clock


@dataclass(frozen=True)
class EndpointTarget:
    ip: str
    port: int
    node_name: str = ""


@dataclass
class ServicePortRule:
    """One (service, port) load-balancing rule."""

    namespace: str
    service: str
    port_name: str
    protocol: str
    cluster_ip: str
    port: int
    node_port: int
    endpoints: List[EndpointTarget] = field(default_factory=list)

    @property
    def chain_id(self) -> str:
        """Stable chain suffix (iptables/proxier.go servicePortChainName:
        first 16 chars of base32 sha256)."""
        h = hashlib.sha256(
            f"{self.namespace}/{self.service}:{self.port_name}".encode()).hexdigest()
        return h[:16].upper()


@dataclass
class RuleSet:
    rules: List[ServicePortRule] = field(default_factory=list)

    def by_service(self) -> Dict[str, List[ServicePortRule]]:
        out: Dict[str, List[ServicePortRule]] = {}
        for r in self.rules:
            out.setdefault(f"{r.namespace}/{r.service}", []).append(r)
        return out


class FakeBackend:
    """Captures applied rulesets (what proxier unit tests assert on)."""

    def __init__(self):
        self.applied: List[RuleSet] = []

    @property
    def current(self) -> Optional[RuleSet]:
        return self.applied[-1] if self.applied else None

    def apply(self, ruleset: RuleSet) -> None:
        self.applied.append(ruleset)


class IptablesBackend(FakeBackend):
    """Renders iptables-save-style text (iptables/proxier.go writes the same
    shape through iptables-restore: KUBE-SERVICES dispatch, KUBE-SVC-* per
    service port with statistic-mode random split, KUBE-SEP-* per endpoint)."""

    def render(self) -> str:
        rs = self.current
        if rs is None:
            return ""
        lines = ["*nat", ":KUBE-SERVICES - [0:0]"]
        for rule in rs.rules:
            lines.append(f":KUBE-SVC-{rule.chain_id} - [0:0]")
            for i, _ in enumerate(rule.endpoints):
                lines.append(f":KUBE-SEP-{rule.chain_id}-{i} - [0:0]")
        for rule in rs.rules:
            comment = f"{rule.namespace}/{rule.service}:{rule.port_name}"
            lines.append(
                f'-A KUBE-SERVICES -d {rule.cluster_ip}/32 -p {rule.protocol.lower()} '
                f'--dport {rule.port} -m comment --comment "{comment} cluster IP" '
                f"-j KUBE-SVC-{rule.chain_id}")
            n = len(rule.endpoints)
            for i, ep in enumerate(rule.endpoints):
                if i < n - 1:
                    prob = 1.0 / (n - i)
                    lines.append(
                        f"-A KUBE-SVC-{rule.chain_id} -m statistic --mode random "
                        f"--probability {prob:.5f} -j KUBE-SEP-{rule.chain_id}-{i}")
                else:
                    lines.append(f"-A KUBE-SVC-{rule.chain_id} "
                                 f"-j KUBE-SEP-{rule.chain_id}-{i}")
            for i, ep in enumerate(rule.endpoints):
                lines.append(
                    f"-A KUBE-SEP-{rule.chain_id}-{i} -p {rule.protocol.lower()} "
                    f"-j DNAT --to-destination {ep.ip}:{ep.port}")
        lines.append("COMMIT")
        return "\n".join(lines)


class NftablesBackend(FakeBackend):
    """Renders an nftables-style table (nftables/proxier.go structure:
    one vmap dispatch, numgen-based endpoint selection)."""

    def render(self) -> str:
        rs = self.current
        if rs is None:
            return ""
        lines = ["table ip kube-proxy {", "  chain services {"]
        for rule in rs.rules:
            lines.append(
                f"    ip daddr {rule.cluster_ip} {rule.protocol.lower()} "
                f"dport {rule.port} jump svc-{rule.chain_id}")
        lines.append("  }")
        for rule in rs.rules:
            lines.append(f"  chain svc-{rule.chain_id} {{")
            n = len(rule.endpoints)
            if n:
                targets = " , ".join(
                    f"{i} : jump sep-{rule.chain_id}-{i}" for i in range(n))
                lines.append(f"    numgen random mod {n} vmap {{ {targets} }}")
            else:
                lines.append("    reject")
            lines.append("  }")
            for i, ep in enumerate(rule.endpoints):
                lines.append(f"  chain sep-{rule.chain_id}-{i} {{")
                lines.append(f"    dnat to {ep.ip}:{ep.port}")
                lines.append("  }")
        lines.append("}")
        return "\n".join(lines)


class BoundedFrequencyRunner:
    """Coalesces sync requests: at most one run per min_interval
    (pkg/util/async/bounded_frequency_runner.go)."""

    def __init__(self, fn, min_interval: float = 1.0, clock: Optional[Clock] = None):
        self.fn = fn
        self.min_interval = min_interval
        self.clock = clock or Clock()
        self._last_run = float("-inf")
        self._pending = False

    def run(self) -> bool:
        """Request a run; executes now if allowed, else marks pending."""
        now = self.clock.now()
        if now - self._last_run >= self.min_interval:
            self._last_run = now
            self._pending = False
            self.fn()
            return True
        self._pending = True
        return False

    def retry_pending(self) -> bool:
        """Run a deferred request once the interval has elapsed."""
        if self._pending:
            return self.run()
        return False


class Proxier(Controller):
    """Watches services + endpointslices; any change triggers a full-state
    rules rebuild through the backend (level-triggered like syncProxyRules)."""

    watch_kinds = ("services", "endpointslices")

    def __init__(self, store: APIStore, backend=None, node_name: str = "",
                 clock: Optional[Clock] = None, min_sync_interval: float = 0.0):
        super().__init__(store, clock)
        self.backend = backend if backend is not None else FakeBackend()
        self.node_name = node_name
        self.syncs = 0
        self._runner = BoundedFrequencyRunner(
            self._sync_now, min_interval=min_sync_interval, clock=self.clock)

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        return "*"  # any change rebuilds the full state

    def sync(self, key: str) -> None:
        self._runner.run()

    def reconcile_once(self) -> int:
        n = super().reconcile_once()
        # a sync coalesced during the throttle window runs once the interval
        # elapses (the reference runner schedules a timer for this)
        if self._runner.retry_pending():
            n += 1
        return n

    def sync_proxy_rules(self) -> RuleSet:
        """Force an immediate full sync (tests); returns the ruleset."""
        self._sync_now()
        return self.backend.current

    def _sync_now(self) -> None:
        services, _ = self.store.list("services")
        slices, _ = self.store.list("endpointslices")
        by_service: Dict[str, List[EndpointSlice]] = {}
        for s in slices:
            svc_name = s.metadata.labels.get(EndpointSlice.LABEL_SERVICE_NAME)
            if svc_name:
                by_service.setdefault(
                    f"{s.metadata.namespace}/{svc_name}", []).append(s)
        rules: List[ServicePortRule] = []
        for svc in services:
            if svc.spec.type == "ExternalName" or not svc.spec.ports:
                continue
            if svc.spec.cluster_ip == "None":
                continue  # headless: no VIP, no rules (proxier skips these)
            cluster_ip = svc.spec.cluster_ip or self._synth_ip(svc)
            eps: List[Tuple[str, str]] = []  # (ip, node)
            for s in sorted(by_service.get(svc.key, []),
                            key=lambda x: x.metadata.name):
                for e in s.endpoints:
                    if e.ready and e.addresses:
                        eps.append((e.addresses[0], e.node_name))
            for port in svc.spec.ports:
                rules.append(ServicePortRule(
                    namespace=svc.metadata.namespace,
                    service=svc.metadata.name,
                    port_name=port.name,
                    protocol=port.protocol,
                    cluster_ip=cluster_ip,
                    port=port.port,
                    node_port=port.node_port,
                    endpoints=[EndpointTarget(ip=ip, port=port.resolved_target(),
                                              node_name=node)
                               for ip, node in eps],
                ))
        self.backend.apply(RuleSet(rules=rules))
        self.syncs += 1

    @staticmethod
    def _synth_ip(svc: Service) -> str:
        """Deterministic ClusterIP from the service uid (no real allocator)."""
        h = hashlib.sha1((svc.metadata.uid or svc.key).encode()).digest()
        return f"172.16.{h[0]}.{max(h[1], 1)}"
