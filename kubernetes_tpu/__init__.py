"""kubernetes_tpu — a TPU-native cluster orchestrator with Kubernetes' capabilities.

A from-scratch, TPU-first framework (JAX / XLA / Pallas / pjit) re-providing the
capabilities of Kubernetes (reference: kubernetes/kubernetes ~v1.33): a declarative typed
API store with watch semantics, reconciling controllers, a binding surface, and — as its
core — a pod scheduler that reframes kube-scheduler's per-pod Filter/Score loop
(reference: pkg/scheduler/schedule_one.go) as a batched pods x nodes assignment problem
solved on a TPU mesh.

Layer map (mirrors SURVEY.md §1, redesigned TPU-first):
  api/        L0: typed object model (Pod, Node, labels, quantities)
  store/      L1-L2: in-memory versioned store with watch bus (etcd+apiserver fusion)
  scheduler/  L5: framework extension points, serial oracle, queue, cache, batch driver
  snapshot/   cluster state as struct-of-arrays + incremental device mirroring
  ops/        vectorized filter/score plugins -> feasibility/cost tensors (jit)
  parallel/   mesh construction, shard_map'd solvers, collectives over ICI
  models/     end-to-end "solver models" (greedy / auction / sinkhorn assignment)
  controllers/ L4: reconciling control loops (workload controllers, node lifecycle)
  utils/      clocks, backoff, misc
"""

__version__ = "0.1.0"
