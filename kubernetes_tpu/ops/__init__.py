"""Vectorized (jitted) plugin semantics and batch solvers."""
