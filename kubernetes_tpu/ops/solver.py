"""Jitted batch solvers over the pods x nodes tensors.

The TPU replacement for prioritizeNodes()/the per-pod loop (reference:
pkg/scheduler/schedule_one.go:65,754 — the north-star site). Two solvers:

  greedy_scan  — lax.scan over the priority-ordered pod batch; each step runs
                 ALL filters+scores vectorized over nodes, argmaxes, and updates
                 capacity/spread state. Bit-compatible with the serial oracle
                 (same order, same integer formulas, lowest-index tie-break),
                 so parity is exact.
  (auction/sinkhorn solvers land in models/ in a later milestone)

All arithmetic is int32 (matching Go's integer score math) except
BalancedAllocation (float, like balanced_allocation.go).

Score composition mirrors runtime.RunScorePlugins with the default weights
(default_plugins.go:30): Fit(Least)x1 + Balancedx1 + NodeAffinityx2(norm) +
TaintTolerationx3(rev-norm) + PodTopologySpreadx2(special norm) + ImageLocalityx1.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..scheduler.framework import MAX_NODE_SCORE

INT_MIN = jnp.int32(-(2**31) + 1)


class SolverInputs(NamedTuple):
    """Device-resident view of ClusterTensors + PodBatchTensors (all jnp)."""

    # node state
    alloc: jnp.ndarray  # [N, R] int32
    used: jnp.ndarray  # [N, R]
    used_nz: jnp.ndarray  # [N, R]
    pod_count: jnp.ndarray  # [N]
    max_pods: jnp.ndarray  # [N]
    # class tables
    filter_ok: jnp.ndarray  # [C, N] bool
    aff_ok: jnp.ndarray  # [C, N] bool
    napref_raw: jnp.ndarray  # [C, N] int32
    has_napref: jnp.ndarray  # [C] bool
    taint_cnt: jnp.ndarray  # [C, N] int32
    img_score: jnp.ndarray  # [C, N] int32
    class_ports: jnp.ndarray  # [C, Pt] bool
    node_ports: jnp.ndarray  # [N, Pt] bool (existing usage; dynamic state seeds)
    # topology
    topo_id: jnp.ndarray  # [Kk, N] int32
    selcls_count: jnp.ndarray  # [SC, N] int32
    class_matches_selcls: jnp.ndarray  # [C, SC] int32
    # constraints (padded to >=1 with class=-1 sentinels)
    ct_class: jnp.ndarray
    ct_key: jnp.ndarray
    ct_sel: jnp.ndarray
    ct_max_skew: jnp.ndarray
    ct_min_domains: jnp.ndarray
    ct_self_match: jnp.ndarray
    st_class: jnp.ndarray
    st_key: jnp.ndarray
    st_sel: jnp.ndarray
    st_max_skew: jnp.ndarray
    st_self_match: jnp.ndarray
    # pod batch
    req: jnp.ndarray  # [P, R]
    req_nz: jnp.ndarray  # [P, R]
    class_of_pod: jnp.ndarray  # [P]
    balanced_active: jnp.ndarray  # [P] bool


def _pad_ct(*arrays, sentinel_class=-1):
    """Ensure constraint arrays are non-empty (jit-stable shapes)."""
    if arrays[0].size:
        return [jnp.asarray(a, dtype=jnp.int32) for a in arrays]
    out = [jnp.full((1,), sentinel_class, dtype=jnp.int32)]
    out += [jnp.zeros((1,), dtype=jnp.int32) for _ in arrays[1:]]
    return out


def make_inputs(cluster, batch) -> Tuple[SolverInputs, int]:
    """numpy -> device arrays. Returns (inputs, D_max)."""
    t = batch.tables
    kk = max(cluster.topo_id.shape[0], 1)
    n = cluster.n
    topo_id = cluster.topo_id if cluster.topo_id.size else np.full((1, n), -1, np.int32)
    selcls = cluster.selcls_count if cluster.selcls_count.size else np.zeros((1, n), np.int32)
    cms = batch.class_matches_selcls
    if cms.shape[1] == 0:
        cms = np.zeros((cms.shape[0], 1), np.int32)
    d_max = int(cluster.num_domains.max()) if cluster.num_domains.size else 1

    ct = _pad_ct(batch.ct_class, batch.ct_key, batch.ct_sel, batch.ct_max_skew,
                 batch.ct_min_domains, batch.ct_self_match)
    st = _pad_ct(batch.st_class, batch.st_key, batch.st_sel, batch.st_max_skew,
                 batch.st_self_match)

    inputs = SolverInputs(
        alloc=jnp.asarray(cluster.alloc), used=jnp.asarray(cluster.used),
        used_nz=jnp.asarray(cluster.used_nz), pod_count=jnp.asarray(cluster.pod_count),
        max_pods=jnp.asarray(cluster.max_pods),
        filter_ok=jnp.asarray(t.filter_ok), aff_ok=jnp.asarray(t.aff_ok),
        napref_raw=jnp.asarray(t.napref_raw), has_napref=jnp.asarray(t.has_napref),
        taint_cnt=jnp.asarray(t.taint_cnt), img_score=jnp.asarray(t.img_score),
        class_ports=jnp.asarray(t.class_ports), node_ports=jnp.asarray(t.node_ports),
        topo_id=jnp.asarray(topo_id), selcls_count=jnp.asarray(selcls),
        class_matches_selcls=jnp.asarray(cms),
        ct_class=ct[0], ct_key=ct[1], ct_sel=ct[2], ct_max_skew=ct[3],
        ct_min_domains=ct[4], ct_self_match=ct[5],
        st_class=st[0], st_key=st[1], st_sel=st[2], st_max_skew=st[3],
        st_self_match=st[4],
        req=jnp.asarray(batch.req), req_nz=jnp.asarray(batch.req_nz),
        class_of_pod=jnp.asarray(batch.class_of_pod),
        balanced_active=jnp.asarray(batch.balanced_active),
    )
    return inputs, d_max


# ---------------------------------------------------------------------------
# vectorized plugin pieces (each mirrors a serial plugin formula exactly)
# ---------------------------------------------------------------------------


def fit_feasible(alloc, used, pod_count, max_pods, req):
    """NodeResourcesFit Filter (fit.go:499): req <= alloc - used per resource
    (zero requests always fit) AND pod count headroom."""
    ok = jnp.all((req[None, :] == 0) | (req[None, :] <= alloc - used), axis=1)
    return ok & (pod_count + 1 <= max_pods)


def least_allocated_score(alloc2, used2, req2):
    """leastResourceScorer over cpu+memory (least_allocated.go:30), int math."""
    u = used2 + req2[None, :]
    per = jnp.where(
        (alloc2 > 0) & (u <= alloc2),
        (alloc2 - u) * MAX_NODE_SCORE // jnp.maximum(alloc2, 1),
        0,
    )
    wsum = jnp.maximum(jnp.sum((alloc2 > 0).astype(jnp.int32), axis=1), 1)
    return jnp.sum(per * (alloc2 > 0), axis=1) // wsum


def balanced_score(alloc2, used2, req2, active):
    """balancedResourceScorer 2-resource shortcut (balanced_allocation.go:145)."""
    u = (used2 + req2[None, :]).astype(jnp.float32)
    a = alloc2.astype(jnp.float32)
    frac = jnp.where(a > 0, jnp.minimum(u / jnp.maximum(a, 1.0), 1.0), 0.0)
    n_frac = jnp.sum((a > 0).astype(jnp.int32), axis=1)
    std2 = jnp.abs(frac[:, 0] - frac[:, 1]) / 2.0
    std = jnp.where(n_frac == 2, std2, 0.0)
    score = ((1.0 - std) * MAX_NODE_SCORE).astype(jnp.int32)
    return jnp.where(active, score, 0)


def default_normalize(raw, feasible, reverse: bool):
    """DefaultNormalizeScore over the feasible (scored) set (normalize_score.go)."""
    mx = jnp.max(jnp.where(feasible, raw, 0))
    scaled = jnp.where(mx > 0, MAX_NODE_SCORE * raw // jnp.maximum(mx, 1), 0)
    if reverse:
        out = jnp.where(mx > 0, MAX_NODE_SCORE - scaled, MAX_NODE_SCORE)
    else:
        out = scaled
    return out


def pts_counts(aff_row, dyn_selcls, topo_row, sel_idx, d_max):
    """Per-domain matching-pod counts for one constraint: segment-sum of the
    per-node counts over counting-eligible nodes (filtering.go calPreFilterState)."""
    per_node = jnp.where(aff_row & (topo_row >= 0), dyn_selcls[sel_idx], 0)
    seg = jnp.where(topo_row >= 0, topo_row, d_max)  # park missing in overflow slot
    return jax.ops.segment_sum(per_node, seg, num_segments=d_max + 1)[:d_max]


def pts_domain_valid(aff_row, topo_row, d_max):
    has = jnp.where(aff_row & (topo_row >= 0), 1, 0)
    seg = jnp.where(topo_row >= 0, topo_row, d_max)
    return jax.ops.segment_max(has, seg, num_segments=d_max + 1)[:d_max] > 0


def pod_row_feasibility_score(inp: SolverInputs, req, req_nz, cls, bal_active):
    """F[N], C[N] for one pod against the *initial* snapshot state (no
    intra-batch dynamics): the shared row formula for the extender surface,
    the 2D-sharded F/C kernel, and the group-level transport solvers. Score
    composition = default weights (default_plugins.go:30) minus the dynamic
    PTS/IPA terms (callers route those batches to the scan solver)."""
    cls = jnp.maximum(cls, 0)
    feas = inp.filter_ok[cls]
    feas &= fit_feasible(inp.alloc, inp.used, inp.pod_count, inp.max_pods, req)
    feas &= ~jnp.any(inp.node_ports & inp.class_ports[cls][None, :], axis=1)
    alloc2 = inp.alloc[:, :2]
    least = least_allocated_score(alloc2, inp.used_nz[:, :2], req_nz[:2])
    bal = balanced_score(alloc2, inp.used[:, :2], req[:2], bal_active)
    napref = jnp.where(inp.has_napref[cls],
                       default_normalize(inp.napref_raw[cls], feas, reverse=False), 0)
    taint = default_normalize(inp.taint_cnt[cls], feas, reverse=True)
    total = least + bal + 2 * napref + 3 * taint + inp.img_score[cls]
    return feas, total


# ---------------------------------------------------------------------------
# the greedy scan solver
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("d_max",))
def greedy_scan_solve(inp: SolverInputs, d_max: int):
    """Sequential-within-batch greedy assignment, one lax.scan step per pod.

    Exactly the serial pipeline: filter -> score -> argmax (lowest index wins
    ties) -> commit. Returns assignment[P] int32 node index (-1 unschedulable)
    and the final node state.
    """

    def step(state, pod):
        used, used_nz, pod_count, dyn_selcls, port_used = state
        req, req_nz, cls, bal_active = pod
        cls = jnp.maximum(cls, 0)

        feas = inp.filter_ok[cls]
        feas &= fit_feasible(inp.alloc, used, pod_count, inp.max_pods, req)
        # NodePorts (node_ports.go), dynamic: in-batch placements claim ports
        feas &= ~jnp.any(port_used & inp.class_ports[cls][None, :], axis=1)

        aff_row = inp.aff_ok[cls]

        # --- PodTopologySpread DoNotSchedule (filtering.go:340) ---
        def ct_feas(ct_c, ct_k, ct_s, ct_skew, ct_mind, ct_self):
            active = ct_c == cls
            topo_row = inp.topo_id[ct_k]
            dc = pts_counts(aff_row, dyn_selcls, topo_row, ct_s, d_max)
            valid = pts_domain_valid(aff_row, topo_row, d_max)
            n_valid = jnp.sum(valid.astype(jnp.int32))
            mmn = jnp.min(jnp.where(valid, dc, 2**30))
            mmn = jnp.where((ct_mind > 0) & (ct_mind > n_valid), 0, mmn)
            mmn = jnp.where(n_valid == 0, 0, mmn)
            node_dc = jnp.where(topo_row >= 0, dc[jnp.clip(topo_row, 0, d_max - 1)], 0)
            skew = node_dc + ct_self - mmn
            ok = (topo_row >= 0) & (skew <= ct_skew)
            return jnp.where(active, ok, True)

        ct_ok = jax.vmap(ct_feas)(inp.ct_class, inp.ct_key, inp.ct_sel,
                                  inp.ct_max_skew, inp.ct_min_domains, inp.ct_self_match)
        feas &= jnp.all(ct_ok, axis=0)

        # --- scores ---
        alloc2 = inp.alloc[:, :2]
        least = least_allocated_score(alloc2, used_nz[:, :2], req_nz[:2])
        bal = balanced_score(alloc2, used[:, :2], req[:2], bal_active)
        napref = jnp.where(inp.has_napref[cls],
                           default_normalize(inp.napref_raw[cls], feas, reverse=False), 0)
        taint = default_normalize(inp.taint_cnt[cls], feas, reverse=True)
        img = inp.img_score[cls]

        # --- PTS ScheduleAnyway score (scoring.go) ---
        def st_score(st_c, st_k, st_s, st_skew, st_self):
            active = st_c == cls
            topo_row = inp.topo_id[st_k]
            dc = pts_counts(aff_row, dyn_selcls, topo_row, st_s, d_max)
            # domain set/size from the *feasible* nodes (initPreScoreState)
            valid_feas = pts_domain_valid(feas, topo_row, d_max)
            size = jnp.sum(valid_feas.astype(jnp.int32))
            w = jnp.log(size.astype(jnp.float32) + 2.0)
            node_dc = jnp.where(topo_row >= 0, dc[jnp.clip(topo_row, 0, d_max - 1)], 0)
            contrib = node_dc.astype(jnp.float32) * w + (st_skew - 1).astype(jnp.float32)
            # nodes missing the topology key are "IgnoredNodes" (scoring.go:121)
            ignored_n = active & (topo_row < 0)
            return jnp.where(active, contrib, 0.0), ignored_n, active

        st_contrib, st_ignored, st_active = jax.vmap(st_score)(
            inp.st_class, inp.st_key, inp.st_sel, inp.st_max_skew, inp.st_self_match)
        any_st = jnp.any(st_active)
        ignored = jnp.any(st_ignored, axis=0)  # [N]
        pts_raw = jnp.round(jnp.sum(st_contrib, axis=0)).astype(jnp.int32)
        # NormalizeScore: MAX*(max+min-s)//max over feasible, non-ignored nodes;
        # ignored nodes score 0 (scoring.go:256)
        norm_mask = feas & ~ignored
        pmx = jnp.max(jnp.where(norm_mask, pts_raw, -(2**30)))
        pmn = jnp.min(jnp.where(norm_mask, pts_raw, 2**30))
        pts = jnp.where(
            pmx > 0,
            MAX_NODE_SCORE * (pmx + pmn - pts_raw) // jnp.maximum(pmx, 1),
            MAX_NODE_SCORE,
        )
        pts = jnp.where(any_st & ~ignored & jnp.any(norm_mask), pts, 0)

        total = least + bal + 2 * napref + 3 * taint + 2 * pts + img

        # --- selectHost: deterministic argmax (lowest index on ties) ---
        masked = jnp.where(feas, total, INT_MIN)
        best = jnp.argmax(masked).astype(jnp.int32)
        ok = feas[best]
        node = jnp.where(ok, best, -1)

        # --- commit ---
        onehot = (jnp.arange(used.shape[0]) == node)
        used = used + jnp.where(ok, onehot[:, None] * req[None, :], 0).astype(jnp.int32)
        used_nz = used_nz + jnp.where(ok, onehot[:, None] * req_nz[None, :], 0).astype(jnp.int32)
        pod_count = pod_count + jnp.where(ok, onehot.astype(jnp.int32), 0)
        bump = inp.class_matches_selcls[cls][:, None] * onehot[None, :].astype(jnp.int32)
        dyn_selcls = dyn_selcls + jnp.where(ok, bump, 0)
        port_used = port_used | (ok & onehot)[:, None] & inp.class_ports[cls][None, :]
        return (used, used_nz, pod_count, dyn_selcls, port_used), node

    init = (inp.used, inp.used_nz, inp.pod_count, inp.selcls_count, inp.node_ports)
    (used, used_nz, pod_count, dyn_selcls, port_used), assignment = jax.lax.scan(
        step, init, (inp.req, inp.req_nz, inp.class_of_pod, inp.balanced_active)
    )
    return assignment, used, pod_count
