"""Jitted batch solvers over the pods x nodes tensors.

The TPU replacement for prioritizeNodes()/the per-pod loop (reference:
pkg/scheduler/schedule_one.go:65,754 — the north-star site). Two solvers:

  greedy_scan  — lax.scan over the priority-ordered pod batch; each step runs
                 ALL filters+scores vectorized over nodes, argmaxes, and updates
                 capacity/spread state. Bit-compatible with the serial oracle
                 (same order, same integer formulas, lowest-index tie-break),
                 so parity is exact.
  (auction/sinkhorn solvers land in models/ in a later milestone)

All arithmetic is int32 (matching Go's integer score math) except
BalancedAllocation (float, like balanced_allocation.go).

Score composition mirrors runtime.RunScorePlugins with the default weights
(default_plugins.go:30): Fit(Least)x1 + Balancedx1 + NodeAffinityx2(norm) +
TaintTolerationx3(rev-norm) + PodTopologySpreadx2(special norm) + ImageLocalityx1.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..scheduler.framework import MAX_NODE_SCORE

INT_MIN = jnp.int32(-(2**31) + 1)


class SolverInputs(NamedTuple):
    """Device-resident view of ClusterTensors + PodBatchTensors (all jnp)."""

    # node state
    alloc: jnp.ndarray  # [N, R] int32
    used: jnp.ndarray  # [N, R]
    used_nz: jnp.ndarray  # [N, R]
    pod_count: jnp.ndarray  # [N]
    max_pods: jnp.ndarray  # [N]
    # class tables
    filter_ok: jnp.ndarray  # [C, N] bool
    aff_ok: jnp.ndarray  # [C, N] bool
    napref_raw: jnp.ndarray  # [C, N] int32
    has_napref: jnp.ndarray  # [C] bool
    taint_cnt: jnp.ndarray  # [C, N] int32
    img_score: jnp.ndarray  # [C, N] int32
    class_ports: jnp.ndarray  # [C, Pt] bool
    node_ports: jnp.ndarray  # [N, Pt] bool (existing usage; dynamic state seeds)
    # topology
    topo_id: jnp.ndarray  # [Kk, N] int32
    selcls_count: jnp.ndarray  # [SC, N] int32
    class_matches_selcls: jnp.ndarray  # [C, SC] int32
    # constraints (padded to >=1 with class=-1 sentinels)
    ct_class: jnp.ndarray
    ct_key: jnp.ndarray
    ct_sel: jnp.ndarray
    ct_max_skew: jnp.ndarray
    ct_min_domains: jnp.ndarray
    ct_self_match: jnp.ndarray
    st_class: jnp.ndarray
    st_key: jnp.ndarray
    st_sel: jnp.ndarray
    st_max_skew: jnp.ndarray
    st_self_match: jnp.ndarray
    # inter-pod affinity (snapshot/ipa.py; per-class padded tables, -1 pads —
    # each scan step gathers ONE class row, so per-step cost is the max term
    # count of a class, not the batch total)
    ra_key: jnp.ndarray  # [C, RAm] incoming required affinity
    ra_sel: jnp.ndarray
    rn_key: jnp.ndarray  # [C, RNm] incoming required anti-affinity
    rn_sel: jnp.ndarray
    pp_key: jnp.ndarray  # [C, PPm] incoming preferred
    pp_sel: jnp.ndarray
    pp_weight: jnp.ndarray  # [C, PPm] signed, 0 pads
    grp_key: jnp.ndarray  # [G] topo row per holder group
    grp_count: jnp.ndarray  # [G, N] existing holders per node (dyn seed)
    class_holds_grp: jnp.ndarray  # [C, G]
    ea_grp: jnp.ndarray  # [C, Em] required-anti groups matching the class
    sym_grp: jnp.ndarray  # [C, Sm] symmetric score groups matching the class
    sym_weight: jnp.ndarray  # [C, Sm] signed, 0 pads
    class_self_ok: jnp.ndarray  # [C] bool
    class_has_ra: jnp.ndarray  # [C] bool
    # pod batch
    req: jnp.ndarray  # [P, R]
    req_nz: jnp.ndarray  # [P, R]
    class_of_pod: jnp.ndarray  # [P]
    balanced_active: jnp.ndarray  # [P] bool
    # gang slice-packing bonus (scheduler/gang.py): per-(class, node) static
    # score added when the batch carries gang members; None for gang-free
    # batches — the has_gang static gate keeps it out of the compiled program
    # entirely (never traced, never uploaded)
    gang_bonus: Optional[jnp.ndarray] = None  # [C, N] int32


def _pad_ct(*arrays, sentinel_class=-1):
    """Ensure constraint arrays are non-empty (jit-stable shapes)."""
    if arrays[0].size:
        return [jnp.asarray(a, dtype=jnp.int32) for a in arrays]
    out = [jnp.full((1,), sentinel_class, dtype=jnp.int32)]
    out += [jnp.zeros((1,), dtype=jnp.int32) for _ in arrays[1:]]
    return out


def make_inputs(cluster, batch, device=None) -> Tuple[SolverInputs, int]:
    """numpy -> device arrays. Returns (inputs, D_max).

    device, when given, is TensorCache.device_views' dict of HBM-resident
    arrays (alloc/used/used_nz/pod_count/max_pods, selcls_count) maintained by
    scatter updates — those fields skip the host->device upload here."""
    device = device or {}

    def dev(name, host):
        got = device.get(name)
        return got if got is not None else jnp.asarray(host)

    t = batch.tables
    kk = max(cluster.topo_id.shape[0], 1)
    n = cluster.n
    topo_id = cluster.topo_id if cluster.topo_id.size else np.full((1, n), -1, np.int32)
    selcls = cluster.selcls_count if cluster.selcls_count.size else np.zeros((1, n), np.int32)
    cms = batch.class_matches_selcls
    if cms.shape[1] == 0:
        cms = np.zeros((cms.shape[0], 1), np.int32)
    d_max = int(cluster.num_domains.max()) if cluster.num_domains.size else 1

    ct = _pad_ct(batch.ct_class, batch.ct_key, batch.ct_sel, batch.ct_max_skew,
                 batch.ct_min_domains, batch.ct_self_match)
    st = _pad_ct(batch.st_class, batch.st_key, batch.st_sel, batch.st_max_skew,
                 batch.st_self_match)
    ipa = batch.ipa
    g = max(ipa.grp_key.size, 1)
    grp_key = ipa.grp_key if ipa.grp_key.size else np.zeros(1, np.int32)
    grp_count = ipa.grp_count if ipa.grp_count.size else np.zeros((1, n), np.int32)
    chg = ipa.class_holds_grp
    assert chg.shape[1] == g, f"class_holds_grp width {chg.shape[1]} != {g}"

    inputs = SolverInputs(
        alloc=dev("alloc", cluster.alloc), used=dev("used", cluster.used),
        used_nz=dev("used_nz", cluster.used_nz),
        pod_count=dev("pod_count", cluster.pod_count),
        max_pods=dev("max_pods", cluster.max_pods),
        filter_ok=jnp.asarray(t.filter_ok), aff_ok=jnp.asarray(t.aff_ok),
        napref_raw=jnp.asarray(t.napref_raw), has_napref=jnp.asarray(t.has_napref),
        taint_cnt=jnp.asarray(t.taint_cnt), img_score=jnp.asarray(t.img_score),
        class_ports=jnp.asarray(t.class_ports), node_ports=jnp.asarray(t.node_ports),
        topo_id=jnp.asarray(topo_id),
        selcls_count=dev("selcls_count", selcls),
        class_matches_selcls=jnp.asarray(cms),
        ct_class=ct[0], ct_key=ct[1], ct_sel=ct[2], ct_max_skew=ct[3],
        ct_min_domains=ct[4], ct_self_match=ct[5],
        st_class=st[0], st_key=st[1], st_sel=st[2], st_max_skew=st[3],
        st_self_match=st[4],
        ra_key=jnp.asarray(ipa.ra_key), ra_sel=jnp.asarray(ipa.ra_sel),
        rn_key=jnp.asarray(ipa.rn_key), rn_sel=jnp.asarray(ipa.rn_sel),
        pp_key=jnp.asarray(ipa.pp_key), pp_sel=jnp.asarray(ipa.pp_sel),
        pp_weight=jnp.asarray(ipa.pp_weight),
        grp_key=jnp.asarray(grp_key), grp_count=jnp.asarray(grp_count),
        class_holds_grp=jnp.asarray(chg),
        ea_grp=jnp.asarray(ipa.ea_grp),
        sym_grp=jnp.asarray(ipa.sym_grp), sym_weight=jnp.asarray(ipa.sym_weight),
        class_self_ok=jnp.asarray(ipa.class_self_ok),
        class_has_ra=jnp.asarray(ipa.class_has_ra),
        req=jnp.asarray(batch.req), req_nz=jnp.asarray(batch.req_nz),
        class_of_pod=jnp.asarray(batch.class_of_pod),
        balanced_active=jnp.asarray(batch.balanced_active),
        gang_bonus=(jnp.asarray(batch.gang_bonus)
                    if getattr(batch, "gang_bonus", None) is not None
                    else None),
    )
    return inputs, d_max


# ---------------------------------------------------------------------------
# vectorized plugin pieces (each mirrors a serial plugin formula exactly)
# ---------------------------------------------------------------------------


def fit_feasible(alloc, used, pod_count, max_pods, req):
    """NodeResourcesFit Filter (fit.go:499): req <= alloc - used per resource
    (zero requests always fit) AND pod count headroom."""
    ok = jnp.all((req[None, :] == 0) | (req[None, :] <= alloc - used), axis=1)
    return ok & (pod_count + 1 <= max_pods)


def least_allocated_score(alloc2, used2, req2):
    """leastResourceScorer over cpu+memory (least_allocated.go:30), int math."""
    u = used2 + req2[None, :]
    per = jnp.where(
        (alloc2 > 0) & (u <= alloc2),
        (alloc2 - u) * MAX_NODE_SCORE // jnp.maximum(alloc2, 1),
        0,
    )
    wsum = jnp.maximum(jnp.sum((alloc2 > 0).astype(jnp.int32), axis=1), 1)
    return jnp.sum(per * (alloc2 > 0), axis=1) // wsum


def balanced_score(alloc2, used2, req2, active):
    """balancedResourceScorer 2-resource shortcut (balanced_allocation.go:145)."""
    u = (used2 + req2[None, :]).astype(jnp.float32)
    a = alloc2.astype(jnp.float32)
    frac = jnp.where(a > 0, jnp.minimum(u / jnp.maximum(a, 1.0), 1.0), 0.0)
    n_frac = jnp.sum((a > 0).astype(jnp.int32), axis=1)
    std2 = jnp.abs(frac[:, 0] - frac[:, 1]) / 2.0
    std = jnp.where(n_frac == 2, std2, 0.0)
    score = ((1.0 - std) * MAX_NODE_SCORE).astype(jnp.int32)
    return jnp.where(active, score, 0)


def default_normalize(raw, feasible, reverse: bool):
    """DefaultNormalizeScore over the feasible (scored) set (normalize_score.go)."""
    mx = jnp.max(jnp.where(feasible, raw, 0))
    scaled = jnp.where(mx > 0, MAX_NODE_SCORE * raw // jnp.maximum(mx, 1), 0)
    if reverse:
        out = jnp.where(mx > 0, MAX_NODE_SCORE - scaled, MAX_NODE_SCORE)
    else:
        out = scaled
    return out


def pts_counts(aff_row, dyn_selcls, topo_row, sel_idx, d_max):
    """Per-domain matching-pod counts for one constraint: segment-sum of the
    per-node counts over counting-eligible nodes (filtering.go calPreFilterState)."""
    per_node = jnp.where(aff_row & (topo_row >= 0), dyn_selcls[sel_idx], 0)
    seg = jnp.where(topo_row >= 0, topo_row, d_max)  # park missing in overflow slot
    return jax.ops.segment_sum(per_node, seg, num_segments=d_max + 1)[:d_max]


def pts_domain_valid(aff_row, topo_row, d_max):
    has = jnp.where(aff_row & (topo_row >= 0), 1, 0)
    seg = jnp.where(topo_row >= 0, topo_row, d_max)
    return jax.ops.segment_max(has, seg, num_segments=d_max + 1)[:d_max] > 0


def pod_row_feasibility_score(inp: SolverInputs, req, req_nz, cls, bal_active):
    """F[N], C[N] for one pod against the *initial* snapshot state (no
    intra-batch dynamics): the shared row formula for the extender surface,
    the 2D-sharded F/C kernel, and the group-level transport solvers. Score
    composition = default weights (default_plugins.go:30) minus the dynamic
    PTS/IPA terms (callers route those batches to the scan solver)."""
    cls = jnp.maximum(cls, 0)
    feas = inp.filter_ok[cls]
    feas &= fit_feasible(inp.alloc, inp.used, inp.pod_count, inp.max_pods, req)
    feas &= ~jnp.any(inp.node_ports & inp.class_ports[cls][None, :], axis=1)
    alloc2 = inp.alloc[:, :2]
    least = least_allocated_score(alloc2, inp.used_nz[:, :2], req_nz[:2])
    bal = balanced_score(alloc2, inp.used[:, :2], req[:2], bal_active)
    napref = jnp.where(inp.has_napref[cls],
                       default_normalize(inp.napref_raw[cls], feas, reverse=False), 0)
    taint = default_normalize(inp.taint_cnt[cls], feas, reverse=True)
    total = least + bal + 2 * napref + 3 * taint + inp.img_score[cls]
    return feas, total


# ---------------------------------------------------------------------------
# the greedy scan solver
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("d_max", "has_ipa", "has_ct",
                                             "has_st", "has_gang"))
def greedy_scan_solve(inp: SolverInputs, d_max: int, has_ipa: bool = True,
                      has_ct: bool = True, has_st: bool = True,
                      has_gang: bool = False):
    """Sequential-within-batch greedy assignment, one lax.scan step per pod.

    Exactly the serial pipeline: filter -> score -> argmax (lowest index wins
    ties) -> commit. Returns assignment[P] int32 node index (-1 unschedulable)
    and the final node state.

    has_ipa / has_ct / has_st are STATIC gates: constraint-free batches
    compile a variant without the inter-pod-affinity gathers and the
    topology-spread segment sums (the round-1 -> round-2 scan regression on
    SchedulingBasic came from paying those on every batch — VERDICT r3 weak
    #4). Passing True everywhere is always semantically safe; the gates are a
    pure speed knob for batches whose tables are empty."""

    def _dom_node_count(per_node, topo_row):
        """Per-node view of the node's topology-domain total of `per_node`
        (nodes missing the key read 0)."""
        seg = jnp.where(topo_row >= 0, topo_row, d_max)
        dom = jax.ops.segment_sum(jnp.where(topo_row >= 0, per_node, 0), seg,
                                  num_segments=d_max + 1)[:d_max]
        return jnp.where(topo_row >= 0, dom[jnp.clip(topo_row, 0, d_max - 1)], 0)

    def step(state, pod):
        used, used_nz, pod_count, dyn_selcls, dyn_grp, port_used = state
        req, req_nz, cls, bal_active = pod
        cls = jnp.maximum(cls, 0)

        feas = inp.filter_ok[cls]
        feas &= fit_feasible(inp.alloc, used, pod_count, inp.max_pods, req)
        # NodePorts (node_ports.go), dynamic: in-batch placements claim ports
        feas &= ~jnp.any(port_used & inp.class_ports[cls][None, :], axis=1)

        aff_row = inp.aff_ok[cls]

        if has_ipa:
            # --- InterPodAffinity Filter (filtering.go:415) ---
            # rule 1: no existing/placed pod's required anti-affinity is
            # violated (satisfyExistingPodsAntiAffinity): the incoming pod may
            # not land in a topology domain containing any holder of a
            # matching anti term.
            def ea_fn(g):
                active = g >= 0
                g = jnp.maximum(g, 0)
                topo_row = inp.topo_id[inp.grp_key[g]]
                cnt = _dom_node_count(dyn_grp[g], topo_row)
                return jnp.where(active, (topo_row < 0) | (cnt == 0), True)

            ea_ok = jax.vmap(ea_fn)(inp.ea_grp[cls])
            feas &= jnp.all(ea_ok, axis=0)

            # rule 2: incoming required affinity (satisfyPodAffinity): every
            # term's domain must contain a matching pod; nodes missing any
            # term's key are out; the first-pod exception admits a
            # self-matching pod when no matching pod exists anywhere (global
            # count zero across all terms).
            def ra_fn(k_, s_):
                active = k_ >= 0
                k_ = jnp.maximum(k_, 0)
                s_ = jnp.maximum(s_, 0)
                topo_row = inp.topo_id[k_]
                cnt = _dom_node_count(dyn_selcls[s_], topo_row)
                has_key = topo_row >= 0
                glob = jnp.sum(jnp.where(has_key, dyn_selcls[s_], 0))
                pos = jnp.where(active, has_key & (cnt > 0), True)
                keys = jnp.where(active, has_key, True)
                glob_zero = jnp.where(active, glob == 0, True)
                return pos, keys, glob_zero

            ra_pos, ra_keys, ra_glob0 = jax.vmap(ra_fn)(inp.ra_key[cls], inp.ra_sel[cls])
            ra_ok = jnp.all(ra_keys, axis=0) & (
                jnp.all(ra_pos, axis=0)
                | (jnp.all(ra_glob0) & inp.class_self_ok[cls])
            )
            feas &= jnp.where(inp.class_has_ra[cls], ra_ok, True)

            # rule 3: incoming required anti-affinity (satisfyPodAntiAffinity)
            def rn_fn(k_, s_):
                active = k_ >= 0
                k_ = jnp.maximum(k_, 0)
                s_ = jnp.maximum(s_, 0)
                topo_row = inp.topo_id[k_]
                cnt = _dom_node_count(dyn_selcls[s_], topo_row)
                return jnp.where(active, (topo_row < 0) | (cnt == 0), True)

            rn_ok = jax.vmap(rn_fn)(inp.rn_key[cls], inp.rn_sel[cls])
            feas &= jnp.all(rn_ok, axis=0)

        if has_ct:
            # --- PodTopologySpread DoNotSchedule (filtering.go:340) ---
            def ct_feas(ct_c, ct_k, ct_s, ct_skew, ct_mind, ct_self):
                active = ct_c == cls
                topo_row = inp.topo_id[ct_k]
                dc = pts_counts(aff_row, dyn_selcls, topo_row, ct_s, d_max)
                valid = pts_domain_valid(aff_row, topo_row, d_max)
                n_valid = jnp.sum(valid.astype(jnp.int32))
                mmn = jnp.min(jnp.where(valid, dc, 2**30))
                mmn = jnp.where((ct_mind > 0) & (ct_mind > n_valid), 0, mmn)
                mmn = jnp.where(n_valid == 0, 0, mmn)
                node_dc = jnp.where(topo_row >= 0, dc[jnp.clip(topo_row, 0, d_max - 1)], 0)
                skew = node_dc + ct_self - mmn
                ok = (topo_row >= 0) & (skew <= ct_skew)
                return jnp.where(active, ok, True)

            ct_ok = jax.vmap(ct_feas)(inp.ct_class, inp.ct_key, inp.ct_sel,
                                      inp.ct_max_skew, inp.ct_min_domains,
                                      inp.ct_self_match)
            feas &= jnp.all(ct_ok, axis=0)

        # --- scores ---
        alloc2 = inp.alloc[:, :2]
        least = least_allocated_score(alloc2, used_nz[:, :2], req_nz[:2])
        bal = balanced_score(alloc2, used[:, :2], req[:2], bal_active)
        napref = jnp.where(inp.has_napref[cls],
                           default_normalize(inp.napref_raw[cls], feas, reverse=False), 0)
        taint = default_normalize(inp.taint_cnt[cls], feas, reverse=True)
        img = inp.img_score[cls]

        if has_st:
            # --- PTS ScheduleAnyway score (scoring.go) ---
            def st_score(st_c, st_k, st_s, st_skew, st_self):
                active = st_c == cls
                topo_row = inp.topo_id[st_k]
                dc = pts_counts(aff_row, dyn_selcls, topo_row, st_s, d_max)
                # domain set/size from the *feasible* nodes (initPreScoreState)
                valid_feas = pts_domain_valid(feas, topo_row, d_max)
                size = jnp.sum(valid_feas.astype(jnp.int32))
                w = jnp.log(size.astype(jnp.float32) + 2.0)
                node_dc = jnp.where(topo_row >= 0, dc[jnp.clip(topo_row, 0, d_max - 1)], 0)
                contrib = node_dc.astype(jnp.float32) * w + (st_skew - 1).astype(jnp.float32)
                # nodes missing the topology key are "IgnoredNodes" (scoring.go:121)
                ignored_n = active & (topo_row < 0)
                return jnp.where(active, contrib, 0.0), ignored_n, active

            st_contrib, st_ignored, st_active = jax.vmap(st_score)(
                inp.st_class, inp.st_key, inp.st_sel, inp.st_max_skew, inp.st_self_match)
            any_st = jnp.any(st_active)
            ignored = jnp.any(st_ignored, axis=0)  # [N]
            pts_raw = jnp.round(jnp.sum(st_contrib, axis=0)).astype(jnp.int32)
            # NormalizeScore: MAX*(max+min-s)//max over feasible, non-ignored
            # nodes; ignored nodes score 0 (scoring.go:256)
            norm_mask = feas & ~ignored
            pmx = jnp.max(jnp.where(norm_mask, pts_raw, -(2**30)))
            pmn = jnp.min(jnp.where(norm_mask, pts_raw, 2**30))
            pts = jnp.where(
                pmx > 0,
                MAX_NODE_SCORE * (pmx + pmn - pts_raw) // jnp.maximum(pmx, 1),
                MAX_NODE_SCORE,
            )
            pts = jnp.where(any_st & ~ignored & jnp.any(norm_mask), pts, 0)
        else:
            pts = jnp.int32(0)

        if has_ipa:
            # --- InterPodAffinity Score (scoring.go) ---
            # incoming preferred terms: +/-weight per matching pod in the domain
            def pp_fn(k_, s_, w_):
                active = k_ >= 0
                k_ = jnp.maximum(k_, 0)
                s_ = jnp.maximum(s_, 0)
                topo_row = inp.topo_id[k_]
                cnt = _dom_node_count(dyn_selcls[s_], topo_row)
                return jnp.where(active, w_ * cnt, 0)

            pp_contrib = jnp.sum(jax.vmap(pp_fn)(
                inp.pp_key[cls], inp.pp_sel[cls], inp.pp_weight[cls]), axis=0)

            # symmetric: existing/placed pods' preferred terms matching the
            # incoming pod, plus their required affinity x hardPodAffinityWeight
            def sym_fn(g, w_):
                active = g >= 0
                g = jnp.maximum(g, 0)
                topo_row = inp.topo_id[inp.grp_key[g]]
                cnt = _dom_node_count(dyn_grp[g], topo_row)
                return jnp.where(active, w_ * cnt, 0)

            sym_contrib = jnp.sum(jax.vmap(sym_fn)(
                inp.sym_grp[cls], inp.sym_weight[cls]), axis=0)

            ipa_raw = pp_contrib + sym_contrib
            # normalize_score: MAX*(v-min)/(max-min) over feasible nodes, 0 when
            # uniform (interpod_affinity.py normalize_score). int32: weights
            # (<=100) x domain pod counts keep MAX*(v-min) under 2^31.
            imx = jnp.max(jnp.where(feas, ipa_raw, -(2**30)))
            imn = jnp.min(jnp.where(feas, ipa_raw, 2**30))
            idiff = imx - imn
            ipa_score = jnp.where(
                feas & (idiff > 0),
                (MAX_NODE_SCORE * (ipa_raw - imn)) // jnp.maximum(idiff, 1),
                0,
            ).astype(jnp.int32)
        else:
            ipa_score = jnp.int32(0)

        total = least + bal + 2 * napref + 3 * taint + 2 * pts + 2 * ipa_score + img
        if has_gang:
            # gang slice packing (scheduler/gang.py): a static per-class row,
            # like img — feasibility already masked the infeasible nodes
            total = total + inp.gang_bonus[cls]

        # --- selectHost: deterministic argmax (lowest index on ties) ---
        masked = jnp.where(feas, total, INT_MIN)
        best = jnp.argmax(masked).astype(jnp.int32)
        ok = feas[best]
        node = jnp.where(ok, best, -1)

        # --- commit ---
        onehot = (jnp.arange(used.shape[0]) == node)
        used = used + jnp.where(ok, onehot[:, None] * req[None, :], 0).astype(jnp.int32)
        used_nz = used_nz + jnp.where(ok, onehot[:, None] * req_nz[None, :], 0).astype(jnp.int32)
        pod_count = pod_count + jnp.where(ok, onehot.astype(jnp.int32), 0)
        bump = inp.class_matches_selcls[cls][:, None] * onehot[None, :].astype(jnp.int32)
        dyn_selcls = dyn_selcls + jnp.where(ok, bump, 0)
        gbump = inp.class_holds_grp[cls][:, None] * onehot[None, :].astype(jnp.int32)
        dyn_grp = dyn_grp + jnp.where(ok, gbump, 0)
        port_used = port_used | (ok & onehot)[:, None] & inp.class_ports[cls][None, :]
        return (used, used_nz, pod_count, dyn_selcls, dyn_grp, port_used), node

    init = (inp.used, inp.used_nz, inp.pod_count, inp.selcls_count, inp.grp_count,
            inp.node_ports)
    (used, used_nz, pod_count, dyn_selcls, dyn_grp, port_used), assignment = jax.lax.scan(
        step, init, (inp.req, inp.req_nz, inp.class_of_pod, inp.balanced_active)
    )
    return assignment, used, pod_count
