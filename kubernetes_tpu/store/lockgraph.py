"""Runtime lock-graph witness — the dynamic complement of schedlint LK001
(ISSUE 20).

`_OrderedRLock` (store/store.py, armed by STORE_LOCK_ORDER_CHECK=1 and the
pytest autouse fixture) already ASSERTS the ordering table on every
acquisition; this module makes the whole run a WITNESS: every fresh
acquisition made while another ordered lock is held records the edge
(held -> acquired) here, with the full acquisition stack captured on the
edge's FIRST sighting only (steady-state cost after that is one dict hit).
At the end of the tier-1 run the recorded edge set is diffed against the
LK001 ordering table:

  * an edge between two ranked locks that is not strictly ascending in
    rank is an inversion — reported with BOTH stacks (the first-seen
    stack of the offending edge and of its reverse, when witnessed);
  * any cycle in the witnessed graph (ranked or not) is a latent deadlock
    — reported with the first-seen stack of every edge on the cycle;
  * edges between unranked (scratch/test) locks are informational.

`ktl vet --lock-graph` renders the witnessed graph; the session-scoped
fixture in tests/conftest.py fails the run loudly on a dirty diff and
exports the graph as JSON when LOCK_GRAPH_EXPORT is set.
"""

from __future__ import annotations

import json
import threading
import traceback
from typing import Dict, List, Optional, Tuple

# The LK001 ordering table (must match the _OrderedRLock names built in
# store/store.py APIStore.__init__): rank strictly ascends along every
# legal acquisition edge.
ORDER_TABLE: Dict[str, int] = {
    "_lock (global RV)": 0,
    "_pods_lock (pods shard)": 1,
    "_nodes_lock (nodes shard)": 2,
}

_STACK_LIMIT = 16


class LockGraphWitness:
    """Edge-set recorder for ordered-lock acquisitions.

    record() is called with the lock the thread already holds (top of its
    per-store stack) and the lock being acquired. The hot path is a plain
    dict membership check — the stack capture (the expensive part) happens
    only the first time an edge is seen. Counts are best-effort under the
    GIL (a lost increment never loses the EDGE)."""

    def __init__(self):
        self._mu = threading.Lock()
        # (held_name, acq_name) -> edge record
        self.edges: Dict[Tuple[str, str], Dict] = {}

    def record(self, held_name: str, held_rank: int,
               acq_name: str, acq_rank: int) -> None:
        key = (held_name, acq_name)
        e = self.edges.get(key)
        if e is not None:
            e["count"] += 1
            return
        stack = "".join(traceback.format_stack(limit=_STACK_LIMIT)[:-1])
        with self._mu:
            if key not in self.edges:
                self.edges[key] = {
                    "held": held_name, "held_rank": held_rank,
                    "acquired": acq_name, "acquired_rank": acq_rank,
                    "count": 1, "first_stack": stack,
                }
            else:
                self.edges[key]["count"] += 1

    def clear(self) -> None:
        with self._mu:
            self.edges.clear()

    # -- the diff --------------------------------------------------------------

    def diff(self, table: Optional[Dict[str, int]] = None) -> Dict:
        """Diff the witnessed edge set against the ordering table."""
        table = ORDER_TABLE if table is None else table
        edges = dict(self.edges)
        violations: List[Dict] = []
        for (held, acq), e in edges.items():
            hr, ar = table.get(held), table.get(acq)
            if hr is None or ar is None:
                continue
            if ar <= hr:
                rev = edges.get((acq, held))
                violations.append({
                    "edge": f"{held} -> {acq}",
                    "why": f"rank {hr} -> {ar} is not ascending "
                           f"(LK001 ordering table)",
                    "stack": e["first_stack"],
                    "reverse_stack": rev["first_stack"] if rev else None,
                })
        cycles = self._cycles(edges)
        unknown = sorted(
            f"{held} -> {acq}" for (held, acq) in edges
            if held not in table or acq not in table)
        return {
            "edges": len(edges),
            "acquisitions": sum(e["count"] for e in edges.values()),
            "violations": violations,
            "cycles": cycles,
            "unknown_edges": unknown,
            "clean": not violations and not cycles,
        }

    def _cycles(self, edges: Dict[Tuple[str, str], Dict]) -> List[Dict]:
        graph: Dict[str, List[str]] = {}
        for held, acq in edges:
            graph.setdefault(held, []).append(acq)
        out: List[Dict] = []
        seen_cycles = set()
        WHITE, GREY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}

        def visit(node: str, path: List[str]) -> None:
            color[node] = GREY
            for nxt in graph.get(node, ()):
                if color.get(nxt, WHITE) == GREY:
                    i = path.index(nxt)
                    cyc = path[i:] + [nxt]
                    key = frozenset(zip(cyc, cyc[1:]))
                    if key in seen_cycles:
                        continue
                    seen_cycles.add(key)
                    out.append({
                        "cycle": " -> ".join(cyc),
                        "stacks": {
                            f"{a} -> {b}":
                                edges[(a, b)]["first_stack"]
                            for a, b in zip(cyc, cyc[1:])
                            if (a, b) in edges},
                    })
                elif color.get(nxt, WHITE) == WHITE:
                    visit(nxt, path + [nxt])
            color[node] = BLACK

        for n in list(graph):
            if color[n] == WHITE:
                visit(n, [n])
        return out

    # -- rendering / export ----------------------------------------------------

    def as_dict(self, table: Optional[Dict[str, int]] = None) -> Dict:
        return {
            "order_table": ORDER_TABLE if table is None else table,
            "edges": [dict(e) for e in self.edges.values()],
            "diff": self.diff(table),
        }

    def export(self, path: str,
               table: Optional[Dict[str, int]] = None) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.as_dict(table), f, indent=2)

    def render(self, table: Optional[Dict[str, int]] = None) -> str:
        table = ORDER_TABLE if table is None else table
        report = self.diff(table)
        lines = ["lock-graph witness (held -> acquired, runtime edges):"]
        for (held, acq), e in sorted(self.edges.items()):
            hr = table.get(held, "?")
            ar = table.get(acq, "?")
            lines.append(f"  {held} [rank {hr}] -> {acq} [rank {ar}]  "
                         f"x{e['count']}")
        if not self.edges:
            lines.append("  (no multi-lock acquisitions witnessed)")
        for v in report["violations"]:
            lines.append(f"INVERSION: {v['edge']} — {v['why']}")
            lines.append("  first acquisition stack:")
            lines.extend("    " + ln for ln in v["stack"].splitlines())
            if v["reverse_stack"]:
                lines.append("  reverse edge's first stack:")
                lines.extend("    " + ln
                             for ln in v["reverse_stack"].splitlines())
        for c in report["cycles"]:
            lines.append(f"CYCLE: {c['cycle']}")
            for edge, stack in c["stacks"].items():
                lines.append(f"  {edge} first acquisition stack:")
                lines.extend("    " + ln for ln in stack.splitlines())
        lines.append(
            f"witness: {report['edges']} distinct edge(s), "
            f"{report['acquisitions']} lock-held acquisitions, "
            f"{len(report['violations'])} inversion(s), "
            f"{len(report['cycles'])} cycle(s)"
            + (" — CLEAN against the LK001 ordering table"
               if report["clean"] else ""))
        return "\n".join(lines)


# the process-wide witness every STORE_LOCK_ORDER_CHECK'd store records
# into (tests that seed deliberate inversions build their own instance)
WITNESS = LockGraphWitness()
