"""Shared-memory column segments (ISSUE 19): the columnar store's numeric
columns as `multiprocessing.shared_memory` blocks that cross process
boundaries.

The columnar pod-row store (ISSUE 15) made the scheduler pipeline's hot
fields flat numpy arrays; this module lets those arrays live in named
POSIX shared memory so a WORKER PROCESS (scheduler/mpsched.py) can map
them read-only and solve against live cluster state without pickling a
single Pod — columns or keys only ever cross the boundary (schedlint
MP001).

Layout per column group ("arena"):

  {base}.ctl        16-byte control segment: [magic, latest_generation].
                    Its NAME never changes — readers resolve the live data
                    segment through it, so grow-by-remap never strands a
                    late attacher.
  {base}.g{N}       generation-N data segment: a 64-byte header
                    [magic, generation, nrows, capacity, version,
                    moved_to_gen, ncols, reserved] followed by the columns
                    back-to-back at fixed capacity. Offsets are derived
                    from (schema, capacity) — both sides share the schema
                    in code, the header carries the capacity.

Grow-by-remap: the owner allocates {base}.g{N+1} at double capacity,
copies every column, publishes the new generation in the control segment,
stamps the OLD header's moved_to_gen, and unlinks the old name. Readers
holding the old mapping still read it safely (unlink removes the name,
not the mapping), notice moved_to_gen (or the control generation) on
their next refresh(), and remap.

Ownership: the creating process is the only writer — readers get numpy
views with `writeable=False` (the MU001 read-only contract, extended
across the process boundary). The `version` field is a seqlock over the
HEADER (nrows), not the column bytes: concurrent column reads may tear,
which is fine for every consumer here — worker reads are advisory
(row_rv snapshots are re-validated by the owner at bind arbitration).

Lifecycle (schedlint MP002): every create is paired with close+unlink on
a finally/stop path — ShmArena.close() unlinks the data AND control
segments and is idempotent; readers close their mappings only. A leaked
`/dev/shm/ktpu-*` entry after stop() is a bug the MultiProcess bench rung
and tests/test_mpsched.py assert against.

Python 3.10 caveat: SharedMemory registers with the resource tracker even
on ATTACH (fixed only in 3.13's track=False), and multiprocessing
children SHARE the parent's tracker process — so a reader's registration
is a duplicate entry in the owner's cache, and unregistering it would
delete the owner's crash-cleanup protection. Attaches here suppress the
registration instead (`_attach`); only the owner's create-side
registration exists, which is exactly the crash-cleanup the tracker is
for.
"""

from __future__ import annotations

import os
import secrets
from typing import Dict, List, Optional, Sequence, Tuple

try:
    import numpy as np
except Exception:  # pragma: no cover - numpy-less rigs run the dict path
    np = None  # type: ignore

try:
    from multiprocessing import resource_tracker, shared_memory
except Exception:  # pragma: no cover - exotic platforms
    shared_memory = None  # type: ignore
    resource_tracker = None  # type: ignore

MAGIC = 0x4B545055  # "KTPU"
HEADER_WORDS = 8  # int64 each
HEADER_BYTES = HEADER_WORDS * 8
_H_MAGIC, _H_GEN, _H_NROWS, _H_CAP, _H_VER, _H_MOVED, _H_NCOLS, _H_RSV = \
    range(HEADER_WORDS)
CTL_WORDS = 2
CTL_BYTES = CTL_WORDS * 8

# the one prefix every arena name carries: leak checks (bench rung, tests)
# scan /dev/shm for it, so a forgotten close() cannot hide
NAME_PREFIX = "ktpu"


def available() -> bool:
    return np is not None and shared_memory is not None


def _attach(name: str):
    """Attach to an existing segment WITHOUT a resource_tracker
    registration. Python 3.10 registers on attach too (module docstring),
    and multiprocessing children share the parent's tracker process — so an
    attach-then-unregister would delete the OWNER's crash-cleanup entry
    from the shared cache (and make the owner's later unlink a noisy
    double-unregister). Suppressing the register call leaves the owner's
    registration — the only one that should exist — untouched."""
    if resource_tracker is None:  # pragma: no cover - exotic platforms
        return shared_memory.SharedMemory(name=name)
    reg = resource_tracker.register
    try:
        resource_tracker.register = lambda *a, **kw: None
        return shared_memory.SharedMemory(name=name)
    finally:
        resource_tracker.register = reg


def fresh_base_name(tag: str = "cols") -> str:
    """A collision-resistant arena base name: pid + random suffix, carrying
    the leak-scan prefix."""
    return f"{NAME_PREFIX}-{os.getpid()}-{tag}-{secrets.token_hex(4)}"


def leaked_segments() -> List[str]:
    """Every live /dev/shm entry carrying the arena prefix — the unlink-
    clean assertion's probe (empty on non-Linux fallback)."""
    try:
        return sorted(n for n in os.listdir("/dev/shm")
                      if n.startswith(NAME_PREFIX))
    except Exception:  # pragma: no cover - non-Linux
        return []


def _col_bytes(schema: Sequence[Tuple[str, str]], capacity: int) -> int:
    return sum(np.dtype(dt).itemsize for _n, dt in schema) * capacity


def _map_columns(buf, schema: Sequence[Tuple[str, str]], capacity: int,
                 writeable: bool) -> Dict[str, "np.ndarray"]:
    """Column views over a segment buffer at the layout the schema +
    capacity imply. Offsets are deterministic: header, then each column at
    its dtype's itemsize * capacity."""
    cols: Dict[str, np.ndarray] = {}
    off = HEADER_BYTES
    for name, dt in schema:
        d = np.dtype(dt)
        arr = np.ndarray((capacity,), dtype=d, buffer=buf, offset=off)
        if not writeable:
            arr.flags.writeable = False
        cols[name] = arr
        off += d.itemsize * capacity
    return cols


class ShmArena:
    """Owner side of one shared column group. All mutation happens in the
    creating process; `publish()` makes a row count visible to readers."""

    def __init__(self, schema: Sequence[Tuple[str, str]],
                 capacity: int = 1024, base_name: Optional[str] = None):
        if not available():
            raise RuntimeError("shared-memory columns need numpy + "
                               "multiprocessing.shared_memory")
        self.schema = [(n, str(np.dtype(d))) for n, d in schema]
        self.base_name = base_name or fresh_base_name()
        self.capacity = int(capacity)
        self.generation = 0
        self._closed = False
        self._ctl = shared_memory.SharedMemory(
            name=f"{self.base_name}.ctl", create=True, size=CTL_BYTES)
        ctl = np.ndarray((CTL_WORDS,), dtype=np.int64, buffer=self._ctl.buf)
        ctl[0] = MAGIC
        ctl[1] = 0
        self._seg = None
        self._alloc_segment(self.capacity, generation=0)

    # -- segment lifecycle -----------------------------------------------------

    def _seg_name(self, gen: int) -> str:
        return f"{self.base_name}.g{gen}"

    def _alloc_segment(self, capacity: int, generation: int) -> None:
        size = HEADER_BYTES + _col_bytes(self.schema, capacity)
        seg = shared_memory.SharedMemory(
            name=self._seg_name(generation), create=True, size=size)
        hdr = np.ndarray((HEADER_WORDS,), dtype=np.int64, buffer=seg.buf)
        hdr[_H_MAGIC] = MAGIC
        hdr[_H_GEN] = generation
        # schedlint: allow(SEQ002) fresh segment: no reader can map this
        # generation until the control word flips, so the header/column
        # writes here need no version bracket (the first publish() does)
        hdr[_H_NROWS] = 0
        hdr[_H_CAP] = capacity
        hdr[_H_VER] = 0
        hdr[_H_MOVED] = 0
        hdr[_H_NCOLS] = len(self.schema)
        self._seg = seg
        self._hdr = hdr
        self.capacity = capacity
        self.generation = generation
        self.arrays = _map_columns(seg.buf, self.schema, capacity,
                                   writeable=True)

    def grow(self, min_capacity: int) -> None:
        """Grow-by-remap: new generation at >= min_capacity (pow2 doubling),
        columns copied, control bumped, old header stamped with the forward
        pointer, old NAME unlinked (live mappings stay valid)."""
        new_cap = max(self.capacity, 1)
        while new_cap < min_capacity:
            new_cap *= 2
        old_seg, old_hdr, old_arrays = self._seg, self._hdr, self.arrays
        old_nrows = int(old_hdr[_H_NROWS])
        gen = self.generation + 1
        self._alloc_segment(new_cap, generation=gen)
        for name, _dt in self.schema:
            src = old_arrays[name]
            self.arrays[name][: len(src)] = src
        # schedlint: allow(SEQ002) grow-by-remap writes into the NEW
        # generation's segment, invisible to readers until ctl[1] flips
        # below — the version bracket is only needed once it is live
        self._hdr[_H_NROWS] = old_nrows
        ctl = np.ndarray((CTL_WORDS,), dtype=np.int64, buffer=self._ctl.buf)
        ctl[1] = gen
        old_hdr[_H_MOVED] = gen
        old_seg.close()
        try:
            old_seg.unlink()
        except Exception:  # pragma: no cover - raced external unlink
            pass

    def publish(self, nrows: int) -> None:
        """Seqlock publish of the visible row count (odd version = publish
        in progress)."""
        hdr = self._hdr
        hdr[_H_VER] += 1
        hdr[_H_NROWS] = nrows
        hdr[_H_VER] += 1

    @property
    def nrows(self) -> int:
        return int(self._hdr[_H_NROWS])

    def close(self) -> None:
        """Unlink everything this arena created. Idempotent; safe to call
        from finally/stop paths (schedlint MP002)."""
        if self._closed:
            return
        self._closed = True
        try:
            if self._seg is not None:
                self._seg.close()
                self._seg.unlink()
        except Exception:
            pass
        finally:
            try:
                self._ctl.close()
                self._ctl.unlink()
            except Exception:
                pass

    def __enter__(self) -> "ShmArena":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> Dict:
        return {"base": self.base_name, "generation": self.generation,
                "capacity": self.capacity, "nrows": self.nrows,
                "columns": [n for n, _d in self.schema],
                "bytes": HEADER_BYTES + _col_bytes(self.schema,
                                                   self.capacity)}


class ShmArenaReader:
    """Reader side: maps the live generation READ-ONLY; refresh() follows
    grow-by-remap. Safe in any process (including the owner's, for tests).
    Column reads are advisory — see the module docstring's seqlock note."""

    def __init__(self, base_name: str, schema: Sequence[Tuple[str, str]]):
        if not available():
            raise RuntimeError("shared-memory columns need numpy + "
                               "multiprocessing.shared_memory")
        self.base_name = base_name
        self.schema = [(n, str(np.dtype(d))) for n, d in schema]
        self._ctl = _attach(f"{base_name}.ctl")
        ctl = np.ndarray((CTL_WORDS,), dtype=np.int64, buffer=self._ctl.buf)
        if int(ctl[0]) != MAGIC:
            raise ValueError(f"{base_name}: bad arena magic")
        self._ctl_arr = ctl
        self._seg = None
        self.generation = -1
        self._attach(int(ctl[1]))

    def _attach(self, gen: int) -> None:
        seg = _attach(f"{self.base_name}.g{gen}")
        hdr = np.ndarray((HEADER_WORDS,), dtype=np.int64, buffer=seg.buf)
        hdr.flags.writeable = False
        if int(hdr[_H_MAGIC]) != MAGIC:
            seg.close()
            raise ValueError(f"{self.base_name}.g{gen}: bad segment magic")
        if int(hdr[_H_NCOLS]) != len(self.schema):
            seg.close()
            raise ValueError(f"{self.base_name}.g{gen}: schema mismatch "
                             f"({int(hdr[_H_NCOLS])} cols, expected "
                             f"{len(self.schema)})")
        old = self._seg
        self._seg = seg
        self._hdr = hdr
        self.generation = gen
        self.capacity = int(hdr[_H_CAP])
        self.arrays = _map_columns(seg.buf, self.schema, self.capacity,
                                   writeable=False)
        if old is not None:
            old.close()

    def refresh(self) -> bool:
        """Follow a grow-by-remap if one happened; True when remapped."""
        gen = int(self._ctl_arr[1])
        if gen != self.generation or int(self._hdr[_H_MOVED]):
            self._attach(gen)
            return True
        return False

    @property
    def nrows(self) -> int:
        """Seqlock-consistent row count (retries a mid-publish read)."""
        hdr = self._hdr
        for _ in range(64):
            v0 = int(hdr[_H_VER])
            n = int(hdr[_H_NROWS])
            if v0 % 2 == 0 and int(hdr[_H_VER]) == v0:
                return n
        return int(hdr[_H_NROWS])  # pragma: no cover - writer wedged mid-pub

    def close(self) -> None:
        """Close the mappings (readers never unlink — the owner owns the
        names; MP002's close half)."""
        try:
            if self._seg is not None:
                self._seg.close()
                self._seg = None
        finally:
            try:
                self._ctl.close()
            except Exception:
                pass


# -- the columnar store's numeric segments (ISSUE 19 tentpole) -----------------

# the PodColumns numeric columns that cross the process boundary — the
# scheduler pipeline's hot fields (store/columnar.py module docstring).
# bool diverged rides as int8 (numpy bool itemsize 1, stable across procs).
POD_COLS_SCHEMA = (
    ("ns_id", "int32"),
    ("node_id", "int32"),
    ("row_rv", "int64"),
    ("phase_id", "int32"),
    ("priority", "int64"),
    ("rank", "int32"),
    ("diverged", "bool"),
)

# the mpsched owner's per-round worker feeds (scheduler/mpsched.py):
# node shard columns ...
NODE_COLS_SCHEMA = (
    ("alloc_cpu", "int64"),   # allocatable cpu, millicores
    ("alloc_mem", "int64"),   # allocatable memory, bytes
    ("alloc_pods", "int64"),  # allocatable pod slots
    ("used_cpu", "int64"),    # committed cpu of bound/assumed pods
    ("used_mem", "int64"),
    ("used_pods", "int64"),
    ("worker", "int32"),      # owning worker slot; -1 = excluded (tainted)
)

# ... and the pending-pod batch columns (requests + routing). Workers read
# row_rv/node_id for these store_rows straight from the POD_COLS segment.
BATCH_COLS_SCHEMA = (
    ("store_row", "int64"),   # row into the store's pod columns
    ("cpu", "int64"),         # request, millicores
    ("mem", "int64"),         # request, bytes
    ("worker", "int32"),      # assigned worker slot this round
)
