from .store import (  # noqa: F401
    ADDED,
    BOOKMARK,
    DELETED,
    MODIFIED,
    AlreadyBoundError,
    AlreadyExistsError,
    APIStore,
    ConflictError,
    Event,
    NotFoundError,
    ResourceVersionTooOldError,
    Watch,
)
