"""In-memory versioned object store with watch semantics.

Fuses the roles of etcd3 + the apiserver registry + the watch cache into one
process-local component (reference: staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go,
storage/cacher/cacher.go:261, endpoints/handlers/watch.go:187). Semantics preserved:

  - A single monotonically increasing resourceVersion across all writes
    (etcd revision analog); every object carries the RV of its last write.
  - Optimistic concurrency: update/delete fail on RV conflict
    (reference: apiserver GuaranteedUpdate precondition behavior).
  - LIST returns a consistent snapshot + the RV it is current to; WATCH from that RV
    streams every subsequent event exactly once, in order — the List+Watch contract
    client-go's Reflector relies on (reference: tools/cache/reflector.go:394).
  - Transactional pod binding: sets spec.nodeName iff still unset
    (reference: BindingREST.Create, pkg/registry/core/pod/storage/storage.go:149).

The store is thread-safe. Watch delivery is via per-subscriber unbounded queues;
a slow watcher never blocks writers (the reference's Cacher drops/terminates slow
watchers; we buffer instead — acceptable in-process).

Concurrency (sharded locking): the store carries a GLOBAL lock plus PER-KIND
shards for its two high-traffic kinds, so the scheduler's bind worker can
commit whole batches without stalling every other client and a kubelet
heartbeat storm on `nodes` never queues behind a pod bind batch:

  LOCK-ORDERING TABLE (LK001 — acquire strictly in ascending rank, release
  in any order; composite helpers below always enter in rank order):

    rank | lock            | guards
    -----+-----------------+----------------------------------------------
      0  | _lock           | resourceVersion allocation, the kind map,
         |                 | every non-sharded kind's rows, watcher
         |                 | registration, event history, event emission
      1  | _pods_lock      | the `pods` rows AND the columnar pod-row
         |                 | table (store/columnar.py PodColumns)
      2  | _nodes_lock     | the `nodes` rows (ISSUE 15 satellite,
         |                 | following the pods-shard precedent)
    leaf | partition locks | PartitionRouter._route_lock /
         |                 | PartitionedScheduler._dispatch_lock — strictly
         |                 | after the whole store chain
         |                 | (scheduler/partition.py lock discipline)

  bind_many validates under the pods shard ALONE (the expensive part), so
  ingest/list/create traffic on other kinds proceeds concurrently; the
  commit (contiguous RV range, row/column writes, event emission) then runs
  in ONE short critical section under global + shard, which keeps the
  List+Watch contract exact — a LIST observes either none or all of the
  writes at the RV it returns.

  GENERALIZED ORDERING RULE: a thread holding any shard must not acquire a
  lock of LOWER rank (bind_many RELEASES the shard between its validate and
  commit phases and re-validates raced rows instead of holding through).
  Reversing the order deadlocks against every writer of that kind. ENFORCED
  twice: statically by schedlint rule LK001 (analysis/rules/locks.py,
  tier-1-gated, generalized over the ranked shard set) and at runtime by
  the _OrderedRLock wrappers (STORE_LOCK_ORDER_CHECK=1 / the pytest autouse
  fixture), which raise LockOrderViolation on inversion.

Event allocation (clone-free commits): pod events on the bind / status /
delete hot paths are LAZY — the Event initially SHARES the stored object
(safe: the store never mutates stored objects in place, later writes REPLACE
them), and a private per-object clone is materialized at most once, on first
delivery or replay to a non-coalescing watcher (_materialize_event). In the
scheduler steady state (only coalescing watchers subscribed) a 100k-bind
batch allocates ONE clone per pod instead of two. The external read-only
event contract is unchanged: per-object watchers only ever receive (and
replay) materialized private events, and the mutation detector fingerprints
both forms, so a consumer mutating either is still caught.

Native host commit (ISSUE 11): the per-pod loops of bind_many and
delete_pods — clone, row swap, RV stamp, event append — run inside the
in-tree C-API engine (native/hostcommit.cpp, ctypes.PyDLL, compiled on
first use) when it is available, entered ONCE per chunk. The engine replays
exactly the Python loops' object operations (the Python code below stays as
the oracle and the no-g++ fallback; tests/test_native_commit.py pins
byte-identical rows, RV sequence, and event streams), so the store's
critical sections shrink ~5x without any semantic change. Selection:
APIStore(native_commit=) or env STORE_NATIVE_COMMIT / the engine-level
HOSTSCHED_NATIVE_COMMIT kill switch.

  NATIVE LOCK RULE: the PyDLL commit entries HOLD the GIL and are legal
  under the store locks (they are plain interpreter work, just cheaper).
  The GIL-RELEASING kernels (ctypes CDLL in native/hostsched.py —
  native_greedy_solve, native_commit_deltas) are BLOCKING calls under LK002
  and must NEVER run inside a store/scheduler lock region: dropping the GIL
  while holding a store lock invites every classic lock/GIL interleaving
  (a GIL-waiting thread that needs this lock, a lock-waiting thread that
  holds the GIL). schedlint flags them like any other blocking call.

Columnar pod-row store (ISSUE 15): when numpy is available (and
STORE_COLUMNAR / APIStore(columnar=) don't opt out), the pod rows ALSO live
in a struct-of-arrays table (store/columnar.py PodColumns: interned
node/namespace/phase ids, rv/priority/rank int columns, gang keys and
signature-memo refs) and bind_many commits by COLUMN WRITES — node_id[rows],
a contiguous rv range, one diverged-bitmap set, ONE LazyBindBatch event
marker per chunk — with ZERO per-pod dict/Event allocation on the
steady-state path. The full Pod object of a bound row, and the per-object
Events of the batch, materialize LAZILY (at most once) when an API read, a
non-coalescing watcher, a history replay, or a cold field access needs them
— the ISSUE 4 lazy-event idiom extended from events to rows. Every other
write path (create/update/status/delete, the single bind) stays on the dict
rows and keeps the columns coherent via PodColumns.sync/insert/remove; a
diverged row (columns ahead of the dict object) is reconciled by
_materialize_pod_row before any dict-path read or write touches it. The
dict store remains bit-for-bit the oracle: STORE_COLUMNAR=0, columnar=False,
a missing numpy, or a store without the lazy/deep-copy event contract all
run the pure dict path end to end (tests/test_columnar_store.py pins
placements, RV sequence, and event streams byte-identical across the two).
"""

from __future__ import annotations

import copy
import itertools
import queue
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..api.types import Pod
from ..chaos import faultinject as _chaos
from ..obs import tracebuf as _tracebuf
from . import columnar as _columnar

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"

_watch_seq = itertools.count()

_metrics_mod = None


def _metrics():
    """kubernetes_tpu.server.metrics, imported on first use — the store
    cannot import the server package at module load (rest.py imports the
    store), and the watch/bind telemetry paths (ISSUE 7) only need it once
    something is actually observed."""
    global _metrics_mod
    if _metrics_mod is None:
        from ..server import metrics as m

        _metrics_mod = m
    return _metrics_mod


@dataclass(frozen=True)
class Event:
    type: str
    kind: str
    obj: Any
    resource_version: int
    # the object's previous stored state (None on create). Lets filtered
    # watchers decide scope transitions the way the reference's watchCache
    # does (predicate on prevObj vs obj); read-only like obj.
    prev: Any = None
    # lazy-materialization slot for hot-path pod events: a mutable
    # [materialized Event or None, cloner] pair, None on eager events. The
    # obj of a lazy event IS the stored object; APIStore._materialize_event
    # builds (once) the private clone handed to non-coalescing watchers.
    # compare=False keeps Event equality identical to the eager form.
    lazy: Any = field(default=None, compare=False, repr=False)
    # watch-propagation stamp (ISSUE 9): perf_counter at store commit,
    # SHARED across a batched write's events (one clock read per batch).
    # 0.0 = unstamped (propagation tracing disabled). compare=False keeps
    # Event equality identical to the pre-stamp form.
    commit_ts: float = field(default=0.0, compare=False, repr=False)


@dataclass(frozen=True)
class CoalescedEvent:
    """One multi-object event for a whole batched write (bind_many /
    create_many chunk) — the internal fast-path channel. Only watchers that
    subscribed with coalesce=True receive these; every other watcher sees the
    per-object `events` individually, so the external watch API is unchanged.

    origin is the writer's opaque tag (a scheduler passes its own so it can
    short-circuit re-ingesting its own bind confirmations); None for writers
    that don't tag. resource_version is the LAST rv in the batch."""

    type: str
    kind: str
    events: Tuple[Event, ...]
    resource_version: int
    origin: Optional[str] = None
    # the batch's shared commit stamp (ISSUE 9 satellite: the coalesced
    # fast path must carry it too, or propagation histograms would silently
    # exclude the NorthStar ingest path). 0.0 = tracing disabled.
    commit_ts: float = 0.0


class LazyBindBatch:
    """ONE history/event marker for a whole columnar bind_many chunk
    (ISSUE 15) — the lazy-event idiom extended from events to rows. The
    commit captures only O(batch) state: the key strings, the PRE-bind base
    object refs (the events' `prev`), the interned node ids (plus a ref to
    the append-only name table, so resolution is lock-free on any thread),
    the first rv of the contiguous range, and the shared commit stamp.

    Per-object Events materialize AT MOST ONCE for the whole consumer set
    (`events()`, double-checked under a per-batch lock): each gets a fresh
    bind clone of its base with the committed node/rv applied and a lazy
    slot ([None, cloner]) so non-coalescing watchers receive their private
    clones through the ordinary _materialize_event path. Field-for-field
    the stream is identical to the dict path's; identity-wise the event
    objects are private to the batch (never the stored row), which is
    strictly safer under the read-only event contract. In the scheduler
    steady state — only coalescing watchers, origin-tagged self-skip — a
    100k-bind run never materializes any of it."""

    __slots__ = ("type", "kind", "rv0", "n", "keys", "bases", "node_ids",
                 "node_names", "cloner", "commit_ts", "_mat", "_mlock")

    def __init__(self, etype: str, rv0: int, keys, bases, node_ids,
                 node_names, cloner, commit_ts: float):
        self.type = etype
        self.kind = "pods"
        self.rv0 = rv0  # rv of the FIRST event; the range is contiguous
        self.n = len(keys)
        self.keys = keys
        self.bases = bases
        self.node_ids = node_ids
        self.node_names = node_names  # append-only intern table (shared ref)
        self.cloner = cloner
        self.commit_ts = commit_ts
        self._mat = None  # materialized per-object Event list (once)
        self._mlock = threading.Lock()

    def __len__(self) -> int:
        return self.n

    @property
    def resource_version(self) -> int:
        """The LAST rv of the batch (watch-watermark semantics, matching
        CoalescedEvent.resource_version)."""
        return self.rv0 + self.n - 1

    def count_since(self, since_rv: int) -> int:
        """How many of this batch's events have rv > since_rv."""
        if since_rv < self.rv0:
            return self.n
        return max(0, self.n - (since_rv - self.rv0 + 1))

    def events(self) -> List["Event"]:
        """The batch's per-object events in rv order (materialized once,
        thread-safe: consumers iterate on their own threads outside any
        store lock; builds touch only batch-captured refs, never the store,
        so taking the batch lock under the store lock — replay — is safe)."""
        mat = self._mat
        if mat is not None:
            return mat
        with self._mlock:
            if self._mat is None:
                cloner = self.cloner
                names = self.node_names
                ids = self.node_ids.tolist() if hasattr(
                    self.node_ids, "tolist") else list(self.node_ids)
                rv = self.rv0
                etype = self.type
                ts = self.commit_ts
                out = []
                for i in range(self.n):
                    base = self.bases[i]
                    obj = cloner(base)
                    obj.spec.node_name = names[ids[i]]
                    obj.metadata.resource_version = rv + i
                    out.append(_make_event(etype, "pods", obj, rv + i, base,
                                           [None, cloner], ts))
                self._mat = out
            return self._mat

    def events_since(self, since_rv: int) -> List["Event"]:
        evs = self.events()
        if since_rv < self.rv0:
            return evs
        return evs[since_rv - self.rv0 + 1:]


class _LazyEventSeq:
    """The `events` member of a columnar CoalescedEvent: len() is O(1) (the
    scheduler's origin-tagged self/peer skip), iteration/indexing
    materializes the batch once for every consumer."""

    __slots__ = ("_batch",)

    def __init__(self, batch: LazyBindBatch):
        self._batch = batch

    def __len__(self) -> int:
        return self._batch.n

    def __iter__(self):
        return iter(self._batch.events())

    def __getitem__(self, i):
        return self._batch.events()[i]


class ConflictError(Exception):
    pass


class ResourceVersionTooOldError(Exception):
    """Watch requested from an RV older than retained history — the client must
    relist (reference: apiserver 'too old resource version' / 410 Gone)."""


class NotFoundError(Exception):
    pass


class AlreadyExistsError(Exception):
    pass


class AlreadyBoundError(Exception):
    pass


# The per-pod bind_many error phrase for a lost bind race (another writer
# set spec.node_name first). The store OWNS the message format — both the
# Python commit loop and the native engine (native/hostcommit.cpp) build
# exactly this phrase — so consumers recognize conflicts through the
# predicate below instead of each growing its own string match. ISSUE 12:
# the partitioned scheduler treats a recognized conflict as a FACT (the pod
# is bound; drop it locally), never as an error to retry.
_BIND_CONFLICT_PHRASE = " is already bound to "


def is_bind_conflict(message: str) -> bool:
    """True when a bind/bind_many per-pod error message reports the
    already-bound conflict (vs infrastructure errors or not-found)."""
    return _BIND_CONFLICT_PHRASE in message


class MutationDetectedError(Exception):
    """A watch consumer mutated an event object (client-go's cache mutation
    detector failure: informer objects are shared and must be read-only)."""


class MutationDetector:
    """Fingerprints emitted event objects and detects later mutation.

    reference: client-go tools/cache/mutation_detector.go — enabled by env
    (KUBE_CACHE_MUTATION_DETECTOR); here: APIStore(mutation_detector=True) or
    env CACHE_MUTATION_DETECTOR=true, then call store.check_mutations() (the
    test tier does this at teardown)."""

    LIMIT = 5_000

    def __init__(self):
        self._entries = []  # (event, fingerprint json)

    @staticmethod
    def _fingerprint(obj) -> str:
        import json as _json

        from ..api.serialize import to_dict

        try:
            return _json.dumps(to_dict(obj), sort_keys=True, default=repr)
        except Exception:
            return repr(obj)

    def record(self, ev: "Event") -> None:
        self._entries.append((ev, self._fingerprint(ev.obj)))
        if len(self._entries) > self.LIMIT:
            del self._entries[: self.LIMIT // 4]

    def check(self) -> None:
        for ev, fp in self._entries:
            now = self._fingerprint(ev.obj)
            if now != fp:
                raise MutationDetectedError(
                    f"{ev.type} {ev.kind} event object at rv "
                    f"{ev.resource_version} was mutated after emission:\n"
                    f"was: {fp}\nnow: {now}")


def pod_structural_clone(pod):
    """Fast pod clone for the bind/status hot paths: fresh Pod, ObjectMeta
    (with own labels/annotations/owner_references/finalizers containers),
    PodSpec, and PodStatus (own conditions list) — ~20x cheaper than deepcopy.

    The deep members that stay SHARED (containers, tolerations, affinity,
    topology-spread constraints, volumes, node_selector) are treated as
    immutable by every store consumer: the store itself never mutates stored
    objects (writes replace them), and clients mutate only top-level metadata
    dicts / spec.node_name / status fields — all cloned here."""
    meta = _shallow(pod.metadata)
    meta.labels = dict(meta.labels)
    meta.annotations = dict(meta.annotations)
    meta.owner_references = list(meta.owner_references)
    meta.finalizers = list(meta.finalizers)
    spec = _shallow(pod.spec)
    status = _shallow(pod.status)
    status.conditions = list(status.conditions)
    new = _shallow(pod)
    new.metadata = meta
    new.spec = spec
    new.status = status
    return new


def _shallow(obj):
    """Shallow copy without copy.copy's __reduce_ex__ machinery (~4x
    faster; this runs 3x per bind at 100k-bind rates). Replacing the fresh
    instance's __dict__ with a C-level dict copy beats update() into the
    lazily-created empty dict by another ~30%."""
    new = object.__new__(obj.__class__)
    new.__dict__ = obj.__dict__.copy()
    return new


def _make_event(etype, kind, obj, rv, prev=None, lazy=None, commit_ts=0.0):
    """Hot-path Event constructor: the frozen-dataclass __init__ goes through
    object.__setattr__ per field (~1.8µs — real money at 100k events per
    bind batch); building the instance dict directly is ~4x cheaper and
    produces an identical instance (frozen dataclasses keep their fields in
    __dict__)."""
    ev = object.__new__(Event)
    # frozen dataclasses also veto __dict__ assignment through their
    # __setattr__ — go around it the same way their own __init__ does
    object.__setattr__(ev, "__dict__",
                       {"type": etype, "kind": kind, "obj": obj,
                        "resource_version": rv, "prev": prev, "lazy": lazy,
                        "commit_ts": commit_ts})
    return ev


def pod_bind_clone(pod):
    """Minimal clone for the bind hot path: fresh Pod/ObjectMeta/PodSpec
    shells only. A bind mutates exactly spec.node_name and
    metadata.resource_version, so status and every metadata container
    (labels, annotations, owner_references, finalizers) stay SHARED with the
    source — the same read-only contract pod_structural_clone already applies
    to containers/tolerations/affinity, extended to the remaining members.
    Any later write that does touch those goes through pod_structural_clone
    (update_pod_status, caller-facing returns), which re-privatizes them.

    _shallow is inlined: this runs twice per bind (assume clone + store
    commit clone) at 100k-bind rates, and the call overhead alone is
    measurable there."""
    new = object.__new__(pod.__class__)
    new.__dict__ = pod.__dict__.copy()
    meta = object.__new__(pod.metadata.__class__)
    meta.__dict__ = pod.metadata.__dict__.copy()
    spec = object.__new__(pod.spec.__class__)
    spec.__dict__ = pod.spec.__dict__.copy()
    new.metadata = meta
    new.spec = spec
    return new


class Watch:
    """A single watch subscription. Iterate or .get(timeout). Call .stop() to end.

    Buffers are BOUNDED (maxsize events): a consumer that stops draining is
    terminated instead of growing the queue without limit — the reference's
    Cacher does the same to slow watchers (cacher.go terminateAllWatchers /
    per-watcher buffer overflow). A terminated watcher must relist+rewatch
    (`terminated` flips True and the stream ends)."""

    DEFAULT_MAXSIZE = 10_000

    def __init__(self, store: "APIStore", kind=None,
                 maxsize: int = DEFAULT_MAXSIZE, coalesce: bool = False,
                 ring: bool = False):
        self._q: "queue.Queue[Optional[Event]]" = queue.Queue(maxsize=maxsize or 0)
        # ring=True turns the bounded buffer into a RING (ISSUE 12
        # satellite): on overflow the OLDEST buffered delivery is dropped —
        # counted as reason="ring_overflow" — and the subscription survives
        # with a gap instead of terminating. For observability consumers
        # (`ktl ... -w` dashboards) that tolerate a lossy stream, this
        # removes the indirect backpressure of eviction: a terminated
        # watcher relists, and a LIST of a 100k-pod store under the global
        # lock IS the stall the bind workers would feel. Correctness
        # consumers (informer caches, the scheduler) keep ring=False — they
        # NEED the terminate->relist signal, a silent gap would corrupt them.
        self.ring = ring
        self.ring_dropped = 0  # lifetime ring_overflow drops (telemetry)
        self._store = store
        # stable subscriber id for the per-subscriber queue-length gauge
        # (store_watch_subscriber_queue_length) and watch_telemetry()
        self.id = f"w{next(_watch_seq)}"
        # kind: None = all kinds; a str = one kind; a set/tuple = several
        # (components subscribe to exactly what they handle, so high-volume
        # kinds they ignore — e.g. events — never fill their buffers)
        self._kinds = (None if kind is None
                       else {kind} if isinstance(kind, str) else set(kind))
        # coalesce=True opts into the internal fast-path channel: a batched
        # write (bind_many/create_many chunk) arrives as ONE CoalescedEvent
        # (counting as one buffered item) instead of N per-object events.
        # Consumers must handle both — history replay is always per-object.
        self.coalesce = coalesce
        self._stopped = False
        self.terminated = False  # True when evicted for falling behind
        # optional ping invoked after each delivery — the select-based
        # watch mux (server/watchmux.py) wakes on it instead of spending a
        # blocked thread per stream
        self.on_event = None
        # watch-propagation tracing (ISSUE 9): dequeue taps are O(1) — they
        # append (events, t_dequeue) ops here; per-event settlement into the
        # store's commit->delivery histograms runs at the next read surface
        # (watch_telemetry) or inline past _PROP_OPS_CAP, billed to
        # stat_sink (the scheduler wires its flight recorder in so the <2%
        # budget covers this tap too). last_delivered_rv feeds the rv-lag
        # gauge; _prop_min_rv excludes replayed history from the latency
        # distribution (a late subscriber's replay is catch-up, not bus lag).
        self._prop_ops: deque = deque()
        self.last_delivered_rv = 0
        self._prop_min_rv = 0
        self.stat_sink = None

    _PROP_OPS_CAP = 64

    def _note_delivered(self, evs) -> None:
        """O(1) dequeue tap: ONE perf_counter read for the drained batch,
        one deque append (refs only — the consumer holds the events alive
        through its own processing anyway), one rv watermark store."""
        self.last_delivered_rv = evs[-1].resource_version
        if not self._store._watch_propagation:
            return
        self._prop_ops.append((evs, time.perf_counter()))
        if len(self._prop_ops) > self._PROP_OPS_CAP:
            self._store._settle_propagation(self, inline=True)

    def _deliver(self, ev: Event) -> None:
        if self.terminated or self._stopped:
            return
        if _chaos.ACTIVE is not None and _chaos.ACTIVE.should_drop(
                "watch.deliver", ev.kind):
            # injected delivery drop (drop-only site: lock held). Counted
            # (ISSUE 7 satellite): a dropped delivery was invisible from
            # /metrics, so chaos runs couldn't prove the resync actually
            # recovered anything
            self._store._note_watch_drop("chaos", ev.kind)
            return
        if self._kinds is None or ev.kind in self._kinds:
            try:
                self._q.put_nowait(ev)
                cb = self.on_event
                if cb is not None:
                    # schedlint: allow(LK002) on_event is the watchmux wake
                    # ping — non-blocking by contract (a selector set/notify;
                    # server/watchmux.py); the delivery itself is put_nowait
                    cb()
            except queue.Full:
                self._overflow(ev)

    def _deliver_coalesced(self, cev: "CoalescedEvent") -> None:
        """Deliver a whole batched write as one buffered item (fast-path
        channel; only called for coalesce=True watchers)."""
        if self.terminated or self._stopped:
            return
        if _chaos.ACTIVE is not None and _chaos.ACTIVE.should_drop(
                "watch.deliver", cev.kind):
            # injected drop of a whole coalesced batch — counted once (the
            # unit dropped is the delivery, matching the injection site)
            self._store._note_watch_drop("chaos", cev.kind)
            return
        if self._kinds is None or cev.kind in self._kinds:
            try:
                self._q.put_nowait(cev)
                cb = self.on_event
                if cb is not None:
                    # schedlint: allow(LK002) same non-blocking wake-ping
                    # contract as _deliver above
                    cb()
            except queue.Full:
                self._overflow(cev)

    def _overflow(self, item=None) -> None:
        if self.ring and item is not None:
            # ring mode: drop the OLDEST buffered delivery to make room for
            # the newest — the subscription survives with a counted gap.
            # Everything here is non-blocking (get_nowait/put_nowait), so
            # the emitting writer (a partition's bind worker inside its
            # commit section) is never backpressured by a slow dashboard.
            try:
                old = self._q.get_nowait()
            except queue.Empty:
                old = None  # consumer drained between Full and here: the
                # slot freed itself, nothing was actually lost
            if old is not None:
                self.ring_dropped += 1
                self._store._note_watch_drop("ring_overflow", old.kind)
            try:
                self._q.put_nowait(item)
                return
            except queue.Full:
                # raced with a concurrent writer refilling the slot: this
                # delivery is the drop instead
                self.ring_dropped += 1
                self._store._note_watch_drop("ring_overflow", item.kind)
                return
        # slow watcher: evict rather than buffer forever; drop one
        # event to make room for the end-of-stream sentinel (the
        # stream is void anyway — the consumer must relist)
        self.terminated = True
        self._store._note_watch_drop("overflow", "")
        self._store._unsubscribe(self)
        try:
            self._q.get_nowait()
        except queue.Empty:
            pass
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass

    def get(self, timeout: Optional[float] = None) -> Optional[Event]:
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if ev is not None:
            self._note_delivered((ev,))
        return ev

    def drain(self, max_n: Optional[int] = None) -> List[Event]:
        """Drain buffered events; max_n bounds the take so a capped consumer
        LEAVES the remainder buffered (a break mid-list would silently drop
        already-dequeued events — the north-star 100k backlog lost 90% of
        its ADDED events to exactly that)."""
        out = []
        while max_n is None or len(out) < max_n:
            try:
                ev = self._q.get_nowait()
            except queue.Empty:
                break
            if ev is not None:
                out.append(ev)
        if out:
            self._note_delivered(out)
        return out

    def __iter__(self):
        while not self._stopped:
            ev = self._q.get()
            if ev is None:
                return
            self._note_delivered((ev,))
            yield ev

    def stop(self) -> None:
        self._stopped = True
        self._store._unsubscribe(self)
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass  # consumer is behind anyway; it checks _stopped/terminated


class LockOrderViolation(RuntimeError):
    """The runtime companion of schedlint LK001 tripped: a thread acquired
    the global RV lock while already holding the pods shard (the docstring's
    mandatory order reversed — a latent deadlock against every pod write)."""


class _LockOrderState(threading.local):
    """Per-store, per-thread held-lock stack for the order assertion."""

    def __init__(self):
        self.stack = []


class _OrderedRLock:
    """RLock wrapper asserting the store's lock-ordering rule at runtime —
    the dynamic half of schedlint LK001, catching acquisition orders the
    static pass cannot prove (callbacks, reflection, test doubles). Enabled
    per store via APIStore(lock_order_check=True) or env
    STORE_LOCK_ORDER_CHECK=1 (pytest turns it on for every test store via an
    autouse fixture in tests/conftest.py; set the env var on the daemon to
    run it in production).

    Rule: acquiring a lock of LOWER rank than one already held (global=0 <
    shard=1) raises LockOrderViolation — unless the thread already holds the
    lock (reentrant acquires never deadlock). The stack is per-store, so two
    independent stores never alias ranks.

    ISSUE 20: every fresh acquisition made while another ordered lock is
    held also RECORDS the edge (held -> acquired) into the lock-graph
    witness (store/lockgraph.py) — the whole tier-1 run becomes an actual
    acquisition-edge set that is diffed against the LK001 ordering table
    at session teardown, and `ktl vet --lock-graph` renders it. The record
    hot path is one dict hit; stacks are captured only on an edge's first
    sighting."""

    __slots__ = ("_lock", "_rank", "_name", "_state", "_witness")

    def __init__(self, name: str, rank: int, state: _LockOrderState,
                 witness=None):
        from .lockgraph import WITNESS

        self._lock = threading.RLock()
        self._rank = rank
        self._name = name
        self._state = state
        self._witness = WITNESS if witness is None else witness

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        stack = self._state.stack
        if all(held is not self for held in stack):  # fresh, not reentrant
            for held in stack:
                if held._rank > self._rank:
                    raise LockOrderViolation(
                        f"acquiring {self._name} while holding "
                        f"{held._name}: store/store.py mandates _lock "
                        "(global RV) -> _pods_lock (pods shard), never "
                        "reversed (schedlint LK001)")
            if stack:
                top = stack[-1]
                self._witness.record(top._name, top._rank,
                                     self._name, self._rank)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            stack.append(self)
        return ok

    def release(self) -> None:
        stack = self._state.stack
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


class _LockPair:
    """Context manager acquiring the global RV lock then a kind shard, in the
    module docstring's mandatory order (both RLocks, so nesting under either
    is fine)."""

    __slots__ = ("a", "b")

    def __init__(self, a, b):
        self.a, self.b = a, b

    def __enter__(self):
        self.a.acquire()
        self.b.acquire()
        return self

    def __exit__(self, *exc):
        self.b.release()
        self.a.release()


class _LockChain:
    """_LockPair generalized to the full ranked chain (ISSUE 15 satellite:
    the nodes shard makes three): acquires every lock in the ordering
    table's ascending-rank order, releases in reverse. Safe to nest under
    any prefix of itself (all RLocks)."""

    __slots__ = ("locks",)

    def __init__(self, *locks):
        self.locks = locks

    def __enter__(self):
        for lk in self.locks:
            lk.acquire()
        return self

    def __exit__(self, *exc):
        for lk in reversed(self.locks):
            lk.release()


class APIStore:
    """The hub every component is a client of (SURVEY.md §1)."""

    def __init__(self, deep_copy_on_write: bool = True,
                 mutation_detector: Optional[bool] = None,
                 lazy_pod_events: Optional[bool] = None,
                 lock_order_check: Optional[bool] = None,
                 watch_propagation: bool = True,
                 native_commit: Optional[bool] = None,
                 columnar: Optional[bool] = None,
                 history_limit: int = 50_000):
        import os

        if lock_order_check is None:
            lock_order_check = os.environ.get(
                "STORE_LOCK_ORDER_CHECK", "").lower() in ("1", "true")
        if lock_order_check:
            # runtime LK001: rank-asserting lock wrappers (see _OrderedRLock)
            state = _LockOrderState()
            self._lock = _OrderedRLock("_lock (global RV)", 0, state)
            self._pods_lock = _OrderedRLock("_pods_lock (pods shard)", 1,
                                            state)
            self._nodes_lock = _OrderedRLock("_nodes_lock (nodes shard)", 2,
                                             state)
        else:
            self._lock = threading.RLock()
            # the per-kind shards — see the module docstring's lock-ordering
            # TABLE (_lock -> _pods_lock -> _nodes_lock, ascending rank only)
            self._pods_lock = threading.RLock()
            self._nodes_lock = threading.RLock()
        self._pods_pair = _LockPair(self._lock, self._pods_lock)
        self._nodes_pair = _LockPair(self._lock, self._nodes_lock)
        self._store_chain = _LockChain(self._lock, self._pods_lock,
                                       self._nodes_lock)
        self._rv = 0  # monotonic resourceVersion, read via .rv
        if mutation_detector is None:
            mutation_detector = os.environ.get(
                "CACHE_MUTATION_DETECTOR", "").lower() in ("1", "true")
        self._mutation_detector = MutationDetector() if mutation_detector else None
        # lazy pod events (module docstring): default on; STORE_LAZY_POD_EVENTS=0
        # or the constructor arg force the eager per-event clones (the parity
        # oracle the columnar-pipeline tests compare against)
        if lazy_pod_events is None:
            lazy_pod_events = os.environ.get(
                "STORE_LAZY_POD_EVENTS", "").lower() not in ("0", "false")
        self._lazy_pod_events = lazy_pod_events
        # native host commit engine (module docstring): default on whenever
        # the C-API engine compiles; STORE_NATIVE_COMMIT=0 or the constructor
        # arg force the Python oracle (the parity tests' knob). Resolution of
        # engine availability is lazy — first bind decides, so a fresh
        # checkout's one-time g++ compile never blocks construction.
        if native_commit is None:
            native_commit = os.environ.get(
                "STORE_NATIVE_COMMIT", "").lower() not in ("0", "false")
        self._native_commit = native_commit
        # columnar pod-row table (ISSUE 15, module docstring): default on
        # when numpy is importable AND the store carries the lazy/deep-copy
        # event contract the column commit path is written against; the
        # env/constructor knobs and a numpy-less rig all fall back to the
        # pure dict path (the byte-for-bit oracle).
        if columnar is None:
            columnar = _columnar.env_enabled()
        self._cols = (_columnar.PodColumns(pod_bind_clone)
                      if columnar and _columnar.numpy_available()
                      and deep_copy_on_write and self._lazy_pod_events
                      else None)
        # kind -> {"namespace/name" or "name": obj}. The sharded kinds' row
        # dicts exist from birth so shard-only paths never mutate the kind
        # map. NOTE: a pod row may be STALE while its columnar row is
        # diverged (bind committed by column writes only) — internal readers
        # go through _materialize_pod_row / _materialize_pod_rows first.
        self._objects: Dict[str, Dict[str, Any]] = {"pods": {}, "nodes": {}}
        # bounded event history for watch replay (RV-ordered; columnar bind
        # chunks retain ONE LazyBindBatch marker each). The bound is the
        # store's steady-state memory knob (ISSUE 13): each retained eager
        # event pins an object clone (a lazy batch pins only base refs), so
        # a churning control plane holds up to ~history_limit x pod-size
        # bytes HERE at equilibrium. The default is BOUNDED a few churn
        # waves deep (ISSUE 15 satellite: the 200k-event watch-replay leak
        # the first soak run caught must be impossible to reintroduce by
        # forgetting the kwarg) — a resume older than the floor relists,
        # the contract subscribers already handle, and the rss/alloc trend
        # gates verify the plateau.
        self._history: List[Any] = []
        self._history_n = 0  # EVENT count (batch markers count their size)
        self._history_limit = history_limit
        # all events with rv > _history_floor_rv are retained
        self._history_floor_rv = 0
        self._watchers: List[Watch] = []
        self._deep_copy = deep_copy_on_write
        # watch-bus telemetry (ISSUE 7 satellite): per-reason dropped
        # delivery counts (chaos injection, overflow eviction) kept as plain
        # ints here (the drop sites run under the store lock) and mirrored
        # into store_watch_dropped_deliveries_total
        self._watch_drops: Dict[str, int] = {}
        self._watch_metrics_registered = False
        # watch-propagation tracing (ISSUE 9): commit->dequeue latency per
        # kind. Events carry a perf_counter commit stamp (one read per
        # batched write); subscriber dequeue taps record O(1) ops settled
        # HERE at render time (watch_telemetry) under a private lock — never
        # the store lock (LK002). False disables stamps AND taps (the
        # parity-oracle knob for the on/off byte-identical test).
        self._watch_propagation = watch_propagation
        self._prop_lock = threading.Lock()
        self._prop_hist: Dict[str, Any] = {}  # kind -> metrics.Histogram
        self._prop_settle_s = 0.0

    # -- helpers ---------------------------------------------------------------

    @property
    def rv(self) -> int:
        """Current (highest committed) resourceVersion."""
        with self._lock:
            return self._rv

    def _native_commit_engine(self):
        """The loaded C-API commit engine, or None (disabled / no g++ /
        env-killed). The first call on a fresh checkout pays the one-time
        g++ compile; every later call is an attribute check + env probe."""
        if not self._native_commit:
            return None
        from ..native import hostcommit

        return hostcommit if hostcommit.available() else None

    def _kind_lock(self, kind: str):
        """The lock(s) an op touching `kind` rows plus RV/history must hold:
        the global lock alone for most kinds, global + shard (ascending rank
        order) for the sharded kinds (pods, nodes)."""
        if kind == "pods":
            return self._pods_pair
        if kind == "nodes":
            return self._nodes_pair
        return self._lock

    def _materialize_pod_row(self, key: str) -> None:
        """Reconcile ONE diverged columnar row into its dict object before a
        dict-path read/write touches it (caller holds the pods shard). No-op
        on the dict path or for clean/missing rows."""
        if self._cols is not None:
            self._cols.materialize_key(key, self._objects["pods"])

    def _materialize_pod_rows(self) -> None:
        """Reconcile EVERY diverged columnar row (LIST / snapshot reads;
        caller holds the pods shard). Cost is one bind clone per row bound
        since the last full read — exactly the clones the columnar commit
        skipped, paid once and only when someone actually reads the rows."""
        if self._cols is not None:
            self._cols.materialize_all(self._objects["pods"])

    @staticmethod
    def object_key(obj) -> str:
        meta = obj.metadata
        ns = getattr(meta, "namespace", None)
        return f"{ns}/{meta.name}" if ns else meta.name

    def _copy(self, obj):
        """Full isolation copy: get/list results and stored create/update
        inputs must be immune to caller mutation, however deep."""
        return copy.deepcopy(obj) if self._deep_copy else obj

    def _event_copy(self, obj):
        """Copy for WATCH EVENTS — the fan-out hot path under churn. Event
        objects carry the client-go read-only contract (that is what the
        mutation detector polices), so pods take the ~20x cheaper structural
        clone; core Events (recorder narration — one store write per victim
        under preemption storms) take a flat-field clone; other kinds keep
        deepcopy. get/list/storage copies stay on _copy: their callers never
        signed the event contract."""
        if self._deep_copy:
            if type(obj) is Pod:
                return pod_structural_clone(obj)
            if type(obj).__name__ == "Event" and hasattr(obj, "involved_kind"):
                # core/v1 Event: scalar fields + metadata — a fresh shell
                # with a private metadata is full isolation minus the shared
                # metadata containers, same contract as pod events
                new = _shallow(obj)
                new.metadata = _shallow(obj.metadata)
                return new
        return self._copy(obj)

    def _emit(self, etype: str, kind: str, obj, prev=None) -> None:
        # Events carry a copy, never the stored object. For pods the copy is
        # a STRUCTURAL clone: top-level metadata/spec/status are private, but
        # nested spec members (containers, volumes, tolerations, ...) are
        # shared with the stored pod — event objects are read-only all the
        # way down, and the mutation detector polices exactly that contract.
        self._emit_prepared(etype, kind, self._event_copy(obj), prev=prev)

    def check_mutations(self) -> None:
        """Raise MutationDetectedError if any watcher mutated an event object
        (no-op unless the detector is enabled)."""
        if self._mutation_detector is not None:
            self._mutation_detector.check()

    def _emit_prepared(self, etype: str, kind: str, obj, prev=None) -> None:
        """Emit an event whose object is ALREADY private to the event (hot
        write paths pre-clone instead of paying a second deepcopy here).
        prev is the replaced stored object — orphaned from the store by this
        very write, so sharing it with watchers is safe (read-only)."""
        self._emit_event(Event(etype, kind, obj, self._rv, prev,
                               commit_ts=self._commit_stamp()))

    def _commit_stamp(self) -> float:
        """The propagation commit stamp for an event being emitted right now
        (0.0 when tracing is off). Batched writes read perf_counter ONCE and
        share the stamp across the batch instead of calling this per event."""
        return time.perf_counter() if self._watch_propagation else 0.0

    def _pod_event(self, etype: str, obj, cloner, prev=None) -> Event:
        """Event for a just-committed pod write (the clone-free commit hot
        path). Lazy fast path: the event SHARES `obj` (the stored object, or
        delete's orphaned post-delete clone — never mutated in place; later
        writes replace the row) and materializes a private per-object clone
        only for non-coalescing consumers (_materialize_event). Falls back
        to the eager clone when lazy events are disabled (the parity oracle
        knob) or the store doesn't isolate at all (deep_copy_on_write=False
        shares everywhere already)."""
        ts = self._commit_stamp()
        if not self._deep_copy:
            return _make_event(etype, "pods", obj, self._rv, prev,
                               commit_ts=ts)
        if self._lazy_pod_events:
            return _make_event(etype, "pods", obj, self._rv, prev,
                               lazy=[None, cloner], commit_ts=ts)
        return _make_event(etype, "pods", cloner(obj), self._rv, prev,
                           commit_ts=ts)

    def _materialize_event(self, ev: Event) -> Event:
        """The per-object form of a lazy event: a private clone of the shared
        stored object, built at most ONCE (first delivery or replay to a
        non-coalescing watcher) and reused for every later per-object
        consumer — all of them see the same object identity, exactly like
        the eager path. Callers hold _lock. The detector fingerprints the
        materialized object too, so a watcher mutating it is caught even
        though the emission-time record covered only the shared form."""
        lazy = ev.lazy
        if lazy is None:
            return ev
        mat = lazy[0]
        if mat is None:
            # the materialized form keeps the ORIGINAL commit stamp:
            # propagation measures commit->dequeue, not clone time
            mat = _make_event(ev.type, ev.kind, lazy[1](ev.obj),
                              ev.resource_version, ev.prev,
                              commit_ts=ev.commit_ts)
            if self._mutation_detector is not None:
                self._mutation_detector.record(mat)
            lazy[0] = mat
        return mat

    def _emit_event(self, ev: Event) -> None:
        """History + delivery for one event. Lazy events reach coalescing
        watchers (and history) in their shared form; per-object watchers get
        the materialized private clone."""
        if self._mutation_detector is not None:
            self._mutation_detector.record(ev)
        self._history.append(ev)
        self._history_n += 1
        self._trim_history()
        # snapshot: _deliver may evict (unsubscribe) a slow watcher mid-loop
        for w in list(self._watchers):
            if ev.lazy is not None and not w.coalesce:
                w._deliver(self._materialize_event(ev))
            else:
                w._deliver(ev)

    def _emit_batch(self, etype: str, kind: str, events: List[Event],
                    origin: Optional[str]) -> None:
        """Emit one batched write: per-object events go to history and every
        per-object watcher (external semantics unchanged — ordering and rv
        monotonicity are the list order), while coalesce=True watchers get a
        single CoalescedEvent for the whole batch (the internal fast path;
        one buffered item, one wake-up). Lazy events materialize their
        per-object clones once for the whole watcher set."""
        if not events:
            return
        if self._mutation_detector is not None:
            for ev in events:
                self._mutation_detector.record(ev)
        self._history.extend(events)
        self._history_n += len(events)
        self._trim_history()
        cev = None
        mat = None
        for w in list(self._watchers):
            if w.coalesce:
                if cev is None:
                    # the batch's shared stamp rides the coalesced form too
                    # (ISSUE 9 satellite: without it the NorthStar ingest
                    # path would be invisible to propagation histograms)
                    cev = CoalescedEvent(etype, kind, tuple(events),
                                         events[-1].resource_version, origin,
                                         events[-1].commit_ts)
                w._deliver_coalesced(cev)
            else:
                if mat is None:
                    mat = [self._materialize_event(ev) for ev in events]
                for ev in mat:
                    w._deliver(ev)

    def _trim_history(self) -> None:
        """Enforce the retained-event bound (caller holds _lock). History
        items are Events or whole LazyBindBatch markers; trimming drops
        whole items from the front until the overshoot plus a quarter of the
        bound is gone (hysteresis: one trim per ~limit/4 events, not one per
        event) and advances the replay floor to the last dropped rv."""
        if self._history_n <= self._history_limit:
            return
        target = (self._history_n - self._history_limit
                  + self._history_limit // 4)
        dropped = 0
        i = 0
        h = self._history
        while i < len(h) and dropped < target:
            item = h[i]
            dropped += item.n if type(item) is LazyBindBatch else 1
            i += 1
        self._history_floor_rv = h[i - 1].resource_version
        del h[:i]
        self._history_n -= dropped

    def history_events(self, since_rv: int = -1):
        """Flat per-object iteration of the retained history with rv >
        since_rv — the debug/testing read surface (pod-conservation audits,
        bind-transition counts). Columnar bind batches materialize their
        per-object events on demand; items are read-only like any event."""
        with self._lock:
            items = list(self._history)
        for item in items:
            if type(item) is LazyBindBatch:
                for ev in item.events_since(since_rv):
                    yield ev
            elif item.resource_version > since_rv:
                yield item

    # -- CRUD ------------------------------------------------------------------

    def create(self, kind: str, obj) -> Any:
        with self._kind_lock(kind):
            objs = self._objects.setdefault(kind, {})
            key = self.object_key(obj)
            if key in objs:
                raise AlreadyExistsError(f"{kind} {key} already exists")
            obj = self._copy(obj)
            self._rv += 1
            obj.metadata.resource_version = self._rv
            objs[key] = obj
            if kind == "pods" and self._cols is not None:
                self._cols.insert(key, obj)
            self._emit(ADDED, kind, obj)
            return obj

    def create_many(self, kind: str, objects: Iterable[Any],
                    origin: Optional[str] = None,
                    consume: bool = False) -> Tuple[int, List[Tuple[str, str]]]:
        """Bulk create under ONE lock acquisition with ONE coalesced ADDED
        event for the batch (per-object events still reach history and
        per-object watchers — see _emit_batch). Per-object failures
        (AlreadyExists) don't abort the batch; returns (created_count,
        [(key, error message), ...]) like bind_many.

        consume=True transfers OWNERSHIP of the passed objects to the store
        (no isolation copy — the bulk-loader contract: the caller must never
        touch them again). Default False keeps create()'s copy semantics."""
        errors: List[Tuple[str, str]] = []
        created = 0
        events: List[Event] = []
        with self._kind_lock(kind):
            objs = self._objects.setdefault(kind, {})
            cols = self._cols if kind == "pods" else None
            # ONE shared commit stamp for the whole batch (ISSUE 9): the
            # coalesced ingest path must carry propagation stamps too
            t_commit = self._commit_stamp()
            for obj in objects:
                key = self.object_key(obj)
                if key in objs:
                    errors.append((key, f"{kind} {key} already exists"))
                    continue
                if not consume:
                    obj = self._copy(obj)
                self._rv += 1
                obj.metadata.resource_version = self._rv
                objs[key] = obj
                if cols is not None:
                    cols.insert(key, obj)
                events.append(_make_event(ADDED, kind, self._event_copy(obj),
                                          self._rv, commit_ts=t_commit))
                created += 1
            self._emit_batch(ADDED, kind, events, origin)
        return created, errors

    def get(self, kind: str, key: str) -> Any:
        """Returns a copy (when deep_copy_on_write) — like a REST GET, each read is a
        fresh decode, so caller mutation can never corrupt stored state.
        Sharded-kind reads take the kind shard alone (no RV is returned, and
        every row commit of that kind holds its shard), so a bind batch in
        its validate phase never stalls them on the global lock."""
        if kind == "pods":
            lock = self._pods_lock
        elif kind == "nodes":
            lock = self._nodes_lock
        else:
            lock = self._lock
        with lock:
            if kind == "pods":
                # a columnar-bound row materializes on first read (shard
                # alone suffices: no RV allocation, no event emission)
                self._materialize_pod_row(key)
            try:
                return self._copy(self._objects.get(kind, {})[key])
            except KeyError:
                raise NotFoundError(f"{kind} {key} not found") from None

    def update(self, kind: str, obj, check_rv: bool = True) -> Any:
        with self._kind_lock(kind):
            objs = self._objects.setdefault(kind, {})
            key = self.object_key(obj)
            if kind == "pods":
                # the rv-conflict check below must see the row's CURRENT
                # state, not a pre-bind base a diverged columnar row stands
                # in front of
                self._materialize_pod_row(key)
            if key not in objs:
                raise NotFoundError(f"{kind} {key} not found")
            if check_rv and objs[key].metadata.resource_version != obj.metadata.resource_version:
                raise ConflictError(
                    f"{kind} {key}: rv {obj.metadata.resource_version} != "
                    f"{objs[key].metadata.resource_version}"
                )
            old = objs[key]
            obj = self._copy(obj)
            self._rv += 1
            obj.metadata.resource_version = self._rv
            objs[key] = obj
            if kind == "pods" and self._cols is not None:
                row = self._cols.key2row.get(key)
                if row is not None:
                    self._cols.sync(row, obj)
            self._emit(MODIFIED, kind, obj, prev=old)
            return obj

    def guaranteed_update(self, kind: str, key: str, mutate: Callable[[Any], Any], max_retries: int = 16) -> Any:
        """Read-modify-write with conflict retry (reference: etcd3 GuaranteedUpdate)."""
        for _ in range(max_retries):
            cur = self.get(kind, key)
            updated = mutate(copy.deepcopy(cur))
            try:
                return self.update(kind, updated)
            except ConflictError:
                continue
        raise ConflictError(f"{kind} {key}: too many conflicts")

    def delete(self, kind: str, key: str) -> Any:
        with self._kind_lock(kind):
            objs = self._objects.get(kind, {})
            if kind == "pods":
                # the DELETED event's clone source must carry the committed
                # bind a diverged columnar row holds in its columns
                self._materialize_pod_row(key)
            if key not in objs:
                raise NotFoundError(f"{kind} {key} not found")
            old = objs.pop(key)
            if kind == "pods" and self._cols is not None:
                self._cols.remove(key)
            # The DELETED event carries the object at its post-delete RV (client-go
            # convention: watchers track progress from obj.metadata.resourceVersion).
            # Pods take ONE structural clone (hot under preemption victim
            # storms: the async preparation worker deletes victims at batch
            # rate): the stamped clone is shared lazily with the event AND
            # returned — the return value is the history/event object, so it
            # carries the event read-only contract (the mutation detector
            # polices it; in-repo delete consumers serialize or discard it).
            # Other kinds keep the deepcopy + event-copy pair.
            if self._deep_copy and type(old) is Pod:
                obj = pod_structural_clone(old)
                self._rv += 1
                obj.metadata.resource_version = self._rv
                self._emit_event(self._pod_event(
                    DELETED, obj, pod_structural_clone, prev=old))
                return obj
            obj = self._copy(old)
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._emit(DELETED, kind, obj, prev=old)
            return obj

    def list(self, kind: str, predicate: Optional[Callable[[Any], bool]] = None) -> Tuple[List[Any], int]:
        """Consistent snapshot + the RV it is current to. Items are copies (when
        deep_copy_on_write), like a REST LIST response."""
        with self._kind_lock(kind):
            if kind == "pods":
                self._materialize_pod_rows()
            items = list(self._objects.get(kind, {}).values())
            if predicate is not None:
                items = [o for o in items if predicate(o)]
            return [self._copy(o) for o in items], self._rv

    def list_many(self, kinds: Iterable[str]) -> Tuple[Dict[str, List[Any]], int]:
        """Consistent multi-kind snapshot under one RV — the safe way to seed an
        informer over several kinds (a per-kind list+watch would race: an object
        created between two lists is in neither the lists nor the replay).
        Takes the global lock plus every requested shard, in the ordering
        table's ascending-rank order."""
        kinds = list(kinds)
        has_pods = "pods" in kinds
        has_nodes = "nodes" in kinds
        if has_pods and has_nodes:
            lock = self._store_chain
        elif has_pods:
            lock = self._pods_pair
        elif has_nodes:
            lock = self._nodes_pair
        else:
            lock = self._lock
        with lock:
            if has_pods:
                self._materialize_pod_rows()
            out = {k: [self._copy(o) for o in self._objects.get(k, {}).values()] for k in kinds}
            return out, self._rv

    def resource_version(self) -> int:
        with self._lock:
            return self._rv

    def kinds(self) -> List[str]:
        """Kinds that currently hold at least one object (discovery-equivalent)."""
        with self._lock:
            return [k for k, objs in self._objects.items() if objs]

    def transaction(self, kind: Optional[str] = None):
        """Hold the store locks across several operations (reentrant), making
        a read-check-write sequence atomic against other threads — the
        stand-in for the reference's etcd txn around quota check+create.
        Default (kind=None) takes the full chain (global + every shard, in
        the ordering table's rank order) — safe for any sequence. Callers
        that provably touch only one kind's rows can pass it to take the
        narrower lock set, so they don't stall holding the chain behind a
        bind batch's shard-only validate phase."""
        if kind == "pods":
            return self._pods_pair
        if kind == "nodes":
            return self._nodes_pair
        if kind is not None:
            return self._lock
        return self._store_chain

    # -- watch -----------------------------------------------------------------

    def watch(self, kind=None, since_rv: int = -1,
              maxsize: int = Watch.DEFAULT_MAXSIZE,
              coalesce: bool = False, ring: bool = False) -> Watch:
        """Subscribe to events. since_rv >= 0 replays history events with rv > since_rv
        first (the Reflector resume contract); since_rv == -1 means 'from now'.
        Raises ResourceVersionTooOldError if since_rv predates retained history
        or the replay alone would overflow the watch buffer — the caller must
        relist (410 Gone analog). maxsize bounds the per-watcher buffer; a
        consumer that falls that far behind is evicted (Watch.terminated).
        coalesce=True opts into CoalescedEvent delivery for batched writes
        (replay is still per-object). ring=True makes the bounded buffer a
        lossy ring for slow OBSERVABILITY consumers: overflow drops the
        oldest delivery (counted, reason="ring_overflow") and the
        subscription survives instead of terminating into a relist storm —
        see Watch.__init__; never use it for a consumer that builds a cache
        from the stream."""
        with self._lock:
            if 0 <= since_rv < self._history_floor_rv:
                raise ResourceVersionTooOldError(
                    f"rv {since_rv} is older than retained history (floor "
                    f"{self._history_floor_rv}); relist required"
                )
            replay = []
            replay_n = 0
            if since_rv >= 0:
                # history items are Events or whole LazyBindBatch markers;
                # count before materializing anything (a too-old resume must
                # not pay for events it will never deliver)
                for item in self._history:
                    if type(item) is LazyBindBatch:
                        c = item.count_since(since_rv)
                        if c:
                            replay.append(item)
                            replay_n += c
                    elif item.resource_version > since_rv:
                        replay.append(item)
                        replay_n += 1
                if maxsize and replay_n >= maxsize:
                    raise ResourceVersionTooOldError(
                        f"replay of {replay_n} events from rv {since_rv} exceeds "
                        f"the watch buffer ({maxsize}); relist required")
            w = Watch(self, kind, maxsize=maxsize, coalesce=coalesce,
                      ring=ring)
            # propagation baseline (ISSUE 9): replayed history is catch-up,
            # not bus lag — only events committed AFTER this subscription
            # enter the latency distribution. The delivered-RV watermark
            # starts at the resume point (or now) so the lag gauge reads 0
            # until real commits outrun the consumer.
            w._prop_min_rv = self._rv
            w.last_delivered_rv = since_rv if since_rv >= 0 else self._rv
            for item in replay:
                # a non-coalescing subscriber arriving mid/after a lazy batch
                # must see fully private event objects, same as live delivery
                # (replay is always per-object — columnar batches expand)
                if type(item) is LazyBindBatch:
                    for ev in item.events_since(since_rv):
                        w._deliver(ev if coalesce
                                   else self._materialize_event(ev))
                else:
                    w._deliver(item if coalesce
                               else self._materialize_event(item))
            self._watchers.append(w)
            # first successful subscription: expose this store's subscribers
            # to the render-time queue-length gauge (weakref — a collected
            # store silently drops out). Flag flipped under the lock so two
            # concurrent first watch() calls can't both register (duplicate
            # series would break /metrics scrapers); the registry call
            # itself stays outside the critical section (LK002).
            register = not self._watch_metrics_registered
            self._watch_metrics_registered = True
        if register:
            _metrics().register_watch_source(weakref.ref(self))
        return w

    def _unsubscribe(self, w: Watch) -> None:
        with self._lock:
            try:
                self._watchers.remove(w)
            except ValueError:
                pass

    def _note_watch_drop(self, reason: str, kind: str) -> None:
        """Count one dropped watch delivery (chaos injection or overflow
        eviction) — rare by construction, so the metrics import/inc on this
        path costs nothing in the steady state."""
        self._watch_drops[reason] = self._watch_drops.get(reason, 0) + 1
        _metrics().store_watch_dropped.inc(reason=reason, kind=kind)

    # -- watch propagation (ISSUE 9) -------------------------------------------

    def _prop_child(self, kind: str):
        """The per-kind commit->dequeue histogram (created on first use,
        under the private propagation lock — never the store lock)."""
        with self._prop_lock:
            h = self._prop_hist.get(kind)
            if h is None:
                m = _metrics()
                h = self._prop_hist[kind] = m.Histogram(
                    "watch_propagation", buckets=m.PROPAGATION_BUCKETS)
            return h

    def _settle_propagation(self, w: Watch, inline: bool = False) -> None:
        """Settle one subscriber's pending dequeue ops into the per-kind
        propagation histograms (private + the process-wide Prometheus
        series). Runs at read surfaces (watch_telemetry) or inline on the
        consuming thread past the ops cap — inline cost bills the watch's
        stat_sink (the scheduler's flight recorder), read-side cost accrues
        to the settle_seconds counter only. Concurrent settlers are safe:
        deque.popleft hands each op to exactly one of them."""
        ops = w._prop_ops
        if not ops:
            return
        t0 = time.perf_counter()
        m = _metrics()
        min_rv = w._prop_min_rv
        by_kind: Dict[str, List[float]] = {}
        bulk: List[Tuple[str, float, int]] = []
        while True:
            try:
                evs, t = ops.popleft()
            except IndexError:
                break
            for ev in evs:
                ts = ev.commit_ts
                if ts <= 0.0 or ev.resource_version <= min_rv:
                    continue  # unstamped, or replayed catch-up history
                if type(ev) is CoalescedEvent:
                    # the whole batch shares ONE stamp: n observations of
                    # one value, one bucket probe (Histogram.observe_n)
                    bulk.append((ev.kind, t - ts, len(ev.events)))
                else:
                    by_kind.setdefault(ev.kind, []).append(t - ts)
        for kind, vals in by_kind.items():
            h = self._prop_child(kind)
            res = h.bucket_counts(vals)
            if res is not None:
                # one numpy bucket pass feeds the private histogram AND the
                # process-wide series (identical bucket layouts)
                h.observe_counts(*res)
                m.store_watch_propagation.child(kind).observe_counts(*res)
        for kind, val, n in bulk:
            self._prop_child(kind).observe_n(val, n)
            m.store_watch_propagation.child(kind).observe_n(val, n)
        dt = time.perf_counter() - t0
        with self._prop_lock:
            self._prop_settle_s += dt
        # trace timeline (ISSUE 18): one slice per settlement PASS (a pass
        # drains every pending dequeue op — never per event)
        if _tracebuf.ACTIVE is not None:
            settled = sum(len(v) for v in by_kind.values()) \
                + sum(n for _k, _v, n in bulk)
            _tracebuf.ACTIVE.note_span(
                "watch", "settle", t0, t0 + dt, cat="watch",
                args={"events": settled, "inline": inline})
        if inline:
            sink = w.stat_sink
            if sink is not None:
                sink.note_self_time(dt)

    def clear_watch_propagation(self) -> None:
        """Reset the settled propagation distributions (the bench clears at
        the measured window's start, like flightrec.clear())."""
        with self._prop_lock:
            self._prop_hist.clear()
            self._prop_settle_s = 0.0

    def watch_propagation_summary(self) -> Dict:
        """Per-kind + merged commit->dequeue distribution: what `ktl sched
        stats` renders, the bench rungs publish, and the
        watch_propagation_p99_s SLO key (scheduler/slo.py) gates. Callers
        that need fresh numbers go through watch_telemetry(), which settles
        every subscriber's pending ops first."""
        m = _metrics()
        with self._prop_lock:
            hists = dict(self._prop_hist)
            settle = self._prop_settle_s
        merged = m.Histogram("merged", buckets=m.PROPAGATION_BUCKETS)
        kinds: Dict[str, Dict] = {}
        for kind, h in sorted(hists.items()):
            counts, total_sum, n = h.counts_snapshot()
            if n == 0:
                continue
            merged.observe_counts(counts, total_sum, n)
            kinds[kind] = {
                "count": n,
                "mean_s": round(total_sum / n, 6),
                "p50_s": round(h.quantile(0.50), 6),
                "p99_s": round(h.quantile(0.99), 6),
            }
        total_sum, n = merged.snapshot()
        return {
            "kinds": kinds,
            "count": n,
            "p50_s": round(merged.quantile(0.50), 6) if n else None,
            "p99_s": round(merged.quantile(0.99), 6) if n else None,
            "settle_seconds": round(settle, 6),
        }

    def watch_subscriber_telemetry(self) -> List[Dict]:
        """Subscriber rows only — the cheap read the /metrics GaugeFuncs
        use per scrape. Settles pending propagation ops first (keeps the
        Prometheus propagation series fresh and the per-watch op deques
        empty — a falsy no-op when nothing is pending) but SKIPS the
        merged-summary construction watch_telemetry() does, which the
        gauges never read. The rv watermark is against the GLOBAL
        resourceVersion stream (etcd-revision semantics), so a
        kind-filtered subscriber's lag includes unrelated commits — like
        the reference's watch-cache lag, it measures staleness, not
        undelivered matching events."""
        with self._lock:
            watchers = list(self._watchers)
            rv = self._rv
        for w in watchers:
            # outside the store lock (LK002)
            self._settle_propagation(w)
        return [{"id": w.id,
                 "queue_length": w._q.qsize(),
                 "coalesce": w.coalesce,
                 "ring": w.ring,
                 "ring_dropped": w.ring_dropped,
                 "terminated": w.terminated,
                 "last_delivered_rv": w.last_delivered_rv,
                 "rv_lag": max(0, rv - w.last_delivered_rv)}
                for w in watchers]

    def watch_lag(self) -> Dict:
        """Subscriber count + worst delivered-RV lag as a PURE O(subscribers)
        read — no propagation-op settlement, no distribution construction.
        The window-close probe (obs/timeseries.py, ISSUE 13) calls this every
        few seconds; settlement stays owned by the surfaces that publish
        distributions (watch_telemetry / the /metrics gauges)."""
        with self._lock:
            watchers = list(self._watchers)
            rv = self._rv
        return {"subscribers": len(watchers),
                "max_rv_lag": max((max(0, rv - w.last_delivered_rv)
                                   for w in watchers), default=0)}

    def watch_telemetry(self) -> Dict:
        """Per-subscriber watch-bus state (ISSUE 7 satellite; propagation +
        rv-lag columns ISSUE 9): live subscriber ids with buffered-event
        counts and delivered-RV watermarks, the dropped-delivery counters,
        and the settled commit->dequeue propagation distribution — what
        `ktl sched stats`, /debug/controlstats, and the bench rungs read."""
        with self._lock:
            drops = dict(self._watch_drops)
        return {
            "subscribers": self.watch_subscriber_telemetry(),
            "dropped": drops,
            "propagation": self.watch_propagation_summary(),
        }

    # -- columnar read surfaces (ISSUE 15) -------------------------------------

    @property
    def columnar(self) -> bool:
        """True when the columnar pod-row table is engaged (numpy present,
        not opted out, lazy/deep-copy event contract)."""
        return self._cols is not None

    def pod_columns(self):
        """Read-only view over the live pod columns (store/columnar.py
        PodColumnsView), or None on the dict path. The view's rows/arrays
        are STORE-RETURNED READ-ONLY objects — the same contract as event
        objects and get/list results (schedlint MU001 recognizes this call
        as a taint source; the numpy members also refuse writes at runtime).
        Take it under transaction(\"pods\") for a consistent snapshot, or
        read it lock-free as advisory telemetry."""
        if self._cols is None:
            return None
        with self._pods_lock:
            return _columnar.PodColumnsView(self._cols)

    def capture_sig_memos(self, pods) -> int:
        """Back-fill the columnar sig column from pod objects whose
        signature memos were primed outside the store (ISSUE 17 satellite,
        the PR 15 carryover). The scheduler calls this at the batch's
        bind/assume edge, right after build_pod_batch primed
        `_class_sig`/`_req_sig` on its queue pods: those refs anchor to the
        same spec/labels objects the stored rows share (structural clones
        copy __dict__ at the C level), so a row re-synced later by a
        status/relist write keeps a seedable signature instead of starting
        over. Returns the number of rows captured; 0 on the dict path."""
        if self._cols is None:
            return 0
        captured = 0
        with self._pods_lock:
            for p in pods:
                if self._cols.capture(p.key, p):
                    captured += 1
        return captured

    def columnar_stats(self) -> Optional[Dict]:
        """Columnar-table telemetry (rows, diverged count, lifetime lazy
        materializations, intern-table sizes) — what `ktl sched stats` and
        sched_stats()[\"store_columnar\"] render; None on the dict path."""
        if self._cols is None:
            return None
        with self._pods_lock:
            return self._cols.stats()

    def enable_shm(self) -> Optional[str]:
        """Back the columnar numeric segments with a shared-memory arena
        (ISSUE 19, store/shm.py): existing columns migrate into named
        /dev/shm segments a worker process can map read-only by the
        returned base name. Idempotent (returns the live arena's name);
        None on the dict path or when shm/numpy is unavailable. The store
        process stays the ONLY writer — everything still mutates under the
        pods shard exactly as before, just into shared bytes."""
        if self._cols is None:
            return None
        from . import shm as _shm

        if not _shm.available():
            return None
        with self._pods_lock:
            if self._cols._arena is not None:
                return self._cols._arena.base_name
            arena = _shm.ShmArena(_shm.POD_COLS_SCHEMA,
                                  capacity=len(self._cols.keys))
            try:
                self._cols.attach_arena(arena)
            except Exception:
                arena.close()
                raise
            return arena.base_name

    @property
    def shm_name(self) -> Optional[str]:
        """The live pod-column arena's base name (None until enable_shm)."""
        cols = self._cols
        return cols._arena.base_name if cols is not None and \
            cols._arena is not None else None

    def shm_close(self) -> None:
        """Detach + unlink the pod-column arena (idempotent). Whoever called
        enable_shm() owns calling this on its stop/finally path so a
        teardown never leaks /dev/shm segments — schedlint MP002's
        close+unlink half. The columns fall back to private numpy arrays
        with contents preserved."""
        if self._cols is None:
            return
        with self._pods_lock:
            arena = self._cols._arena
            if arena is None:
                return
            cols = self._cols
            cap = len(cols.keys)
            for attr in cols._SHM_ATTRS:
                shared = getattr(cols, attr)
                setattr(cols, attr,
                        _columnar.np.array(shared[:cap], copy=True))
            cols._arena = None
            arena.close()

    # -- scheduling-specific transactional surfaces ----------------------------

    def _pod_internal(self, key: str):
        # dict-path consumers (single bind, status writes) need the CURRENT
        # row: reconcile a diverged columnar row first (caller holds the
        # shard, which is all materialization needs)
        self._materialize_pod_row(key)
        try:
            return self._objects.get("pods", {})[key]
        except KeyError:
            raise NotFoundError(f"pods {key} not found") from None

    def bind(self, namespace: str, name: str, node_name: str) -> Any:
        """Atomic pod->node binding (reference: BindingREST.Create,
        pkg/registry/core/pod/storage/storage.go:149 — guaranteed-update that fails
        if the pod is already bound to a different node).

        Hot path: binds happen at batch-solver rate (the north star is 100k),
        so the stored object is ONE bind-specialized clone and the event
        shares it lazily (_pod_event) — per-object watchers get their private
        clone on first delivery."""
        with self._pods_pair:
            key = f"{namespace}/{name}"
            pod = self._pod_internal(key)
            if pod.spec.node_name:
                raise AlreadyBoundError(f"pod {key} is already bound to {pod.spec.node_name}")
            new = pod_bind_clone(pod)
            new.spec.node_name = node_name
            self._rv += 1
            new.metadata.resource_version = self._rv
            self._objects["pods"][key] = new
            if self._cols is not None:
                row = self._cols.key2row.get(key)
                if row is not None:
                    self._cols.sync(row, new)
            self._emit_event(self._pod_event(MODIFIED, new, pod_bind_clone,
                                             prev=pod))
            # the caller's copy is distinct from both the stored object and
            # the event object (mutating it must corrupt neither); the full
            # structural clone re-privatizes the metadata containers too
            return pod_structural_clone(new)

    def bind_many(self, bindings: Iterable[Tuple[str, str, str]],
                  origin: Optional[str] = None) -> Tuple[int, List[Tuple[str, str]]]:
        """Batched bind: one lock acquisition for a whole solver batch.
        bindings = (namespace, name, node_name) triples. Returns
        (bound_count, [(key, error message) ...]) — per-pod failures do not
        abort the batch (each binding is its own transaction, like N
        BindingREST calls back-to-back).

        origin tags the batch's CoalescedEvent so the writer can recognize
        its own bind MODIFIED events on re-ingest (the scheduler's bind
        worker confirms its assumes directly and skips them); foreign
        consumers and per-object watchers are unaffected.

        Two phases (module docstring lock-ordering rule): validate + ONE
        pod_bind_clone per pod under the kind shard ALONE — the expensive
        part, concurrent with every non-pod store client — then a short
        commit under global+shard that stamps a contiguous RV range, inserts
        the rows, and emits lazy events sharing the stored objects. Rows
        that changed between the phases (a concurrent store.bind from the
        serial fallback path) are re-validated by stored-object identity."""
        # commit-latency histogram (ISSUE 7 satellite): ONE observation per
        # bind_many call — a bind-worker chunk — covering both phases. The
        # before/after metric for the direction-1 native commit-loop port.
        # Observed on success returns only (an injected raise never committed)
        t0 = time.perf_counter()
        if _chaos.ACTIVE is not None:
            # injected transient store failure (raises/delays BEFORE any
            # lock): the caller's retry/backoff is what the chaos tests prove
            _chaos.ACTIVE.fire("store.bind_many")
        if self._cols is not None:
            # columnar pod-row path (ISSUE 15, module docstring): commit by
            # column writes, zero per-pod dict/Event allocation
            return self._bind_many_columnar(bindings, origin, t0)
        errors: List[Tuple[str, str]] = []
        prepared: List = []  # (key, old stored pod, new clone, node_name)
        pods = self._objects["pods"]
        native = self._native_commit_engine()
        with self._pods_lock:
            if native is not None:
                # native validate+clone loop — identical entries/errors,
                # ~5x fewer interpreter cycles under the shard (PyDLL: GIL
                # held, non-blocking — legal here per the module docstring)
                native.bind_prepare(pods, bindings, prepared, errors)
            else:
                for namespace, name, node_name in bindings:
                    key = f"{namespace}/{name}"
                    pod = pods.get(key)
                    if pod is None:
                        errors.append((key, f"pods {key} not found"))
                        continue
                    if pod.spec.node_name:
                        errors.append(
                            (key, f"pod {key} is already bound to {pod.spec.node_name}"))
                        continue
                    new = pod_bind_clone(pod)
                    new.spec.node_name = node_name
                    prepared.append((key, pod, new, node_name))
        bound = 0
        if not prepared:
            _metrics().store_bind_many_duration.observe(
                time.perf_counter() - t0)
            return bound, errors
        if native is not None and _chaos.ACTIVE is not None:
            # injected native-commit failure (ISSUE 11 satellite): fires in
            # the phase gap — clones made, NOTHING committed, no lock held —
            # so a mid-chunk native fault leaves the store untouched and the
            # caller's retry/requeue machinery (bind worker supervision)
            # must conserve every pod (ChaosChurn_20k exercises this)
            _chaos.ACTIVE.fire("native.commit")
        events: List[Event] = []
        # mode decided once per batch; rv and the event constructor live in
        # locals — the loop below runs 100k times per north-star solve
        lazy_on = self._deep_copy and self._lazy_pod_events
        eager = self._deep_copy and not self._lazy_pod_events
        append = events.append
        get = pods.get
        with self._lock:
            with self._pods_lock:
                rv = self._rv
                # shared propagation stamp for the whole commit (one read)
                t_commit = self._commit_stamp()
                if native is not None:
                    mode = 1 if lazy_on else (2 if eager else 0)
                    rv, bound = native.bind_commit(
                        pods, prepared, events, errors, rv, mode, t_commit,
                        pod_bind_clone, MODIFIED)
                else:
                    for key, old, new, node_name in prepared:
                        if get(key) is not old:
                            # raced between the phases: re-validate on the
                            # current row (also catches duplicate keys within
                            # one batch — the second commit sees the first)
                            cur = get(key)
                            if cur is None:
                                errors.append((key, f"pods {key} not found"))
                                continue
                            if cur.spec.node_name:
                                errors.append(
                                    (key, f"pod {key} is already bound to "
                                          f"{cur.spec.node_name}"))
                                continue
                            old = cur
                            new = pod_bind_clone(cur)
                            new.spec.node_name = node_name
                        rv += 1
                        new.metadata.resource_version = rv
                        pods[key] = new
                        if lazy_on:
                            append(_make_event(MODIFIED, "pods", new, rv, old,
                                               [None, pod_bind_clone],
                                               t_commit))
                        elif eager:
                            append(_make_event(MODIFIED, "pods",
                                               pod_bind_clone(new), rv, old,
                                               commit_ts=t_commit))
                        else:
                            append(_make_event(MODIFIED, "pods", new, rv, old,
                                               commit_ts=t_commit))
                        bound += 1
                self._rv = rv
                self._emit_batch(MODIFIED, "pods", events, origin)
        _metrics().store_bind_many_duration.observe(time.perf_counter() - t0)
        return bound, errors

    def _bind_many_columnar(self, bindings, origin: Optional[str],
                            t0: float) -> Tuple[int, List[Tuple[str, str]]]:
        """bind_many on the columnar pod-row table (ISSUE 15). Same two
        phases and the same external contract as the dict path — identical
        RV sequence, error messages, event-stream content across both
        coalesce modes — but the commit is COLUMN WRITES (node ids, one
        contiguous rv range, the diverged bitmap) plus ONE LazyBindBatch
        event marker, instead of a clone-and-swap + Event per pod. Raced
        rows between the phases are re-validated against the row-rv
        snapshot (every row write bumps it; delete poisons it), mirroring
        the dict path's stored-object identity check."""
        cols = self._cols
        errors: List[Tuple[str, str]] = []
        native = self._native_commit_engine()
        if native is not None:
            bindings = bindings if isinstance(bindings, (list, tuple)) \
                else list(bindings)
        with self._pods_lock:
            rows, ids, keys, rv_snap = cols.bind_prepare(
                bindings, errors, native)
        if not len(rows):
            _metrics().store_bind_many_duration.observe(
                time.perf_counter() - t0)
            return 0, errors
        if native is not None and _chaos.ACTIVE is not None:
            # same injected phase-gap boundary as the dict path (ISSUE 11):
            # rows validated, NOTHING committed, no lock held — a mid-chunk
            # fault leaves the columns (and the dict rows) untouched
            _chaos.ACTIVE.fire("native.commit")
        bound = 0
        with self._lock:
            with self._pods_lock:
                rv0 = self._rv
                t_commit = self._commit_stamp()
                bound, keys, bases, ids = cols.commit_bind(
                    rows, ids, keys, rv_snap, rv0, errors)
                if bound:
                    self._rv = rv0 + bound
                    batch = LazyBindBatch(MODIFIED, rv0 + 1, keys, bases,
                                          ids, cols.node_names,
                                          pod_bind_clone, t_commit)
                    self._emit_bind_batch(batch, origin)
        _metrics().store_bind_many_duration.observe(time.perf_counter() - t0)
        return bound, errors

    def _emit_bind_batch(self, batch: LazyBindBatch,
                         origin: Optional[str]) -> None:
        """History + delivery for one columnar bind batch: ONE retained
        marker, ONE CoalescedEvent per coalescing watcher (lazy events
        sequence — len() without materialization), per-object watchers get
        the materialized stream through the ordinary lazy-slot path. With
        the mutation detector armed the batch materializes eagerly right
        here, so emission-time fingerprints exist exactly like the dict
        path's (the detector is a test-tier knob; the zero-alloc claim is
        about the production steady state)."""
        if self._mutation_detector is not None:
            for ev in batch.events():
                self._mutation_detector.record(ev)
        self._history.append(batch)
        self._history_n += batch.n
        self._trim_history()
        cev = None
        mat = None
        for w in list(self._watchers):
            if w.coalesce:
                if cev is None:
                    cev = CoalescedEvent(batch.type, "pods",
                                         _LazyEventSeq(batch),
                                         batch.resource_version, origin,
                                         batch.commit_ts)
                w._deliver_coalesced(cev)
            else:
                if mat is None:
                    mat = [self._materialize_event(ev)
                           for ev in batch.events()]
                for ev in mat:
                    w._deliver(ev)

    def delete_pods(self, keys: Iterable[str],
                    origin: Optional[str] = None) -> Tuple[int, List[Tuple[str, str]]]:
        """Batched pod delete: one lock acquisition + one coalesced DELETED
        batch for a whole victim set — the bulk companion of delete() on the
        SAME native commit entry as bind_many (ISSUE 11 satellite: the
        PreemptionAsync preparation worker's per-victim delete() calls were
        the residual GIL-bound store path). Per-pod semantics preserved
        exactly: each deleted pod's event carries ONE structural clone at its
        post-delete RV with prev=old (lazy, like delete()); per-key misses
        don't abort the batch. Returns (deleted_count, [(key, error), ...]).

        Victim sets are small (bounded by one preemption batch), so a single
        critical section is fine — this path never sees 100k-pod chunks."""
        keys = list(keys)
        errors: List[Tuple[str, str]] = []
        events: List[Event] = []
        deleted = 0
        native = self._native_commit_engine()
        if native is not None and _chaos.ACTIVE is not None:
            # same injected boundary as bind_many's (no lock held yet)
            _chaos.ACTIVE.fire("native.commit")
        with self._pods_pair:
            pods = self._objects["pods"]
            if self._cols is not None:
                # victims bound by a columnar batch materialize first: the
                # DELETED events' clone source must carry the committed
                # node/rv. Victim sets are preemption-batch sized, so the
                # per-victim clone here is not a hot-path cost (bind_many is
                # the 100k-rate entry; the columnar win lives there).
                for key in keys:
                    self._cols.materialize_key(key, pods)
            t_commit = self._commit_stamp()
            if native is not None:
                # same three event modes as bind_many (share-mode stores
                # ride native mode 0 there too — no asymmetry between the
                # two commit entries)
                mode = (0 if not self._deep_copy
                        else 1 if self._lazy_pod_events else 2)
                self._rv, deleted = native.delete_commit(
                    pods, keys, events, errors, self._rv, mode, t_commit,
                    pod_structural_clone, DELETED)
            else:
                # build-then-pop, exactly like the native engine: every
                # clone/event is constructed BEFORE any row is removed, so a
                # mid-batch failure leaves the store untouched (no
                # popped-but-never-narrated pods); a duplicate key errors
                # like the pop it replaces
                rv = self._rv
                found: List[str] = []
                seen = set()
                for key in keys:
                    old = None if key in seen else pods.get(key)
                    if old is None:
                        errors.append((key, f"pods {key} not found"))
                        continue
                    seen.add(key)
                    found.append(key)
                    rv += 1
                    if not self._deep_copy:
                        old.metadata.resource_version = rv
                        events.append(_make_event(DELETED, "pods", old, rv,
                                                  old, commit_ts=t_commit))
                    else:
                        obj = pod_structural_clone(old)
                        obj.metadata.resource_version = rv
                        if self._lazy_pod_events:
                            events.append(_make_event(
                                DELETED, "pods", obj, rv, old,
                                [None, pod_structural_clone], t_commit))
                        else:
                            events.append(_make_event(
                                DELETED, "pods", pod_structural_clone(obj),
                                rv, old, commit_ts=t_commit))
                    deleted += 1
                for key in found:
                    del pods[key]
                self._rv = rv
            if self._cols is not None:
                # drop the freed rows (no-op for error keys that never had
                # one; second occurrence of a duplicate is already gone)
                for key in keys:
                    if key not in pods:
                        self._cols.remove(key)
            self._emit_batch(DELETED, "pods", events, origin)
        return deleted, errors

    def update_pod_status(self, namespace: str, name: str, mutate_status: Callable[[Any], None]) -> Any:
        """Status-subresource write (hot under failure storms: ONE structural
        clone for the store; the event shares it lazily, the caller's return
        stays a private clone)."""
        with self._pods_pair:
            key = f"{namespace}/{name}"
            old = self._pod_internal(key)
            pod = pod_structural_clone(old)
            mutate_status(pod.status)
            self._rv += 1
            pod.metadata.resource_version = self._rv
            self._objects["pods"][key] = pod
            if self._cols is not None:
                row = self._cols.key2row.get(key)
                if row is not None:
                    self._cols.sync(row, pod)
            self._emit_event(self._pod_event(MODIFIED, pod,
                                             pod_structural_clone, prev=old))
            return pod_structural_clone(pod)
