"""Columnar pod-row store (ISSUE 15): struct-of-arrays for the pod rows.

The native commit engine (ISSUE 11) shrank the bind/assume/batch-build
loops ~3-5x, but the remaining per-pod floor is the C-level dict copies
themselves (~0.6µs per Pod/ObjectMeta/PodSpec clone, paid twice per bind).
This module removes the STORE half of that floor: the hot fields the
scheduler pipeline actually touches per pod live in parallel columns
(numpy int arrays + interned string tables + parallel object-ref lists),
and `bind_many` commits by COLUMN WRITES — `node_id[rows] = ids`,
`row_rv[rows] = arange(rv0+1, ...)`, one diverged-bitmap set — instead of
clone-and-swap. The full Pod object for a bound row is materialized
LAZILY, at most once, when an API read / a non-coalescing watcher / a cold
field access needs the whole object (the ISSUE 4 lazy-event idiom,
extended from events to rows).

Columns per row (the scheduler pipeline's hot fields):

  keys[]        "namespace/name" (object list; the row identity)
  ns_id[]       interned namespace id (int32)
  name[]        pod name (object list)
  uid[]         metadata.uid (object list)
  node_id[]     interned node name id; -1 = unbound (int32) — AUTHORITATIVE
                for bound-ness (the dict row of a diverged row is stale)
  row_rv[]      the row's current resourceVersion (int64; -1 = free row) —
                authoritative for diverged rows, mirror otherwise
  phase_id[]    interned status.phase id (int32)
  priority[]    spec.priority (int64)
  rank[]        pod-group.scheduling/rank label, -1 when absent (int32)
  gang[]        pod-group key ("" when not a gang member; object list)
  sig[]         (class-signature, request-signature) memo REFS captured from
                the pod's __dict__ at sync (the tensorizer's admission-primed
                memos — snapshot/tensorizer.py SIG_MEMO_KEYS; clones share
                __dict__ copies so materialized rows keep them for free)
  base[]        the stored Pod object (object list). For a DIVERGED row this
                is the PRE-BIND object: node_id/row_rv above carry the
                committed bind until materialization swaps in the bound clone.
  diverged[]    bool bitmap: True = columns carry state the base object (and
                the store's dict row) does not yet reflect

Locking: every column mutation happens under the store's pods shard
(`_pods_lock`) — PodColumns itself is lock-free and trusts its caller
(store/store.py documents the order). The intern tables are append-only, so
LazyBindBatch consumers resolve node ids -> names lock-free on their own
threads.

Fallback: no numpy, `STORE_COLUMNAR=0`, `APIStore(columnar=False)`, or a
store configured without the lazy/deep-copy event contract all disable the
columns — the dict store below is the oracle and stays bit-for-bit.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

try:  # numpy is the whole point of the SoA layout; without it, dict path
    import numpy as np
except Exception:  # pragma: no cover - exercised via monkeypatch in tests
    np = None  # type: ignore

from ..api.podgroup import pod_gang_rank, pod_group_key


def numpy_available() -> bool:
    return np is not None


def env_enabled() -> bool:
    """STORE_COLUMNAR env gate (default on, like STORE_NATIVE_COMMIT)."""
    return os.environ.get("STORE_COLUMNAR", "").lower() not in ("0", "false")


# the pod-carried memo keys whose refs the sig column captures; single
# source of truth lives with the memos' owner (snapshot/tensorizer.py) —
# imported lazily so a store-only consumer never pays the tensorizer import
_SIG_KEYS_FALLBACK = ("_class_sig", "_req_sig")


def _sig_memo_keys() -> Tuple[str, ...]:
    try:
        from ..snapshot.tensorizer import SIG_MEMO_KEYS

        return SIG_MEMO_KEYS[:2]
    except Exception:  # pragma: no cover - tensorizer always importable here
        return _SIG_KEYS_FALLBACK


class PodColumnsView:
    """Read-only view over the live columns (`APIStore.pod_columns()`).

    The numpy members are non-writeable VIEWS of the live arrays and the
    list/table members are the live objects — everything here carries the
    store-returned READ-ONLY contract (schedlint MU001 recognizes
    `pod_columns()` as a taint source; the arrays also enforce it at
    runtime via writeable=False). Snapshot consistency: take it under
    `store.transaction("pods")` or treat the values as advisory telemetry.
    """

    __slots__ = ("n", "keys", "base", "uid", "name", "ns_id", "node_id",
                 "row_rv", "phase_id", "priority", "rank", "gang", "sig",
                 "diverged", "node_names", "namespaces", "phases", "key2row")

    def __init__(self, cols: "PodColumns"):
        n = cols.n

        def ro(arr):
            v = arr[:n].view()
            v.flags.writeable = False
            return v

        self.n = n
        self.keys = cols.keys
        self.base = cols.base
        self.uid = cols.uid
        self.name = cols.name
        self.ns_id = ro(cols.ns_id)
        self.node_id = ro(cols.node_id)
        self.row_rv = ro(cols.row_rv)
        self.phase_id = ro(cols.phase_id)
        self.priority = ro(cols.priority)
        self.rank = ro(cols.rank)
        self.gang = cols.gang
        self.sig = cols.sig
        self.diverged = ro(cols.diverged)
        self.node_names = cols.node_names
        self.namespaces = cols.namespaces
        self.phases = cols.phases
        # live row index (key -> row into the columns above) — lets column
        # consumers (tensorizer sig re-seed) address rows by pod key without
        # an O(rows) scan; read-only by the view's contract
        self.key2row = cols.key2row


class PodColumns:
    """The struct-of-arrays pod-row table. All mutation under the caller's
    pods-shard lock (see module docstring)."""

    _INITIAL_CAP = 1024

    def __init__(self, bind_cloner: Callable[[Any], Any]):
        self._bind_cloner = bind_cloner
        cap = self._INITIAL_CAP
        self.n = 0  # high-water row count (free rows included)
        self.key2row: Dict[str, int] = {}
        self.keys: List[Optional[str]] = [None] * cap
        self.base: List[Any] = [None] * cap
        self.uid: List[Optional[str]] = [None] * cap
        self.name: List[Optional[str]] = [None] * cap
        self.gang: List[str] = [""] * cap
        self.sig: List[Any] = [None] * cap
        self.ns_id = np.full(cap, -1, dtype=np.int32)
        self.node_id = np.full(cap, -1, dtype=np.int32)
        self.row_rv = np.full(cap, -1, dtype=np.int64)
        self.phase_id = np.full(cap, -1, dtype=np.int32)
        self.priority = np.zeros(cap, dtype=np.int64)
        self.rank = np.full(cap, -1, dtype=np.int32)
        self.diverged = np.zeros(cap, dtype=bool)
        self._free: List[int] = []
        self._diverged_n = 0
        # optional shared-memory backing (ISSUE 19): when attach_arena()
        # migrates the numeric columns into a store/shm.py arena, the attrs
        # above are rebound to the arena's shared arrays and worker
        # processes map the same bytes read-only
        self._arena = None
        # interned string tables (append-only: lock-free reads are safe)
        self.node_names: List[str] = []
        self._node_ids: Dict[str, int] = {}
        self.namespaces: List[str] = []
        self._ns_ids: Dict[str, int] = {}
        self.phases: List[str] = []
        self._phase_ids: Dict[str, int] = {}
        self.materialized_total = 0  # lifetime lazy row materializations
        self.sig_captured = 0  # lifetime capture_sig_memos column writes
        self._sig_keys = _sig_memo_keys()

    # -- intern tables ---------------------------------------------------------

    def intern_node(self, name: str) -> int:
        return self._intern(self.node_names, self._node_ids, name)

    def _intern(self, table: List[str], ids: Dict[str, int], val: str) -> int:
        i = ids.get(val)
        if i is None:
            i = len(table)
            ids[val] = i
            table.append(val)
        return i

    # -- row lifecycle ---------------------------------------------------------

    # the numeric columns an shm arena carries across the process boundary
    # (schema: store/shm.py POD_COLS_SCHEMA) and the fills their fresh
    # regions need (-1 is a sentinel everywhere it appears)
    _SHM_ATTRS = ("ns_id", "node_id", "row_rv", "phase_id", "priority",
                  "rank", "diverged")
    _SHM_FILLS = {"ns_id": -1, "node_id": -1, "row_rv": -1, "phase_id": -1,
                  "rank": -1}

    def attach_arena(self, arena) -> None:
        """Migrate the numeric columns into a store/shm.py ShmArena: each
        attr above is rebound to the arena's shared array (contents copied,
        fresh region filled with the column's sentinel). Caller holds the
        pods shard; after this every mutation below lands directly in the
        shared bytes and worker processes see it without pickling."""
        cap = len(self.keys)
        if arena.capacity < cap:
            arena.grow(cap)
        for attr in self._SHM_ATTRS:
            src = getattr(self, attr)
            dst = arena.arrays[attr]
            dst[: len(src)] = src
            fill = self._SHM_FILLS.get(attr)
            if fill is not None and len(dst) > len(src):
                dst[len(src):] = fill
            setattr(self, attr, dst)
        self._arena = arena
        arena.publish(self.n)

    def _grow(self) -> None:
        cap = len(self.keys)
        new = cap * 2
        pad = new - cap
        self.keys.extend([None] * pad)
        self.base.extend([None] * pad)
        self.uid.extend([None] * pad)
        self.name.extend([None] * pad)
        self.gang.extend([""] * pad)
        self.sig.extend([None] * pad)
        arena = self._arena
        if arena is not None:
            if arena.capacity < new:
                old_cap = arena.capacity
                arena.grow(new)
                for attr in self._SHM_ATTRS:
                    arr = arena.arrays[attr]
                    fill = self._SHM_FILLS.get(attr)
                    if fill is not None:
                        arr[old_cap:] = fill
                    setattr(self, attr, arr)
            return
        for attr, fill in (("ns_id", -1), ("node_id", -1), ("phase_id", -1),
                           ("rank", -1)):
            old = getattr(self, attr)
            arr = np.full(new, fill, dtype=old.dtype)
            arr[:cap] = old
            setattr(self, attr, arr)
        rv = np.full(new, -1, dtype=np.int64)
        rv[:cap] = self.row_rv
        self.row_rv = rv
        pr = np.zeros(new, dtype=np.int64)
        pr[:cap] = self.priority
        self.priority = pr
        dv = np.zeros(new, dtype=bool)
        dv[:cap] = self.diverged
        self.diverged = dv

    def insert(self, key: str, pod) -> int:
        """New row for a just-stored pod (create path). Caller guarantees the
        key is fresh."""
        if self._free:
            row = self._free.pop()
        else:
            row = self.n
            if row >= len(self.keys):
                self._grow()
            self.n += 1
            if self._arena is not None:
                self._arena.publish(self.n)
        self.keys[row] = key
        meta = pod.metadata
        self.uid[row] = meta.uid
        self.name[row] = meta.name
        self.ns_id[row] = self._intern(self.namespaces, self._ns_ids,
                                       meta.namespace or "")
        self.key2row[key] = row
        self.sync(row, pod)
        return row

    def sync(self, row: int, pod) -> None:
        """Refresh a row from a (new) stored object — every dict-path write
        (create/update/bind/status) keeps the columns coherent through here.
        Clears divergence: the dict row IS the object passed in."""
        self.base[row] = pod
        self.node_id[row] = (self.intern_node(pod.spec.node_name)
                             if pod.spec.node_name else -1)
        self.row_rv[row] = pod.metadata.resource_version
        self.phase_id[row] = self._intern(self.phases, self._phase_ids,
                                          pod.status.phase or "")
        self.priority[row] = pod.spec.priority or 0
        labels = pod.metadata.labels
        if labels:
            self.gang[row] = pod_group_key(pod)
            self.rank[row] = pod_gang_rank(pod)
        else:
            self.gang[row] = ""
            self.rank[row] = -1
        d = pod.__dict__
        k1, k2 = self._sig_keys
        cs, rs = d.get(k1), d.get(k2)
        cur = self.sig[row]
        if cur is not None:
            # a re-sync must not CLOBBER a previously captured memo ref the
            # incoming parse lacks (ISSUE 17 satellite, the PR 15 carryover:
            # status/relist writes hand fresh objects with empty memo slots,
            # and the rebalancer's evict→re-place waves re-sync constantly).
            # Keeping a stale ref is safe by construction — the tensorizer's
            # seed_memos validates the identity anchors (spec, labels)
            # before applying, so a ref whose spec was since replaced simply
            # never seeds.
            if cs is None:
                cs = cur[0]
            if rs is None:
                rs = cur[1]
        self.sig[row] = (cs, rs)
        if self.diverged[row]:
            self.diverged[row] = False
            self._diverged_n -= 1

    def capture(self, key: str, pod) -> bool:
        """Back-fill the sig column from a pod object whose memos were
        primed OUTSIDE the store (the tensorizer's build_pod_batch, at the
        batch's bind/assume edge): the scheduler's pod shares spec identity
        with the stored object (structural clones share deep members), so
        its memo refs seed future parses of this row. Only fills components
        the column does not already have — sync() owns refreshes."""
        row = self.key2row.get(key)
        if row is None:
            return False
        d = pod.__dict__
        k1, k2 = self._sig_keys
        cs, rs = d.get(k1), d.get(k2)
        if cs is None and rs is None:
            return False
        cur = self.sig[row]
        if cur is not None:
            if cur[0] is not None:
                cs = cur[0]
            if cur[1] is not None:
                rs = cur[1]
            if (cs is cur[0] and rs is cur[1]):
                return False
        self.sig[row] = (cs, rs)
        self.sig_captured += 1
        return True

    def remove(self, key: str) -> None:
        row = self.key2row.pop(key, None)
        if row is None:
            return
        if self.diverged[row]:
            self.diverged[row] = False
            self._diverged_n -= 1
        self.keys[row] = None
        self.base[row] = None
        self.uid[row] = None
        self.name[row] = None
        self.gang[row] = ""
        self.sig[row] = None
        self.node_id[row] = -1
        self.row_rv[row] = -1  # invalidates any in-flight bind's rv snapshot
        self._free.append(row)

    # -- the bind hot path -----------------------------------------------------

    def bind_prepare(self, bindings, errors: List[Tuple[str, str]],
                     native=None):
        """Phase 1 (caller holds the pods shard): validate each
        (namespace, name, node) against the COLUMNS — no clone, no object
        walk — and intern the node names. Returns (rows int32[], ids
        int32[], keys list, rv_snap int64[]): the accepted entries' row
        indices, interned node ids, key strings, and the rows' rv values
        (the commit phase re-validates raced rows against these: every row
        write bumps row_rv, and remove() poisons it with -1, so a changed
        value is exactly "this row raced"). Error messages match the dict
        path byte-for-byte."""
        if native is not None:
            rows, ids, keys = native.columnar_prepare(
                self.key2row, bindings, self._node_ids, self.node_names,
                self.node_id, errors)
        else:
            key2row = self.key2row
            node_id = self.node_id
            names = self.node_names
            node_ids = self._node_ids
            row_list: List[int] = []
            id_list: List[int] = []
            keys = []
            for namespace, name, node_name in bindings:
                key = f"{namespace}/{name}"
                row = key2row.get(key)
                if row is None:
                    errors.append((key, f"pods {key} not found"))
                    continue
                cur = node_id[row]
                if cur >= 0:
                    errors.append(
                        (key,
                         f"pod {key} is already bound to {names[cur]}"))
                    continue
                nid = node_ids.get(node_name)
                if nid is None:
                    # append-then-map, matching the C loop: a failure
                    # between the two leaves only an orphan table entry
                    nid = len(names)
                    names.append(node_name)
                    node_ids[node_name] = nid
                row_list.append(row)
                id_list.append(nid)
                keys.append(key)
            rows = np.asarray(row_list, dtype=np.int32)
            ids = np.asarray(id_list, dtype=np.int32)
        rv_snap = self.row_rv[rows].copy() if len(rows) else \
            np.zeros(0, dtype=np.int64)
        return rows, ids, keys, rv_snap

    def commit_bind(self, rows, ids, keys, rv_snap, rv0: int,
                    errors: List[Tuple[str, str]]):
        """Phase 2 (caller holds global + shard): re-validate rows that
        changed between the phases (a concurrent single bind / delete /
        create reusing a freed row — and duplicate keys within one batch,
        where the second occurrence must see the first, like the dict
        path's re-validate branch), then commit the survivors by COLUMN
        WRITES: node ids, a contiguous rv range, the diverged bitmap. Zero
        per-pod object allocation on the clean path. Returns (n, keys,
        bases, ids): the committed count plus the per-entry key strings,
        pre-bind base refs, and node ids the LazyBindBatch event marker
        captures."""
        n = len(rows)
        if n == 0:
            return 0, [], [], ids
        ok_all = bool(((self.node_id[rows] < 0)
                       & (self.row_rv[rows] == rv_snap)).all())
        if not ok_all or len(np.unique(rows)) != n:
            # raced/duplicate entries: per-entry slow path against CURRENT
            # state (we hold both locks now — no further races). Bound keys
            # within this very batch are tracked so a duplicate errors like
            # the dict path's second commit ("already bound to" the first
            # occurrence's node).
            key2row = self.key2row
            node_id = self.node_id
            names = self.node_names
            keep_rows: List[int] = []
            keep_ids: List[int] = []
            keep_keys: List[str] = []
            batch_bound: Dict[str, str] = {}
            ids_list = ids.tolist()
            for i in range(n):
                key = keys[i]
                first = batch_bound.get(key)
                if first is not None:
                    errors.append(
                        (key, f"pod {key} is already bound to {first}"))
                    continue
                row = key2row.get(key)
                if row is None:
                    errors.append((key, f"pods {key} not found"))
                    continue
                cur = node_id[row]
                if cur >= 0:
                    errors.append(
                        (key,
                         f"pod {key} is already bound to {names[cur]}"))
                    continue
                keep_rows.append(row)
                keep_ids.append(ids_list[i])
                keep_keys.append(key)
                batch_bound[key] = names[ids_list[i]]
            rows = np.asarray(keep_rows, dtype=np.int32)
            ids = np.asarray(keep_ids, dtype=np.int32)
            keys = keep_keys
            n = len(rows)
            if n == 0:
                return 0, [], [], ids
        bases = [self.base[r] for r in rows.tolist()]
        self.node_id[rows] = ids
        self.row_rv[rows] = np.arange(rv0 + 1, rv0 + 1 + n, dtype=np.int64)
        self.diverged[rows] = True
        self._diverged_n += n
        return n, keys, bases, ids

    # -- lazy row materialization ----------------------------------------------

    def materialize(self, row: int, objs: Dict[str, Any]):
        """Build the bound Pod object a diverged row stands for — ONE bind
        clone of the pre-bind base with the column node/rv applied — swap it
        into the store's dict row and the base column, and clear divergence.
        Runs at most once per row per bind (caller holds the pods shard)."""
        base = self.base[row]
        pod = self._bind_cloner(base)
        pod.spec.node_name = self.node_names[self.node_id[row]]
        pod.metadata.resource_version = int(self.row_rv[row])
        key = self.keys[row]
        objs[key] = pod
        self.base[row] = pod
        self.diverged[row] = False
        self._diverged_n -= 1
        self.materialized_total += 1
        return pod

    def materialize_key(self, key: str, objs: Dict[str, Any]):
        """Materialize one row iff diverged; None when clean/missing."""
        row = self.key2row.get(key)
        if row is not None and self.diverged[row]:
            return self.materialize(row, objs)
        return None

    def materialize_all(self, objs: Dict[str, Any]) -> int:
        """Materialize every diverged row (LIST and full-snapshot reads)."""
        if not self._diverged_n:
            return 0
        rows = np.nonzero(self.diverged[: self.n])[0].tolist()
        for row in rows:
            self.materialize(row, objs)
        return len(rows)

    # -- telemetry -------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        if self._arena is not None:
            return dict(self._base_stats(), shm=self._arena.stats())
        return self._base_stats()

    def _base_stats(self) -> Dict[str, Any]:
        return {
            "rows": len(self.key2row),
            "capacity": len(self.keys),
            "free": len(self._free),
            "diverged": int(self._diverged_n),
            "materialized_total": self.materialized_total,
            "bound": int((self.node_id[: self.n] >= 0).sum()),
            "node_table": len(self.node_names),
            "phase_table": len(self.phases),
            "sig_captured": self.sig_captured,
        }
