"""L5 — the scheduler: framework, serial oracle, queue, cache, batch TPU driver."""

from .cache import Cache  # noqa: F401
from .framework import (  # noqa: F401
    MAX_NODE_SCORE,
    Code,
    CycleState,
    NodeInfo,
    PodInfo,
    PreFilterResult,
    Snapshot,
    Status,
)
from .flightrec import FlightRecorder, StageClock  # noqa: F401
from .gang import GangDirectory  # noqa: F401
from .queue import QueuedPodInfo, SchedulingQueue  # noqa: F401
from .runtime import DEFAULT_WEIGHTS, Framework  # noqa: F401
from .serial import ScheduleResult, Scheduler, num_feasible_nodes_to_find  # noqa: F401
