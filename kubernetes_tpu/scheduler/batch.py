"""Batch TPU scheduler — drains whole pending-pod batches and solves them jointly.

The batching analog of ScheduleOne (SURVEY.md §2.4 'Pod-level serialization'):
pods are popped in queue (priority) order, tensorized against the current cache
snapshot, solved on device with the greedy scan kernel (ops/solver.py), and the
resulting assignments are assumed + bound through the same store surface the
serial path uses. Classes with features the device path doesn't cover yet
(inter-pod affinity, non-default PTS inclusion policies) fall back to the serial
oracle pod-by-pod — the framework-gating stance of the north star (solver
behind the same extension surface, serial path always available).
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..snapshot.tensorizer import TensorCache, build_cluster_tensors, build_pod_batch
from ..store import (MODIFIED, APIStore, NotFoundError, pod_bind_clone,
                     pod_structural_clone)
from .flightrec import FlightRecorder, StageClock, register_scheduler
from .framework import Status
from .queue import QueuedPodInfo
from .runtime import Framework
from .serial import Scheduler


class BatchScheduler(Scheduler):
    """solver: 'exact' (scan, bit-parity with serial), 'fast' (water-filling),
    'auction' / 'sinkhorn' (global transportation solvers with warm-started
    duals — models/transport.py), 'native' (the C++ host engine — scan parity
    for constraint-free batches; native/hostsched.cpp), or 'auto' (fast when
    the batch has no topology-spread constraints, exact otherwise)."""

    def __init__(self, store: APIStore, framework: Framework, batch_size: int = 4096,
                 solver: str = "exact", pipeline_binds: bool = True,
                 columnar: bool = True, flight_recorder: bool = True,
                 flight_capacity: int = FlightRecorder.DEFAULT_CAPACITY, **kw):
        super().__init__(store, framework, **kw)
        self.batch_size = batch_size
        self.solver = solver
        self.batches_solved = 0
        # flight recorder (scheduler/flightrec.py): per-batch stage timing +
        # bounded trace ring, surfaced via /debug/schedstats and `ktl sched
        # stats`. Stage marks are per BATCH (a handful of perf_counter reads),
        # so enabled-vs-disabled placement parity and the <2% overhead budget
        # both hold (tests/test_flightrec.py, tests/test_bench_quick.py).
        self.flightrec = FlightRecorder(capacity=flight_capacity,
                                        enabled=flight_recorder)
        self.queue.stat_sink = self.flightrec
        register_scheduler(self._bind_origin, self)
        # per-batch unschedulable-reason attribution (set during
        # schedule_batch; _handle_failure taps Status.plugin into it)
        self._batch_reasons: Optional[Dict[str, int]] = None
        self.preempt_victims_total = 0  # victims chosen by _batch_preempt
        self.trace_threshold = 1.0  # ScheduleBatch Trace log threshold (s)
        self.transport_state = None  # warm duals carried across batches
        # generation-diff incremental tensorization (cache.go:186 analog)
        self._tensor_cache = TensorCache()
        # columnar=True is the batched host pipeline: coalesced watch ingest,
        # structural+scatter-add assume accounting, self-bind short-circuit.
        # False restores the per-pod paths (the parity oracle for tests).
        self.columnar = columnar
        self.watch_coalesce = columnar
        # Bind pipelining (schedule_one.go:120-132 bindingCycle-in-goroutine
        # analog): assume_pod runs synchronously so the next solve's snapshot
        # sees the capacity, while the store.bind writes flush on a worker
        # thread overlapped with solve(N+1).
        self.pipeline_binds = pipeline_binds
        # commit sub-batch size: each bind_many+confirm cycle covers this
        # many pods, so commit(N) overlaps the scheduling thread's work on
        # solve(N+1) at chunk granularity instead of whole-batch granularity
        # (and each store critical section stays short)
        self.bind_chunk = 4096
        self._bind_q: _queue.Queue = _queue.Queue()
        self._bind_worker: Optional[threading.Thread] = None
        self._bind_errors: List = []
        self._bind_successes = 0  # folded into scheduled_count on the
        self._bind_err_lock = threading.Lock()  # scheduling thread (no race)
        # assumed pods whose worker-side confirm missed (assume expired /
        # foreign interference): re-ingested on the scheduling thread at the
        # next drain, like any foreign MODIFIED
        self._bind_confirm_leftovers: List = []
        # async bind failures, surfaced to schedule_batch callers (the worker
        # requeues them internally, but "my bind_many failed" was invisible):
        # [(pod key, message)], drained via take_bind_failures()
        self.bind_failures: List = []
        # gang scheduling (scheduler/gang.py): PodGroup quorums + placed
        # members, fed by the watch plumbing in serial.py; the queue holds
        # gang members in staging until quorum, and schedule_batch enforces
        # the all-or-nothing veto. Inactive (one attr read) until a PodGroup
        # exists.
        from .gang import GangDirectory

        self.gangs = GangDirectory()
        self.queue.set_gang_hooks(self.gangs.group_of,
                                  self.gangs.quorum_ready,
                                  lambda: self.gangs.active)
        self.gang_vetoes = 0  # gangs stripped post-solve (observability)

    def schedule_batch(self, timeout: Optional[float] = 0.0) -> int:
        """Drain up to batch_size pods, solve jointly, bind. Returns #pods handled.

        Instrumented per BATCH (never per pod): a StageClock marks each
        pipeline stage boundary, the marks feed the scheduler_batch_stage
        histograms + a utiltrace-style Trace (logged past trace_threshold),
        and one flight-recorder record captures the batch's outcome, counts,
        and unschedulable-reason attribution. batch_solve_duration is
        observed in a try/finally with an outcome label
        (scheduled/unschedulable/error — mirroring scheduling_attempts) on
        EVERY path that pops a batch, including the no-nodes early return
        and errors. An empty pop schedules nothing and observes nothing by
        design (its pump cost folds into the aggregate outside buckets)."""
        from ..ops.solver import greedy_scan_solve, make_inputs
        from ..server import metrics as m
        from ..utils.tracing import Trace

        fr = self.flightrec
        clock = StageClock()
        # queue_add accrues into the recorder's outside bucket at its own
        # call site (inside this pump); difference it out so the "ingest"
        # residual stays disjoint from its sub-stage
        sub0 = fr.outside_seconds("queue_add")
        # pump until the watch drains — bounded: a 100k-pod backlog must
        # reach the queue as ONE batch (not batch_size/10k sub-solves), but
        # sustained event arrival must not starve scheduling forever
        for _ in range(8):
            if self.pump_events(max_events=self.batch_size) < self.batch_size:
                break
        clock.mark("ingest")
        clock.sub("ingest", fr.outside_seconds("queue_add") - sub0)
        qps = self.queue.pop_batch(self.batch_size, timeout=timeout)
        clock.mark("pop")
        if not qps:
            # no batch to pin these marks to: fold idle pump/poll time into
            # the aggregate buckets (confirm-heavy idle cycles still show)
            for name, sec in clock.stages.items():
                fr.add_outside(name, sec)
            return 0
        m.batch_size_gauge.set(len(qps))
        trace = Trace("ScheduleBatch", pods=len(qps))
        failed0 = self.failed_count
        victims0 = self.preempt_victims_total
        self._batch_reasons = reasons = {}
        outcome = "error"  # overwritten unless the body raises
        out: Dict = {}
        try:
            self._schedule_batch_inner(qps, clock, trace, m,
                                       greedy_scan_solve, make_inputs, out)
            outcome = ("scheduled"
                       if out.get("dispatched", 0)
                       + out.get("serial_scheduled", 0) > 0
                       else "unschedulable")
            return len(qps)
        finally:
            self._batch_reasons = None
            self.batches_solved += 1
            t_fin = time.perf_counter()
            total = clock.total()
            for name, sec in clock.stages.items():
                m.batch_stage_duration.observe(sec, name)
            m.batch_solve_duration.observe(total, outcome)
            if self.gangs is not None and self.gangs.active:
                m.gang_staged.set(self.queue.gang_staged_count())
            fr.record(
                pods=len(qps), nodes=out.get("nodes", 0), outcome=outcome,
                solver=self.solver, stages=clock.stages, total_s=total,
                scheduled=out.get("dispatched", 0)
                + out.get("serial_scheduled", 0),
                unschedulable=self.failed_count - failed0,
                fallback=out.get("fallback", 0),
                preempted=self.preempt_victims_total - victims0,
                reasons=reasons, gang=out.get("gang"),
                solver_iterations=getattr(self.transport_state,
                                          "iterations", None))
            trace.log_if_long(self.trace_threshold)
            fr.note_self_time(time.perf_counter() - t_fin)

    def _schedule_batch_inner(self, qps, clock, trace, m,
                              greedy_scan_solve, make_inputs, out) -> None:
        """The batch pipeline body (schedule_batch owns the try/finally
        bookkeeping around it). Fills `out` with nodes/dispatched/fallback/
        gang counts for the flight record."""
        snapshot = self.cache.update_snapshot()
        out["nodes"] = len(snapshot)
        if len(snapshot) == 0:
            clock.mark("tensorize")
            for qp in qps:
                self._handle_failure(qp, Status.unschedulable("no nodes available to schedule pods"))
            return

        cluster, changed_nodes = self._tensor_cache.cluster_tensors(snapshot)
        clock.mark("tensorize")
        trace.step("Tensorized cluster", nodes=len(snapshot))
        pods = [qp.pod for qp in qps]
        batch = build_pod_batch(
            pods, snapshot, cluster, ns_labels=self._ns_labels,
            hard_pod_affinity_weight=self._hard_pod_affinity_weight(),
            reuse=self._tensor_cache, changed_nodes=changed_nodes,
            gangs=self.gangs)

        fallback_mask = batch.fallback_class[batch.class_of_pod]
        device_idx = np.nonzero(~fallback_mask)[0]
        fallback_idx = np.nonzero(fallback_mask)[0]
        out["fallback"] = int(fallback_idx.size)
        clock.mark("build_pod_batch")
        trace.step("Built pod batch", device=int(device_idx.size),
                   fallback=int(fallback_idx.size))

        if device_idx.size:
            sub = _subset_batch(batch, device_idx)
            # gang members present in the device batch? (solver bias + the
            # all-or-nothing post-solve pass). The native and transport
            # backends don't model the slice-packing bonus, so gang batches
            # take the fast/exact paths (which do).
            has_gang = (sub.gang_of_pod is not None
                        and bool((sub.gang_of_pod >= 0).any()))
            # 'fast' means fast-when-legal: the water-fill kernel has no
            # topology-spread or inter-pod-affinity handling, so constrained
            # batches always take the exact scan path regardless of mode.
            constraint_free = (batch.ct_class.size == 0 and batch.st_class.size == 0
                               and not batch.ipa.has_any)
            use_fast = self.solver in ("fast", "auto") and constraint_free
            use_transport = (self.solver in ("auction", "sinkhorn")
                             and constraint_free and not has_gang)
            assignment = None
            if self.solver == "native" and constraint_free and not has_gang:
                from ..native import native_available, native_greedy_solve

                if native_available():
                    assignment, _ = native_greedy_solve(cluster, sub)
            # device upload happens only for paths that consume it; cluster
            # tensors ride the persistent HBM mirrors (diff streaming)
            inputs = d_max = None
            if assignment is None:
                inputs, d_max = make_inputs(
                    cluster, sub,
                    device=self._tensor_cache.device_views(cluster))
            if use_transport:
                from ..models.transport import transport_solve
                from ..models.waterfill import make_groups

                solved = transport_solve(
                    inputs, make_groups(sub), method=self.solver,
                    state=self.transport_state, node_names=cluster.node_names,
                )
                if solved is not None:
                    assignment, self.transport_state = solved
            if use_fast:
                from ..models.waterfill import make_groups, waterfill_solve

                assignment = waterfill_solve(inputs, make_groups(sub))
            if assignment is None:
                # static gates: constraint-free batches compile the scan
                # variant without IPA gathers / PTS segment sums
                assignment, _, _ = greedy_scan_solve(
                    inputs, d_max, has_ipa=bool(batch.ipa.has_any),
                    has_ct=bool(batch.ct_class.size),
                    has_st=bool(batch.st_class.size),
                    has_gang=bool(has_gang and sub.gang_bonus is not None))
            assignment = np.asarray(assignment)
            # All-or-nothing gang veto (scheduler/gang.py), BEFORE any assume
            # or bind: a gang whose in-batch placements (plus members already
            # placed) miss min_member is stripped wholesale — its placed rows
            # become unplaced for every downstream consumer (bind loop,
            # capacity fold in _handle_device_rejects) — and requeued as a
            # unit. gang_requeue: gang id -> members collected for requeue.
            gang_requeue: Dict[int, List[QueuedPodInfo]] = {}
            hopeless: set = set()
            veto = None
            gang_info: Optional[Dict[str, int]] = None
            if has_gang:
                from .gang import gang_veto_mask

                gang_info = out["gang"] = {
                    "staged": self.queue.gang_staged_count(),
                    "vetoed": 0, "assume_vetoed": 0, "released": 0,
                    "hopeless": 0}
                gkeys = batch.gang_keys
                need = np.array(
                    [max(0, (self.gangs.min_member(k) or 0)
                         - self.gangs.placed_count(k)) for k in gkeys],
                    dtype=np.int64)
                veto, _satisfied = gang_veto_mask(
                    assignment, np.asarray(sub.gang_of_pod), need)
                # a gang needing more members than one solve can ever see is
                # unsatisfiable by this configuration — park it with a
                # diagnostic instead of livelocking through backoff retries
                hopeless.update(np.nonzero(need > self.batch_size)[0].tolist())
                if veto.any():
                    n_vetoed = int(np.unique(sub.gang_of_pod[veto]).size)
                    self.gang_vetoes += n_vetoed
                    gang_info["vetoed"] = n_vetoed
                    m.gang_vetoed_total.inc(n_vetoed, reason="solver")
                    assignment = np.where(veto, -1, assignment)
            clock.mark("solve")
            trace.step("Device solve done", solver=self.solver)
            # Two phases: bind every device assignment FIRST, then handle the
            # rejected pods. Handling mid-loop would see capacity still
            # promised to not-yet-bound assignments and double-book nodes.
            rejected = []
            to_bind = []
            bind_rows: List[int] = []  # full-batch pod row per to_bind entry
            bind_nodes: List[int] = []  # cluster node index per to_bind entry
            bind_gang: List[int] = []  # gang id per entry (gang batches only)
            use_columnar = self.columnar and batch.raw_req is not None
            clone = pod_bind_clone if use_columnar else pod_structural_clone
            node_names = cluster.node_names
            sub_gang = (np.asarray(sub.gang_of_pod).tolist()
                        if has_gang else None)
            veto_list = veto.tolist() if veto is not None else None
            # .tolist() once: per-element int() of numpy scalars is
            # measurable at 100k pods
            assign_list = np.asarray(assignment).tolist()
            for j, pi in enumerate(device_idx.tolist()):
                gid = sub_gang[j] if sub_gang is not None else -1
                if veto_list is not None and veto_list[j]:
                    gang_requeue.setdefault(gid, []).append(qps[pi])
                    continue
                nidx = assign_list[j]
                if nidx < 0:
                    if gid >= 0:
                        # unplaced extra of a SATISFIED gang: fail it alone —
                        # and never preempt to place part of a gang, so it
                        # skips the _batch_preempt path entirely
                        self._handle_failure(qps[pi], Status.unschedulable(
                            f"0/{len(node_names)} nodes are available "
                            "(gang member; preemption skipped)",
                            plugin="NodeResourcesFit"))
                    else:
                        rejected.append((j, qps[pi]))
                else:
                    to_bind.append((qps[pi], node_names[nidx],
                                    clone(qps[pi].pod)))
                    bind_rows.append(pi)
                    bind_nodes.append(nidx)
                    if sub_gang is not None:
                        bind_gang.append(gid)
            if to_bind:
                # bulk assume under one cache lock, then hand the worker
                # CHUNKED batches: per-pod puts left bind_many at ~53-pod
                # batches under queue contention, while one 100k batch
                # would hold the store lock against every consumer
                pairs = [(assumed, node) for _qp, node, assumed in to_bind]
                if use_columnar:
                    batch_has_ports = bool(
                        batch.class_has_host_ports is None
                        or batch.class_has_host_ports[
                            batch.class_of_pod[bind_rows]].any())
                    # structural phase only; resource totals follow as one
                    # scatter-add in _columnar_account
                    bad = self.cache.assume_pods_structural(
                        pairs, check_ports=batch_has_ports)
                else:
                    bad = self.cache.assume_pods(pairs)
                bad_gangs = set()
                for i, msg in sorted(bad, reverse=True):
                    qp, node, _assumed = to_bind.pop(i)
                    bind_rows.pop(i)
                    bind_nodes.pop(i)
                    gid = bind_gang.pop(i) if bind_gang else -1
                    if gid >= 0:
                        bad_gangs.add(gid)
                        gang_requeue.setdefault(gid, []).append(qp)
                    else:
                        self._handle_failure(qp, Status.error(msg))
                if bad_gangs:
                    # all-or-nothing at assume time: a gang that lost a
                    # member releases every already-assumed sibling BEFORE
                    # any bind fires. On the columnar path phase 2 hasn't
                    # run yet, so the release must be the structural inverse
                    # (forget_pods_structural) — forget_pod would subtract
                    # resource totals that were never added.
                    if gang_info is not None:
                        gang_info["assume_vetoed"] = len(bad_gangs)
                        m.gang_vetoed_total.inc(len(bad_gangs),
                                                reason="assume")
                    released = []
                    for i in range(len(to_bind) - 1, -1, -1):
                        gid = bind_gang[i]
                        if gid in bad_gangs:
                            qp, _node, assumed = to_bind.pop(i)
                            bind_rows.pop(i)
                            bind_nodes.pop(i)
                            bind_gang.pop(i)
                            released.append(assumed)
                            gang_requeue.setdefault(gid, []).append(qp)
                    if gang_info is not None:
                        gang_info["released"] = len(released)
                    if use_columnar:
                        self.cache.forget_pods_structural(
                            released, check_ports=batch_has_ports)
                    else:
                        for assumed in released:
                            self.cache.forget_pod(assumed)
                if bind_gang:
                    # surviving members count toward quorum from assume on
                    # (our own bind confirmations bypass the event stream)
                    for i, (_qp, _node, assumed) in enumerate(to_bind):
                        if bind_gang[i] >= 0:
                            self.gangs.note_assumed(assumed)
                if use_columnar and to_bind:
                    self._columnar_account(batch, cluster, snapshot,
                                           bind_rows, bind_nodes,
                                           batch_has_ports)
                clock.mark("assume")
                trace.step("Assumed placements", bound=len(to_bind))
                out["dispatched"] = len(to_bind)
                sync_bind_s = 0.0
                for lo in range(0, len(to_bind), self.bind_chunk):
                    chunk = to_bind[lo:lo + self.bind_chunk]
                    if self.pipeline_binds:
                        self._ensure_bind_worker()
                        self._bind_q.put(chunk)
                    else:
                        t0 = time.perf_counter()
                        self._bind_batch(chunk)
                        sync_bind_s += time.perf_counter() - t0
                if not self.pipeline_binds:
                    self._drain_bind_results()
                clock.mark("dispatch")
                # synchronous binds ran inside the dispatch span AND are
                # observed as the "bind" stage by _bind_batch — keep the
                # stages disjoint (measured locally, so this holds with the
                # flight recorder disabled too)
                clock.sub("dispatch", sync_bind_s)
                trace.step("Dispatched binds")
            if rejected:
                self._handle_device_rejects(rejected, snapshot, cluster, sub,
                                            assignment)
            if gang_requeue:
                if gang_info is not None:
                    gang_info["hopeless"] = sum(
                        1 for g in gang_requeue if g in hopeless)
                self._requeue_gangs(gang_requeue, batch.gang_keys or [],
                                    hopeless)
            if rejected or gang_requeue:
                clock.mark("reject")
                trace.step("Handled rejects", rejected=len(rejected))
            else:
                clock.skip()

        # Serial fallback, in original priority order among themselves.
        # NOTE: gang members whose class needs the serial path (volumes, DRA)
        # schedule individually — all-or-nothing is enforced for device-path
        # classes, the shape training gangs actually take.
        if len(fallback_idx):
            fb0 = self.scheduled_count
            for pi in fallback_idx:
                self._serial_one(qps[pi])
            out["serial_scheduled"] = self.scheduled_count - fb0
            clock.mark("fallback")
            trace.step("Serial fallback done", pods=len(fallback_idx))

    def _requeue_gangs(self, groups: Dict[int, List[QueuedPodInfo]],
                       keys: List[str],
                       hopeless: frozenset = frozenset()) -> None:
        """Gang-aware rejection handling: a vetoed (or assume-rolled-back)
        gang re-enters the queue AS A UNIT — one shared backoff expiry via
        SchedulingQueue.add_gang_backoff, so the members re-stage and
        re-admit together instead of dribbling through the unschedulable map
        one cluster event at a time. One FailedScheduling narration per gang
        (not per member: a 250-rank gang must not write 250 events per
        veto). `hopeless` gangs (min_member beyond what one solve can see)
        park unschedulable with a diagnostic instead — retrying on a timer
        would livelock."""
        for gid, members in groups.items():
            key = keys[gid] if 0 <= gid < len(keys) else "<unknown>"
            if gid in hopeless:
                status = Status.unschedulable(
                    f"pod group {key} needs more members than the solver "
                    f"batch size ({self.batch_size}) can place together; "
                    "raise batch_size or lower minMember",
                    plugin="GangScheduling")
                for m in members:
                    self._handle_failure(m, status)
                continue
            self.failed_count += len(members)
            if self._batch_reasons is not None:
                self._batch_reasons["GangScheduling"] = (
                    self._batch_reasons.get("GangScheduling", 0)
                    + len(members))
            for m in members:
                m.unschedulable_plugins = ("GangScheduling",)
            self.recorder.event(
                members[0].pod, "Warning", "FailedScheduling",
                f"pod group {key}: {len(members)} member(s) cannot be placed "
                "together (all-or-nothing); gang requeued")
            self.queue.add_gang_backoff(members)

    def _columnar_account(self, batch, cluster, snapshot, bind_rows,
                          bind_nodes, has_ports: bool = True) -> None:
        """Phase 2 of the columnar assume: per-node requested-resource deltas
        for the whole solved batch as numpy scatter-adds keyed by the
        tensorizer's node index — one Resource poke per touched node in the
        cache, and (when nothing foreign intervened and no host ports are in
        play) a direct feed of TensorCache's generation diff so solve(N+1)
        skips the per-node requantize walk entirely."""
        rows = np.asarray(bind_rows, dtype=np.int64)
        nodes = np.asarray(bind_nodes, dtype=np.int64)
        n, r = cluster.n, len(cluster.resource_dims)
        d_used = np.zeros((n, r), dtype=np.int64)
        d_used_nz = np.zeros((n, r), dtype=np.int64)
        np.add.at(d_used, nodes, batch.raw_req[rows])
        np.add.at(d_used_nz, nodes, batch.raw_req_nz[rows])
        d_count = np.bincount(nodes, minlength=n)
        touched = np.unique(nodes)
        final_gen = self.cache.apply_node_resource_deltas(
            cluster.resource_dims,
            [(cluster.node_names[i], d_used[i], d_used_nz[i])
             for i in touched],
            expected_gen=snapshot.generation)
        if final_gen is not None and not has_ports:
            self._tensor_cache.apply_assume_deltas(
                touched, d_used[touched], d_used_nz[touched],
                d_count[touched], tensorized_gen=snapshot.generation,
                assume_gen=final_gen)

    def _handle_device_rejects(self, rejected, snapshot, cluster, sub,
                               assignment) -> None:
        """Failure handling for pods the device solver could not place.

        When the batch is constraint-free (no PTS DoNotSchedule rows, no
        inter-pod affinity), preemption candidates are computed as dense
        priority-tier tensors (_batch_preempt) — the vector analog of the
        reference's parallel DryRunPreemption (preemption.go:680) — and only
        the single chosen node per pod is verified with the real serial
        filters. Constrained batches keep the serial PostFilter path, because
        evicting victims can change PTS/IPA feasibility in ways the tier math
        does not model."""
        import itertools

        import numpy as np

        from .framework import CycleState

        # post-batch capacity: fold every in-batch assignment into used state
        used = cluster.used.astype(np.int64).copy()
        pod_count = cluster.pod_count.astype(np.int64).copy()
        a = np.asarray(assignment)
        placed = a >= 0
        if placed.any():
            np.add.at(used, a[placed], sub.req[placed])
            np.add.at(pod_count, a[placed], 1)
        alloc = cluster.alloc.astype(np.int64)
        max_pods = cluster.max_pods.astype(np.int64)

        filter_ok = sub.tables.filter_ok
        node_names = cluster.node_names
        n = len(node_names)

        constraint_free = sub.ct_class.size == 0 and not sub.ipa.has_any
        if constraint_free:
            # in-batch placements per node: the verify step must see them
            placed_by_node = {}
            for jj in np.nonzero(placed)[0]:
                placed_by_node.setdefault(int(a[jj]), []).append(sub.pods[jj])
            remaining = self._batch_preempt(
                rejected, snapshot, cluster, sub, alloc, used, pod_count,
                max_pods, placed_by_node)
            # the tier math is strictly more permissive than the serial dry
            # run for constraint-free pods (it ignores port conflicts), so a
            # pod with no tier candidate has no serial candidate either —
            # fail it without a second sweep.
            for j, qp in remaining:
                # attributed to Fit so hint-gated requeue fires on node
                # capacity / assigned-pod-freed events
                self._handle_failure(qp, Status.unschedulable(
                    f"0/{n} nodes are available", plugin="NodeResourcesFit"))
            return

        # Constrained batch: synthesize the per-node failure map (vectorized;
        # shared Status instances per category) and run the serial PostFilter.
        unres = Status.unresolvable("node(s) didn't match the pod's static predicates")
        nofit = Status.unschedulable("Insufficient resources on the node")
        inbatch = Status.unschedulable("node rejected by in-batch constraints")
        names_arr = np.array(node_names)
        for j, qp in rejected:
            pod = qp.pod
            cls = int(sub.class_of_pod[j])
            req = sub.req[j].astype(np.int64)
            fits = np.all((req[None, :] == 0) | (req[None, :] <= alloc - used),
                          axis=1) & (pod_count + 1 <= max_pods)
            static_ok = filter_ok[cls]
            failed = {}
            failed.update(zip(names_arr[~static_ok].tolist(), itertools.repeat(unres)))
            failed.update(zip(names_arr[static_ok & ~fits].tolist(), itertools.repeat(nofit)))
            failed.update(zip(names_arr[static_ok & fits].tolist(), itertools.repeat(inbatch)))
            fw = self._fw(pod) or self.framework
            state = CycleState()
            fw.run_pre_filter(state, pod, snapshot)
            from .serial import ScheduleResult

            result = ScheduleResult(
                status=Status.unschedulable(f"0/{n} nodes are available"),
                failed_nodes=failed, state=state,
                evaluated_nodes=n)
            self._maybe_preempt(qp, result)
            self._handle_failure(qp, result.status, result.failed_nodes)

    def _preemption_plugin(self, fw):
        from .plugins.default_preemption import DefaultPreemption

        for p in fw.post_filter_plugins:
            if isinstance(p, DefaultPreemption):
                return p
        return None

    def _batch_preempt(self, rejected, snapshot, cluster, sub, alloc, used,
                       pod_count, max_pods, placed_by_node):
        """Tiered batch preemption (reference: preemption.go DryRunPreemption
        :680 + SelectCandidate :396, reframed as tensor math).

        For each rejected pod at priority p, candidate nodes are those where
        the pod fits after evicting every pod with priority < p — computed
        once per distinct tier as dense [N,R] freed-capacity tensors. Node
        selection follows pick_one_node_for_preemption's order (fewest PDB
        violations, lowest max victim priority, smallest priority sum, fewest
        victims, index). Only the chosen node runs the serial dry run
        (_dry_run_node), which produces the MINIMAL victim set via the
        reprieve pass and exact PDB accounting; its victims update the tier
        tensors so later pods in the batch see the new capacity.

        Returns the (j, qp) pairs that could not be preempted."""
        import numpy as np

        from ..api import compute_pod_resource_request
        from ..snapshot.tensorizer import _quantize
        from .framework import CycleState, PodInfo
        from .plugins.default_preemption import Candidate

        n = cluster.n
        dims = cluster.resource_dims
        r = len(dims)

        # flatten bound pods into victim arrays (one pass over the snapshot)
        v_node, v_prio, v_req, v_pods = [], [], [], []
        node_victims: List[List[int]] = [[] for _ in range(n)]
        for i, ni in enumerate(snapshot.node_info_list):
            for pi in ni.pods:
                p = pi.pod
                node_victims[i].append(len(v_pods))
                v_node.append(i)
                v_prio.append(p.spec.priority)
                v_req.append(_quantize(
                    compute_pod_resource_request(p), dims, is_request=True))
                v_pods.append(p)
        if not v_pods:
            return list(rejected)
        v_node = np.array(v_node, np.int64)
        v_prio = np.array(v_prio, np.int64)
        v_req = np.array(v_req, np.int64).reshape(len(v_pods), r)
        v_alive = np.ones(len(v_pods), dtype=bool)

        plugin_by_fw: dict = {}

        def plugin_for(pod):
            fw = self._fw(pod) or self.framework
            got = plugin_by_fw.get(id(fw))
            if got is None:
                got = (fw, self._preemption_plugin(fw))
                plugin_by_fw[id(fw)] = got
            return got

        # PDB exhaustion per victim (approximate violation count for node
        # selection; the serial dry run on the chosen node is exact). Listed
        # from the store directly — profiles without DefaultPreemption must
        # not blind the batch to budgets.
        try:
            pdbs, _ = self.store.list("poddisruptionbudgets")
        except Exception:
            pdbs = []
        v_pdb_blocked = np.zeros(len(v_pods), dtype=bool)
        if pdbs:
            for vi, p in enumerate(v_pods):
                v_pdb_blocked[vi] = any(
                    pd.metadata.namespace == p.metadata.namespace
                    and pd.selector is not None
                    and pd.selector.matches(p.metadata.labels)
                    and pd.disruptions_allowed <= 0
                    for pd in pdbs)

        tier_cache: dict = {}

        def tier(p):
            got = tier_cache.get(p)
            if got is None:
                mask = v_alive & (v_prio < p)
                freed = np.zeros((n, r), np.int64)
                np.add.at(freed, v_node[mask], v_req[mask])
                cnt = np.zeros(n, np.int64)
                np.add.at(cnt, v_node[mask], 1)
                psum = np.zeros(n, np.int64)
                np.add.at(psum, v_node[mask], v_prio[mask])
                viol = np.zeros(n, np.int64)
                if pdbs:
                    np.add.at(viol, v_node[mask & v_pdb_blocked], 1)
                pmax = np.full(n, -(2**31), np.int64)
                np.maximum.at(pmax, v_node[mask], v_prio[mask])
                got = [freed, cnt, psum, viol, pmax]
                tier_cache[p] = got
            return got

        filter_ok = sub.tables.filter_ok
        node_names = cluster.node_names
        remaining = []
        nominated_by_node: Dict[int, List] = {}
        for j, qp in rejected:
            pod = qp.pod
            fw, plugin = plugin_for(pod)
            if plugin is None or pod.spec.preemption_policy == "Never":
                remaining.append((j, qp))
                continue
            p = pod.spec.priority
            cls = int(sub.class_of_pod[j])
            req = sub.req[j].astype(np.int64)
            freed, cnt, psum, viol, pmax = tier(p)
            fits = np.all((req[None, :] == 0)
                          | (req[None, :] <= alloc - used + freed), axis=1)
            fits &= pod_count + 1 - cnt <= max_pods
            cand_mask = fits & filter_ok[cls] & (cnt > 0)
            if not cand_mask.any():
                remaining.append((j, qp))
                continue
            idxs = np.nonzero(cand_mask)[0]
            order = np.lexsort((idxs, cnt[idxs], psum[idxs], pmax[idxs], viol[idxs]))
            # candidate cap mirrors GetOffsetAndNumCandidates (preemption.go:595)
            num_candidates = max(plugin.MIN_CANDIDATE_NODES_ABSOLUTE,
                                 n * plugin.MIN_CANDIDATE_NODES_PERCENTAGE // 100)
            state = CycleState()
            _, st = fw.run_pre_filter(state, pod, snapshot)
            chosen = None
            if st.is_success():
                for oi in order[:num_candidates]:  # best-ranked first
                    nn = int(idxs[oi])
                    ni = snapshot.node_info_list[nn]
                    # the snapshot NodeInfo is pre-batch: drop victims an
                    # earlier pod in this batch already claimed (v_alive
                    # False) and add in-batch placements/nominations, or the
                    # dry run re-selects dead victims and frees nothing
                    dead = [v_pods[vi] for vi in node_victims[nn]
                            if not v_alive[vi]]
                    extra = list(placed_by_node.get(nn, ()))
                    extra += nominated_by_node.get(nn, [])
                    if dead or extra:
                        ni = ni.clone()
                        for dp_ in dead:
                            ni.remove_pod(dp_)
                        for xp in extra:
                            ni.add_pod(PodInfo(xp))
                    got = plugin._dry_run_node(state, pod, ni, pdbs)
                    if got is not None:
                        chosen = (nn, got)
                        break
            if chosen is None:
                remaining.append((j, qp))
                continue
            nn, cand = chosen
            victims = cand.victims
            self.preempt_victims_total += len(victims)
            vkeys = {v.key for v in victims}
            freed_now = np.zeros(r, np.int64)
            for vi in node_victims[nn]:
                if v_alive[vi] and v_pods[vi].key in vkeys:
                    v_alive[vi] = False
                    freed_now += v_req[vi]
                    for tp, (tfreed, tcnt, tpsum, tviol, _tp) in tier_cache.items():
                        if v_prio[vi] < tp:
                            tfreed[nn] -= v_req[vi]
                            tcnt[nn] -= 1
                            tpsum[nn] -= v_prio[vi]
                            if v_pdb_blocked[vi]:
                                tviol[nn] -= 1
            # max victim priority can only be recomputed, not decremented
            for tp, arrs in tier_cache.items():
                alive = [int(v_prio[vi]) for vi in node_victims[nn]
                         if v_alive[vi] and v_prio[vi] < tp]
                arrs[4][nn] = max(alive) if alive else -(2**31)
            used[nn] += req - freed_now
            pod_count[nn] += 1 - len(victims)
            nominated_by_node.setdefault(nn, []).append(pod)
            plugin._prepare_candidate(cand, pod)
            qp.pod.status.nominated_node_name = node_names[nn]
            self.preemption_count += 1
            self._handle_failure(qp, Status.unschedulable(
                f"preempted {len(victims)} pod(s) on {node_names[nn]}; "
                "waiting for victims to terminate", plugin="NodeResourcesFit"))
        return remaining

    def _handle_failure(self, qp: QueuedPodInfo, status: Status,
                        failed_nodes: Optional[Dict[str, Status]] = None) -> None:
        """Taps the failure's attribution (plugin, else the reason text) into
        the current batch's flight record before the shared requeue path."""
        sink = self._batch_reasons
        if sink is not None:
            key = status.plugin or (status.reasons[0][:80] if status.reasons
                                    else status.code.name.lower())
            sink[key] = sink.get(key, 0) + 1
        super()._handle_failure(qp, status, failed_nodes)

    def sched_stats(self) -> Dict:
        """The /debug/schedstats payload: live counters + the flight
        recorder's aggregate stage table and last-batch record (the
        machine-generated successor of ROADMAP's hand-maintained table)."""
        active, backoff, unsched = self.queue.lengths()
        gang = None
        if self.gangs is not None and self.gangs.active:
            from ..server import metrics as m

            expired = self.gangs.quorum_expired_count(self.cache.contains)
            m.gang_quorum_expired_assumes.set(expired)
            gang = {"staged": self.queue.gang_staged_count(),
                    "vetoes": self.gang_vetoes,
                    "quorum_expired_assumes": expired}
        fr = self.flightrec
        return {
            "solver": self.solver,
            "batch_size": self.batch_size,
            "batches_solved": self.batches_solved,
            "scheduled": self.scheduled_count,
            "failed": self.failed_count,
            "preemptions": self.preemption_count,
            "preempt_victims": self.preempt_victims_total,
            "queue": {"active": active, "backoff": backoff,
                      "unschedulable": unsched},
            "gang": gang,
            "recorder": {"enabled": fr.enabled, "capacity": fr.capacity,
                         "records": len(fr),
                         "self_seconds": round(fr.self_seconds, 6)},
            "stages": fr.stage_table(),
            "last_batch": fr.last(),
        }

    def _hard_pod_affinity_weight(self) -> int:
        for fw in self.profiles.values():
            for p in fw.plugins:
                if p.name == "InterPodAffinity":
                    return getattr(p, "hard_pod_affinity_weight", 1)
        return 1

    def _bind_one(self, qp: QueuedPodInfo, node_name: str, assumed,
                  async_mode: bool) -> None:
        try:
            self.store.bind(qp.pod.metadata.namespace, qp.pod.metadata.name, node_name)
            self.cache.finish_binding(assumed)
            if async_mode:
                with self._bind_err_lock:
                    self._bind_successes += 1
            else:
                self.scheduled_count += 1
        except Exception as e:
            self.cache.forget_pod(assumed)
            if self.gangs is not None:
                self.gangs.note_forgotten(assumed)
            if async_mode:
                # surfaced on the scheduling thread at the next drain; handling
                # failures re-enters the queue, which isn't bind-thread-safe
                with self._bind_err_lock:
                    self._bind_errors.append((qp, Status.error(str(e))))
            else:
                self._handle_failure(qp, Status.error(str(e)))

    def _ensure_bind_worker(self) -> None:
        if self._bind_worker is None or not self._bind_worker.is_alive():
            self._bind_worker = threading.Thread(target=self._bind_loop, daemon=True)
            self._bind_worker.start()

    def _bind_loop(self) -> None:
        """Drains the bind queue in PIPELINED sub-batches: items queued at
        wake-up are merged only up to bind_chunk pods per store.bind_many +
        confirm cycle, so commit(N) runs while the scheduling thread works
        on solve(N+1) — chunk-granular overlap instead of one monolithic
        commit that the scheduling thread can only wait behind (the
        bind_wait stall the PR 3 stage table surfaced)."""
        while True:
            item = self._bind_q.get()
            if item is None:
                self._bind_q.task_done()
                return
            batches = [item]  # each queue item is a LIST of bind triples
            merged = len(item)
            done = False
            while merged < self.bind_chunk:
                try:
                    nxt = self._bind_q.get_nowait()
                except _queue.Empty:
                    break
                if nxt is None:
                    done = True
                    break
                batches.append(nxt)
                merged += len(nxt)
            try:
                self._bind_batch([t for b in batches for t in b])
            finally:
                for _ in batches:
                    self._bind_q.task_done()
                if done:
                    self._bind_q.task_done()  # the sentinel
            if done:
                return

    def _bind_batch(self, items) -> None:
        t0 = time.perf_counter()
        try:
            self._bind_batch_inner(items)
        finally:
            t1 = time.perf_counter()
            self.flightrec.add_outside("bind", t1 - t0)
            from ..server import metrics as m

            m.batch_stage_duration.observe(t1 - t0, "bind")
            self.flightrec.note_self_time(time.perf_counter() - t1)

    def _bind_batch_inner(self, items) -> None:
        triples = [(qp.pod.metadata.namespace, qp.pod.metadata.name, node)
                   for qp, node, _assumed in items]
        # chunked: each bind_many holds the store locks once; a single
        # 100k-bind hold would starve every other store consumer. A chunk
        # that throws fails ONLY its own pods — earlier chunks already
        # committed and must not be forgotten/requeued.
        errors = []
        for lo in range(0, len(triples), self.bind_chunk):
            chunk = triples[lo:lo + self.bind_chunk]
            try:
                _bound, errs = self.store.bind_many(
                    chunk, origin=self._bind_origin)
                errors.extend(errs)
            except Exception as e:
                errors.extend((f"{ns}/{name}", str(e))
                              for ns, name, _node in chunk)
        if not errors:
            # common case: whole sub-batch committed. On the coalesced
            # pipeline the assume-CONFIRM piggybacks right here (one cache
            # lock) instead of a later event re-ingest — the scheduler skips
            # its own origin-tagged MODIFIED batches entirely, removing the
            # old finish_binding ttl window AND the confirm stage from the
            # scheduling thread. Leftovers (assume expired, foreign rebind)
            # re-ingest on the scheduling thread at the next drain. The
            # per-pod pipeline (watch_coalesce=False, the parity oracle)
            # keeps the finish_binding + event-confirm flow byte-for-byte.
            if self.watch_coalesce:
                pairs = [(qp.pod.key, node) for qp, node, _a in items]
                leftover = self.cache.confirm_assumed_bulk(pairs)
                with self._bind_err_lock:
                    self._bind_successes += len(items)
                    if leftover:
                        self._bind_confirm_leftovers.extend(
                            items[i][2] for i in leftover)
            else:
                self.cache.finish_binding_bulk([a for _qp, _node, a in items])
                with self._bind_err_lock:
                    self._bind_successes += len(items)
            return
        errmap = dict(errors)
        confirm = []
        with self._bind_err_lock:
            for qp, node, assumed in items:
                msg = errmap.get(qp.pod.key)
                if msg is None:
                    if self.watch_coalesce:
                        confirm.append((qp.pod.key, node, assumed))
                    else:
                        self.cache.finish_binding(assumed)
                    self._bind_successes += 1
                else:
                    self.cache.forget_pod(assumed)
                    if self.gangs is not None:
                        self.gangs.note_forgotten(assumed)
                    self._bind_errors.append((qp, Status.error(msg)))
            if confirm:
                leftover = self.cache.confirm_assumed_bulk(
                    [(k, n) for k, n, _a in confirm])
                self._bind_confirm_leftovers.extend(
                    confirm[i][2] for i in leftover)

    def _drain_bind_results(self) -> None:
        """Fold completed async binds into counters and re-handle failures on
        the scheduling thread (handleBindingCycleError -> requeue). Does NOT
        wait for in-flight binds — callable every cycle under sustained load.
        Failures are requeued AND recorded in bind_failures so callers of
        schedule_batch can observe them (take_bind_failures)."""
        with self._bind_err_lock:
            done, self._bind_successes = self._bind_successes, 0
            errs, self._bind_errors = self._bind_errors, []
            leftovers, self._bind_confirm_leftovers = (
                self._bind_confirm_leftovers, [])
        self.scheduled_count += done
        for pod in leftovers:
            # worker-side confirm missed (assume expired / foreign write got
            # in first): re-read the COMMITTED object — the assume-time clone
            # is stale (pre-bind rv, possibly older labels), and the pod may
            # have been deleted since (re-ingesting the clone would resurrect
            # it in the cache; the event-stream confirm of old couldn't,
            # because it ran in rv order) — then take the full ingest path,
            # exactly like a foreign MODIFIED, correcting the cache
            try:
                cur = self.store.get("pods", pod.key)
            except NotFoundError:
                continue  # deleted since the bind: nothing left to account
            self._handle_pod(MODIFIED, cur)
        if errs:
            self.flightrec.note_bind_failures(
                [(qp.pod.key, status.message()) for qp, status in errs])
        for qp, status in errs:
            self.bind_failures.append((qp.pod.key, status.message()))
            self._handle_failure(qp, status)
        if len(self.bind_failures) > 100_000:
            del self.bind_failures[:50_000]  # bounded if never drained

    def take_bind_failures(self) -> List:
        """Drain the (pod key, error message) log of asynchronous bind
        failures observed since the last call. The pods themselves were
        already requeued via the normal failure path; this surfaces WHAT
        failed to callers of schedule_batch/flush_binds, which otherwise
        only ever see success counts."""
        out, self.bind_failures = self.bind_failures, []
        return out

    def flush_binds(self) -> None:
        """Wait for queued store.bind writes, then drain results. The wait is
        recorded as the "bind_wait" stage — the scheduling thread's stall on
        in-flight binds, the residual the stage table needs to explain wall
        time when binds don't fully overlap the next solve."""
        t0 = time.perf_counter()
        if self._bind_worker is not None:
            self._bind_q.join()
        self.flightrec.add_outside("bind_wait", time.perf_counter() - t0)
        self._drain_bind_results()

    def _serial_one(self, qp: QueuedPodInfo) -> None:
        result = self.schedule_pod(qp.pod)
        if not result.suggested_host:
            self._maybe_preempt(qp, result)
            self._handle_failure(qp, result.status, result.failed_nodes)
            return
        # Full commit chain (Reserve/Permit/PreBind/PostBind) — fallback pods
        # (volumes, inter-pod affinity) depend on these extension points.
        self._commit_cycle(qp, result)

    def start(self) -> None:
        """Background loop: batch solve instead of one-pod cycles."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                handled = self.schedule_batch(timeout=0.0)
                # drain async-bind outcomes every cycle (bind failures must
                # requeue even under sustained load), full flush only on idle
                self._drain_bind_results()
                if handled == 0:
                    self.flush_binds()
                    self.pump_events()
                    self.queue.flush_backoff_completed()
                    self.queue.flush_unschedulable_left_over()
                    self.sweep_expired_assumes()
                    self._stop.wait(0.05)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def run_until_idle(self, max_cycles: int = 10_000) -> int:
        n = 0
        while n < max_cycles:
            if self.schedule_batch(timeout=0.0) == 0:
                # quiesce: flush in-flight binds (may requeue failures), then
                # drain events + expired assumes before declaring idle
                self.flush_binds()
                self.pump_events()
                self.sweep_expired_assumes()
                if self.schedule_batch(timeout=0.0) == 0:
                    break
            n += 1
        self.flush_binds()
        return n


def _subset_batch(batch, idx):
    """View of a PodBatchTensors restricted to pod rows idx (class tables shared)."""
    import dataclasses

    return dataclasses.replace(
        batch,
        pods=[batch.pods[i] for i in idx],
        class_of_pod=batch.class_of_pod[idx],
        req=batch.req[idx],
        req_nz=batch.req_nz[idx],
        balanced_active=batch.balanced_active[idx],
        raw_req=None if batch.raw_req is None else batch.raw_req[idx],
        raw_req_nz=None if batch.raw_req_nz is None else batch.raw_req_nz[idx],
        gang_of_pod=(None if batch.gang_of_pod is None
                     else batch.gang_of_pod[idx]),
    )
