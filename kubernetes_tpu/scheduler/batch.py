"""Batch TPU scheduler — drains whole pending-pod batches and solves them jointly.

The batching analog of ScheduleOne (SURVEY.md §2.4 'Pod-level serialization'):
pods are popped in queue (priority) order, tensorized against the current cache
snapshot, solved on device with the greedy scan kernel (ops/solver.py), and the
resulting assignments are assumed + bound through the same store surface the
serial path uses. Classes with features the device path doesn't cover yet
(inter-pod affinity, non-default PTS inclusion policies) fall back to the serial
oracle pod-by-pod — the framework-gating stance of the north star (solver
behind the same extension surface, serial path always available).
"""

from __future__ import annotations

import queue as _queue
import random as _random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..chaos import faultinject as _chaos
from ..chaos.faultinject import FaultKill
from ..obs import tracebuf as _tracebuf
from ..obs.timeseries import TimeSeriesRecorder
from ..snapshot.tensorizer import TensorCache, build_cluster_tensors, build_pod_batch
from ..store import (MODIFIED, APIStore, NotFoundError, is_bind_conflict,
                     pod_bind_clone, pod_structural_clone)
from .breaker import SolverCircuitBreaker
from .flightrec import FlightRecorder, StageClock, register_scheduler
from .framework import Status
from .podtrace import PodTracer
from .queue import QueuedPodInfo
from .runtime import Framework
from .serial import Scheduler


class _RequeuedChunk(list):
    """A bind chunk getting its ONE supervised retry after an escaped
    bind-worker exception (or a dead-worker recovery). A second escape fails
    its pods through the normal bind-error path instead of re-queueing again
    — no livelock on a deterministic fault."""


class BatchScheduler(Scheduler):
    """solver: 'exact' (scan, bit-parity with serial), 'fast' (water-filling),
    'auction' / 'sinkhorn' (global transportation solvers with warm-started
    duals — models/transport.py), 'native' (the C++ host engine — scan parity
    for constraint-free batches; native/hostsched.cpp), or 'auto' (fast when
    the batch has no topology-spread constraints, exact otherwise)."""

    BIND_FAILURE_LOG_CAP = 10_000  # take_bind_failures log bound

    def __init__(self, store: APIStore, framework: Framework, batch_size: int = 4096,
                 solver: str = "exact", pipeline_binds: bool = True,
                 columnar: bool = True, flight_recorder: bool = True,
                 flight_capacity: int = FlightRecorder.DEFAULT_CAPACITY,
                 breaker_threshold: int = 3, breaker_cooldown_s: float = 30.0,
                 bind_retries: int = 3, bind_retry_base_s: float = 0.05,
                 pod_trace: Optional[bool] = None,
                 trace_sample_k: int = PodTracer.DEFAULT_SAMPLE_K,
                 ts_window_s: float = 5.0, rank_align: bool = True,
                 gang_preemption: bool = True, **kw):
        super().__init__(store, framework, **kw)
        self.batch_size = batch_size
        self.solver = solver
        self.batches_solved = 0
        # flight recorder (scheduler/flightrec.py): per-batch stage timing +
        # bounded trace ring, surfaced via /debug/schedstats and `ktl sched
        # stats`. Stage marks are per BATCH (a handful of perf_counter reads),
        # so enabled-vs-disabled placement parity and the <2% overhead budget
        # both hold (tests/test_flightrec.py, tests/test_bench_quick.py).
        self.flightrec = FlightRecorder(capacity=flight_capacity,
                                        enabled=flight_recorder)
        self.queue.stat_sink = self.flightrec
        # sampled pod lifecycle tracer (scheduler/podtrace.py, ISSUE 7):
        # reservoir-samples K pods per window at queue admission, stamps
        # lifecycle edges with SHARED per-batch/per-chunk timestamps, and
        # feeds the all-pods submit->bound latency histogram. Follows the
        # recorder's enable switch unless pod_trace says otherwise; its
        # self-time accrues to the same <2% budget.
        self.podtrace = PodTracer(
            clock=self.clock, sample_k=trace_sample_k,
            enabled=flight_recorder if pod_trace is None else pod_trace,
            stat_sink=self.flightrec)
        self.queue.trace_sink = self.podtrace
        # windowed time-series (obs/timeseries.py, ISSUE 13): fixed-interval
        # windows over the batch pipeline — per-stage p50/p99, pods/s, and
        # window-close probe columns (queue depth, breaker state, watch lag,
        # partition counters, resource sampler). ONE note_batch tap per
        # schedule_batch; the flight recorder forwards its outside buckets
        # (bind / bind_wait / queue_add) so overlapped stages window too.
        # No stat_sink: its taps already run inside callers' measured
        # self-time windows — a sink would double-bill the budget.
        self.timeseries = TimeSeriesRecorder(
            window_s=ts_window_s, enabled=flight_recorder)
        self.flightrec.timeseries = self.timeseries
        # optional obs/resource.py ResourceSampler (attach_resource_sampler):
        # RSS / GC / live-object / per-thread CPU columns for the soak gates
        self.resource_sampler = None
        self._register_window_probes()
        # queue-depth/oldest-age gauge refresh throttle (satellite): the
        # telemetry scan is O(queue), so gauges update at most 1/s per pump
        self._q_telemetry_next = 0.0
        self._q_telemetry_last: Optional[Dict] = None
        self._q_telemetry_lock = threading.Lock()
        register_scheduler(self._bind_origin, self)
        # per-batch unschedulable-reason attribution (set during
        # schedule_batch; _handle_failure taps Status.plugin into it)
        self._batch_reasons: Optional[Dict[str, int]] = None
        self.preempt_victims_total = 0  # victims chosen by _batch_preempt
        self.trace_threshold = 1.0  # ScheduleBatch Trace log threshold (s)
        self.transport_state = None  # warm duals carried across batches
        # generation-diff incremental tensorization (cache.go:186 analog)
        self._tensor_cache = TensorCache()
        # columnar=True is the batched host pipeline: coalesced watch ingest,
        # structural+scatter-add assume accounting, self-bind short-circuit.
        # False restores the per-pod paths (the parity oracle for tests).
        self.columnar = columnar
        self.watch_coalesce = columnar
        # Cache-row mode (ISSUE 16): eligible device batches land as columnar
        # cache rows with zero per-pod objects. Resolved once at construction
        # like the store's columnar switch (STORE_COLUMNAR sweeps the whole
        # pipeline to its object-path oracle).
        from .cachecols import available as _cachecols_available

        self._cache_columnar = columnar and _cachecols_available()
        # Bind pipelining (schedule_one.go:120-132 bindingCycle-in-goroutine
        # analog): assume_pod runs synchronously so the next solve's snapshot
        # sees the capacity, while the store.bind writes flush on a worker
        # thread overlapped with solve(N+1).
        self.pipeline_binds = pipeline_binds
        # commit sub-batch size: each bind_many+confirm cycle covers this
        # many pods, so commit(N) overlaps the scheduling thread's work on
        # solve(N+1) at chunk granularity instead of whole-batch granularity
        # (and each store critical section stays short)
        self.bind_chunk = 4096
        self._bind_q: _queue.Queue = _queue.Queue()
        self._bind_worker: Optional[threading.Thread] = None
        self._bind_errors: List = []
        self._bind_successes = 0  # folded into scheduled_count on the
        self._bind_err_lock = threading.Lock()  # scheduling thread (no race)
        # assumed pods whose worker-side confirm missed (assume expired /
        # foreign interference): re-ingested on the scheduling thread at the
        # next drain, like any foreign MODIFIED
        self._bind_confirm_leftovers: List = []
        # async bind failures, surfaced to schedule_batch callers (the worker
        # requeues them internally, but "my bind_many failed" was invisible):
        # (pod key, message) pairs drained via take_bind_failures(). BOUNDED:
        # under sustained bind faults with no drainer the deque evicts oldest
        # entries and counts them instead of leaking (ISSUE 6 satellite)
        self.bind_failures: deque = deque(maxlen=self.BIND_FAILURE_LOG_CAP)
        self.bind_failures_dropped = 0
        # failure domains (ISSUE 6): solver circuit breaker (trips the fast
        # solver to the exact scan oracle after `breaker_threshold`
        # consecutive solver exceptions, half-open recovery after cooldown),
        # transient-bind retry policy, and bind-worker supervision state
        self.breaker = SolverCircuitBreaker(clock=self.clock,
                                            threshold=breaker_threshold,
                                            cooldown_s=breaker_cooldown_s)
        self.bind_retries = bind_retries
        self.bind_retry_base_s = bind_retry_base_s
        # the solver path the last _solve_device call executed (or was
        # executing when it raised) — what the breaker is fed, since the
        # MODE label alone would credit a constrained batch's scan run to
        # the fast path (scheduler/breaker.py path_matches_mode)
        self._solve_path = "exact"
        # constraint propose-and-repair observability (ISSUE 8): the last
        # batch's RepairStats (feeds the flight record) + running totals for
        # sched_stats/ktl — a pathological repair loop (rounds pinned at the
        # bound, heavy residual, full_scan re-solves) must be visible
        self._last_repair = None
        self.repair_totals = {
            "batches": 0, "rounds": 0, "proposed": 0, "repaired": 0,
            "residual": 0, "full_scan": 0, "violations": 0}
        # in-flight bind chunks (each owing one task_done): recorded by the
        # worker before commit, cleared after bookkeeping — non-empty with a
        # DEAD worker means a hard kill stranded them, and the liveness check
        # in _drain_bind_results re-queues them and settles the join() debt
        self._bind_inflight: List = []
        self.bind_worker_restarts = 0  # supervised escapes + dead-worker recoveries
        # gang scheduling (scheduler/gang.py): PodGroup quorums + placed
        # members, fed by the watch plumbing in serial.py; the queue holds
        # gang members in staging until quorum, and schedule_batch enforces
        # the all-or-nothing veto. Inactive (one attr read) until a PodGroup
        # exists.
        # partitioned scheduling (scheduler/partition.py, ISSUE 12):
        # installed by PartitionedScheduler on its pipelines; all inert on a
        # standalone scheduler. reroute_hook(qp, status) -> bool intercepts
        # a plain shard-capacity unschedulable verdict and moves the pod to
        # another partition's queue (True = ownership transferred, no local
        # requeue/narration); conflict_sink(qp, msg) consumes a LOST
        # cross-partition bind race (the pod IS bound — the store decided —
        # so the losing pipeline drops it instead of requeueing a pod that
        # no longer needs scheduling).
        self.partition_index: Optional[int] = None
        self.reroute_hook = None
        self.conflict_sink = None
        self.partition_conflicts = 0  # bind conflicts this pipeline LOST
        self.partition_reroutes = 0  # pods handed to another partition
        from .gang import GangDirectory
        from .gangpreempt import GangPreemptor

        self.gangs = GangDirectory()
        self.queue.set_gang_hooks(self.gangs.group_of,
                                  self.gangs.quorum_ready,
                                  lambda: self.gangs.active)
        self.gang_vetoes = 0  # gangs stripped post-solve (observability)
        # gang-aware preemption (scheduler/gangpreempt.py, ISSUE 14): a
        # solver-vetoed gang tries a min-cost victim cover on one ICI slice
        # before requeueing; rank_align gates the post-solve rank→ring
        # permutation (models/gangcover.py). Both inert on gang-free runs.
        self.rank_align = rank_align
        self.gangpreempt = GangPreemptor(self) if gang_preemption else None
        # background rebalancer (scheduler/rebalance.py, ISSUE 17):
        # installed by enable_rebalancer(); run_until_idle's quiesce path
        # paces it, sched_stats()["rebalance"] publishes its totals. Inert
        # (one attr read) until installed.
        self.rebalancer = None

    def schedule_batch(self, timeout: Optional[float] = 0.0) -> int:
        """Drain up to batch_size pods, solve jointly, bind. Returns #pods handled.

        Instrumented per BATCH (never per pod): a StageClock marks each
        pipeline stage boundary, the marks feed the scheduler_batch_stage
        histograms + a utiltrace-style Trace (logged past trace_threshold),
        and one flight-recorder record captures the batch's outcome, counts,
        and unschedulable-reason attribution. batch_solve_duration is
        observed in a try/finally with an outcome label
        (scheduled/unschedulable/error — mirroring scheduling_attempts) on
        EVERY path that pops a batch, including the no-nodes early return
        and errors. An empty pop schedules nothing and observes nothing by
        design (its pump cost folds into the aggregate outside buckets)."""
        from ..ops.solver import greedy_scan_solve, make_inputs
        from ..server import metrics as m
        from ..utils.tracing import Trace

        fr = self.flightrec
        clock = StageClock()
        # queue_add accrues into the recorder's outside bucket at its own
        # call site (inside this pump); difference it out so the "ingest"
        # residual stays disjoint from its sub-stage
        sub0 = fr.outside_seconds("queue_add")
        # pump until the watch drains — bounded: a 100k-pod backlog must
        # reach the queue as ONE batch (not batch_size/10k sub-solves), but
        # sustained event arrival must not starve scheduling forever
        for _ in range(8):
            if self.pump_events(max_events=self.batch_size) < self.batch_size:
                break
        clock.mark("ingest")
        clock.sub("ingest", fr.outside_seconds("queue_add") - sub0)
        qps = self.queue.pop_batch(self.batch_size, timeout=timeout)
        clock.mark("pop")
        if not qps:
            # no batch to pin these marks to: fold idle pump/poll time into
            # the aggregate buckets (confirm-heavy idle cycles still show)
            for name, sec in clock.stages.items():
                fr.add_outside(name, sec)
            return 0
        m.batch_size_gauge.set(len(qps))
        # ONE full-batch pass finds the sampled pods (set-membership per pod,
        # nothing when the sample is empty); later stage stamps touch only
        # the <=K hits (scheduler/podtrace.py)
        self.podtrace.batch_popped(qps)
        trace = Trace("ScheduleBatch", pods=len(qps))
        failed0 = self.failed_count
        victims0 = self.preempt_victims_total
        self._batch_reasons = reasons = {}
        outcome = "error"  # overwritten unless the body raises
        out: Dict = {}
        # circuit breaker (scheduler/breaker.py): pick THIS batch's solver —
        # the configured one while CLOSED, the exact scan while OPEN, a
        # single probe of the configured one when HALF_OPEN
        out["solver"] = self.breaker.effective_solver(self.solver)
        self._last_repair = None  # set by _note_repair on the repair path
        m.solver_breaker_state.set(self.breaker.code)
        try:
            self._schedule_batch_inner(qps, clock, trace, m,
                                       greedy_scan_solve, make_inputs, out)
            outcome = ("scheduled"
                       if out.get("dispatched", 0)
                       + out.get("serial_scheduled", 0) > 0
                       else "error" if "batch_error" in out
                       else "unschedulable")
            return len(qps)
        finally:
            self._batch_reasons = None
            self.batches_solved += 1
            t_fin = time.perf_counter()
            total = clock.total()
            for name, sec in clock.stages.items():
                m.batch_stage_duration.observe(sec, name)
            m.batch_solve_duration.observe(total, outcome)
            if self.gangs is not None and self.gangs.active:
                m.gang_staged.set(self.queue.gang_staged_count())
            fr.record(
                pods=len(qps), nodes=out.get("nodes", 0), outcome=outcome,
                solver=out.get("solver", self.solver), stages=clock.stages,
                total_s=total,
                scheduled=out.get("dispatched", 0)
                + out.get("serial_scheduled", 0),
                unschedulable=self.failed_count - failed0,
                fallback=out.get("fallback", 0),
                preempted=self.preempt_victims_total - victims0,
                reasons=reasons, gang=out.get("gang"),
                repair=(self._last_repair.as_dict()
                        if self._last_repair is not None else None),
                solver_iterations=getattr(self.transport_state,
                                          "iterations", None),
                breaker=(self.breaker.state
                         if self.breaker.state != "closed" else None),
                error=out.get("batch_error"))
            # windowed time-series (ISSUE 13): ONE tap per batch, inside the
            # t_fin self-time window so its cost bills to the <2% budget
            self.timeseries.note_batch(
                clock.stages, pods=len(qps),
                scheduled=out.get("dispatched", 0)
                + out.get("serial_scheduled", 0),
                failed=self.failed_count - failed0)
            # unified trace timeline (ISSUE 18): ONE tap per batch when a
            # buffer is armed — the batch envelope + stage slices land on
            # this pipeline's track (tid = p<i>-sched), inside the same
            # self-time window so the cost bills to the <2% budget
            if _tracebuf.ACTIVE is not None:
                tb = _tracebuf.ACTIVE
                tb.attach_clock(self.clock)
                tb.note_batch(
                    self._thread_label("sched"), t_end=t_fin,
                    stages=clock.stages, pods=len(qps),
                    scheduled=out.get("dispatched", 0)
                    + out.get("serial_scheduled", 0),
                    outcome=outcome,
                    solver=out.get("solver", self.solver),
                    breaker=self.breaker.state)
            trace.log_if_long(self.trace_threshold)
            self._update_queue_telemetry()
            fr.note_self_time(time.perf_counter() - t_fin)

    def _schedule_batch_inner(self, qps, clock, trace, m,
                              greedy_scan_solve, make_inputs, out) -> None:
        """The batch pipeline body (schedule_batch owns the try/finally
        bookkeeping around it). Fills `out` with nodes/dispatched/fallback/
        gang counts for the flight record."""
        # Materialization barrier (ISSUE 16): a CONSTRAINED batch walks the
        # snapshot's pod lists (PTS selector counts, IPA existing-pod terms)
        # — collapse columnar cache rows into PodInfos before the snapshot is
        # taken so those walks see every pod. The predicate is a strict
        # superset of batch.has_constraints (ct/st/ipa all derive from these
        # two spec fields), checked pod-by-pod with early exit; the
        # steady-state constraint-free batch never materializes — that IS the
        # zero-alloc path.
        if self.cache.columnar_rows():
            for qp in qps:
                spec = qp.pod.spec
                if (spec.affinity is not None
                        or spec.topology_spread_constraints):
                    self.cache.materialize_columnar_rows()
                    break
        snapshot = self.cache.update_snapshot()
        out["nodes"] = len(snapshot)
        if len(snapshot) == 0:
            clock.mark("tensorize")
            for qp in qps:
                self._handle_failure(qp, Status.unschedulable("no nodes available to schedule pods"))
            return

        cluster, changed_nodes = self._tensor_cache.cluster_tensors(snapshot)
        clock.mark("tensorize")
        trace.step("Tensorized cluster", nodes=len(snapshot))
        pods = [qp.pod for qp in qps]
        store_cols = None
        if self.columnar:
            getcols = getattr(self.store, "pod_columns", None)
            if getcols is not None:
                store_cols = getcols()
        batch = build_pod_batch(
            pods, snapshot, cluster, ns_labels=self._ns_labels,
            hard_pod_affinity_weight=self._hard_pod_affinity_weight(),
            reuse=self._tensor_cache, changed_nodes=changed_nodes,
            gangs=self.gangs, store_cols=store_cols)
        if store_cols is not None:
            # bind/assume-edge sig capture (ISSUE 17 satellite): the batch
            # build just primed _class_sig/_req_sig on these pods — write
            # the refs back into the store's sig column so rows re-synced
            # by later status/relist writes keep a seedable signature. ONE
            # batched call per batch (HP001), not a per-pod ride-along.
            cap = getattr(self.store, "capture_sig_memos", None)
            if cap is not None:
                cap(pods)

        fallback_mask = batch.fallback_class[batch.class_of_pod]
        # Gang semantic hole CLOSED (ISSUE 8 satellite; ROADMAP direction 4
        # carryover): a gang member whose class needs the serial path
        # (volumes, DRA, non-default PTS policies) used to schedule
        # INDIVIDUALLY there — silently breaking all-or-nothing. The whole
        # gang is vetoed instead, with a narrated reason: every in-batch
        # member (device and fallback rows alike) fails unschedulable and
        # ONE Warning event names the gangs; a pod or PodGroup update
        # re-queues them through the normal unschedulable machinery.
        gang_strip = None
        if batch.gang_of_pod is not None:
            gof = np.asarray(batch.gang_of_pod)
            bad_gof = np.unique(gof[(gof >= 0) & fallback_mask])
            if bad_gof.size:
                gang_strip = np.isin(gof, bad_gof)
                names = ", ".join(batch.gang_keys[g] for g in bad_gof.tolist())
                self.gang_vetoes += int(bad_gof.size)
                m.gang_vetoed_total.inc(int(bad_gof.size),
                                        reason="serial_fallback")
                strip_rows = np.nonzero(gang_strip)[0].tolist()
                self.recorder.event(
                    qps[strip_rows[0]].pod, "Warning", "GangVetoed",
                    f"gang(s) {names} vetoed: a member class requires "
                    "serial-fallback scheduling (volumes/DRA), where "
                    "all-or-nothing placement cannot be enforced")
                for pi in strip_rows:
                    self._handle_failure(qps[pi], Status.unschedulable(
                        "gang member class requires serial-fallback "
                        "scheduling; all-or-nothing placement is only "
                        "enforced on the batched path (gang vetoed)"))
        if gang_strip is not None:
            device_idx = np.nonzero(~fallback_mask & ~gang_strip)[0]
            fallback_idx = np.nonzero(fallback_mask & ~gang_strip)[0]
        else:
            device_idx = np.nonzero(~fallback_mask)[0]
            fallback_idx = np.nonzero(fallback_mask)[0]
        out["fallback"] = int(fallback_idx.size)
        clock.mark("build_pod_batch")
        trace.step("Built pod batch", device=int(device_idx.size),
                   fallback=int(fallback_idx.size))

        assignment = None
        if device_idx.size:
            sub = _subset_batch(batch, device_idx)
            # gang members present in the device batch? (solver bias + the
            # all-or-nothing post-solve pass). The native and transport
            # backends don't model the slice-packing bonus, so gang batches
            # take the fast/exact paths (which do).
            has_gang = (sub.gang_of_pod is not None
                        and bool((sub.gang_of_pod >= 0).any()))
            solver = out.get("solver", self.solver)
            # Solver failure domain (ISSUE 6): a solver exception no longer
            # loses the batch — no assume has happened yet at solve time, so
            # the device pods requeue into the backoff tier as a unit and the
            # circuit breaker decides whether the NEXT batch degrades to the
            # exact scan oracle (scheduler/breaker.py).
            try:
                assignment = self._solve_device(solver, cluster, batch, sub,
                                                has_gang, greedy_scan_solve,
                                                make_inputs)
            except FaultKill:
                raise  # an injected hard death is not a handled fault
            except Exception as e:
                self._handle_solver_error(e, qps, device_idx, solver, out, m)
                clock.mark("solve")
                trace.step("Solver failed; batch requeued",
                           error=type(e).__name__)
                assignment = None
            else:
                self.breaker.record_success(self._solve_path, self.solver)
        if device_idx.size and assignment is not None:
            # All-or-nothing gang veto (scheduler/gang.py), BEFORE any assume
            # or bind: a gang whose in-batch placements (plus members already
            # placed) miss min_member is stripped wholesale — its placed rows
            # become unplaced for every downstream consumer (bind loop,
            # capacity fold in _handle_device_rejects) — and requeued as a
            # unit. gang_requeue: gang id -> members collected for requeue.
            gang_requeue: Dict[int, List[QueuedPodInfo]] = {}
            hopeless: set = set()
            solver_vetoed: set = set()
            gang_need = None
            veto = None
            gang_info: Optional[Dict[str, int]] = None
            if has_gang:
                from .gang import gang_veto_mask

                gang_info = out["gang"] = {
                    "staged": self.queue.gang_staged_count(),
                    "vetoed": 0, "assume_vetoed": 0, "released": 0,
                    "hopeless": 0}
                gkeys = batch.gang_keys
                gang_need = need = np.array(
                    [max(0, (self.gangs.min_member(k) or 0)
                         - self.gangs.placed_count(k)) for k in gkeys],
                    dtype=np.int64)
                veto, _satisfied = gang_veto_mask(
                    assignment, np.asarray(sub.gang_of_pod), need)
                # a gang needing more members than one solve can ever see is
                # unsatisfiable by this configuration — park it with a
                # diagnostic instead of livelocking through backoff retries
                hopeless.update(np.nonzero(need > self.batch_size)[0].tolist())
                if veto.any():
                    vetoed_gids = np.unique(sub.gang_of_pod[veto])
                    n_vetoed = int(vetoed_gids.size)
                    # solver-vetoed gangs are the gang-preemption candidates
                    # (an assume-time veto means the gang FIT — a capacity
                    # race, not a room problem)
                    solver_vetoed = set(vetoed_gids.tolist())
                    self.gang_vetoes += n_vetoed
                    gang_info["vetoed"] = n_vetoed
                    m.gang_vetoed_total.inc(n_vetoed, reason="solver")
                    assignment = np.where(veto, -1, assignment)
                # rank-aware placement (ISSUE 14): permute which MEMBER gets
                # which node within each (gang, class, request) group so rank
                # order follows ICI ring position — a free permutation of an
                # identical-pod group, run ONLY when some member carries a
                # rank label (rank-less gang batches stay byte-identical)
                if (self.rank_align and sub.gang_rank is not None
                        and bool((np.asarray(sub.gang_rank) >= 0).any())):
                    assignment = self._rank_align_assignment(
                        cluster, sub, assignment, gang_info)
            clock.mark("solve")
            trace.step("Device solve done", solver=solver)
            self.podtrace.batch_stage("solve")  # shared per-batch stamp
            # Two phases: bind every device assignment FIRST, then handle the
            # rejected pods. Handling mid-loop would see capacity still
            # promised to not-yet-bound assignments and double-book nodes.
            rejected = []
            to_bind = []
            bind_rows: List[int] = []  # full-batch pod row per to_bind entry
            bind_nodes: List[int] = []  # cluster node index per to_bind entry
            bind_gang: List[int] = []  # gang id per entry (gang batches only)
            use_columnar = self.columnar and batch.raw_req is not None
            # Zero-object dispatch (ISSUE 16): a gang-free, constraint-free,
            # port-free device batch hands the bind worker the ORIGINAL pod
            # refs (the bind path only reads key + target node) and lands in
            # the cache as columnar ROWS — no pod_bind_clone, no PodInfo, no
            # per-pod allocation at all. Any gang/constraint/port in the
            # batch keeps the structural path byte-for-byte.
            cols_rows_ok = (use_columnar and self._cache_columnar
                            and not has_gang
                            and not batch.has_constraints
                            and batch.class_has_host_ports is not None
                            and not bool(batch.class_has_host_ports[
                                batch.class_of_pod[device_idx]].any()))
            clone = pod_bind_clone if use_columnar else pod_structural_clone
            node_names = cluster.node_names
            sub_gang = (np.asarray(sub.gang_of_pod).tolist()
                        if has_gang else None)
            veto_list = veto.tolist() if veto is not None else None
            # .tolist() once: per-element int() of numpy scalars is
            # measurable at 100k pods
            assign_list = np.asarray(assignment).tolist()
            for j, pi in enumerate(device_idx.tolist()):
                gid = sub_gang[j] if sub_gang is not None else -1
                if veto_list is not None and veto_list[j]:
                    gang_requeue.setdefault(gid, []).append(qps[pi])
                    continue
                nidx = assign_list[j]
                if nidx < 0:
                    if gid >= 0:
                        # unplaced extra of a SATISFIED gang: fail it alone —
                        # and never preempt to place part of a gang, so it
                        # skips the _batch_preempt path entirely
                        self._handle_failure(qps[pi], Status.unschedulable(
                            f"0/{len(node_names)} nodes are available "
                            "(gang member; preemption skipped)",
                            plugin="NodeResourcesFit"))
                    else:
                        rejected.append((j, qps[pi]))
                else:
                    qp = qps[pi]
                    to_bind.append((qp, node_names[nidx],
                                    qp.pod if cols_rows_ok else clone(qp.pod)))
                    bind_rows.append(pi)
                    bind_nodes.append(nidx)
                    if sub_gang is not None:
                        bind_gang.append(gid)
            if to_bind:
                # bulk assume under one cache lock, then hand the worker
                # CHUNKED batches: per-pod puts left bind_many at ~53-pod
                # batches under queue contention, while one 100k batch
                # would hold the store lock against every consumer
                pairs = [(assumed, node) for _qp, node, assumed in to_bind]
                batch_has_ports = True
                if cols_rows_ok:
                    batch_has_ports = False  # port-free by the dispatch gate
                elif use_columnar:
                    batch_has_ports = bool(
                        batch.class_has_host_ports is None
                        or batch.class_has_host_ports[
                            batch.class_of_pod[bind_rows]].any())
                # Assume/dispatch failure domain (ISSUE 6): an exception in
                # this window used to strand the whole batch's assumes. The
                # guard rolls back every entry whose chunk has NOT reached
                # the bind path and requeues it with backoff; dispatched
                # chunks are in flight, owned by the bind worker's own
                # retry/error machinery.
                accounted = False
                dispatched_hi = 0
                sync_bind_s = 0.0
                try:
                    if cols_rows_ok:
                        # row-mode phase 1: the placements land as columnar
                        # rows, zero per-pod objects; resource totals follow
                        # as one scatter-add in _columnar_account
                        bad = self.cache.assume_pods_columnar(pairs)
                    elif use_columnar:
                        # structural phase only; resource totals follow as
                        # one scatter-add in _columnar_account
                        bad = self.cache.assume_pods_structural(
                            pairs, check_ports=batch_has_ports)
                    else:
                        bad = self.cache.assume_pods(pairs)
                except FaultKill:
                    raise
                except Exception as e:
                    self._rollback_undispatched(
                        e, to_bind, bind_gang, 0, use_columnar, False,
                        batch_has_ports, m, out)
                    to_bind = []
                    bad = []
                bad_gangs = set()
                for i, msg in sorted(bad, reverse=True):
                    qp, node, _assumed = to_bind.pop(i)
                    bind_rows.pop(i)
                    bind_nodes.pop(i)
                    gid = bind_gang.pop(i) if bind_gang else -1
                    if gid >= 0:
                        bad_gangs.add(gid)
                        gang_requeue.setdefault(gid, []).append(qp)
                    else:
                        self._handle_failure(qp, Status.error(msg))
                if bad_gangs:
                    # all-or-nothing at assume time: a gang that lost a
                    # member releases every already-assumed sibling BEFORE
                    # any bind fires. On the columnar path phase 2 hasn't
                    # run yet, so the release must be the structural inverse
                    # (forget_pods_structural) — forget_pod would subtract
                    # resource totals that were never added.
                    if gang_info is not None:
                        gang_info["assume_vetoed"] = len(bad_gangs)
                        m.gang_vetoed_total.inc(len(bad_gangs),
                                                reason="assume")
                    released = []
                    for i in range(len(to_bind) - 1, -1, -1):
                        gid = bind_gang[i]
                        if gid in bad_gangs:
                            qp, _node, assumed = to_bind.pop(i)
                            bind_rows.pop(i)
                            bind_nodes.pop(i)
                            bind_gang.pop(i)
                            released.append(assumed)
                            gang_requeue.setdefault(gid, []).append(qp)
                    if gang_info is not None:
                        gang_info["released"] = len(released)
                    if use_columnar:
                        self.cache.forget_pods_structural(
                            released, check_ports=batch_has_ports)
                    else:
                        for assumed in released:
                            self.cache.forget_pod(assumed)
                if bind_gang:
                    # surviving members count toward quorum from assume on
                    # (our own bind confirmations bypass the event stream)
                    for i, (_qp, _node, assumed) in enumerate(to_bind):
                        if bind_gang[i] >= 0:
                            self.gangs.note_assumed(assumed)
                try:
                    if use_columnar and to_bind:
                        self._columnar_account(batch, cluster, snapshot,
                                               bind_rows, bind_nodes,
                                               batch_has_ports)
                        accounted = True
                    clock.mark("assume")
                    trace.step("Assumed placements", bound=len(to_bind))
                    self.podtrace.batch_stage("assume")
                    out["dispatched"] = len(to_bind)
                    # dispatch edge = handed to the bind path; stamped BEFORE
                    # the chunk loop so the synchronous-bind mode (which
                    # completes spans inside the loop) still records it
                    self.podtrace.batch_stage("dispatch")
                    for lo in range(0, len(to_bind), self.bind_chunk):
                        chunk = to_bind[lo:lo + self.bind_chunk]
                        if self.pipeline_binds:
                            self._ensure_bind_worker()
                            self._bind_q.put(chunk)
                        else:
                            t0 = time.perf_counter()
                            self._bind_batch(chunk)
                            sync_bind_s += time.perf_counter() - t0
                        dispatched_hi = lo + len(chunk)
                    if not self.pipeline_binds:
                        self._drain_bind_results()
                except FaultKill:
                    raise
                except Exception as e:
                    self._rollback_undispatched(
                        e, to_bind, bind_gang, dispatched_hi, use_columnar,
                        accounted, batch_has_ports, m, out)
                    out["dispatched"] = dispatched_hi
                clock.mark("dispatch")
                # synchronous binds ran inside the dispatch span AND are
                # observed as the "bind" stage by _bind_batch — keep the
                # stages disjoint (measured locally, so this holds with the
                # flight recorder disabled too)
                clock.sub("dispatch", sync_bind_s)
                trace.step("Dispatched binds")
            if rejected:
                self._handle_device_rejects(rejected, snapshot, cluster, sub,
                                            assignment)
            if gang_requeue:
                if gang_info is not None:
                    gang_info["hopeless"] = sum(
                        1 for g in gang_requeue if g in hopeless)
                # gang preemption (ISSUE 14): solver-vetoed gangs get ONE
                # victim-cover attempt before requeueing; context built
                # lazily only when an eligible gang exists
                preempt_ctx = None
                if (self.gangpreempt is not None and gang_need is not None
                        and any(g in solver_vetoed and g not in hopeless
                                for g in gang_requeue)):
                    preempt_ctx = self.gangpreempt.build_ctx(
                        snapshot, cluster, sub, assignment, gang_need)
                self._requeue_gangs(gang_requeue, batch.gang_keys or [],
                                    hopeless, preempt_gids=solver_vetoed,
                                    preempt_ctx=preempt_ctx,
                                    gang_info=gang_info)
            if rejected or gang_requeue:
                clock.mark("reject")
                trace.step("Handled rejects", rejected=len(rejected))
            else:
                clock.skip()

        # Serial fallback, in original priority order among themselves.
        # Gang members never reach here: a gang touching a serial-fallback
        # class was vetoed above (all-or-nothing cannot be enforced on the
        # per-pod path).
        if len(fallback_idx):
            # (columnar cache rows are collapsed by schedule_pod itself
            # before it snapshots — the serial plugins walk pod lists)
            fb0 = self.scheduled_count
            for pi in fallback_idx:
                self._serial_one(qps[pi])
            out["serial_scheduled"] = self.scheduled_count - fb0
            clock.mark("fallback")
            trace.step("Serial fallback done", pods=len(fallback_idx))

    def _solve_device(self, solver, cluster, batch, sub, has_gang,
                      greedy_scan_solve, make_inputs) -> np.ndarray:
        """One device-batch solver dispatch, parameterized by the (possibly
        breaker-degraded) solver choice. 'fast' means fast-when-legal: the
        water-fill kernel has no topology-spread or inter-pod-affinity
        handling, so constrained batches always take the exact scan path
        regardless of mode. Any exception propagates to the failure-domain
        handler in _schedule_batch_inner (the batch requeues; it is never
        lost)."""
        from .breaker import REPRESENTATIVE

        # _solve_path tracks the path actually executing at every point so
        # both the success return and an exception anywhere in here
        # attribute to the right solver (the breaker must never credit a
        # scan outcome to the fast path, or vice versa). Routing is decided
        # BEFORE the injected fire so a chaos fault on a constrained
        # fast-mode batch attributes to the repair kernel it would have run
        # — tripping the breaker to the scan exactly like a waterfill fault.
        self._solve_path = REPRESENTATIVE.get(solver, solver)
        constraint_free = not batch.has_constraints
        use_fast = solver in ("fast", "auto") and constraint_free
        # constrained batches under the fast/auto modes ride the
        # propose-and-repair pipeline (models/repair.py, ISSUE 8); every
        # other mode's constrained batches stay on the scan oracle
        use_repair = solver in ("fast", "auto") and not constraint_free
        use_transport = (solver in ("auction", "sinkhorn")
                         and constraint_free and not has_gang)
        if use_repair:
            self._solve_path = "repair"
        elif not constraint_free:
            self._solve_path = "exact"  # the scan owns constrained batches
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.fire("solver.solve")
        assignment = None
        if solver == "native" and constraint_free and not has_gang:
            from ..native import native_available, native_greedy_solve

            if native_available():
                self._solve_path = "native"
                assignment, _ = native_greedy_solve(cluster, sub)
                if assignment is None:
                    self._solve_path = "exact"
        # device upload happens only for paths that consume it; cluster
        # tensors ride the persistent HBM mirrors (diff streaming)
        inputs = d_max = None
        if assignment is None:
            inputs, d_max = make_inputs(
                cluster, sub,
                device=self._tensor_cache.device_views(cluster))
        if use_transport:
            from ..models.transport import transport_solve
            from ..models.waterfill import make_groups

            self._solve_path = solver
            solved = transport_solve(
                inputs, make_groups(sub), method=solver,
                state=self.transport_state, node_names=cluster.node_names,
            )
            if solved is not None:
                assignment, self.transport_state = solved
            else:
                self._solve_path = "exact"  # declined: the scan takes it
        if use_fast:
            from ..models.waterfill import make_groups, waterfill_solve

            self._solve_path = "fast"
            assignment = waterfill_solve(inputs, make_groups(sub))
        if use_repair:
            from ..models.repair import repair_solve

            solved = repair_solve(
                inputs, sub, d_max,
                has_gang=bool(has_gang and sub.gang_bonus is not None))
            if solved is not None:
                assignment, rstats = solved
                self._note_repair(rstats)
            else:
                # problem shape exceeds the fast path's sort-key range:
                # decline to the oracle, exactly like waterfill_solve
                self._solve_path = "exact"
        if assignment is None:
            # static gates: constraint-free batches compile the scan
            # variant without IPA gathers / PTS segment sums
            self._solve_path = "exact"
            assignment, _, _ = greedy_scan_solve(
                inputs, d_max, has_ipa=bool(batch.ipa.has_any),
                has_ct=bool(batch.ct_class.size),
                has_st=bool(batch.st_class.size),
                has_gang=bool(has_gang and sub.gang_bonus is not None))
        return np.asarray(assignment)

    def _note_repair(self, rstats) -> None:
        """Fold one constrained batch's RepairStats into the metrics and the
        running totals (ONE call per batch, never per pod)."""
        from ..server import metrics as m

        self._last_repair = rstats
        t = self.repair_totals
        t["batches"] += 1
        t["rounds"] += rstats.rounds
        t["proposed"] += rstats.proposed
        t["repaired"] += rstats.repaired
        t["residual"] += rstats.residual
        t["full_scan"] += int(rstats.full_scan)
        m.constraint_repair_rounds.observe(rstats.rounds)
        for kind, v in rstats.violations.items():
            if v:
                t["violations"] += v
                m.constraint_violations_total.inc(v, kind=kind)

    def _handle_solver_error(self, e, qps, device_idx, solver, out, m) -> None:
        """Solver failure domain: requeue the device pods with backoff (the
        pods are fine — the INFRASTRUCTURE hiccuped, so no cluster event is
        needed before retrying), feed the circuit breaker, and narrate ONCE
        per batch (a 100k-pod batch must not write 100k events)."""
        qps_dev = [qps[pi] for pi in device_idx.tolist()]
        tripped = self.breaker.record_failure(self._solve_path, self.solver)
        m.solver_breaker_state.set(self.breaker.code)
        m.batch_retries_total.inc(len(qps_dev), stage="solve",
                                  reason=type(e).__name__)
        self.queue.add_backoff(qps_dev)
        sink = self._batch_reasons
        if sink is not None:
            sink["SolverError"] = sink.get("SolverError", 0) + len(qps_dev)
        out["batch_error"] = f"{type(e).__name__}: {e}"[:200]
        msg = (f"solver {solver} failed ({type(e).__name__}: {str(e)[:120]});"
               f" {len(qps_dev)} pod(s) requeued with backoff")
        if tripped:
            msg += (f"; circuit breaker OPEN — degrading to "
                    f"{self.breaker.effective_solver(self.solver)} for "
                    f"{self.breaker.cooldown_s:g}s")
        self.recorder.event(qps_dev[0].pod, "Warning", "SchedulerError", msg)

    def _rollback_undispatched(self, e, to_bind, bind_gang, dispatched,
                               use_columnar, accounted, batch_has_ports,
                               m, out) -> int:
        """Assume/dispatch failure domain: roll back every to_bind entry at
        index >= `dispatched` (its chunk never reached the bind path) and
        requeue it with backoff. Before _columnar_account ran, the rollback
        is the STRUCTURAL inverse (phase-2 resource totals were never added
        — forget_pod would drive them negative); after it, forget_pod is the
        exact inverse. A failure INSIDE _columnar_account leaves the few
        already-poked nodes conservatively over-counted (capacity looks
        smaller than it is — the safe direction) until the diff path
        requantizes or resync_from_store rebuilds."""
        stranded = to_bind[dispatched:]
        if not stranded:
            return 0
        released = [assumed for _qp, _node, assumed in stranded]
        if use_columnar and not accounted:
            self.cache.forget_pods_structural(released,
                                              check_ports=batch_has_ports)
        else:
            for assumed in released:
                self.cache.forget_pod(assumed)
        if self.gangs is not None and bind_gang:
            for i in range(dispatched, len(to_bind)):
                if bind_gang[i] >= 0:
                    self.gangs.note_forgotten(to_bind[i][2])
        self.queue.add_backoff([qp for qp, _node, _assumed in stranded])
        m.batch_retries_total.inc(len(stranded), stage="dispatch",
                                  reason=type(e).__name__)
        sink = self._batch_reasons
        if sink is not None:
            sink["DispatchError"] = (sink.get("DispatchError", 0)
                                     + len(stranded))
        out["batch_error"] = f"{type(e).__name__}: {e}"[:200]
        self.recorder.event(
            stranded[0][0].pod, "Warning", "SchedulerError",
            f"assume/dispatch failed ({type(e).__name__}: {str(e)[:120]}); "
            f"{len(stranded)} assumed pod(s) rolled back and requeued")
        return len(stranded)

    def _requeue_gangs(self, groups: Dict[int, List[QueuedPodInfo]],
                       keys: List[str],
                       hopeless: frozenset = frozenset(),
                       preempt_gids: frozenset = frozenset(),
                       preempt_ctx: Optional[Dict] = None,
                       gang_info: Optional[Dict] = None) -> None:
        """Gang-aware rejection handling: a vetoed (or assume-rolled-back)
        gang re-enters the queue AS A UNIT — one shared backoff expiry via
        SchedulingQueue.add_gang_backoff, so the members re-stage and
        re-admit together instead of dribbling through the unschedulable map
        one cluster event at a time. One FailedScheduling narration per gang
        (not per member: a 250-rank gang must not write 250 events per
        veto). `hopeless` gangs (min_member beyond what one solve can see)
        park unschedulable with a diagnostic instead — retrying on a timer
        would livelock.

        Gang preemption (ISSUE 14): a SOLVER-vetoed gang (in preempt_gids,
        with a built preempt_ctx) first tries a victim cover
        (scheduler/gangpreempt.py). A fired cover PARKS the gang — its
        members are neither failures nor requeued here, they wait in the
        parked tier for victim termination; a partial-room veto (or an
        inapplicable attempt) falls through to the normal unit requeue."""
        for gid, members in groups.items():
            key = keys[gid] if 0 <= gid < len(keys) else "<unknown>"
            if gid in hopeless:
                status = Status.unschedulable(
                    f"pod group {key} needs more members than the solver "
                    f"batch size ({self.batch_size}) can place together; "
                    "raise batch_size or lower minMember",
                    plugin="GangScheduling")
                for m in members:
                    self._handle_failure(m, status)
                continue
            if preempt_ctx is not None and gid in preempt_gids:
                got = self.gangpreempt.try_preempt(key, gid, members,
                                                   preempt_ctx)
                # trace timeline (ISSUE 18): one instant per preemption
                # ATTEMPT (per gang, never per member)
                if _tracebuf.ACTIVE is not None:
                    fired = got is not None and not got.get("vetoed")
                    _tracebuf.ACTIVE.instant(
                        self._thread_label("sched"),
                        "gang_preempt:%s" % ("fired" if fired else "vetoed"),
                        cat="gang",
                        args={"gang": key,
                              "victims": (got or {}).get("victims", 0)})
                if got is not None and not got.get("vetoed"):
                    # cover fired: the gang is PARKED awaiting victim
                    # termination — not a scheduling failure
                    if gang_info is not None:
                        gang_info["preempted"] = (
                            gang_info.get("preempted", 0) + 1)
                        gang_info["preempt_victims"] = (
                            gang_info.get("preempt_victims", 0)
                            + got["victims"])
                        gang_info["cover_cost"] = (
                            gang_info.get("cover_cost", 0) + got["cost"])
                    if self._batch_reasons is not None:
                        self._batch_reasons["GangPreemption"] = (
                            self._batch_reasons.get("GangPreemption", 0)
                            + len(members))
                    continue
                if got is not None and gang_info is not None:
                    gang_info["preempt_vetoed_partial"] = (
                        gang_info.get("preempt_vetoed_partial", 0) + 1)
            self.failed_count += len(members)
            if self._batch_reasons is not None:
                self._batch_reasons["GangScheduling"] = (
                    self._batch_reasons.get("GangScheduling", 0)
                    + len(members))
            for m in members:
                m.unschedulable_plugins = ("GangScheduling",)
            self.recorder.event(
                members[0].pod, "Warning", "FailedScheduling",
                f"pod group {key}: {len(members)} member(s) cannot be placed "
                "together (all-or-nothing); gang requeued")
            self.queue.add_gang_backoff(members)

    def _rank_align_assignment(self, cluster, sub, assignment,
                               gang_info: Optional[Dict]) -> np.ndarray:
        """Rank-aware placement pass (ISSUE 14): within each (gang, class,
        request) group — where members are interchangeable by construction —
        permute WHICH member gets WHICH node so rank order follows ICI ring
        position (models/gangcover.py rank_align; sorted-to-sorted matching
        minimizes consecutive-rank hop distance). The node SET is untouched:
        feasibility, capacity accounting, and the gang veto all see the same
        multiset. Publishes the before/after mean neighbor distance into the
        batch's gang flight-record dict."""
        from ..models.gangcover import (alignment_groups,
                                        mean_neighbor_distance, rank_align)
        from .gang import node_slice_positions

        slice_ids, pos = node_slice_positions(cluster)
        if slice_ids is None:
            return assignment  # no ICI topology: adjacency is moot
        a = np.asarray(assignment, dtype=np.int64)
        gop = np.asarray(sub.gang_of_pod)
        ranks = np.asarray(sub.gang_rank, dtype=np.int64)
        groups = alignment_groups(gop, np.asarray(sub.class_of_pod),
                                  np.asarray(sub.req),
                                  np.asarray(sub.req_nz))
        # rank-less members order AFTER ranked siblings, by row
        # (deterministic); keys stay far under the int32 sentinels
        eff_rank = np.where(ranks >= 0, ranks,
                            1_000_000 + np.arange(len(ranks)))
        # per-member position key: slice-major ring position of the assigned
        # node; unlabeled nodes sort after every labeled one, unplaced last
        stride = cluster.n + 1
        node_key = np.where(
            slice_ids >= 0, slice_ids * stride + np.maximum(pos, 0),
            2**28 + np.arange(cluster.n))
        placed = a >= 0
        pos_key = np.where(placed, node_key[np.maximum(a, 0)], 2**30)
        aligned = rank_align(a, groups, eff_rank, pos_key)
        # adjacency pre/post telemetry is observability, not placement —
        # pure-Python per-member passes, so it rides the flight recorder's
        # enable switch like every other non-essential measurement
        if gang_info is not None and self.flightrec.enabled:
            from .gang import ring_lengths

            ranked = ranks >= 0
            ring_len = ring_lengths(slice_ids, pos)

            def dist(assign):
                aa = np.asarray(assign)
                ok = ranked & (aa >= 0)
                sl = np.where(ok, slice_ids[np.maximum(aa, 0)], -1)
                pp = np.where(ok, pos[np.maximum(aa, 0)], -1)
                return mean_neighbor_distance(
                    np.where(ranked, gop, -1).tolist(), ranks.tolist(),
                    sl.tolist(), pp.tolist(), ring_len)

            pre, post = dist(a), dist(aligned)
            if pre is not None:
                gang_info["adjacency_pre"] = round(pre, 3)
            if post is not None:
                gang_info["adjacency_post"] = round(post, 3)
            gang_info["rank_aligned"] = int((aligned != a).sum())
        return aligned.astype(np.int32)

    def _columnar_account(self, batch, cluster, snapshot, bind_rows,
                          bind_nodes, has_ports: bool = True) -> None:
        """Phase 2 of the columnar assume: per-node requested-resource deltas
        for the whole solved batch as numpy scatter-adds keyed by the
        tensorizer's node index — one Resource poke per touched node in the
        cache, and (when nothing foreign intervened and no host ports are in
        play) a direct feed of TensorCache's generation diff so solve(N+1)
        skips the per-node requantize walk entirely."""
        rows = np.asarray(bind_rows, dtype=np.int64)
        nodes = np.asarray(bind_nodes, dtype=np.int64)
        n, r = cluster.n, len(cluster.resource_dims)
        from ..native import hostcommit, native_available, native_commit_deltas

        if native_available() and hostcommit.available():
            # ONE GIL-free C pass (ctypes CDLL releases the GIL for the
            # call) replacing two np.add.at dispatches + bincount + unique.
            # NO lock is held here — the CDLL kernels are blocking calls
            # under schedlint LK002 (store/store.py NATIVE LOCK RULE).
            # Gated on hostcommit.available() too so the documented kill
            # switch (HOSTSCHED_NATIVE_COMMIT=0) forces the pure-numpy
            # fallback on EVERY native-commit path, this one included.
            d_used, d_used_nz, d_count, touched = native_commit_deltas(
                rows, nodes, batch.raw_req, batch.raw_req_nz, n)
        else:
            d_used = np.zeros((n, r), dtype=np.int64)
            d_used_nz = np.zeros((n, r), dtype=np.int64)
            np.add.at(d_used, nodes, batch.raw_req[rows])
            np.add.at(d_used_nz, nodes, batch.raw_req_nz[rows])
            d_count = np.bincount(nodes, minlength=n)
            touched = np.unique(nodes)
        final_gen = self.cache.apply_node_resource_deltas(
            cluster.resource_dims,
            [(cluster.node_names[i], d_used[i], d_used_nz[i])
             for i in touched],
            expected_gen=snapshot.generation)
        if final_gen is not None and not has_ports:
            self._tensor_cache.apply_assume_deltas(
                touched, d_used[touched], d_used_nz[touched],
                d_count[touched], tensorized_gen=snapshot.generation,
                assume_gen=final_gen)

    def _handle_device_rejects(self, rejected, snapshot, cluster, sub,
                               assignment) -> None:
        """Failure handling for pods the device solver could not place.

        When the batch is constraint-free (no PTS DoNotSchedule rows, no
        inter-pod affinity), preemption candidates are computed as dense
        priority-tier tensors (_batch_preempt) — the vector analog of the
        reference's parallel DryRunPreemption (preemption.go:680) — and only
        the single chosen node per pod is verified with the real serial
        filters. Constrained batches keep the serial PostFilter path, because
        evicting victims can change PTS/IPA feasibility in ways the tier math
        does not model."""
        import itertools

        import numpy as np

        from .framework import CycleState

        if self.cache.columnar_rows():
            # Pre-batch placements held as columnar rows have no PodInfo, so
            # the victim walk below cannot see them. Collapse them and patch
            # the local (pre-batch) snapshot clones in place; rows assumed by
            # THIS batch stay out of the patch — the dry run already sees
            # those via placed_by_node, and the next update_snapshot re-clones
            # every touched node from the cache anyway.
            batch_keys = {p.key for p in sub.pods}
            mat: list = []
            self.cache.materialize_columnar_rows(mat)
            for node_name, pi in mat:
                if pi.pod.key in batch_keys:
                    continue
                ni = snapshot.node_info_map.get(node_name)
                if ni is not None:
                    # raw append: phase 2 already folded the resources into
                    # this clone; keep len(pods)+col_count exact
                    ni.pods.append(pi)
                    ni.col_count -= 1

        # post-batch capacity: fold every in-batch assignment into used state
        used = cluster.used.astype(np.int64).copy()
        pod_count = cluster.pod_count.astype(np.int64).copy()
        a = np.asarray(assignment)
        placed = a >= 0
        if placed.any():
            np.add.at(used, a[placed], sub.req[placed])
            np.add.at(pod_count, a[placed], 1)
        alloc = cluster.alloc.astype(np.int64)
        max_pods = cluster.max_pods.astype(np.int64)

        filter_ok = sub.tables.filter_ok
        node_names = cluster.node_names
        n = len(node_names)

        constraint_free = sub.ct_class.size == 0 and not sub.ipa.has_any
        if constraint_free:
            # in-batch placements per node: the verify step must see them
            placed_by_node = {}
            for jj in np.nonzero(placed)[0]:
                placed_by_node.setdefault(int(a[jj]), []).append(sub.pods[jj])
            remaining = self._batch_preempt(
                rejected, snapshot, cluster, sub, alloc, used, pod_count,
                max_pods, placed_by_node)
            # the tier math is strictly more permissive than the serial dry
            # run for constraint-free pods (it ignores port conflicts), so a
            # pod with no tier candidate has no serial candidate either —
            # fail it without a second sweep.
            for j, qp in remaining:
                # attributed to Fit so hint-gated requeue fires on node
                # capacity / assigned-pod-freed events
                self._handle_failure(qp, Status.unschedulable(
                    f"0/{n} nodes are available", plugin="NodeResourcesFit"))
            return

        # Constrained batch: synthesize the per-node failure map (vectorized;
        # shared Status instances per category) and run the serial PostFilter.
        unres = Status.unresolvable("node(s) didn't match the pod's static predicates")
        nofit = Status.unschedulable("Insufficient resources on the node")
        inbatch = Status.unschedulable("node rejected by in-batch constraints")
        names_arr = np.array(node_names)
        for j, qp in rejected:
            pod = qp.pod
            cls = int(sub.class_of_pod[j])
            req = sub.req[j].astype(np.int64)
            fits = np.all((req[None, :] == 0) | (req[None, :] <= alloc - used),
                          axis=1) & (pod_count + 1 <= max_pods)
            static_ok = filter_ok[cls]
            failed = {}
            failed.update(zip(names_arr[~static_ok].tolist(), itertools.repeat(unres)))
            failed.update(zip(names_arr[static_ok & ~fits].tolist(), itertools.repeat(nofit)))
            failed.update(zip(names_arr[static_ok & fits].tolist(), itertools.repeat(inbatch)))
            fw = self._fw(pod) or self.framework
            state = CycleState()
            fw.run_pre_filter(state, pod, snapshot)
            from .serial import ScheduleResult

            result = ScheduleResult(
                status=Status.unschedulable(f"0/{n} nodes are available"),
                failed_nodes=failed, state=state,
                evaluated_nodes=n)
            self._maybe_preempt(qp, result)
            self._handle_failure(qp, result.status, result.failed_nodes)

    def _preemption_plugin(self, fw):
        from .plugins.default_preemption import DefaultPreemption

        for p in fw.post_filter_plugins:
            if isinstance(p, DefaultPreemption):
                return p
        return None

    def _batch_preempt(self, rejected, snapshot, cluster, sub, alloc, used,
                       pod_count, max_pods, placed_by_node):
        """Tiered batch preemption (reference: preemption.go DryRunPreemption
        :680 + SelectCandidate :396, reframed as tensor math).

        For each rejected pod at priority p, candidate nodes are those where
        the pod fits after evicting every pod with priority < p — computed
        once per distinct tier as dense [N,R] freed-capacity tensors. Node
        selection follows pick_one_node_for_preemption's order (fewest PDB
        violations, lowest max victim priority, smallest priority sum, fewest
        victims, index). Only the chosen node runs the serial dry run
        (_dry_run_node), which produces the MINIMAL victim set via the
        reprieve pass and exact PDB accounting; its victims update the tier
        tensors so later pods in the batch see the new capacity.

        Returns the (j, qp) pairs that could not be preempted."""
        import numpy as np

        from .framework import CycleState, PodInfo
        from .gangpreempt import flatten_snapshot_victims

        n = cluster.n
        dims = cluster.resource_dims
        r = len(dims)

        # flatten bound pods into victim arrays (one snapshot pass) — the
        # helper shared with the gang victim cover (ISSUE 14 satellite)
        v_node, v_prio, v_req, v_pods, node_victims = \
            flatten_snapshot_victims(snapshot, dims)
        if not v_pods:
            return list(rejected)
        v_alive = np.ones(len(v_pods), dtype=bool)

        plugin_by_fw: dict = {}

        def plugin_for(pod):
            fw = self._fw(pod) or self.framework
            got = plugin_by_fw.get(id(fw))
            if got is None:
                got = (fw, self._preemption_plugin(fw))
                plugin_by_fw[id(fw)] = got
            return got

        # PDB exhaustion per victim (approximate violation count for node
        # selection; the serial dry run on the chosen node is exact). Listed
        # from the store directly — profiles without DefaultPreemption must
        # not blind the batch to budgets.
        try:
            pdbs, _ = self.store.list("poddisruptionbudgets")
        except Exception:
            pdbs = []
        v_pdb_blocked = np.zeros(len(v_pods), dtype=bool)
        if pdbs:
            for vi, p in enumerate(v_pods):
                v_pdb_blocked[vi] = any(
                    pd.metadata.namespace == p.metadata.namespace
                    and pd.selector is not None
                    and pd.selector.matches(p.metadata.labels)
                    and pd.disruptions_allowed <= 0
                    for pd in pdbs)

        tier_cache: dict = {}

        def tier(p):
            got = tier_cache.get(p)
            if got is None:
                mask = v_alive & (v_prio < p)
                freed = np.zeros((n, r), np.int64)
                np.add.at(freed, v_node[mask], v_req[mask])
                cnt = np.zeros(n, np.int64)
                np.add.at(cnt, v_node[mask], 1)
                psum = np.zeros(n, np.int64)
                np.add.at(psum, v_node[mask], v_prio[mask])
                viol = np.zeros(n, np.int64)
                if pdbs:
                    np.add.at(viol, v_node[mask & v_pdb_blocked], 1)
                pmax = np.full(n, -(2**31), np.int64)
                np.maximum.at(pmax, v_node[mask], v_prio[mask])
                got = [freed, cnt, psum, viol, pmax]
                tier_cache[p] = got
            return got

        filter_ok = sub.tables.filter_ok
        node_names = cluster.node_names
        remaining = []
        nominated_by_node: Dict[int, List] = {}
        for j, qp in rejected:
            pod = qp.pod
            fw, plugin = plugin_for(pod)
            if plugin is None or pod.spec.preemption_policy == "Never":
                remaining.append((j, qp))
                continue
            p = pod.spec.priority
            cls = int(sub.class_of_pod[j])
            req = sub.req[j].astype(np.int64)
            freed, cnt, psum, viol, pmax = tier(p)
            fits = np.all((req[None, :] == 0)
                          | (req[None, :] <= alloc - used + freed), axis=1)
            fits &= pod_count + 1 - cnt <= max_pods
            cand_mask = fits & filter_ok[cls] & (cnt > 0)
            if not cand_mask.any():
                remaining.append((j, qp))
                continue
            idxs = np.nonzero(cand_mask)[0]
            order = np.lexsort((idxs, cnt[idxs], psum[idxs], pmax[idxs], viol[idxs]))
            # candidate cap mirrors GetOffsetAndNumCandidates (preemption.go:595)
            num_candidates = max(plugin.MIN_CANDIDATE_NODES_ABSOLUTE,
                                 n * plugin.MIN_CANDIDATE_NODES_PERCENTAGE // 100)
            state = CycleState()
            _, st = fw.run_pre_filter(state, pod, snapshot)
            chosen = None
            if st.is_success():
                for oi in order[:num_candidates]:  # best-ranked first
                    nn = int(idxs[oi])
                    ni = snapshot.node_info_list[nn]
                    # the snapshot NodeInfo is pre-batch: drop victims an
                    # earlier pod in this batch already claimed (v_alive
                    # False) and add in-batch placements/nominations, or the
                    # dry run re-selects dead victims and frees nothing
                    dead = [v_pods[vi] for vi in node_victims[nn]
                            if not v_alive[vi]]
                    extra = list(placed_by_node.get(nn, ()))
                    extra += nominated_by_node.get(nn, [])
                    if dead or extra:
                        ni = ni.clone()
                        for dp_ in dead:
                            ni.remove_pod(dp_)
                        for xp in extra:
                            ni.add_pod(PodInfo(xp))
                    got = plugin._dry_run_node(state, pod, ni, pdbs)
                    if got is not None:
                        chosen = (nn, got)
                        break
            if chosen is None:
                remaining.append((j, qp))
                continue
            nn, cand = chosen
            victims = cand.victims
            self.preempt_victims_total += len(victims)
            vkeys = {v.key for v in victims}
            freed_now = np.zeros(r, np.int64)
            for vi in node_victims[nn]:
                if v_alive[vi] and v_pods[vi].key in vkeys:
                    v_alive[vi] = False
                    freed_now += v_req[vi]
                    for tp, (tfreed, tcnt, tpsum, tviol, _tp) in tier_cache.items():
                        if v_prio[vi] < tp:
                            tfreed[nn] -= v_req[vi]
                            tcnt[nn] -= 1
                            tpsum[nn] -= v_prio[vi]
                            if v_pdb_blocked[vi]:
                                tviol[nn] -= 1
            # max victim priority can only be recomputed, not decremented
            for tp, arrs in tier_cache.items():
                alive = [int(v_prio[vi]) for vi in node_victims[nn]
                         if v_alive[vi] and v_prio[vi] < tp]
                arrs[4][nn] = max(alive) if alive else -(2**31)
            used[nn] += req - freed_now
            pod_count[nn] += 1 - len(victims)
            nominated_by_node.setdefault(nn, []).append(pod)
            plugin._prepare_candidate(cand, pod)
            qp.pod.status.nominated_node_name = node_names[nn]
            self.preemption_count += 1
            self._handle_failure(qp, Status.unschedulable(
                f"preempted {len(victims)} pod(s) on {node_names[nn]}; "
                "waiting for victims to terminate", plugin="NodeResourcesFit"))
        return remaining

    def _handle_failure(self, qp: QueuedPodInfo, status: Status,
                        failed_nodes: Optional[Dict[str, Status]] = None) -> None:
        """Taps the failure's attribution (plugin, else the reason text) into
        the current batch's flight record before the shared requeue path.

        Partitioned re-route (ISSUE 12): an UNSCHEDULABLE verdict from a
        pipeline that only sees one node shard is not a cluster verdict —
        the reroute hook offers the pod to the next partition (or the global
        residual pass) instead of parking it, UNLESS preemption nominated a
        node here (victims are terminating on OUR shard; the pod must wait
        locally). A re-routed pod is not a failure: no event, no status
        patch, no failed_count — the terminal verdict belongs to whichever
        pipeline exhausts the routing."""
        hook = self.reroute_hook
        if hook is not None:
            from .framework import Code

            if (status.code == Code.UNSCHEDULABLE
                    and not qp.pod.status.nominated_node_name
                    and hook(qp, status)):
                self.partition_reroutes += 1
                return
        sink = self._batch_reasons
        if sink is not None:
            key = status.plugin or (status.reasons[0][:80] if status.reasons
                                    else status.code.name.lower())
            sink[key] = sink.get(key, 0) + 1
        super()._handle_failure(qp, status, failed_nodes)

    def _update_queue_telemetry(self, want_dict: bool = False) -> Optional[Dict]:
        """Refresh the scheduler_queue_depth{tier} gauges and the
        oldest-pending-age gauge (ISSUE 7 satellite). Called once per pump
        (schedule_batch's finally), throttled to 1/s because the underlying
        scan is O(queue) under the queue lock — gauges are a dashboard read,
        not a control input. The throttle holds for EVERY caller: a read
        surface (want_dict=True) inside the window gets the cached <=1s-old
        dict instead of forcing a rescan, so an aggressive external poller
        (`ktl sched stats -w --interval 0.1` against a 100k backlog) can't
        turn /debug/schedstats into a queue-lock DoS."""
        # claim the refresh slot under a private lock (check-then-act:
        # sched_stats runs on HTTP handler threads concurrently with the
        # pump) so N simultaneous pollers produce ONE scan, not N; the scan
        # itself runs outside the claim lock
        with self._q_telemetry_lock:
            now = self.clock.now()
            if now < self._q_telemetry_next and \
                    self._q_telemetry_last is not None:
                return self._q_telemetry_last if want_dict else None
            self._q_telemetry_next = now + 1.0
        t0 = time.perf_counter()
        tel = self.queue.telemetry()
        from ..server import metrics as m

        for tier in ("active", "backoff", "unschedulable", "gang_staged",
                     "gang_parked"):
            m.queue_depth.set(tel[tier], tier=tier)
        m.queue_oldest_age.set(tel["oldest_pending_age_s"])
        self.flightrec.note_self_time(time.perf_counter() - t0)
        self._q_telemetry_last = tel
        return tel

    def sched_stats(self) -> Dict:
        """The /debug/schedstats payload: live counters + the flight
        recorder's aggregate stage table (now with p50/p99 columns), the
        submit->bound latency distribution, tracer health, and the last-batch
        record (the machine-generated successor of ROADMAP's hand-maintained
        table)."""
        tel = self._update_queue_telemetry(want_dict=True)
        # read the windows FIRST: the read settles an expired open window,
        # and the meta counters below must describe the settled state
        windows = self.timeseries.windows(last=12)
        gang = None
        if self.gangs is not None and self.gangs.active:
            from ..server import metrics as m

            expired = self.gangs.quorum_expired_count(self.cache.contains)
            m.gang_quorum_expired_assumes.set(expired)
            gang = {"staged": self.queue.gang_staged_count(),
                    "parked": self.queue.gang_parked_count(),
                    "vetoes": self.gang_vetoes,
                    "quorum_expired_assumes": expired,
                    # victim-cover stats (ISSUE 14): attempts/preempted/
                    # victims/cover_cost/slices_ripped/vetoed_partial +
                    # release accounting, the `ktl sched stats` gang-
                    # preemption line's source
                    "preemption": (self.gangpreempt.stats()
                                   if self.gangpreempt is not None
                                   else None)}
        fr = self.flightrec
        return {
            "solver": self.solver,
            "batch_size": self.batch_size,
            "batches_solved": self.batches_solved,
            "scheduled": self.scheduled_count,
            "failed": self.failed_count,
            "preemptions": self.preemption_count,
            "preempt_victims": self.preempt_victims_total,
            "queue": {"active": tel["active"], "backoff": tel["backoff"],
                      "unschedulable": tel["unschedulable"],
                      "gang_staged": tel["gang_staged"],
                      "gang_parked": tel.get("gang_parked", 0),
                      "oldest_pending_age_s": round(
                          tel["oldest_pending_age_s"], 3)},
            "latency": self.podtrace.latency_stats(),
            "trace": {"enabled": self.podtrace.enabled,
                      "sample_k": self.podtrace.sample_k,
                      "completed": self.podtrace.completed_total,
                      "live_incomplete": self.podtrace.live_incomplete,
                      "windows_rotated": self.podtrace.windows_rotated},
            "watch": self._watch_summary(),
            "gang": gang,
            "repair": (dict(self.repair_totals,
                            last=self._last_repair.as_dict())
                       if self._last_repair is not None
                       else dict(self.repair_totals)
                       if self.repair_totals["batches"] else None),
            "breaker": self.breaker.describe(),
            # partitioned mode (ISSUE 12): this pipeline's shard identity +
            # the absorbed cross-partition races; None standalone
            "partition": ({
                "index": self.partition_index,
                "nodes": self.cache.node_count(),
                "conflicts": self.partition_conflicts,
                "reroutes": self.partition_reroutes,
            } if self.partition_index is not None else None),
            # background rebalancer (ISSUE 17): fragmentation score +
            # migration/wave/abort totals; None until enable_rebalancer()
            "rebalance": (self.rebalancer.stats()
                          if self.rebalancer is not None else None),
            "bind_worker": {
                "restarts": self.bind_worker_restarts,
                "failures_logged": len(self.bind_failures),
                "failures_dropped": self.bind_failures_dropped,
            },
            # columnar pod-row store (ISSUE 15): rows/diverged/lazy-
            # materialization telemetry from the store this pipeline binds
            # into (None on the dict path) — the observable proof that the
            # steady state stays lazy (diverged grows with binds, while
            # materialized_total only moves when something actually reads
            # the rows)
            "store_columnar": (self.store.columnar_stats()
                               if hasattr(self.store, "columnar_stats")
                               else None),
            # cache rows (ISSUE 16): the scheduler-side half of the columnar
            # pipeline — rows live per steady-state placement, and
            # materialized_total only moves when a constrained batch / serial
            # fallback / conservation check forces object rows
            "cache_columnar": self.cache.columnar_stats(),
            "recorder": {"enabled": fr.enabled, "capacity": fr.capacity,
                         "records": len(fr),
                         "self_seconds": round(fr.self_seconds, 6)},
            # trace timeline (ISSUE 18): arm/drop counters so a full ring
            # is observable from /debug/schedstats and `ktl sched stats`
            "tracebuf": _tracebuf.status(),
            "stages": fr.stage_table(),
            # steady-state telemetry (ISSUE 13): the recent closed windows
            # (the live feed of `ktl sched top` and the windowed SLO keys)
            # plus the resource sampler's summary when one is attached
            "timeseries": {
                "enabled": self.timeseries.enabled,
                "window_s": self.timeseries.window_s,
                "capacity": self.timeseries.capacity,
                "windows_closed": self.timeseries.windows_closed,
                "self_seconds": round(self.timeseries.self_seconds, 6),
            },
            "windows": windows,
            "resource": (self.resource_sampler.summary()
                         if self.resource_sampler is not None else None),
            "last_batch": fr.last(),
        }

    def _register_window_probes(self) -> None:
        """Window-close probes (obs/timeseries.py): each runs ONCE per
        closed window — queue depth (O(tiers), no age scan), breaker state,
        watch-bus lag (pure read, no settlement), the partition's
        conflict/reroute counters, and the resource sampler's latest
        columns. Everything here is lazy: attributes constructed later in
        __init__ (breaker) or installed later (partition_index, sampler)
        resolve at fire time."""
        ts = self.timeseries
        ts.add_probe("queue", lambda: self.queue.depths())
        ts.add_probe("breaker", lambda: {"state": self.breaker.state})
        ts.add_probe("watch", lambda: self.store.watch_lag())
        ts.add_probe("partition", self._partition_window_probe)
        ts.add_probe("resource", self._resource_window_probe)
        # live zero-alloc gauge (ISSUE 16): per-window pod-object
        # materializations across the columnar pipeline (store rows + cache
        # rows). Steady state reads 0 — the end-to-end zero-object claim as
        # a live gauge, not only a bench assertion. One tap per window close
        # (HP001).
        self._alloc_probe_total: Optional[int] = None
        ts.add_probe("alloc", self._alloc_window_probe)

    def _alloc_window_probe(self) -> Optional[Dict]:
        total = 0
        seen = False
        getstats = getattr(self.store, "columnar_stats", None)
        if getstats is not None:
            st = getstats()
            if st is not None:
                total += int(st.get("materialized_total", 0))
                seen = True
        cm = getattr(self.cache, "columnar_materialized", None)
        if cm is not None:
            total += int(cm())
            seen = True
        if not seen:
            return None  # object-path pipeline: the gauge has no meaning
        prev = self._alloc_probe_total
        self._alloc_probe_total = total
        return {"pod_obj_allocs": total - prev if prev is not None else total,
                "materialized_total": total}

    def _partition_window_probe(self) -> Optional[Dict]:
        if self.partition_index is None:
            return None
        return {"index": self.partition_index,
                "conflicts": self.partition_conflicts,
                "reroutes": self.partition_reroutes}

    def _resource_window_probe(self) -> Optional[Dict]:
        s = self.resource_sampler
        if s is None:
            return None
        last = s.latest()
        if last is None:
            return None
        return {"rss_mb": last["rss_mb"],
                "alloc_blocks": last["alloc_blocks"],
                "gc_collections": last["gc"]["collections"],
                "gc_pause_s": last["gc"]["pause_s"],
                # cumulative sampler self-time at window close (difference
                # consecutive windows for the per-window overhead)
                "sampler_self_s": round(s.self_seconds, 6),
                "threads": {k: v["cpu_s"]
                            for k, v in last["threads"].items()}}

    def _thread_label(self, role: str) -> str:
        return (f"p{self.partition_index}-{role}"
                if self.partition_index is not None else role)

    def attach_resource_sampler(self, sampler) -> None:
        """Wire an obs/resource.py ResourceSampler: the sampler's latest
        columns join every closed window (the rss/alloc slope gates' feed),
        and this scheduler's threads register for per-thread CPU
        attribution — the loop thread on start(), the bind worker as it
        spawns, both immediately when already running."""
        self.resource_sampler = sampler
        if sampler is not None:
            if self._thread is not None:
                sampler.register_thread(self._thread_label("sched"),
                                        self._thread)
            if self._bind_worker is not None:
                sampler.register_thread(self._thread_label("bind"),
                                        self._bind_worker)

    def _watch_summary(self) -> Dict:
        """The store watch bus seen from this scheduler (ISSUE 9): settled
        commit->dequeue propagation plus subscriber counts and the worst
        delivered-RV lag — the "watch" section of sched_stats that `ktl
        sched stats` renders and watch_propagation_p99_s gates. One
        watch_telemetry() call (settles pending taps; O(subscribers))."""
        try:
            tel = self.store.watch_telemetry()
        except Exception as e:  # a wedged store must not 500 the endpoint
            return {"error": str(e)}
        subs = tel.get("subscribers") or []
        return {
            "subscribers": len(subs),
            "max_rv_lag": max((s.get("rv_lag", 0) for s in subs), default=0),
            "dropped": tel.get("dropped") or {},
            "propagation": tel.get("propagation") or {},
        }

    def _hard_pod_affinity_weight(self) -> int:
        for fw in self.profiles.values():
            for p in fw.plugins:
                if p.name == "InterPodAffinity":
                    return getattr(p, "hard_pod_affinity_weight", 1)
        return 1

    def _bind_one(self, qp: QueuedPodInfo, node_name: str, assumed,
                  async_mode: bool) -> None:
        try:
            self.store.bind(qp.pod.metadata.namespace, qp.pod.metadata.name, node_name)
            self.cache.finish_binding(assumed)
            if async_mode:
                with self._bind_err_lock:
                    self._bind_successes += 1
            else:
                self.scheduled_count += 1
        except Exception as e:
            self.cache.forget_pod(assumed)
            if self.gangs is not None:
                self.gangs.note_forgotten(assumed)
            if async_mode:
                # surfaced on the scheduling thread at the next drain; handling
                # failures re-enters the queue, which isn't bind-thread-safe
                with self._bind_err_lock:
                    self._bind_errors.append((qp, Status.error(str(e))))
            else:
                self._handle_failure(qp, Status.error(str(e)))

    def _ensure_bind_worker(self) -> None:
        if self._bind_worker is not None and not self._bind_worker.is_alive():
            # a hard-dead worker's in-flight chunks and task_done debt MUST
            # be recovered before a replacement starts: the new worker's
            # first cycle overwrites the shared _bind_inflight record,
            # destroying the evidence — the debt then leaks and flush_binds
            # wedges forever (found by the full-size ChaosChurn_20k rung:
            # the enqueue path won the race against the liveness drain)
            self._recover_dead_worker()
        if self._bind_worker is None:
            # the queue is BOUND at thread start: a crash resync swaps
            # self._bind_q for a fresh queue, and the old worker must keep
            # draining (and exiting on) the queue it was born with
            self._bind_worker = threading.Thread(
                target=self._bind_loop, args=(self._bind_q,), daemon=True)
            self._bind_worker.start()
            if self.resource_sampler is not None:
                # re-registering the label points the CPU column at the
                # replacement worker (a restart keeps one column)
                self.resource_sampler.register_thread(
                    self._thread_label("bind"), self._bind_worker)

    def _bind_loop(self, q: _queue.Queue) -> None:
        """SUPERVISED bind worker (ISSUE 6): _bind_cycle drains one pipelined
        sub-batch; an exception that escapes it (past _bind_batch's own
        error handling) no longer kills the worker silently — the supervisor
        counts the escape and continues, after _bind_cycle re-queued the
        in-flight chunk for ONE retry (a second escape fails its pods). An
        injected FaultKill is the deliberate exception: it is a hard thread
        death, recovered by the liveness check in _drain_bind_results."""
        while True:
            try:
                if self._bind_cycle(q):
                    return
            except FaultKill:
                # hard death by design: exit WITHOUT the cycle bookkeeping
                # (the in-flight chunk stays recorded, its task_done debt
                # unsettled) — exactly what a real thread-killing failure
                # leaves behind; the liveness check recovers both
                return
            except Exception:
                with self._bind_err_lock:
                    self.bind_worker_restarts += 1

    def _bind_cycle(self, q: _queue.Queue) -> bool:
        """One drain cycle: items queued at wake-up are merged only up to
        bind_chunk pods per store.bind_many + confirm cycle, so commit(N)
        runs while the scheduling thread works on solve(N+1) — chunk-granular
        overlap instead of one monolithic commit (the bind_wait stall the
        PR 3 stage table surfaced). Returns True on the shutdown sentinel.

        Bookkeeping contract: the merged batches are recorded in
        _bind_inflight BEFORE commit and cleared — with their task_done debt
        settled — on every handled path. Only a hard kill leaves them
        recorded, which is exactly what the dead-worker liveness check needs
        to re-queue them and unwedge flush_binds."""
        item = q.get()
        if item is None:
            q.task_done()
            return True
        batches = [item]  # each queue item is a LIST of bind triples
        merged = len(item)
        while merged < self.bind_chunk:
            try:
                nxt = q.get_nowait()
            except _queue.Empty:
                break
            if nxt is None:
                # shutdown requested mid-merge: put the sentinel back for
                # the NEXT cycle (settling our get) so this cycle's chunk
                # commits under the normal bookkeeping
                q.put(None)
                q.task_done()
                break
            batches.append(nxt)
            merged += len(nxt)
        with self._bind_err_lock:
            self._bind_inflight = batches
        handled = False
        try:
            if _chaos.ACTIVE is not None:
                _chaos.ACTIVE.fire("bind.worker")
            self._bind_batch([t for b in batches for t in b])
            handled = True
        except Exception:
            self._requeue_inflight(batches, q)
            handled = True
            raise  # the supervisor counts the escape
        finally:
            if handled:
                with self._bind_err_lock:
                    self._bind_inflight = []
                for _ in batches:
                    q.task_done()
            # BaseException (FaultKill): leave _bind_inflight recorded with
            # its task_done debt — _drain_bind_results settles both
        return False

    def _requeue_inflight(self, batches, q: _queue.Queue) -> None:
        """Give each escaped in-flight chunk ONE more trip through the bind
        queue; a chunk that already retried fails its pods through the
        normal bind-error path instead (requeue via _drain_bind_results) —
        a deterministic escape must not livelock the worker."""
        for b in batches:
            if isinstance(b, _RequeuedChunk):
                with self._bind_err_lock:
                    for qp, _node, assumed in b:
                        self.cache.forget_pod(assumed)
                        if self.gangs is not None:
                            self.gangs.note_forgotten(assumed)
                        self._bind_errors.append((qp, Status.error(
                            "bind worker failed twice on this chunk")))
            else:
                q.put(_RequeuedChunk(b))
        from ..server import metrics as m

        # pods, not chunks — the metric's unit across every requeue stage
        m.batch_retries_total.inc(sum(len(b) for b in batches),
                                  stage="worker", reason="escaped")

    def _check_bind_worker_alive(self) -> None:
        """Dead-worker liveness check (ISSUE 6 satellite), run every drain:
        a worker that died hard (FaultKill, MemoryError) with an empty bind
        queue used to stay dead — and its in-flight chunk's unmatched
        task_done debt hung flush_binds forever. Here: recover the stranded
        chunks + debt, and restart the worker if work remains."""
        w = self._bind_worker
        if w is None or w.is_alive():
            return
        self._recover_dead_worker()
        if self._bind_q.unfinished_tasks:
            self._ensure_bind_worker()

    def _recover_dead_worker(self) -> None:
        """Settle a hard-dead worker's estate — shared by the liveness drain
        and the enqueue path (whichever observes the death first): re-queue
        its in-flight chunks for the supervised retry, settle their
        unmatched task_done debt, count the restart, and clear the worker
        ref so _ensure_bind_worker starts a replacement. Runs only on the
        scheduling thread (both callers), so the estate is handed off
        exactly once."""
        with self._bind_err_lock:
            inflight, self._bind_inflight = self._bind_inflight, []
            self.bind_worker_restarts += 1
        self._bind_worker = None
        if inflight:
            self._requeue_inflight(inflight, self._bind_q)
            for _ in inflight:
                self._bind_q.task_done()  # the dead worker's unmatched gets

    def _bind_batch(self, items) -> None:
        t0 = time.perf_counter()
        try:
            self._bind_batch_inner(items)
        finally:
            t1 = time.perf_counter()
            self.flightrec.add_outside("bind", t1 - t0)
            from ..server import metrics as m

            m.batch_stage_duration.observe(t1 - t0, "bind")
            # trace timeline (ISSUE 18): one slice per bind sub-batch on
            # the bind worker's track — overlap with the next solve is
            # visible as concurrent slices on p<i>-sched vs p<i>-bind
            if _tracebuf.ACTIVE is not None:
                _tracebuf.ACTIVE.note_span(
                    self._thread_label("bind"), "bind_chunk", t0, t1,
                    cat="bind", args={"pods": len(items)})
            self.flightrec.note_self_time(time.perf_counter() - t1)

    def _bind_batch_inner(self, items) -> None:
        triples = [(qp.pod.metadata.namespace, qp.pod.metadata.name, node)
                   for qp, node, _assumed in items]
        # chunked: each bind_many holds the store locks once; a single
        # 100k-bind hold would starve every other store consumer. A chunk
        # whose retries are exhausted fails ONLY its own pods — earlier
        # chunks already committed and must not be forgotten/requeued.
        errors = []
        for lo in range(0, len(triples), self.bind_chunk):
            chunk = triples[lo:lo + self.bind_chunk]
            exc = self._bind_chunk_with_retry(chunk, errors)
            if exc is not None:
                errors.extend((f"{ns}/{name}", str(exc))
                              for ns, name, _node in chunk)
        # pod tracer (scheduler/podtrace.py): ONE commit stamp for the whole
        # chunk (batch-boundary timestamps, no per-pod clocks); the confirm
        # stamp is read after the assume-confirm settles below
        pt = self.podtrace
        t_commit = self.clock.now() if pt is not None and pt.enabled else 0.0
        if not errors:
            # common case: whole sub-batch committed. On the coalesced
            # pipeline the assume-CONFIRM piggybacks right here (one cache
            # lock) instead of a later event re-ingest — the scheduler skips
            # its own origin-tagged MODIFIED batches entirely, removing the
            # old finish_binding ttl window AND the confirm stage from the
            # scheduling thread. Leftovers (assume expired, foreign rebind)
            # re-ingest on the scheduling thread at the next drain. The
            # per-pod pipeline (watch_coalesce=False, the parity oracle)
            # keeps the finish_binding + event-confirm flow byte-for-byte.
            if self.watch_coalesce:
                pairs = [(qp.pod.key, node) for qp, node, _a in items]
                leftover = self.cache.confirm_assumed_bulk(pairs)
                with self._bind_err_lock:
                    self._bind_successes += len(items)
                    if leftover:
                        self._bind_confirm_leftovers.extend(
                            items[i][2] for i in leftover)
            else:
                self.cache.finish_binding_bulk([a for _qp, _node, a in items])
                with self._bind_err_lock:
                    self._bind_successes += len(items)
            if pt is not None and pt.enabled:
                pt.chunk_bound(items, t_commit, self.clock.now())
            return
        errmap = dict(errors)
        confirm = []
        with self._bind_err_lock:
            for qp, node, assumed in items:
                msg = errmap.get(qp.pod.key)
                if msg is None:
                    if self.watch_coalesce:
                        confirm.append((qp.pod.key, node, assumed))
                    else:
                        self.cache.finish_binding(assumed)
                    self._bind_successes += 1
                else:
                    self.cache.forget_pod(assumed)
                    if self.gangs is not None:
                        self.gangs.note_forgotten(assumed)
                    self._bind_errors.append((qp, Status.error(msg)))
            if confirm:
                leftover = self.cache.confirm_assumed_bulk(
                    [(k, n) for k, n, _a in confirm])
                self._bind_confirm_leftovers.extend(
                    confirm[i][2] for i in leftover)
        if pt is not None and pt.enabled:
            # partial-failure chunk: failed pods are excluded from both the
            # latency distribution and the sampled stamps (they re-enter the
            # queue and bind later — the tracer sees that attempt instead)
            pt.chunk_bound(items, t_commit, self.clock.now(),
                           errkeys=frozenset(errmap))

    def _bind_chunk_with_retry(self, chunk, errors) -> Optional[Exception]:
        """One chunk's bind_many with transient-failure retry (ISSUE 6):
        an EXCEPTION from bind_many is infrastructure (the per-pod conflict
        errors come back in the error list and are never retried — a
        conflict is a fact, not a fault), so the chunk retries up to
        bind_retries times under exponential backoff with jitter before its
        pods are declared failed. Returns the final exception, or None on
        success. Runs on the bind worker with NO lock held — the sleeps
        stall only the overlapped commit, never the scheduling thread."""
        last: Optional[Exception] = None
        for attempt in range(self.bind_retries + 1):
            if attempt:
                from ..server import metrics as m

                m.batch_retries_total.inc(stage="bind", reason="transient")
                delay = (self.bind_retry_base_s * (2 ** (attempt - 1))
                         * (1.0 + _random.random()))
                time.sleep(delay)
            try:
                _bound, errs = self.store.bind_many(
                    chunk, origin=self._bind_origin)
                errors.extend(errs)
                return None
            except Exception as e:
                last = e
        return last

    def _drain_bind_results(self) -> None:
        """Fold completed async binds into counters and re-handle failures on
        the scheduling thread (handleBindingCycleError -> requeue). Does NOT
        wait for in-flight binds — callable every cycle under sustained load.
        Failures are requeued AND recorded in bind_failures so callers of
        schedule_batch can observe them (take_bind_failures). Also runs the
        dead-worker liveness check: called every schedule_batch cycle, so a
        hard-killed worker is detected within one cycle even when the bind
        queue is empty (ISSUE 6 satellite)."""
        if self.pipeline_binds:
            self._check_bind_worker_alive()
        with self._bind_err_lock:
            done, self._bind_successes = self._bind_successes, 0
            errs, self._bind_errors = self._bind_errors, []
            leftovers, self._bind_confirm_leftovers = (
                self._bind_confirm_leftovers, [])
        self.scheduled_count += done
        for pod in leftovers:
            # worker-side confirm missed (assume expired / foreign write got
            # in first): re-read the COMMITTED object — the assume-time clone
            # is stale (pre-bind rv, possibly older labels), and the pod may
            # have been deleted since (re-ingesting the clone would resurrect
            # it in the cache; the event-stream confirm of old couldn't,
            # because it ran in rv order) — then take the full ingest path,
            # exactly like a foreign MODIFIED, correcting the cache
            try:
                cur = self.store.get("pods", pod.key)
            except NotFoundError:
                continue  # deleted since the bind: nothing left to account
            self._handle_pod(MODIFIED, cur)
        if errs:
            self.flightrec.note_bind_failures(
                [(qp.pod.key, status.message()) for qp, status in errs])
        log = self.bind_failures
        csink = self.conflict_sink
        for qp, status in errs:
            msg = status.message()
            if csink is not None and is_bind_conflict(msg):
                # lost cross-partition bind race (ISSUE 12): the conflict is
                # a FACT — the pod is bound, the store decided the winner —
                # so this pipeline drops it (the assume was already
                # forgotten on the error path) and the coordinator counts
                # the absorbed race. Requeueing would schedule a bound pod.
                self.partition_conflicts += 1
                csink(qp, msg)
                continue
            if len(log) == log.maxlen:
                # bounded (ISSUE 6 satellite): a caller that never drains
                # must not leak under sustained bind faults — evict oldest,
                # count the drop so the loss is observable
                self.bind_failures_dropped += 1
            log.append((qp.pod.key, msg))
            self._handle_failure(qp, status)

    def take_bind_failures(self) -> List:
        """Drain the (pod key, error message) log of asynchronous bind
        failures observed since the last call. The pods themselves were
        already requeued via the normal failure path; this surfaces WHAT
        failed to callers of schedule_batch/flush_binds, which otherwise
        only ever see success counts. Bounded: under sustained faults with
        no drainer the log holds the most recent BIND_FAILURE_LOG_CAP
        entries (bind_failures_dropped counts the evictions)."""
        out = list(self.bind_failures)
        self.bind_failures.clear()
        return out

    def flush_binds(self) -> None:
        """Wait for queued store.bind writes, then drain results. The wait is
        recorded as the "bind_wait" stage — the scheduling thread's stall on
        in-flight binds, the residual the stage table needs to explain wall
        time when binds don't fully overlap the next solve.

        The wait is LIVENESS-AWARE (ISSUE 6): a plain Queue.join() hung
        forever when the worker died hard mid-chunk (the chunk's task_done
        debt was never settled). Here the wait wakes on task_done as before
        but re-checks the worker between naps, so a dead worker is replaced
        and its stranded chunk re-queued instead of wedging the flush."""
        t0 = time.perf_counter()
        if self._bind_worker is not None:
            q = self._bind_q
            while True:
                with q.all_tasks_done:
                    if not q.unfinished_tasks:
                        break
                    q.all_tasks_done.wait(timeout=0.05)
                self._check_bind_worker_alive()
        self.flightrec.add_outside("bind_wait", time.perf_counter() - t0)
        self._drain_bind_results()

    def sweep_expired_assumes(self) -> List[str]:
        """Base sweep plus the gang preemptor's parked-gang deadline: a
        cover whose victim deletions stalled releases its gang back to the
        normal retry ladder (scheduler/gangpreempt.py) — both run from the
        same idle loops."""
        expired = super().sweep_expired_assumes()
        if self.gangpreempt is not None:
            self.gangpreempt.sweep(self.clock.now())
        return expired

    def resync_from_store(self) -> Dict[str, int]:
        """Crash resync (ISSUE 6): rebuild ALL scheduler state from the
        store, as a restarted scheduler process would — proving the store is
        the single source of truth. Bound pods re-enter the cache from the
        LIST, pending pods re-enter the queue fresh (no attempt/backoff
        memory), stale assumes are simply gone (the fresh cache never knew
        them), and the bind pipeline restarts empty.

        In-flight binds are flushed first: a real crash would lose them
        in-process, but their pods are either committed (the LIST sees them
        bound) or still pending (the LIST re-queues them) — the store
        decides, which is the whole point. Flushing just makes the
        simulation deterministic. Returns {nodes, bound, pending,
        dropped_assumes}."""
        self.flush_binds()
        dropped = self.cache.assumed_count()
        # abandon the bind pipeline: sentinel the old worker to death on the
        # queue it was born with (it drains nothing — flush emptied it) and
        # start over with a fresh queue
        if self._bind_worker is not None:
            self._bind_q.put(None)
        self._bind_q = _queue.Queue()
        self._bind_worker = None
        with self._bind_err_lock:
            self._bind_inflight = []
            self._bind_errors = []
            self._bind_successes = 0
            self._bind_confirm_leftovers = []
        self._tensor_cache = TensorCache()
        if self.gangpreempt is not None:
            # parked-gang state is queue state; the fresh LIST re-admits
            # every pending pod, so in-flight cover tracking is stale
            self.gangpreempt.reset()
        counts = self._rebuild_from_store(preserve_queue=False)
        counts["dropped_assumes"] = dropped
        return counts

    def stop(self) -> None:
        """Stop the loop/watch like the base class, AND release the bind
        worker: parked in `q.get()` it would otherwise pin this scheduler's
        entire object graph (cache, store refs, 100k-pod heaps) for the
        process lifetime — the leak the partitioned A/B bench and
        `_absorb_dead`'s corpse.stop() both hit. Items queued before the
        sentinel still commit (FIFO); a later start() gets a fresh queue."""
        super().stop()
        if self._bind_worker is not None:
            self._bind_q.put(None)
            self._bind_q = _queue.Queue()
            self._bind_worker = None

    def _serial_one(self, qp: QueuedPodInfo) -> None:
        result = self.schedule_pod(qp.pod)
        if not result.suggested_host:
            self._maybe_preempt(qp, result)
            self._handle_failure(qp, result.status, result.failed_nodes)
            return
        # Full commit chain (Reserve/Permit/PreBind/PostBind) — fallback pods
        # (volumes, inter-pod affinity) depend on these extension points.
        self._commit_cycle(qp, result)

    def start(self) -> None:
        """Background loop: batch solve instead of one-pod cycles."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                handled = self.schedule_batch(timeout=0.0)
                # drain async-bind outcomes every cycle (bind failures must
                # requeue even under sustained load), full flush only on idle
                self._drain_bind_results()
                if handled == 0:
                    self.flush_binds()
                    self.pump_events()
                    self.queue.flush_backoff_completed()
                    self.queue.flush_unschedulable_left_over()
                    self.sweep_expired_assumes()
                    self._stop.wait(0.05)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        if self.resource_sampler is not None:
            self.resource_sampler.register_thread(
                self._thread_label("sched"), self._thread)

    def enable_rebalancer(self, **kwargs):
        """Attach a background Rebalancer (scheduler/rebalance.py, ISSUE 17)
        to this pipeline; kwargs pass through to its constructor. The
        run_until_idle quiesce path paces it via maybe_cycle(), and
        sched_stats()["rebalance"] publishes its totals. Returns it."""
        from .rebalance import Rebalancer

        self.rebalancer = Rebalancer(self, **kwargs)
        return self.rebalancer

    def run_until_idle(self, max_cycles: int = 10_000) -> int:
        n = 0
        while n < max_cycles:
            if self.schedule_batch(timeout=0.0) == 0:
                # quiesce: flush in-flight binds (may requeue failures), then
                # drain events + expired assumes before declaring idle
                self.flush_binds()
                self.pump_events()
                self.sweep_expired_assumes()
                if self.schedule_batch(timeout=0.0) == 0:
                    # idle: let the rebalancer take a paced defrag cycle —
                    # migrations emit create/delete events, so loop once
                    # more to ingest them before declaring idle for real
                    if self.rebalancer is not None:
                        r = self.rebalancer.maybe_cycle()
                        if r is not None and r.get("migrations"):
                            n += 1
                            continue
                    break
            n += 1
        self.flush_binds()
        return n


def _subset_batch(batch, idx):
    """View of a PodBatchTensors restricted to pod rows idx (class tables shared)."""
    import dataclasses

    return dataclasses.replace(
        batch,
        pods=[batch.pods[i] for i in idx],
        class_of_pod=batch.class_of_pod[idx],
        req=batch.req[idx],
        req_nz=batch.req_nz[idx],
        balanced_active=batch.balanced_active[idx],
        raw_req=None if batch.raw_req is None else batch.raw_req[idx],
        raw_req_nz=None if batch.raw_req_nz is None else batch.raw_req_nz[idx],
        gang_of_pod=(None if batch.gang_of_pod is None
                     else batch.gang_of_pod[idx]),
        gang_rank=(None if batch.gang_rank is None
                   else batch.gang_rank[idx]),
    )
