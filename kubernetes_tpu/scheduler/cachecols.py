"""Columnar cache rows (ISSUE 16): struct-of-arrays for steady-state
assumed/bound pods in the scheduler cache.

The columnar store (ISSUE 15) removed the per-pod object work from the
STORE half of the bind pipeline; the scheduler cache still built a PodInfo
(plus a bind clone at dispatch) for every placement. This module is the
cache half of the same idiom: a solved, constraint-free batch's placements
land as ROWS — the original Pod ref, the row key, and an interned node-name
id in an int32 column — with zero per-pod object allocation. Per-node
resource totals ride the existing phase-2 scatter-add
(Cache.apply_node_resource_deltas), and the per-node row population is one
int on NodeInfo (`col_count`), so the tensorizer's pod_count stays exact
without materializing anything.

Columns per row:

  keys[]     "namespace/name" (object list; the row identity)
  pod[]      the ORIGINAL store/queue Pod object (object list) — never
             cloned, never mutated; held for removal accounting (its
             `_req_cache` memo pair is the exact inverse of the phase-2
             scatter) and for lazy materialization
  node_id[]  interned node-name id (int32)

Rows are created only by `Cache.assume_pods_columnar` under the dispatch
gate (no gangs, no topology-spread/inter-pod-affinity terms, no host
ports), so a row never owes affinity sublists or port claims. A row
MATERIALIZES into a real PodInfo at most once — when a consumer genuinely
needs object rows (a constrained batch's selector counts, the serial
fallback's plugin walks, the conservation checker) — and the lifetime
`materialized_total` counter is the live zero-alloc gauge's feed
(`pod_obj_allocs` window column).

Locking: every mutation happens under the owning Cache's `_lock`;
CacheColumns itself is lock-free and trusts its caller, like the store's
PodColumns. The node-name intern table is append-only (lock-free reads).

Fallback: no numpy or `STORE_COLUMNAR=0` disables the rows — the object
path (PodInfo appends via assume_pods_structural) is the oracle and stays
bit-for-bit.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterator, List, Optional, Tuple

try:  # numpy backs the node_id column; without it, the object path runs
    import numpy as np
except Exception:  # pragma: no cover - exercised via monkeypatch in tests
    np = None  # type: ignore


def numpy_available() -> bool:
    return np is not None


def env_enabled() -> bool:
    """Shares the store's STORE_COLUMNAR gate (default on): one switch
    sweeps the whole columnar pipeline to its object-path oracle."""
    return os.environ.get("STORE_COLUMNAR", "").lower() not in ("0", "false")


def available() -> bool:
    return np is not None and env_enabled()


class CacheColumnsView:
    """Read-only view over the live cache rows (`Cache.pod_columns()`).

    Same contract as the store's PodColumnsView: the numpy member is a
    non-writeable VIEW of the live array, the lists/tables are the live
    objects, and everything carries the store-returned READ-ONLY contract
    (schedlint MU001 recognizes `pod_columns()` as a taint source; the
    array also refuses writes at runtime). Take it under no lock only as
    advisory telemetry."""

    __slots__ = ("n", "keys", "pod", "node_id", "node_names", "key2row")

    def __init__(self, cols: "CacheColumns"):
        n = cols.n
        v = cols.node_id[:n].view()
        v.flags.writeable = False
        self.n = n
        self.keys = cols.keys
        self.pod = cols.pod
        self.node_id = v
        self.node_names = cols.node_names
        self.key2row = cols.key2row


class CacheColumns:
    """The struct-of-arrays cache-row table. All mutation under the owning
    Cache's lock (see module docstring)."""

    _INITIAL_CAP = 1024

    def __init__(self):
        cap = self._INITIAL_CAP
        self.n = 0  # high-water row count (free rows included)
        self.key2row: Dict[str, int] = {}
        self.keys: List[Optional[str]] = [None] * cap
        self.pod: List[Any] = [None] * cap
        self.node_id = np.full(cap, -1, dtype=np.int32)
        self._free: List[int] = []
        # interned node-name table (append-only: lock-free reads are safe)
        self.node_names: List[str] = []
        self._node_ids: Dict[str, int] = {}
        self.inserted_total = 0  # lifetime row inserts (assume placements)
        self.materialized_total = 0  # lifetime row -> PodInfo collapses

    def intern_node(self, name: str) -> int:
        i = self._node_ids.get(name)
        if i is None:
            i = len(self.node_names)
            self._node_ids[name] = i
            self.node_names.append(name)
        return i

    def _grow(self) -> None:
        cap = len(self.keys)
        new = cap * 2
        pad = new - cap
        self.keys.extend([None] * pad)
        self.pod.extend([None] * pad)
        arr = np.full(new, -1, dtype=np.int32)
        arr[:cap] = self.node_id
        self.node_id = arr

    def insert(self, key: str, pod, node_name: str) -> int:
        """New row for an assumed placement. Caller guarantees the key is
        fresh (the assume validation already rejected duplicates)."""
        if self._free:
            row = self._free.pop()
        else:
            row = self.n
            if row >= len(self.keys):
                self._grow()
            self.n += 1
        self.keys[row] = key
        self.pod[row] = pod
        self.node_id[row] = self.intern_node(node_name)
        self.key2row[key] = row
        self.inserted_total += 1
        return row

    def remove(self, key: str) -> Optional[Tuple[Any, str]]:
        """Drop a row; returns (pod, node_name) so the caller can settle the
        node-side accounting, or None when the key has no row."""
        row = self.key2row.pop(key, None)
        if row is None:
            return None
        pod = self.pod[row]
        node_name = self.node_names[self.node_id[row]]
        self.keys[row] = None
        self.pod[row] = None
        self.node_id[row] = -1
        self._free.append(row)
        return pod, node_name

    def rows(self) -> int:
        return len(self.key2row)

    def iter_rows(self) -> Iterator[Tuple[str, Any, str]]:
        """(key, pod, node_name) for every live row (caller holds the cache
        lock; snapshot the output before mutating)."""
        names = self.node_names
        node_id = self.node_id
        pods = self.pod
        for key, row in self.key2row.items():
            yield key, pods[row], names[node_id[row]]

    def stats(self) -> Dict[str, Any]:
        return {
            "rows": len(self.key2row),
            "capacity": len(self.keys),
            "free": len(self._free),
            "inserted_total": self.inserted_total,
            "materialized_total": self.materialized_total,
            "node_table": len(self.node_names),
        }
