"""Global rebalancer & slice defragmenter (ISSUE 17, ROADMAP direction 3).

Placement is one-shot greedy per batch: once a pod binds, nothing revisits
the decision, and fragmentation accretes until arriving gangs can only be
admitted by destroying work through preemption (ISSUE 14). The Rebalancer
is the background optimizer layer on top: it periodically snapshots the
cluster through the scheduler's existing tensorizer, scores per-slice
fragmentation host-side from the cluster tensors (models/defrag.py —
allocation-free at steady state), and when the score crosses the threshold
re-solves the movable remainder as ONE batched tensor problem via the
jitted defrag kernel. The current→target delta compiles into a BOUNDED
migration plan executed as priority-ascending, PDB-respecting waves:

  * per-wave and per-cycle migration budgets are HARD caps — a rebalance
    never thunders; candidates beyond the cycle budget wait for the next
    cycle (budget_clamped stat, never a silent truncation);
  * each wave creates the pre-bound replacement pods FIRST
    (store.create_many) and only then evicts the originals with the batched
    store.delete_pods — a kill between the two leaves a transient
    duplicate, never a lost or double-bound pod (the chaos invariant);
  * an abort path runs before every wave: the caller-supplied slo_probe
    (windowed SLO evaluation, queue-depth guard, ...) returning False
    stops the cycle with the remaining waves unexecuted (slo_aborts stat);
  * the `rebalance.cycle` FaultInject site fires at cycle start, at every
    wave boundary and MID-WAVE (key="midwave", between replacement create
    and victim delete); an injected fault mid-wave rolls the wave's
    replacements back before aborting, a hard kill is the conservation
    chaos case above.

Only pods that are trivially re-placeable migrate: bound, non-gang,
priority below the ceiling, no affinity / node selector / topology spread
/ host ports, and not PDB-exhausted (gangpreempt.pdb_blocked_mask). Gang
members never move — their placement is rank-aligned to the ICI ring
(models/gangcover.py) and a single-member move would break the alignment
the gang paid preemption for.

Exactly ONE rebalancer may run against a store: a second instance (e.g. a
second pipeline of a PartitionedScheduler) would silently double-count the
migration budget, so claims go through a module-level weak registry and
losers count inert_conflict no-ops. Under a PartitionedScheduler the
rebalancer is additionally inert on any SHARD pipeline (partition_index
>= 0) — only the residual full-view pipeline (partition_index == -1) or a
standalone scheduler (None) sees the whole cluster and may own migration.
"""

from __future__ import annotations

import re
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..api.resources import compute_pod_resource_request
from ..api.types import new_uid
from ..chaos import faultinject
from ..obs import tracebuf as _tracebuf
from ..chaos.faultinject import FaultInjected
from ..models.defrag import (DEFRAG_MAX_VICTIMS, defrag_plan,
                             slice_fragmentation)
from ..snapshot.tensorizer import _quantize
from ..store.store import pod_structural_clone
from .gang import node_slice_ids

# one rebalancer per store (satellite 3): store -> weakref(owning
# Rebalancer). Weak on BOTH sides — the registry must neither keep a dead
# store alive nor keep a rebalancer alive through its own claim.
_OWNERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_OWNERS_LOCK = threading.Lock()

_MG_RE = re.compile(r"-mg\d+$")


def _mg_name(name: str, seq: int) -> str:
    """Replacement pod name: strip any prior migration suffix first, so a
    pod migrated twice is web-0-mg7, not web-0-mg3-mg7 (names stay bounded
    however often the rebalancer touches a pod)."""
    return f"{_MG_RE.sub('', name)}-mg{seq}"


class Rebalancer:
    """Background whole-cluster re-solve with bounded migration waves.

    Construct against a (Batch)Scheduler and drive cycles explicitly
    (cycle()) or through the pacing wrapper (maybe_cycle(), wired into
    run_until_idle's quiesce path when attached via
    scheduler.enable_rebalancer()). Thread-safe: one cycle at a time, stats
    under their own lock (GangPreemptor convention)."""

    def __init__(self, sched, *, frag_threshold: float = 0.25,
                 budget_per_wave: int = 8, budget_per_cycle: int = 32,
                 priority_ceiling: int = 100, min_interval_s: float = 0.0,
                 slo_probe: Optional[Callable[[], bool]] = None):
        if budget_per_wave <= 0 or budget_per_cycle <= 0:
            raise ValueError("migration budgets must be positive")
        self.sched = sched
        self.frag_threshold = float(frag_threshold)
        self.budget_per_wave = int(budget_per_wave)
        self.budget_per_cycle = int(budget_per_cycle)
        self.priority_ceiling = int(priority_ceiling)
        self.min_interval_s = float(min_interval_s)
        self.slo_probe = slo_probe
        # single-flight guard for cycle(): a FLAG, not a lock held across
        # the body — the body sleeps (fault delay plans) and dispatches jax
        # (defrag kernel), neither legal under a lock (schedlint LK002)
        self._cycle_active = False
        self._lock = threading.Lock()
        self._last_cycle_ts = float("-inf")
        self._seq = 0
        # victim key -> replacement key, recorded only after the victim's
        # delete committed; resolve_keys follows chains for conservation
        self._moves: Dict[str, str] = {}
        self._totals: Dict[str, float] = {
            "cycles": 0, "noop_cycles": 0, "plans": 0, "migrations": 0,
            "waves": 0, "slo_aborts": 0, "fault_aborts": 0,
            "budget_clamped": 0, "candidates_capped": 0,
            "inert_partition": 0, "inert_conflict": 0,
            "last_frag": 0.0, "last_migrations": 0,
        }

    # -- observability ---------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._totals)

    def reset(self) -> None:
        with self._lock:
            for k in self._totals:
                self._totals[k] = 0

    def resolve_keys(self, keys) -> List[str]:
        """Map submitted pod keys through the migration chain to the key of
        the pod that carries that workload NOW — the conservation-report
        input after a run that migrated some of the submitted pods."""
        with self._lock:
            moves = dict(self._moves)
        out = []
        for k in keys:
            seen = set()
            while k in moves and k not in seen:
                seen.add(k)
                k = moves[k]
            out.append(k)
        return out

    # -- ownership (satellite 3) -----------------------------------------------

    def _claim_store(self) -> bool:
        store = self.sched.store
        with _OWNERS_LOCK:
            ref = _OWNERS.get(store)
            cur = ref() if ref is not None else None
            if cur is None or cur is self:
                _OWNERS[store] = weakref.ref(self)
                return True
            return False

    def release(self) -> None:
        """Drop the store claim so another rebalancer may take over (tests,
        scheduler teardown)."""
        store = self.sched.store
        with _OWNERS_LOCK:
            ref = _OWNERS.get(store)
            if ref is not None and ref() is self:
                del _OWNERS[store]

    # -- driving ---------------------------------------------------------------

    def maybe_cycle(self) -> Optional[dict]:
        """cycle() if at least min_interval_s has passed since the last run
        (None otherwise) — the pacing entry run_until_idle's quiesce path
        calls; a zero interval rebalances on every quiesce."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_cycle_ts < self.min_interval_s:
                return None
            self._last_cycle_ts = now
        return self.cycle()

    def cycle(self) -> dict:
        """One rebalance cycle. Returns a summary dict; mutates nothing when
        inert (wrong partition, lost store claim, a cycle already in
        flight) or when fragmentation is below threshold (the
        allocation-free steady-state no-op)."""
        with self._lock:
            if self._cycle_active:
                return {"ran": False, "reason": "busy"}
            self._cycle_active = True
        res = None
        t0 = time.perf_counter()
        try:
            res = self._cycle_inner()
            return res
        finally:
            t1 = time.perf_counter()
            # trace timeline (ISSUE 18): one slice per cycle (the
            # steady-state no-op included — its near-zero width IS the
            # "rebalance costs nothing when defragmented" evidence)
            if _tracebuf.ACTIVE is not None:
                _tracebuf.ACTIVE.note_span(
                    "rebalance", "cycle", t0, t1, cat="rebalance",
                    args=dict(res) if isinstance(res, dict) else None)
            with self._lock:
                self._cycle_active = False

    def _cycle_inner(self) -> dict:
        t = self._totals
        pi = getattr(self.sched, "partition_index", None)
        if pi is not None and pi >= 0:
            # shard pipeline of a PartitionedScheduler: a shard's cluster
            # view is partial — migrating on it would fight the residual
            with self._lock:
                t["inert_partition"] += 1
            return {"ran": False, "reason": "partition"}
        if not self._claim_store():
            with self._lock:
                t["inert_conflict"] += 1
            return {"ran": False, "reason": "conflict"}
        with self._lock:
            t["cycles"] += 1
        try:
            if faultinject.ACTIVE is not None:
                faultinject.ACTIVE.fire("rebalance.cycle", key="cycle")
        except FaultInjected:
            with self._lock:
                t["fault_aborts"] += 1
            return {"ran": False, "reason": "fault"}

        sched = self.sched
        snapshot = sched.cache.update_snapshot()
        cluster, _ = sched._tensor_cache.cluster_tensors(snapshot)
        slice_ids = node_slice_ids(cluster)
        if slice_ids is None or cluster.n == 0:
            with self._lock:
                t["noop_cycles"] += 1
                t["last_frag"] = 0.0
                t["last_migrations"] = 0
            return {"ran": True, "frag": 0.0, "migrations": 0, "waves": 0}
        used = cluster.used.astype(np.int64)
        free = cluster.alloc.astype(np.int64) - used
        # only resources the cluster consumes can fragment (an unrequested
        # dim's free capacity is evenly spread by construction)
        active = used.sum(axis=0) > 0
        score, per_slice = slice_fragmentation(free, slice_ids, active)
        if score < self.frag_threshold:
            # steady state: tensors + the frag score alone — no pod
            # materialization, no plan, no allocation (pinned by
            # tests/test_rebalance.py against columnar_stats)
            with self._lock:
                t["noop_cycles"] += 1
                t["last_frag"] = score
                t["last_migrations"] = 0
            return {"ran": True, "frag": score, "migrations": 0, "waves": 0}

        # donor slice: in the most fragmented resource dim, the slice
        # holding the most free capacity — the cheapest to finish draining
        # into the rest (consolidation empties IT, the others fill)
        total = per_slice.sum(axis=0)
        nz = (total > 0) & active
        frag_dims = np.zeros(per_slice.shape[1])
        frag_dims[nz] = 1.0 - per_slice[:, nz].max(axis=0) / total[nz]
        dim = int(np.argmax(frag_dims))
        donor = int(np.argmax(per_slice[:, dim]))

        cands, capped = self._candidates(cluster, slice_ids, donor)
        clamped = len(cands) > self.budget_per_cycle
        cands = cands[:self.budget_per_cycle]
        with self._lock:
            t["last_frag"] = score
            if capped:
                t["candidates_capped"] += 1
            if clamped:
                t["budget_clamped"] += 1
        if not cands:
            with self._lock:
                t["noop_cycles"] += 1
                t["last_migrations"] = 0
            return {"ran": True, "frag": score, "migrations": 0, "waves": 0}

        dims = cluster.resource_dims
        v_req = np.array(
            [_quantize(compute_pod_resource_request(p), dims,
                       is_request=True) for p in cands],
            dtype=np.int64).reshape(len(cands), len(dims))
        headroom = (cluster.max_pods.astype(np.int64)
                    - cluster.pod_count.astype(np.int64))
        target_ok = (slice_ids >= 0) & (slice_ids != donor)
        targets = defrag_plan(np.maximum(free, 0), headroom, target_ok, v_req)
        migs: List[Tuple[object, str]] = [
            (p, cluster.node_names[int(ti)])
            for p, ti in zip(cands, targets) if ti >= 0]
        with self._lock:
            t["plans"] += 1
            t["last_migrations"] = len(migs)
        if not migs:
            return {"ran": True, "frag": score, "migrations": 0, "waves": 0}
        moved, waves, aborted = self._execute(migs)
        with self._lock:
            t["migrations"] += moved
            t["waves"] += waves
        return {"ran": True, "frag": score, "migrations": moved,
                "waves": waves, "aborted": aborted}

    # -- candidate selection ---------------------------------------------------

    def _candidates(self, cluster, slice_ids, donor) -> Tuple[list, bool]:
        """Movable pods on the donor slice, priority-ascending (ties by key
        for determinism), PDB-screened. Uses the columnar view to find rows
        without materializing the whole cluster; falls back to store.list on
        a non-columnar store. Returns (pods, capped)."""
        store = self.sched.store
        donor_nodes = {cluster.node_names[i] for i in range(cluster.n)
                       if slice_ids[i] == donor}
        raw = []
        view = (store.pod_columns()
                if hasattr(store, "pod_columns") else None)
        if view is not None:
            for row in range(view.n):
                key = view.keys[row]
                if key is None or view.node_id[row] < 0:
                    continue
                if view.gang[row] or view.priority[row] >= self.priority_ceiling:
                    continue
                if view.node_names[view.node_id[row]] not in donor_nodes:
                    continue
                raw.append(key)
            raw.sort()
            pods = []
            for key in raw:
                try:
                    p = store.get("pods", key)
                except KeyError:
                    continue
                if self._movable(p):
                    pods.append(p)
        else:
            items, _rv = store.list("pods")
            pods = [p for p in items
                    if p.spec.node_name in donor_nodes
                    and (p.spec.priority or 0) < self.priority_ceiling
                    and self._movable(p)]
            pods.sort(key=lambda p: p.key)
        capped = len(pods) > DEFRAG_MAX_VICTIMS
        pods = pods[:DEFRAG_MAX_VICTIMS]
        pdbs, _rv = store.list("poddisruptionbudgets")
        if pdbs:
            from .gangpreempt import pdb_blocked_mask

            blocked = pdb_blocked_mask(pods, pdbs)
            pods = [p for p, b in zip(pods, blocked) if not b]
        pods.sort(key=lambda p: ((p.spec.priority or 0), p.key))
        return pods, capped

    def _movable(self, p) -> bool:
        """Trivially re-placeable: bound, non-terminal, non-gang, and free
        of every placement constraint the defrag kernel does not model."""
        from ..api.podgroup import pod_group_key

        s = p.spec
        if not s.node_name or p.is_terminal():
            return False
        if pod_group_key(p):
            return False
        if s.affinity is not None or getattr(s, "node_selector", None):
            return False
        if getattr(s, "topology_spread_constraints", None):
            return False
        for c in (s.containers or ()):
            if getattr(c, "ports", None):
                return False
        return True

    # -- migration waves -------------------------------------------------------

    def _execute(self, migs) -> Tuple[int, int, bool]:
        """Run the plan in waves of budget_per_wave. Returns (migrated,
        waves, aborted). Create-before-delete per wave: a crash between the
        two leaves a duplicate (replacement + original both bound), never a
        lost pod; an INJECTED mid-wave fault additionally rolls the wave's
        replacements back before aborting."""
        store = self.sched.store
        t = self._totals
        moved = 0
        waves = 0
        for wi in range(0, len(migs), self.budget_per_wave):
            wave = migs[wi:wi + self.budget_per_wave]
            # trace timeline (ISSUE 18): one instant per wave boundary
            if _tracebuf.ACTIVE is not None:
                _tracebuf.ACTIVE.instant(
                    "rebalance", "wave-%d" % (wi // self.budget_per_wave),
                    cat="rebalance", args={"migrations": len(wave)})
            try:
                if faultinject.ACTIVE is not None:
                    faultinject.ACTIVE.fire(
                        "rebalance.cycle",
                        key=f"wave-{wi // self.budget_per_wave}")
            except FaultInjected:
                with self._lock:
                    t["fault_aborts"] += 1
                return moved, waves, True
            if self.slo_probe is not None and not self.slo_probe():
                with self._lock:
                    t["slo_aborts"] += 1
                return moved, waves, True
            reps, vkeys = [], []
            for victim, target in wave:
                with self._lock:
                    self._seq += 1
                    seq = self._seq
                rep = pod_structural_clone(victim)
                rep.metadata.name = _mg_name(victim.metadata.name, seq)
                rep.metadata.uid = new_uid()
                rep.metadata.resource_version = 0
                rep.spec.node_name = target
                # Pod.key's contract is "every rename parses a NEW Pod" —
                # this is the one rename-in-place in tree, so the clone's
                # inherited key memo MUST go (a stale key would make the
                # cache file the replacement under the victim's key and the
                # victim's DELETE would then evict both). The sig memos
                # anchor to the victim's old spec and would never validate;
                # _req_cache is still correct (requests are unchanged) and
                # deliberately kept.
                rep.__dict__.pop("_key_cache", None)
                rep.__dict__.pop("_class_sig", None)
                rep.__dict__.pop("_req_sig", None)
                reps.append(rep)
                vkeys.append(victim.key)
            _created, cerrs = store.create_many("pods", reps,
                                               origin="rebalance")
            failed = {k for k, _m in cerrs}
            rep_keys = [r.key for r in reps]
            live = [(vk, rk) for vk, rk in zip(vkeys, rep_keys)
                    if rk not in failed]
            try:
                if faultinject.ACTIVE is not None:
                    faultinject.ACTIVE.fire("rebalance.cycle", key="midwave")
            except FaultInjected:
                # roll the wave back: evicting nothing beats leaving both
                # copies bound; the originals were never touched
                store.delete_pods([rk for _vk, rk in live],
                                  origin="rebalance")
                with self._lock:
                    t["fault_aborts"] += 1
                return moved, waves, True
            _n, derrs = store.delete_pods([vk for vk, _rk in live],
                                          origin="rebalance")
            dfailed = {k for k, _m in derrs}
            with self._lock:
                for vk, rk in live:
                    if vk not in dfailed:
                        self._moves[vk] = rk
                        moved += 1
            waves += 1
        return moved, waves, False
