"""Scheduler cache: authoritative in-scheduler cluster state with assumed-pod
lifecycle and generation-based incremental snapshotting.

reference: pkg/scheduler/backend/cache/cache.go — cacheImpl :58 (recency-ordered
node list :71-73), UpdateSnapshot :186 (copies only NodeInfos whose Generation
is newer than the snapshot's — the diff stream the TPU tensorizer mirrors into
HBM), AssumePod :361, FinishBinding :376, ForgetPod :404, expiry of assumed pods
(scheduler.go:57-59 durationToExpireAssumedPod).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from ..api import Node, Pod
from ..utils import Clock
from .framework import NodeInfo, PodInfo, Snapshot


class Cache:
    def __init__(self, clock: Optional[Clock] = None, ttl: float = 15.0):
        self._lock = threading.RLock()
        self._clock = clock or Clock()
        self._ttl = ttl
        self._generation = 0
        self._nodes: Dict[str, NodeInfo] = {}
        # pod key -> node name for every known (added or assumed) pod
        self._pod_nodes: Dict[str, str] = {}
        self._assumed: Dict[str, float] = {}  # pod key -> deadline (0 = no expiry yet)
        self._snapshot_generation = -1
        self._snapshot: Optional[Snapshot] = None
        # image name -> shared ImageStateSummary (num_nodes mutated in place)
        self._image_entries: Dict[str, object] = {}

    def _next_gen(self) -> int:
        self._generation += 1
        return self._generation

    def _touch(self, ni: NodeInfo) -> None:
        ni.generation = self._next_gen()

    # -- nodes -----------------------------------------------------------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            ni = self._nodes.get(node.metadata.name)
            if ni is None:
                ni = NodeInfo()
                self._nodes[node.metadata.name] = ni
            elif ni.node is not None:
                self._remove_image_counts(ni.node)
            ni.set_node(node)
            ni.image_states = self._add_image_counts(node)
            self._touch(ni)

    def update_node(self, node: Node) -> None:
        self.add_node(node)

    def remove_node(self, name: str) -> None:
        with self._lock:
            ni = self._nodes.get(name)
            if ni is None:
                return
            if ni.node is not None:
                self._remove_image_counts(ni.node)
            if ni.pods:
                # Bound pods still reference this node: keep the NodeInfo as a
                # placeholder (node=None) so their accounting survives a node
                # flap (reference: cache.go RemoveNode keeps nodeInfo until the
                # last pod is removed). Snapshots skip placeholder nodes.
                ni.node = None
                self._touch(ni)
            else:
                self._nodes.pop(name, None)
            self._generation += 1  # force snapshot rebuild to drop the node

    # Image-state bookkeeping mirrors cache.go's shared imageStates map: one
    # ImageStateSummary object per image, shared by every NodeInfo that has it,
    # with NumNodes mutated in place — O(images of changed node) per event
    # instead of a full-cluster recount.

    def _add_image_counts(self, node: Node):
        from .framework import ImageStateSummary

        states = {}
        for img in node.status.images:
            for nm in img.names:
                entry = self._image_entries.get(nm)
                if entry is None:
                    entry = ImageStateSummary(size=img.size_bytes, num_nodes=0)
                    self._image_entries[nm] = entry
                entry.num_nodes += 1
                entry.size = img.size_bytes
                states[nm] = entry
        return states

    def _remove_image_counts(self, node: Node) -> None:
        for img in node.status.images:
            for nm in img.names:
                entry = self._image_entries.get(nm)
                if entry is not None:
                    entry.num_nodes -= 1
                    if entry.num_nodes <= 0:
                        self._image_entries.pop(nm, None)

    # -- pods ------------------------------------------------------------------

    def add_pod(self, pod: Pod) -> None:
        """A bound pod was observed (informer ADD). Confirms an assumed pod."""
        with self._lock:
            key = pod.key
            if key in self._assumed:
                # confirmation: informer caught up with our optimistic assume
                self._assumed.pop(key, None)
                if self._pod_nodes.get(key) == pod.spec.node_name:
                    return  # already accounted
                self._remove_pod_internal(key)
            elif key in self._pod_nodes:
                return
            self._add_pod_internal(pod)

    def _add_pod_internal(self, pod: Pod) -> None:
        node_name = pod.spec.node_name
        if not node_name:
            return
        ni = self._nodes.get(node_name)
        if ni is None:
            ni = NodeInfo()  # node not yet observed; pods land on a placeholder
            self._nodes[node_name] = ni
        ni.add_pod(PodInfo(pod))
        self._pod_nodes[pod.key] = node_name
        self._touch(ni)

    def _remove_pod_internal(self, key: str) -> None:
        node_name = self._pod_nodes.pop(key, None)
        if node_name is None:
            return
        ni = self._nodes.get(node_name)
        if ni is None:
            return
        ns, name = key.split("/", 1)
        for pi in ni.pods:
            if pi.pod.metadata.namespace == ns and pi.pod.metadata.name == name:
                ni.remove_pod(pi.pod)
                break
        self._touch(ni)

    def update_pod(self, pod: Pod) -> None:
        with self._lock:
            self._remove_pod_internal(pod.key)
            self._add_pod_internal(pod)

    def remove_pod(self, pod: Pod) -> None:
        with self._lock:
            self._assumed.pop(pod.key, None)
            self._remove_pod_internal(pod.key)

    # -- assumed pod lifecycle (cache.go:361-420) ------------------------------

    def assume_pod(self, pod: Pod, node_name: str) -> None:
        with self._lock:
            self._assume_internal(pod, node_name)

    def assume_pods(self, pairs) -> List[Tuple[int, str]]:
        """Bulk assume under ONE lock acquisition (batch-solver rates make
        100k per-pod acquires measurable). pairs = [(pod, node_name)];
        returns (index, error message) for entries that failed."""
        failed = []
        with self._lock:
            for i, (pod, node_name) in enumerate(pairs):
                try:
                    self._assume_internal(pod, node_name)
                except ValueError as e:
                    failed.append((i, str(e)))
        return failed

    def _assume_internal(self, pod: Pod, node_name: str) -> None:
        key = pod.key
        if key in self._pod_nodes:
            raise ValueError(f"pod {key} is already in the cache")
        pod.spec.node_name = node_name
        self._add_pod_internal(pod)
        self._assumed[key] = 0.0  # no expiry until binding finishes

    def finish_binding(self, pod: Pod) -> None:
        with self._lock:
            if pod.key in self._assumed:
                self._assumed[pod.key] = self._clock.now() + self._ttl

    def forget_pod(self, pod: Pod) -> None:
        with self._lock:
            self._assumed.pop(pod.key, None)
            self._remove_pod_internal(pod.key)

    def is_assumed(self, key: str) -> bool:
        with self._lock:
            return key in self._assumed

    def cleanup_expired_assumed_pods(self) -> List[str]:
        with self._lock:
            now = self._clock.now()
            expired = [k for k, dl in self._assumed.items() if dl and dl < now]
            for key in expired:
                self._assumed.pop(key, None)
                self._remove_pod_internal(key)
            return expired

    # -- snapshotting (cache.go:186 UpdateSnapshot) ----------------------------

    def update_snapshot(self) -> Snapshot:
        """Incremental: clone only NodeInfos newer than the last snapshot."""
        with self._lock:
            if self._snapshot is not None and self._snapshot_generation == self._generation:
                return self._snapshot
            prev = self._snapshot.node_info_map if self._snapshot is not None else {}
            new_map: Dict[str, NodeInfo] = {}
            for name, ni in self._nodes.items():
                if ni.node is None:
                    continue  # placeholder without a real Node yet
                old = prev.get(name)
                if old is not None and old.generation == ni.generation:
                    new_map[name] = old
                else:
                    new_map[name] = ni.clone()
            snap = Snapshot(new_map)
            snap.generation = self._generation
            self._snapshot = snap
            self._snapshot_generation = self._generation
            return snap

    def node_count(self) -> int:
        with self._lock:
            return sum(1 for ni in self._nodes.values() if ni.node is not None)

    def pod_count(self) -> int:
        with self._lock:
            return len(self._pod_nodes)
