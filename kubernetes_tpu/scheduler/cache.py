"""Scheduler cache: authoritative in-scheduler cluster state with assumed-pod
lifecycle and generation-based incremental snapshotting.

reference: pkg/scheduler/backend/cache/cache.go — cacheImpl :58 (recency-ordered
node list :71-73), UpdateSnapshot :186 (copies only NodeInfos whose Generation
is newer than the snapshot's — the diff stream the TPU tensorizer mirrors into
HBM), AssumePod :361, FinishBinding :376, ForgetPod :404, expiry of assumed pods
(scheduler.go:57-59 durationToExpireAssumedPod).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from ..api import Node, Pod, compute_pod_resource_request
from ..utils import Clock
from .framework import NodeInfo, PodInfo, Snapshot


def _pod_req_pair(pod: Pod):
    """The pod's (request, non_zero_request) Resource pair — the same
    `_req_cache` memo PodInfo.__init__ and the tensorizer seed, get-or-compute
    so removal accounting works even for a pod that never grew a PodInfo."""
    cached = pod.__dict__.get("_req_cache")
    if cached is None:
        cached = (compute_pod_resource_request(pod),
                  compute_pod_resource_request(pod, non_zero=True))
        pod.__dict__["_req_cache"] = cached
    return cached


class Cache:
    def __init__(self, clock: Optional[Clock] = None, ttl: float = 15.0):
        self._lock = threading.RLock()
        self._clock = clock or Clock()
        self._ttl = ttl
        self._generation = 0
        self._nodes: Dict[str, NodeInfo] = {}
        # pod key -> node name for every known (added or assumed) pod
        self._pod_nodes: Dict[str, str] = {}
        self._assumed: Dict[str, float] = {}  # pod key -> deadline (0 = no expiry yet)
        self._snapshot_generation = -1
        self._snapshot: Optional[Snapshot] = None
        # image name -> shared ImageStateSummary (num_nodes mutated in place)
        self._image_entries: Dict[str, object] = {}
        # Columnar cache rows (scheduler/cachecols.py): created lazily on the
        # first assume_pods_columnar, so object-path schedulers never pay for
        # (or observe) the row table.
        self._cols = None
        # Names of nodes touched since the last snapshot; None = a structural
        # event (node add/remove/promote) happened and the next
        # update_snapshot must do the full generation walk.
        self._dirty_names: Optional[Set[str]] = set()

    def _next_gen(self) -> int:
        self._generation += 1
        return self._generation

    def _touch(self, ni: NodeInfo, name: Optional[str] = None) -> None:
        ni.generation = self._next_gen()
        if name is None:
            self._dirty_names = None
        elif self._dirty_names is not None:
            self._dirty_names.add(name)

    # -- nodes -----------------------------------------------------------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            name = node.metadata.name
            ni = self._nodes.get(name)
            structural = ni is None or ni.node is None
            if ni is None:
                ni = NodeInfo()
                self._nodes[name] = ni
            elif ni.node is not None:
                self._remove_image_counts(ni.node)
            ni.set_node(node)
            ni.image_states = self._add_image_counts(node)
            # a NEW node (or a placeholder promotion) changes the snapshot's
            # node set — the incremental from_prev path can't represent that
            self._touch(ni, None if structural else name)

    def update_node(self, node: Node) -> None:
        self.add_node(node)

    def remove_node(self, name: str) -> None:
        with self._lock:
            ni = self._nodes.get(name)
            if ni is None:
                return
            if ni.node is not None:
                self._remove_image_counts(ni.node)
            if ni.pods or ni.col_count:
                # Bound pods still reference this node: keep the NodeInfo as a
                # placeholder (node=None) so their accounting survives a node
                # flap (reference: cache.go RemoveNode keeps nodeInfo until the
                # last pod is removed). Snapshots skip placeholder nodes.
                ni.node = None
                self._touch(ni, None)
            else:
                self._nodes.pop(name, None)
            self._generation += 1  # force snapshot rebuild to drop the node
            self._dirty_names = None  # node set changed: full snapshot walk

    # Image-state bookkeeping mirrors cache.go's shared imageStates map: one
    # ImageStateSummary object per image, shared by every NodeInfo that has it,
    # with NumNodes mutated in place — O(images of changed node) per event
    # instead of a full-cluster recount.

    def _add_image_counts(self, node: Node):
        from .framework import ImageStateSummary

        states = {}
        for img in node.status.images:
            for nm in img.names:
                entry = self._image_entries.get(nm)
                if entry is None:
                    entry = ImageStateSummary(size=img.size_bytes, num_nodes=0)
                    self._image_entries[nm] = entry
                entry.num_nodes += 1
                entry.size = img.size_bytes
                states[nm] = entry
        return states

    def _remove_image_counts(self, node: Node) -> None:
        for img in node.status.images:
            for nm in img.names:
                entry = self._image_entries.get(nm)
                if entry is not None:
                    entry.num_nodes -= 1
                    if entry.num_nodes <= 0:
                        self._image_entries.pop(nm, None)

    # -- pods ------------------------------------------------------------------

    def add_pod(self, pod: Pod) -> None:
        """A bound pod was observed (informer ADD). Confirms an assumed pod."""
        with self._lock:
            key = pod.key
            if key in self._assumed:
                # confirmation: informer caught up with our optimistic assume
                self._assumed.pop(key, None)
                if self._pod_nodes.get(key) == pod.spec.node_name:
                    return  # already accounted
                self._remove_pod_internal(key)
            elif key in self._pod_nodes:
                return
            self._add_pod_internal(pod)

    def _add_pod_internal(self, pod: Pod) -> None:
        node_name = pod.spec.node_name
        if not node_name:
            return
        ni = self._nodes.get(node_name)
        if ni is None:
            ni = NodeInfo()  # node not yet observed; pods land on a placeholder
            self._nodes[node_name] = ni
        ni.add_pod(PodInfo(pod))
        self._pod_nodes[pod.key] = node_name
        self._touch(ni, node_name)

    def _remove_pod_internal(self, key: str) -> None:
        # Columnar row? Exact inverse of the row's lifecycle: drop the row,
        # subtract its full request pair (phase 2 scatter-added the same
        # `_req_cache` values — the raw layout covers every dim the batch's
        # classes declare, mirroring ni.remove_pod's full subtraction on the
        # object path), decrement the row population.
        cols = self._cols
        if cols is not None:
            got = cols.remove(key)
            if got is not None:
                pod, node_name = got
                self._pod_nodes.pop(key, None)
                ni = self._nodes.get(node_name)
                if ni is not None:
                    ni.col_count -= 1
                    req, req_nz = _pod_req_pair(pod)
                    ni.requested.sub(req)
                    ni.non_zero_requested.sub(req_nz)
                    self._touch(ni, node_name)
                return
        node_name = self._pod_nodes.pop(key, None)
        if node_name is None:
            return
        ni = self._nodes.get(node_name)
        if ni is None:
            return
        ns, name = key.split("/", 1)
        for pi in ni.pods:
            if pi.pod.metadata.namespace == ns and pi.pod.metadata.name == name:
                ni.remove_pod(pi.pod)
                break
        self._touch(ni, node_name)

    def update_pod(self, pod: Pod) -> None:
        with self._lock:
            self._remove_pod_internal(pod.key)
            self._add_pod_internal(pod)

    def remove_pod(self, pod: Pod) -> None:
        with self._lock:
            self._assumed.pop(pod.key, None)
            self._remove_pod_internal(pod.key)

    # -- assumed pod lifecycle (cache.go:361-420) ------------------------------

    def assume_pod(self, pod: Pod, node_name: str) -> None:
        with self._lock:
            self._assume_internal(pod, node_name)

    def assume_pods(self, pairs) -> List[Tuple[int, str]]:
        """Bulk assume under ONE lock acquisition (batch-solver rates make
        100k per-pod acquires measurable). pairs = [(pod, node_name)];
        returns (index, error message) for entries that failed."""
        failed = []
        with self._lock:
            for i, (pod, node_name) in enumerate(pairs):
                try:
                    self._assume_internal(pod, node_name)
                except ValueError as e:
                    failed.append((i, str(e)))
        return failed

    # -- columnar assume (the batched solver's accounting path) ----------------

    def assume_pods_structural(self, pairs,
                               check_ports: bool = True) -> List[Tuple[int, str]]:
        """Phase 1 of the columnar assume: per-pod bookkeeping ONLY —
        validation, _pod_nodes/_assumed entries, PodInfo appends (pods lists,
        affinity sublists, host ports). Requested-resource totals and
        generations are NOT touched; the caller must follow up with
        apply_node_resource_deltas (computed as numpy scatter-adds over the
        solver batch — the per-pod Resource.add loop was a top stage of the
        100k assume). Between the two calls the touched NodeInfos are
        transiently inconsistent (pods appended, requested stale); the
        scheduling thread is the only snapshot taker, so no consumer can
        observe the gap. check_ports=False skips the host-port scan when the
        caller proved no pod in the batch declares host ports (the
        tensorizer's per-class flag). Returns (index, error) for entries
        that failed."""
        from .framework import _host_ports

        # native commit engine (ISSUE 11): the per-pod loop below — key
        # check, node_name stamp, PodInfo build, list appends, bookkeeping
        # dict inserts — replayed in C for port-free batches (PyDLL: GIL
        # held, non-blocking, so legal under the cache lock), ~3x fewer
        # interpreter cycles on the 100k assume. Availability is resolved
        # BEFORE taking the lock: first use may pay the one-time g++
        # compile, and stalling every cache consumer behind it would be a
        # de-facto LK002 violation (store.bind_many hoists the same way).
        native = None
        if not check_ports:
            from ..native import hostcommit

            if hostcommit.available():
                native = hostcommit
        failed = []
        with self._lock:
            if native is not None:
                native.assume_structural(
                    pairs, self._pod_nodes, self._assumed, self._nodes,
                    failed)
                return failed
            pod_nodes = self._pod_nodes
            assumed = self._assumed
            nodes = self._nodes
            for i, (pod, node_name) in enumerate(pairs):
                key = pod.key
                if key in pod_nodes:
                    failed.append((i, f"pod {key} is already in the cache"))
                    continue
                pod.spec.node_name = node_name
                ni = nodes.get(node_name)
                if ni is None:
                    ni = NodeInfo()
                    nodes[node_name] = ni
                pi = PodInfo(pod)
                ni.pods.append(pi)
                if (pi.required_affinity_terms or pi.preferred_affinity_terms
                        or pi.required_anti_affinity_terms
                        or pi.preferred_anti_affinity_terms):
                    ni.pods_with_affinity.append(pi)
                    if pi.required_anti_affinity_terms:
                        ni.pods_with_required_anti_affinity.append(pi)
                if check_ports:
                    for port in _host_ports(pod):
                        ni.used_ports.add(port)
                pod_nodes[key] = node_name
                assumed[key] = 0.0
        return failed

    def assume_pods_columnar(self, pairs) -> List[Tuple[int, str]]:
        """Row-mode phase 1: the zero-object assume. Instead of building a
        PodInfo per placement, each pod lands as a columnar row (key, original
        Pod ref, interned node id) plus one `col_count` increment on its
        NodeInfo — a handful of dict/list/int32 writes, no per-pod Python
        allocation. Phase 2 (apply_node_resource_deltas — the same GIL-free
        commit_deltas scatter output) remains the only resource/generation
        mutation, exactly as on the structural path.

        The dispatch gate guarantees every pod in `pairs` is constraint-free
        (no gang, no affinity/topology-spread terms, no host ports), so rows
        never owe affinity sublists or port claims. Unlike the structural
        path, the pod is NOT stamped with `spec.node_name`: these are the
        store/queue ORIGINALS (MU001 — store-returned objects are read-only),
        and the bind worker only needs key + target node. Returns (index,
        error) for entries that failed validation."""
        failed = []
        with self._lock:
            cols = self._cols
            if cols is None:
                from .cachecols import CacheColumns

                cols = self._cols = CacheColumns()
            pod_nodes = self._pod_nodes
            assumed = self._assumed
            nodes = self._nodes
            for i, (pod, node_name) in enumerate(pairs):
                key = pod.key
                if key in pod_nodes:
                    failed.append((i, f"pod {key} is already in the cache"))
                    continue
                ni = nodes.get(node_name)
                if ni is None:
                    ni = NodeInfo()
                    nodes[node_name] = ni
                cols.insert(key, pod, node_name)
                ni.col_count += 1
                pod_nodes[key] = node_name
                assumed[key] = 0.0
        return failed

    def materialize_columnar_rows(self, out: Optional[list] = None) -> int:
        """Collapse every columnar row into a real PodInfo on its node — the
        escape hatch for consumers that genuinely need object rows (a
        constrained batch's selector counts, the serial fallback's plugin
        walks, the conservation checker). Resources are NOT re-added (phase 2
        already scatter-added them) and rows are constraint-free by the
        dispatch gate, so this is append + generation touch per row. Counted
        in `materialized_total` — the live zero-alloc gauge's feed; at steady
        state this never runs. Returns the number of rows materialized; when
        `out` is given, appends one (node_name, PodInfo) per row so callers
        holding a pre-materialization snapshot can patch their clones."""
        with self._lock:
            cols = self._cols
            if cols is None or not cols.key2row:
                return 0
            rows = list(cols.iter_rows())
            for key, pod, node_name in rows:
                cols.remove(key)
                ni = self._nodes.get(node_name)
                if ni is None:
                    continue
                ni.col_count -= 1
                pi = PodInfo(pod)
                if out is not None:
                    out.append((node_name, pi))
                ni.pods.append(pi)
                if (pi.required_affinity_terms or pi.preferred_affinity_terms
                        or pi.required_anti_affinity_terms
                        or pi.preferred_anti_affinity_terms):
                    ni.pods_with_affinity.append(pi)
                    if pi.required_anti_affinity_terms:
                        ni.pods_with_required_anti_affinity.append(pi)
                self._touch(ni, node_name)
            cols.materialized_total += len(rows)
            return len(rows)

    def pod_columns(self):
        """Read-only columnar view of the live cache rows (CacheColumnsView),
        or None when no row table exists. Store-returned READ-ONLY contract:
        the numpy column refuses writes at runtime and schedlint MU001 taints
        everything reachable from it."""
        with self._lock:
            if self._cols is None:
                return None
            from .cachecols import CacheColumnsView

            return CacheColumnsView(self._cols)

    def columnar_rows(self) -> int:
        with self._lock:
            return self._cols.rows() if self._cols is not None else 0

    def columnar_materialized(self) -> int:
        """Lifetime row->PodInfo collapses (feeds the pod_obj_allocs gauge)."""
        with self._lock:
            return self._cols.materialized_total if self._cols is not None else 0

    def columnar_stats(self) -> Optional[Dict]:
        with self._lock:
            return self._cols.stats() if self._cols is not None else None

    def forget_pods_structural(self, pods, check_ports: bool = True) -> None:
        """Rollback of assume_pods_structural BEFORE the matching
        apply_node_resource_deltas: undo exactly what phase 1 did — the
        _pod_nodes/_assumed entries, the PodInfo appends (pods lists,
        affinity sublists), and (when phase 1 scanned them) the host-port
        claims — WITHOUT the requested-resource subtraction forget_pod
        performs, because phase 2 never added those totals. Subtracting them
        here would drive NodeInfo.requested negative (the gang all-or-nothing
        rollback found this the hard way). check_ports must mirror the
        assume call's flag, or a port another pod legitimately owns could be
        released."""
        from .framework import _host_ports

        with self._lock:
            cols = self._cols
            for pod in pods:
                key = pod.key
                if cols is not None:
                    got = cols.remove(key)
                    if got is not None:
                        # columnar row pre-phase-2: undo exactly what
                        # assume_pods_columnar did (row + bookkeeping +
                        # col_count) with NO resource subtraction
                        _p, node_name = got
                        self._pod_nodes.pop(key, None)
                        self._assumed.pop(key, None)
                        ni = self._nodes.get(node_name)
                        if ni is not None:
                            ni.col_count -= 1
                            self._touch(ni, node_name)
                        continue
                node_name = self._pod_nodes.pop(key, None)
                self._assumed.pop(key, None)
                if node_name is None:
                    continue
                ni = self._nodes.get(node_name)
                if ni is None:
                    continue
                for lst in (ni.pods, ni.pods_with_affinity,
                            ni.pods_with_required_anti_affinity):
                    for i in range(len(lst) - 1, -1, -1):
                        if lst[i].pod.key == key:
                            lst.pop(i)
                            break
                if check_ports:
                    for port in _host_ports(pod):
                        ni.used_ports.discard(port)
                self._touch(ni, node_name)

    def apply_node_resource_deltas(self, resource_dims, node_deltas,
                                   expected_gen: Optional[int] = None
                                   ) -> Optional[int]:
        """Phase 2 of the columnar assume: per-NODE aggregate requested /
        non-zero-requested updates (one Resource poke per touched node
        instead of two Resource.adds per pod) plus the generation touch that
        makes update_snapshot clone exactly these nodes. node_deltas =
        [(node_name, d_raw, d_raw_nz)] with d_* int64 vectors laid out by
        resource_dims (milli-CPU, bytes, bytes, then scalar counts — the
        tensorizer's raw layout, so the same scatter-add feeds both this and
        TensorCache.apply_assume_deltas).

        Returns the generation after the touches IF the cache was still at
        expected_gen on entry — proving, under one lock hold, that every
        generation between the two is one of these touches (the TensorCache
        fast path's precondition). Returns None when a foreign mutation got
        in first (e.g. a bind-worker forget_pod): the deltas still apply,
        but the caller must leave requantization to the normal diff path."""
        from ..api.resources import CPU, EPHEMERAL_STORAGE, MEMORY

        with self._lock:
            clean = expected_gen is None or self._generation == expected_gen
            for node_name, d_raw, d_raw_nz in node_deltas:
                ni = self._nodes.get(node_name)
                if ni is None:
                    continue
                for res, vec in ((ni.requested, d_raw),
                                 (ni.non_zero_requested, d_raw_nz)):
                    for di, dim in enumerate(resource_dims):
                        v = int(vec[di])
                        if not v:
                            continue
                        if dim == CPU:
                            res.milli_cpu += v
                        elif dim == MEMORY:
                            res.memory += v
                        elif dim == EPHEMERAL_STORAGE:
                            res.ephemeral_storage += v
                        else:
                            res.scalar[dim] = res.scalar.get(dim, 0) + v
                self._touch(ni, node_name)
            return self._generation if clean else None

    def confirm_assumed_bulk(self, pairs) -> List[int]:
        """Self-bind short-circuit: confirm assumed pods whose bind MODIFIED
        events came back from our own bind_many — equivalent to add_pod's
        confirmation branch (drop the assume record, accounting already
        matches) without a per-event ingest. pairs = [(pod key, node_name)];
        returns the indices that did NOT match an assume on that node — the
        caller must push those through the full ingest path (foreign bind,
        expired assume, node mismatch)."""
        leftover = []
        with self._lock:
            for i, (key, node_name) in enumerate(pairs):
                if key in self._assumed and self._pod_nodes.get(key) == node_name:
                    del self._assumed[key]
                else:
                    leftover.append(i)
        return leftover

    @property
    def generation(self) -> int:
        """Current mutation counter (snapshots stamp it; TensorCache compares
        it to decide whether its columnar assume deltas fully explain the
        diff since the last tensorize)."""
        with self._lock:
            return self._generation

    def _assume_internal(self, pod: Pod, node_name: str) -> None:
        key = pod.key
        if key in self._pod_nodes:
            raise ValueError(f"pod {key} is already in the cache")
        pod.spec.node_name = node_name
        self._add_pod_internal(pod)
        self._assumed[key] = 0.0  # no expiry until binding finishes

    def finish_binding(self, pod: Pod) -> None:
        with self._lock:
            if pod.key in self._assumed:
                self._assumed[pod.key] = self._clock.now() + self._ttl

    def finish_binding_bulk(self, pods) -> None:
        """finish_binding for a whole committed bind batch: one lock, one
        clock read (the bind worker's per-pod acquires were measurable at
        100k-bind scale)."""
        with self._lock:
            deadline = self._clock.now() + self._ttl
            assumed = self._assumed
            for pod in pods:
                key = pod.key
                if key in assumed:
                    assumed[key] = deadline

    def forget_pod(self, pod: Pod) -> None:
        with self._lock:
            self._assumed.pop(pod.key, None)
            self._remove_pod_internal(pod.key)

    def is_assumed(self, key: str) -> bool:
        with self._lock:
            return key in self._assumed

    def assumed_count(self) -> int:
        """How many pods are currently assumed-but-unconfirmed (the state a
        crash resync drops — resync_from_store reports it)."""
        with self._lock:
            return len(self._assumed)

    def contains(self, key: str) -> bool:
        """Whether the cache accounts for this pod at all (bound or assumed).
        A gang member whose assume EXPIRED out of the cache reads False while
        the GangDirectory may still count it toward quorum — the leak the
        scheduler_gang_quorum_expired_assumes gauge measures."""
        with self._lock:
            return key in self._pod_nodes

    def cleanup_expired_assumed_pods(self) -> List[str]:
        with self._lock:
            now = self._clock.now()
            expired = [k for k, dl in self._assumed.items() if dl and dl < now]
            for key in expired:
                self._assumed.pop(key, None)
                self._remove_pod_internal(key)
            return expired

    # -- snapshotting (cache.go:186 UpdateSnapshot) ----------------------------

    def update_snapshot(self) -> Snapshot:
        """Incremental: clone only NodeInfos newer than the last snapshot.

        Fast path: when every mutation since the last snapshot was tracked by
        name (`_dirty_names` — resource pokes, pod adds/removes on existing
        real nodes), only those names are generation-compared and the
        snapshot derives via Snapshot.from_prev, skipping the O(all nodes)
        walk. Any structural event (node add/remove/promote) clears the set
        to None and the full walk below runs — producing a bit-identical
        result, just slower. The derived snapshot carries
        changed_names/changed_from_gen so the tensorizer can diff by the same
        set instead of identity-walking the node list."""
        with self._lock:
            if self._snapshot is not None and self._snapshot_generation == self._generation:
                return self._snapshot
            prev_snap = self._snapshot
            dirty = self._dirty_names
            if prev_snap is not None and dirty is not None:
                changed: Dict[str, NodeInfo] = {}
                ok = True
                for name in dirty:
                    ni = self._nodes.get(name)
                    if ni is None:
                        ok = False  # vanished without a structural event? full walk
                        break
                    if ni.node is None:
                        continue  # placeholder: excluded from prev too
                    old = prev_snap.node_info_map.get(name)
                    if old is None:
                        ok = False  # appeared without a structural event? full walk
                        break
                    if old.generation != ni.generation:
                        changed[name] = ni.clone()
                if ok:
                    snap = Snapshot.from_prev(prev_snap, changed)
                    snap.generation = self._generation
                    self._snapshot = snap
                    self._snapshot_generation = self._generation
                    self._dirty_names = set()
                    return snap
            prev = prev_snap.node_info_map if prev_snap is not None else {}
            new_map: Dict[str, NodeInfo] = {}
            for name, ni in self._nodes.items():
                if ni.node is None:
                    continue  # placeholder without a real Node yet
                old = prev.get(name)
                if old is not None and old.generation == ni.generation:
                    new_map[name] = old
                else:
                    new_map[name] = ni.clone()
            snap = Snapshot(new_map)
            snap.generation = self._generation
            self._snapshot = snap
            self._snapshot_generation = self._generation
            self._dirty_names = set()
            return snap

    def node_count(self) -> int:
        with self._lock:
            return sum(1 for ni in self._nodes.values() if ni.node is not None)

    def pod_count(self) -> int:
        with self._lock:
            return len(self._pod_nodes)
