"""Solver circuit breaker: degrade to the simplest solver under repeated
solver failures, recover via half-open probes.

The batched pipeline's solvers form a reliability ladder: the jitted
waterfill (and the native C++ engine, and the transport solvers) are the
fast paths; the exact scan solver is the semantics oracle every one of them
is parity-tested against. When the fast path starts throwing — an XLA
compile blow-up, a poisoned device, a native-module fault — losing batch
after batch to the same exception is the brittle behavior ISSUE 6 targets.
The breaker applies the standard circuit-breaker state machine to solver
CHOICE:

  CLOSED     the configured solver runs; consecutive failures are counted.
  OPEN       after `threshold` consecutive failures the breaker trips: every
             batch for `cooldown_s` runs the DEGRADED solver (waterfill ->
             exact scan, native -> the Python/jax path, transport -> scan).
  HALF_OPEN  cooldown expired: ONE batch probes the configured solver.
             Success closes the breaker (a recovery); failure re-opens it
             for another cooldown.

The scheduler calls effective_solver() once per batch (which performs the
OPEN -> HALF_OPEN transition on cooldown expiry) and reports the outcome of
the solve with record_success()/record_failure(). Failures of the DEGRADED
solver are counted but never change state — there is nothing further to
degrade to, and the pods requeue with backoff either way.

Observability: the scheduler_solver_breaker_state gauge (0 closed / 1
half-open / 2 open), trips/recoveries counters in sched_stats(), and the
per-batch flight record's `breaker` + effective `solver` fields.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..utils import Clock

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

# the degradation ladder: every fast path falls back to the exact scan
# solver (the oracle); "exact" has nowhere further to go — the breaker still
# counts and reports, so a failing oracle is at least visible
DEGRADED = {
    "fast": "exact",
    "auto": "exact",
    "native": "exact",
    "auction": "exact",
    "sinkhorn": "exact",
    "exact": "exact",
}

# which EXECUTED path (BatchScheduler._solve_path) represents the preferred
# mode's fast path: a constrained batch under an exact/native/transport mode
# runs the scan regardless of the breaker, and its outcome says NOTHING
# about the failing fast kernel — crediting it to the mode would falsely
# close (or trip) the breaker
REPRESENTATIVE = {
    "fast": "fast",
    "auto": "fast",
    "native": "native",
    "auction": "auction",
    "sinkhorn": "sinkhorn",
    "exact": "exact",
}

# the fast MODE now has two jitted kernels (ISSUE 8): the constraint-free
# waterfill ("fast") and the constrained propose-and-repair pipeline
# ("repair" — models/repair.py). A failure of EITHER is a failure of the
# mode under protection, so both degrade to the exact scan oracle through
# the same trip/cooldown/half-open ladder — and a successful repair batch
# is a genuine probe of the protected mode.
FAST_PATHS = ("fast", "repair")


def path_matches_mode(used: str, preferred: str) -> bool:
    """True when the executed solver path `used` exercised the preferred
    MODE's fast path (the thing the breaker is protecting)."""
    rep = REPRESENTATIVE.get(preferred, preferred)
    if rep == "fast":
        return used in FAST_PATHS
    return used == rep


class SolverCircuitBreaker:
    def __init__(self, clock: Optional[Clock] = None, threshold: int = 3,
                 cooldown_s: float = 30.0):
        self.clock = clock or Clock()
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.state = CLOSED
        self.consecutive_failures = 0
        self.trips = 0  # CLOSED/HALF_OPEN -> OPEN transitions
        self.recoveries = 0  # HALF_OPEN -> CLOSED transitions
        self.failures_total = 0  # every recorded solver failure
        self.degraded_failures = 0  # failures of the degraded solver itself
        self._opened_at = 0.0

    # -- per-batch protocol ----------------------------------------------------

    def effective_solver(self, preferred: str) -> str:
        """The solver MODE this batch should use. Performs the OPEN ->
        HALF_OPEN transition when the cooldown has expired, so the very next
        batch is the probe. CLOSED and HALF_OPEN both run the preferred
        mode (a HALF_OPEN batch IS the probe)."""
        if self.state == OPEN:
            if self.clock.now() - self._opened_at >= self.cooldown_s:
                self.state = HALF_OPEN
            else:
                return DEGRADED.get(preferred, "exact")
        return preferred

    def record_success(self, used: str, preferred: str) -> None:
        """`used` is the EXECUTED solver path (BatchScheduler._solve_path),
        not the mode label: a constrained batch routed to the scan proves
        nothing about the preferred fast path, so it neither closes a
        HALF_OPEN breaker nor resets the failure streak — the breaker keeps
        probing until a batch genuinely exercises the protected path."""
        if not path_matches_mode(used, preferred):
            return
        if self.state == HALF_OPEN:
            self.state = CLOSED
            self.recoveries += 1
        self.consecutive_failures = 0

    def record_failure(self, used: str, preferred: str) -> bool:
        """Returns True when THIS failure tripped the breaker. Failures of
        any path OTHER than the preferred mode's (the degraded scan while
        OPEN, or a constrained batch's scan while CLOSED) are counted but
        never move the state machine — there is nothing to degrade to, and
        tripping on them would just relabel the same failing path."""
        self.failures_total += 1
        if not path_matches_mode(used, preferred):
            self.degraded_failures += 1
            return False
        self.consecutive_failures += 1
        if (self.state == HALF_OPEN
                or self.consecutive_failures >= self.threshold):
            tripped = self.state != OPEN
            self.state = OPEN
            self._opened_at = self.clock.now()
            if tripped:
                self.trips += 1
            return tripped
        return False

    # -- observability ---------------------------------------------------------

    @property
    def code(self) -> int:
        """Gauge encoding: 0 closed, 1 half-open, 2 open."""
        return _STATE_CODE[self.state]

    def describe(self) -> Dict:
        return {
            "state": self.state,
            "consecutive_failures": self.consecutive_failures,
            "failures_total": self.failures_total,
            "degraded_failures": self.degraded_failures,
            "trips": self.trips,
            "recoveries": self.recoveries,
            "threshold": self.threshold,
            "cooldown_s": self.cooldown_s,
        }
