"""3-tier scheduling queue: activeQ + backoffQ + unschedulablePods.

reference: pkg/scheduler/backend/queue/scheduling_queue.go — PriorityQueue :154,
AddUnschedulableIfNotPresent :741, flushBackoffQCompleted :790, Pop :829 (blocks),
MoveAllToActiveOrBackoffQueue :1028; backoff_queue.go:64 (initial 1s, max 10s);
flush cadence: backoff every 1s, unschedulable every 30s (:350).

QueueingHints are simplified to event-kind gating: on a cluster event, all
unschedulable pods move to backoff/active (the pre-hints behavior); per-plugin
hint functions can be layered on later without changing this interface.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api import Pod
from ..utils import Clock

DEFAULT_POD_INITIAL_BACKOFF = 1.0  # seconds (scheduler.go:252)
DEFAULT_POD_MAX_BACKOFF = 10.0  # seconds (scheduler.go:253)
FLUSH_UNSCHEDULABLE_TIMEOUT = 30.0  # scheduling_queue.go:91


class _LessItem:
    """Adapts a QueueSort plugin's less(a, b) into a heap sort key."""

    __slots__ = ("qp", "less")

    def __init__(self, qp, less):
        self.qp = qp
        self.less = less

    def __lt__(self, other):
        return self.less(self.qp, other.qp)

    def __eq__(self, other):
        return not self.less(self.qp, other.qp) and not self.less(other.qp, self.qp)


@dataclass
class QueuedPodInfo:
    """reference: framework types.go:362 QueuedPodInfo."""

    pod: Pod
    timestamp: float = 0.0
    attempts: int = 0
    unschedulable_plugins: Tuple[str, ...] = ()

    @property
    def key(self) -> str:
        return self.pod.key


class SchedulingQueue:
    def __init__(self, clock: Optional[Clock] = None,
                 initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
                 max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
                 less=None, pre_enqueue=None):
        self._clock = clock or Clock()
        self._initial_backoff = initial_backoff
        self._max_backoff = max_backoff
        self._less = less  # (QueuedPodInfo, QueuedPodInfo) -> bool; default priority desc
        # pre_enqueue(pod) -> bool: re-checked on every promotion into activeQ
        # (the reference re-runs PreEnqueue in moveToActiveQ — a gated pod must
        # never reach the active queue via an unrelated cluster event)
        self._pre_enqueue = pre_enqueue
        self._lock = threading.Condition()
        self._seq = itertools.count()
        # activeQ: heap of (sort_key, seq, QueuedPodInfo)
        self._active: List[Tuple] = []
        self._backoff: List[Tuple[float, int, QueuedPodInfo]] = []
        self._unschedulable: Dict[str, QueuedPodInfo] = {}
        self._in_active: Dict[str, QueuedPodInfo] = {}
        self._closed = False

    # -- ordering --------------------------------------------------------------

    def _sort_key(self, qp: QueuedPodInfo):
        # default QueueSort: priority desc, then timestamp asc (priority_sort.go).
        # A custom QueueSort plugin's less() overrides via _LessItem comparison.
        if self._less is not None:
            return _LessItem(qp, self._less)
        return (-qp.pod.spec.priority, qp.timestamp)

    # -- add paths -------------------------------------------------------------

    def add(self, pod: Pod) -> None:
        with self._lock:
            qp = QueuedPodInfo(pod=pod, timestamp=self._clock.now())
            self._push_active(qp)
            self._lock.notify()

    def add_batch(self, pods: List[Pod], pre_gated: bool = False) -> None:
        """Bulk admission for a coalesced watch chunk: ONE lock acquisition
        and one O(n+m) heapify instead of n heappushes (the per-pod adds were
        a top stage of the 100k-backlog ingest). Pop order is identical to n
        add() calls — the heap key (sort_key, seq) is a total order, so heap
        layout doesn't matter. PreEnqueue gating still applies per pod via
        _pre_enqueue (gated pods park in unschedulable, as add() does);
        pre_gated=True skips that re-check when the caller just ran
        PreEnqueue on every pod itself (the coalesced ingest path — add()
        semantics double-run it, microseconds apart, with the same answer)."""
        if not pods:
            return
        with self._lock:
            now = self._clock.now()
            entries = []
            for pod in pods:
                qp = QueuedPodInfo(pod=pod, timestamp=now)
                key = qp.key
                self._unschedulable.pop(key, None)
                if key in self._in_active:
                    continue
                if (not pre_gated and self._pre_enqueue is not None
                        and not self._pre_enqueue(pod)):
                    self._unschedulable[key] = qp  # still gated: stay parked
                    continue
                self._in_active[key] = qp
                entries.append((self._sort_key(qp), next(self._seq), qp))
            if not entries:
                return
            if len(entries) >= len(self._active):
                self._active.extend(entries)
                heapq.heapify(self._active)
            else:
                for e in entries:
                    heapq.heappush(self._active, e)
            self._lock.notify_all()

    def _push_active(self, qp: QueuedPodInfo) -> None:
        self._unschedulable.pop(qp.key, None)
        if qp.key in self._in_active:
            return
        if self._pre_enqueue is not None and not self._pre_enqueue(qp.pod):
            self._unschedulable[qp.key] = qp  # still gated: stay parked
            return
        self._in_active[qp.key] = qp
        heapq.heappush(self._active, (self._sort_key(qp), next(self._seq), qp))

    def add_unschedulable(self, qp: QueuedPodInfo) -> None:
        """AddUnschedulableIfNotPresent (:741): failed pods wait for an event
        (unschedulable map) — backoff applies when they are moved back."""
        with self._lock:
            qp.timestamp = self._clock.now()
            self._unschedulable[qp.key] = qp

    def _backoff_duration(self, attempts: int) -> float:
        d = self._initial_backoff * (2 ** max(attempts - 1, 0))
        return min(d, self._max_backoff)

    def move_all_to_active_or_backoff(self) -> None:
        """MoveAllToActiveOrBackoffQueue (:1028) on a cluster event."""
        self.move_pods_for_event(lambda qp: True)

    def move_pods_for_event(self, should_move) -> None:
        """movePodsToActiveOrBackoffQueue (:1028) gated by QueueingHints:
        should_move(qp) -> bool decides, per unschedulable pod, whether this
        cluster event could make it schedulable (the scheduler derives it from
        the rejecting plugins' hint functions — scheduling_queue.go:263
        QueueingHintMap + podMatchesEvent). Pods that stay are still swept by
        flush_unschedulable_left_over (the reference's safety net)."""
        with self._lock:
            moved = False
            for key, qp in list(self._unschedulable.items()):
                if not should_move(qp):
                    continue
                self._unschedulable.pop(key)
                remaining = self._backoff_remaining(qp)
                if remaining > 0:
                    heapq.heappush(self._backoff, (self._clock.now() + remaining, next(self._seq), qp))
                else:
                    self._push_active(qp)
                moved = True
            if moved:
                self._lock.notify_all()

    def _backoff_remaining(self, qp: QueuedPodInfo) -> float:
        if qp.attempts == 0:
            return 0.0
        expiry = qp.timestamp + self._backoff_duration(qp.attempts)
        return max(0.0, expiry - self._clock.now())

    # -- flush loops (queue.Run :350) ------------------------------------------

    def flush_backoff_completed(self) -> None:
        with self._lock:
            now = self._clock.now()
            moved = False
            while self._backoff and self._backoff[0][0] <= now:
                _, _, qp = heapq.heappop(self._backoff)
                self._push_active(qp)
                moved = True
            if moved:
                self._lock.notify_all()

    def flush_unschedulable_left_over(self) -> None:
        """Pods stuck unschedulable longer than 30s get requeued (:350)."""
        with self._lock:
            now = self._clock.now()
            moved = False
            for key, qp in list(self._unschedulable.items()):
                if now - qp.timestamp > FLUSH_UNSCHEDULABLE_TIMEOUT:
                    self._unschedulable.pop(key)
                    self._push_active(qp)
                    moved = True
            if moved:
                self._lock.notify_all()

    # -- pop -------------------------------------------------------------------

    def pop(self, timeout: Optional[float] = None) -> Optional[QueuedPodInfo]:
        with self._lock:
            while not self._active and not self._closed:
                if not self._lock.wait(timeout=timeout):
                    return None
            if self._closed and not self._active:
                return None
            _, _, qp = heapq.heappop(self._active)
            self._in_active.pop(qp.key, None)
            qp.attempts += 1
            return qp

    def pop_batch(self, max_n: int, timeout: Optional[float] = None) -> List[QueuedPodInfo]:
        """Drain up to max_n pods for a batched TPU solve (the batching analog of
        the one-pod Pop the serial loop uses)."""
        out: List[QueuedPodInfo] = []
        first = self.pop(timeout=timeout)
        if first is None:
            return out
        out.append(first)
        with self._lock:
            if len(self._active) + 1 <= max_n:
                # draining everything: one Timsort beats n heappops and pops
                # in the identical (sort_key, seq) total order
                drained = sorted(self._active)
                self._active = []
                for _, _, qp in drained:
                    self._in_active.pop(qp.key, None)
                    qp.attempts += 1
                    out.append(qp)
                return out
            while self._active and len(out) < max_n:
                _, _, qp = heapq.heappop(self._active)
                self._in_active.pop(qp.key, None)
                qp.attempts += 1
                out.append(qp)
        return out

    # -- removal / updates -----------------------------------------------------

    def update(self, pod: Pod) -> bool:
        """Pod MODIFIED while queued. Only a spec change can affect schedulability
        (reference: eventhandlers.go updatePodInSchedulingQueue + util.PodChanged);
        status-only patches (e.g. our own PodScheduled condition write) must NOT
        requeue, or every failure would loop pod->patch->event->retry forever.
        Returns True if the pod was known to the queue."""
        with self._lock:
            key = pod.key
            tracked = None
            if key in self._in_active:
                tracked = self._in_active[key]
            else:
                for _, _, qp in self._backoff:
                    if qp.key == key:
                        tracked = qp
                        break
                if tracked is None:
                    tracked = self._unschedulable.get(key)
            if tracked is None:
                return False
            # status-only writes don't requeue (our own PodScheduled
            # condition would loop) — EXCEPT resourceClaimStatuses: the
            # resourceclaim controller's stamp resolves template claim
            # references, which gates schedulability exactly like spec
            spec_changed = (tracked.pod.spec != pod.spec
                            or tracked.pod.status.resource_claim_statuses
                            != pod.status.resource_claim_statuses)
            tracked.pod = pod
            if spec_changed:
                if key in self._unschedulable:
                    self._unschedulable.pop(key)
                    remaining = self._backoff_remaining(tracked)
                    if remaining > 0:
                        heapq.heappush(self._backoff, (self._clock.now() + remaining,
                                                       next(self._seq), tracked))
                    else:
                        self._push_active(tracked)
                        self._lock.notify()
                elif key in self._in_active:
                    # Re-sort: the heap key was computed at push time; a spec
                    # change (e.g. priority) must change pop order.
                    self._in_active.pop(key)
                    self._active = [(k, s, q) for k, s, q in self._active if q.key != key]
                    heapq.heapify(self._active)
                    self._push_active(tracked)
                    self._lock.notify()
            return True

    def delete(self, pod: Pod) -> None:
        self.delete_key(pod.key)

    def delete_key(self, key: str) -> None:
        with self._lock:
            self._unschedulable.pop(key, None)
            if key in self._in_active:
                self._in_active.pop(key)
                self._active = [(k, s, qp) for k, s, qp in self._active if qp.key != key]
                heapq.heapify(self._active)
            self._backoff = [(t, s, qp) for t, s, qp in self._backoff if qp.key != key]
            heapq.heapify(self._backoff)

    def tracked_keys(self) -> List[str]:
        """Keys of every pod the queue knows, across all three tiers."""
        with self._lock:
            return (list(self._in_active)
                    + [qp.key for _, _, qp in self._backoff]
                    + list(self._unschedulable))

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    # -- introspection ---------------------------------------------------------

    def lengths(self) -> Tuple[int, int, int]:
        with self._lock:
            return len(self._active), len(self._backoff), len(self._unschedulable)
