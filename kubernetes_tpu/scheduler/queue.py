"""3-tier scheduling queue: activeQ + backoffQ + unschedulablePods.

reference: pkg/scheduler/backend/queue/scheduling_queue.go — PriorityQueue :154,
AddUnschedulableIfNotPresent :741, flushBackoffQCompleted :790, Pop :829 (blocks),
MoveAllToActiveOrBackoffQueue :1028; backoff_queue.go:64 (initial 1s, max 10s);
flush cadence: backoff every 1s, unschedulable every 30s (:350).

QueueingHints are simplified to event-kind gating: on a cluster event, all
unschedulable pods move to backoff/active (the pre-hints behavior); per-plugin
hint functions can be layered on later without changing this interface.

Gang gating (scheduler/gang.py): with gang hooks installed, members of a
PodGroup are held in a STAGING area — a fourth tier next to active/backoff/
unschedulable — until the group reaches quorum (staged + already-placed >=
min_member), then the whole gang is admitted contiguously (one timestamp,
consecutive seqs) so a single solver batch sees it together. A failed gang
re-enters through add_gang_backoff as a unit: one shared expiry, so the
members re-stage and re-admit together.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api import Pod
from ..utils import Clock

DEFAULT_POD_INITIAL_BACKOFF = 1.0  # seconds (scheduler.go:252)
DEFAULT_POD_MAX_BACKOFF = 10.0  # seconds (scheduler.go:253)
FLUSH_UNSCHEDULABLE_TIMEOUT = 30.0  # scheduling_queue.go:91


class _LessItem:
    """Adapts a QueueSort plugin's less(a, b) into a heap sort key."""

    __slots__ = ("qp", "less")

    def __init__(self, qp, less):
        self.qp = qp
        self.less = less

    def __lt__(self, other):
        return self.less(self.qp, other.qp)

    def __eq__(self, other):
        return not self.less(self.qp, other.qp) and not self.less(other.qp, self.qp)


@dataclass
class QueuedPodInfo:
    """reference: framework types.go:362 QueuedPodInfo."""

    pod: Pod
    timestamp: float = 0.0
    attempts: int = 0
    unschedulable_plugins: Tuple[str, ...] = ()
    # first-admission time, NEVER reset by requeues (timestamp is): the
    # submit->bound latency the pod tracer observes spans every retry. Set
    # from the admission batch's shared clock read — no per-pod clock calls.
    submit_ts: float = 0.0
    # the pod's live PodSpan when it is in the tracer's sample, linked at
    # batch-pop time (scheduler/podtrace.py): the bind worker's per-chunk
    # pass then pays ONE attribute read per pod instead of a key build +
    # set lookup. None for the unsampled ~100%.
    trace_span: object = field(default=None, repr=False, compare=False)

    def __post_init__(self):
        if not self.submit_ts:
            self.submit_ts = self.timestamp

    @property
    def key(self) -> str:
        return self.pod.key


class SchedulingQueue:
    def __init__(self, clock: Optional[Clock] = None,
                 initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
                 max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
                 less=None, pre_enqueue=None):
        self._clock = clock or Clock()
        self._initial_backoff = initial_backoff
        self._max_backoff = max_backoff
        self._less = less  # (QueuedPodInfo, QueuedPodInfo) -> bool; default priority desc
        # pre_enqueue(pod) -> bool: re-checked on every promotion into activeQ
        # (the reference re-runs PreEnqueue in moveToActiveQ — a gated pod must
        # never reach the active queue via an unrelated cluster event)
        self._pre_enqueue = pre_enqueue
        self._lock = threading.Condition()
        self._seq = itertools.count()
        # activeQ: heap of (sort_key, seq, QueuedPodInfo)
        self._active: List[Tuple] = []
        self._backoff: List[Tuple[float, int, QueuedPodInfo]] = []
        # key -> entry count in _backoff (duplicates possible transiently):
        # keeps contains() O(1) — the partitioned dispatch layer (ISSUE 12)
        # probes membership once per foreign bound-pod event, which must not
        # cost an O(backoff) scan under chaos backlogs
        self._backoff_keys: Dict[str, int] = {}
        self._unschedulable: Dict[str, QueuedPodInfo] = {}
        self._in_active: Dict[str, QueuedPodInfo] = {}
        self._closed = False
        # gang staging (scheduler/gang.py): group key -> {pod key: qp}. Hooks
        # are installed by the batch scheduler via set_gang_hooks; without
        # them (or while gang_active() is False) every gang path is skipped.
        self._gang_of = None  # (pod) -> Optional[str]
        self._gang_ready = None  # (group, staged_count) -> bool
        self._gang_active = None  # () -> bool
        self._gang_staging: Dict[str, Dict[str, QueuedPodInfo]] = {}
        # parked-gang retry tier (ISSUE 14): gangs whose victim cover fired
        # wait HERE — off the active/backoff heaps — until the preemptor
        # releases them (victims observed deleted, or its deadline sweep).
        # A parked member is still pending for the conservation invariant
        # (tracked_keys / telemetry cover this tier).
        self._gang_parked: Dict[str, Dict[str, QueuedPodInfo]] = {}
        # stage-timing sink (a FlightRecorder, installed by BatchScheduler):
        # bulk-admission wall time accrues to its "queue_add" bucket so the
        # batch pipeline's stage table can attribute ingest sub-stages
        self.stat_sink = None
        # lifecycle-trace sink (a PodTracer, installed by BatchScheduler):
        # notified once per admission batch — AFTER the queue lock releases —
        # with the freshly-admitted QueuedPodInfos for reservoir sampling
        self.trace_sink = None

    def set_gang_hooks(self, gang_of, gang_ready, gang_active) -> None:
        """Install gang gating: gang_of(pod) names the pod's group (None for
        non-members), gang_ready(group, staged) decides quorum, gang_active()
        is the batch-level fast-out (False until any PodGroup exists, so
        gang-free clusters pay one call per admission batch)."""
        with self._lock:
            self._gang_of = gang_of
            self._gang_ready = gang_ready
            self._gang_active = gang_active

    # -- ordering --------------------------------------------------------------

    def _sort_key(self, qp: QueuedPodInfo):
        # default QueueSort: priority desc, then timestamp asc (priority_sort.go).
        # A custom QueueSort plugin's less() overrides via _LessItem comparison.
        if self._less is not None:
            return _LessItem(qp, self._less)
        return (-qp.pod.spec.priority, qp.timestamp)

    # -- add paths -------------------------------------------------------------

    def add(self, pod: Pod) -> None:
        with self._lock:
            qp = QueuedPodInfo(pod=pod, timestamp=self._clock.now())
            self._push_active(qp)
            self._lock.notify()
        ts = self.trace_sink
        if ts is not None:
            ts.admitted((qp,))

    def add_batch(self, pods: List[Pod], pre_gated: bool = False) -> None:
        """Bulk admission for a coalesced watch chunk: ONE lock acquisition
        and one O(n+m) heapify instead of n heappushes (the per-pod adds were
        a top stage of the 100k-backlog ingest). Pop order is identical to n
        add() calls — the heap key (sort_key, seq) is a total order, so heap
        layout doesn't matter. PreEnqueue gating still applies per pod via
        _pre_enqueue (gated pods park in unschedulable, as add() does);
        pre_gated=True skips that re-check when the caller just ran
        PreEnqueue on every pod itself (the coalesced ingest path — add()
        semantics double-run it, microseconds apart, with the same answer)."""
        if not pods:
            return
        sink = self.stat_sink
        if sink is not None and sink.enabled:
            import time as _time

            admitted = []
            t0 = _time.perf_counter()
            try:
                admitted = self._add_batch_locked(pods, pre_gated)
            finally:
                t1 = _time.perf_counter()
                sink.add_outside("queue_add", t1 - t0)
                from ..server import metrics as m

                m.batch_stage_duration.observe(t1 - t0, "queue_add")
                sink.note_self_time(_time.perf_counter() - t1)
        else:
            admitted = self._add_batch_locked(pods, pre_gated)
        ts = self.trace_sink
        if ts is not None and admitted:
            # reservoir sampling at admission (scheduler/podtrace.py), with
            # the queue lock already released; the tracer accounts its own
            # self-time against the recorder budget
            ts.admitted(admitted)

    def _add_batch_locked(self, pods: List[Pod],
                          pre_gated: bool) -> List[QueuedPodInfo]:
        with self._lock:
            now = self._clock.now()
            gang_of = (self._gang_of if self._gang_active is not None
                       and self._gang_active() else None)
            entries = []
            for pod in pods:
                qp = QueuedPodInfo(pod=pod, timestamp=now)
                key = qp.key
                self._unschedulable.pop(key, None)
                if key in self._in_active:
                    continue
                if (not pre_gated and self._pre_enqueue is not None
                        and not self._pre_enqueue(pod)):
                    self._unschedulable[key] = qp  # still gated: stay parked
                    continue
                if gang_of is not None:
                    group = gang_of(pod)
                    if group is not None:
                        for m in self._gang_stage(group, qp):
                            self._in_active[m.key] = m
                            entries.append((self._sort_key(m),
                                            next(self._seq), m))
                        continue
                self._in_active[key] = qp
                entries.append((self._sort_key(qp), next(self._seq), qp))
            if not entries:
                return []
            if len(entries) >= len(self._active):
                self._active.extend(entries)
                heapq.heapify(self._active)
            else:
                for e in entries:
                    heapq.heappush(self._active, e)
            self._lock.notify_all()
            return [e[2] for e in entries]

    def _push_active(self, qp: QueuedPodInfo) -> None:
        self._unschedulable.pop(qp.key, None)
        if qp.key in self._in_active:
            return
        if self._pre_enqueue is not None and not self._pre_enqueue(qp.pod):
            self._unschedulable[qp.key] = qp  # still gated: stay parked
            return
        if self._gang_active is not None and self._gang_active():
            group = self._gang_of(qp.pod)
            if group is not None:
                for m in self._gang_stage(group, qp):
                    self._heap_push(m)
                return
        self._heap_push(qp)

    def _heap_push(self, qp: QueuedPodInfo) -> None:
        self._in_active[qp.key] = qp
        heapq.heappush(self._active, (self._sort_key(qp), next(self._seq), qp))

    def _backoff_push(self, ready: float, qp: QueuedPodInfo) -> None:
        heapq.heappush(self._backoff, (ready, next(self._seq), qp))
        self._backoff_keys[qp.key] = self._backoff_keys.get(qp.key, 0) + 1

    def _backoff_key_drop(self, key: str) -> None:
        n = self._backoff_keys.get(key, 0) - 1
        if n <= 0:
            self._backoff_keys.pop(key, None)
        else:
            self._backoff_keys[key] = n

    # -- gang staging (scheduler/gang.py) --------------------------------------

    def _gang_stage(self, group: str, qp: QueuedPodInfo) -> List[QueuedPodInfo]:
        """Park one gang member in staging; returns the members to admit NOW
        ([] while the group is below quorum). Admitted members share one
        timestamp, so with equal priorities the (sort_key, seq) total order
        pops them contiguously — one solver batch sees the whole gang."""
        self._gang_staging.setdefault(group, {})[qp.key] = qp
        return self._gang_collect(group, requester=qp)

    def _gang_collect(self, group: str,
                      requester: Optional[QueuedPodInfo] = None
                      ) -> List[QueuedPodInfo]:
        staged = self._gang_staging.get(group)
        if (not staged or self._gang_ready is None
                or not self._gang_ready(group, len(staged))):
            return []
        if self._pre_enqueue is not None:
            # gates may have closed on members staged earlier; a newly-gated
            # member breaks quorum and the gang keeps waiting (the reference
            # re-runs PreEnqueue on every promotion into activeQ)
            for key, m in list(staged.items()):
                if m is requester:
                    continue
                if not self._pre_enqueue(m.pod):
                    staged.pop(key)
                    self._unschedulable[key] = m
            if not staged or not self._gang_ready(group, len(staged)):
                if not staged:
                    self._gang_staging.pop(group, None)
                return []
        self._gang_staging.pop(group, None)
        now = self._clock.now()
        members = list(staged.values())
        for m in members:
            m.timestamp = now
        return members

    def reconsider_gangs(self) -> None:
        """Re-evaluate every staged group's quorum — called on PodGroup
        events (a created/raised-quorum PodGroup can unblock members that
        arrived before it)."""
        with self._lock:
            moved = False
            for group in list(self._gang_staging):
                for m in self._gang_collect(group):
                    self._heap_push(m)
                    moved = True
            if moved:
                self._lock.notify_all()

    def park_gang(self, group: str, members: List[QueuedPodInfo]) -> None:
        """Park a preempting gang (ISSUE 14): its victim cover was selected
        and the deletions are in flight — the members wait OUT of every
        retry loop until release_parked_gang moves them back (the preemptor
        calls it when the last victim's DELETED event lands, or from its
        deadline sweep when deletions stall). One gang, one parking slot:
        re-parking replaces (members are the same objects)."""
        if not members:
            return
        with self._lock:
            slot = self._gang_parked.setdefault(group, {})
            for m in members:
                slot[m.key] = m

    def release_parked_gang(self, group: str) -> int:
        """Move a parked gang back through the normal admission path: the
        members re-stage under their group (gang hooks installed), reach
        quorum together, and admit contiguously — the same all-at-once
        re-entry add_gang_backoff gives a vetoed gang, without the backoff
        wait. Returns the number of members released."""
        with self._lock:
            slot = self._gang_parked.pop(group, None)
            if not slot:
                return 0
            now = self._clock.now()
            for m in slot.values():
                m.timestamp = now
                self._push_active(m)
            self._lock.notify_all()
            return len(slot)

    def parked_gang_groups(self) -> List[str]:
        with self._lock:
            return list(self._gang_parked)

    def add_gang_backoff(self, members: List[QueuedPodInfo]) -> None:
        """Requeue a failed gang as a UNIT: every member enters the backoff
        queue under ONE shared expiry (the slowest member's backoff), so the
        gang re-stages and re-admits together when it fires — never member by
        member through the unschedulable map."""
        if not members:
            return
        with self._lock:
            now = self._clock.now()
            dur = max(self._backoff_duration(m.attempts) for m in members)
            ready = now + dur
            for m in members:
                m.timestamp = now
                self._backoff_push(ready, m)

    def add_backoff(self, qps: List[QueuedPodInfo]) -> None:
        """Transient-error requeue (ISSUE 6 failure domains): straight into
        the backoff tier with a per-pod expiry from its attempt count —
        unlike add_unschedulable, no cluster event is needed before the
        retry, which is right for infrastructure faults (a solver crash, a
        store hiccup) where the POD is fine and the retry just needs
        breathing room."""
        if not qps:
            return
        with self._lock:
            now = self._clock.now()
            for qp in qps:
                qp.timestamp = now
                self._backoff_push(
                    now + self._backoff_duration(qp.attempts), qp)

    def add_unschedulable(self, qp: QueuedPodInfo) -> None:
        """AddUnschedulableIfNotPresent (:741): failed pods wait for an event
        (unschedulable map) — backoff applies when they are moved back."""
        with self._lock:
            qp.timestamp = self._clock.now()
            self._unschedulable[qp.key] = qp

    def _backoff_duration(self, attempts: int) -> float:
        d = self._initial_backoff * (2 ** max(attempts - 1, 0))
        return min(d, self._max_backoff)

    def move_all_to_active_or_backoff(self) -> None:
        """MoveAllToActiveOrBackoffQueue (:1028) on a cluster event."""
        self.move_pods_for_event(lambda qp: True)

    def move_pods_for_event(self, should_move) -> None:
        """movePodsToActiveOrBackoffQueue (:1028) gated by QueueingHints:
        should_move(qp) -> bool decides, per unschedulable pod, whether this
        cluster event could make it schedulable (the scheduler derives it from
        the rejecting plugins' hint functions — scheduling_queue.go:263
        QueueingHintMap + podMatchesEvent). Pods that stay are still swept by
        flush_unschedulable_left_over (the reference's safety net)."""
        with self._lock:
            moved = False
            for key, qp in list(self._unschedulable.items()):
                if not should_move(qp):
                    continue
                self._unschedulable.pop(key)
                remaining = self._backoff_remaining(qp)
                if remaining > 0:
                    self._backoff_push(self._clock.now() + remaining, qp)
                else:
                    self._push_active(qp)
                moved = True
            if moved:
                self._lock.notify_all()

    def _backoff_remaining(self, qp: QueuedPodInfo) -> float:
        if qp.attempts == 0:
            return 0.0
        expiry = qp.timestamp + self._backoff_duration(qp.attempts)
        return max(0.0, expiry - self._clock.now())

    # -- flush loops (queue.Run :350) ------------------------------------------

    def flush_backoff_completed(self) -> None:
        with self._lock:
            now = self._clock.now()
            moved = False
            while self._backoff and self._backoff[0][0] <= now:
                _, _, qp = heapq.heappop(self._backoff)
                self._backoff_key_drop(qp.key)
                self._push_active(qp)
                moved = True
            if moved:
                self._lock.notify_all()

    def flush_unschedulable_left_over(self) -> None:
        """Pods stuck unschedulable longer than 30s get requeued (:350)."""
        with self._lock:
            now = self._clock.now()
            moved = False
            for key, qp in list(self._unschedulable.items()):
                if now - qp.timestamp > FLUSH_UNSCHEDULABLE_TIMEOUT:
                    self._unschedulable.pop(key)
                    self._push_active(qp)
                    moved = True
            # gang staging safety net: members of a group with NO PodGroup
            # (quorum hook returns None — deleted, or never created) must
            # not be stranded; after the same 30s window they release as
            # ORDINARY pods. Groups with a live PodGroup below quorum keep
            # waiting — releasing those would break all-or-nothing.
            released = 0
            for group in list(self._gang_staging):
                staged = self._gang_staging[group]
                if (self._gang_ready is None
                        or self._gang_ready(group, len(staged)) is not None):
                    continue
                for key, qp in list(staged.items()):
                    if now - qp.timestamp > FLUSH_UNSCHEDULABLE_TIMEOUT:
                        staged.pop(key)
                        self._heap_push(qp)
                        moved = True
                        released += 1
                if not staged:
                    self._gang_staging.pop(group, None)
            if moved:
                self._lock.notify_all()
        if released:
            from ..server import metrics as m

            m.gang_orphan_released_total.inc(released)

    # -- pop -------------------------------------------------------------------

    def pop(self, timeout: Optional[float] = None) -> Optional[QueuedPodInfo]:
        with self._lock:
            while not self._active and not self._closed:
                if not self._lock.wait(timeout=timeout):
                    return None
            if self._closed and not self._active:
                return None
            _, _, qp = heapq.heappop(self._active)
            self._in_active.pop(qp.key, None)
            qp.attempts += 1
            return qp

    def pop_batch(self, max_n: int, timeout: Optional[float] = None) -> List[QueuedPodInfo]:
        """Drain up to max_n pods for a batched TPU solve (the batching analog of
        the one-pod Pop the serial loop uses)."""
        out: List[QueuedPodInfo] = []
        first = self.pop(timeout=timeout)
        if first is None:
            return out
        out.append(first)
        with self._lock:
            if len(self._active) + 1 <= max_n:
                # draining everything: one Timsort beats n heappops and pops
                # in the identical (sort_key, seq) total order
                drained = sorted(self._active)
                self._active = []
                for _, _, qp in drained:
                    self._in_active.pop(qp.key, None)
                    qp.attempts += 1
                    out.append(qp)
                return out
            while self._active and len(out) < max_n:
                _, _, qp = heapq.heappop(self._active)
                self._in_active.pop(qp.key, None)
                qp.attempts += 1
                out.append(qp)
        return out

    # -- removal / updates -----------------------------------------------------

    def update(self, pod: Pod) -> bool:
        """Pod MODIFIED while queued. Only a spec change can affect schedulability
        (reference: eventhandlers.go updatePodInSchedulingQueue + util.PodChanged);
        status-only patches (e.g. our own PodScheduled condition write) must NOT
        requeue, or every failure would loop pod->patch->event->retry forever.
        Returns True if the pod was known to the queue."""
        with self._lock:
            key = pod.key
            tracked = None
            staged_in = None
            if key in self._in_active:
                tracked = self._in_active[key]
            else:
                for _, _, qp in self._backoff:
                    if qp.key == key:
                        tracked = qp
                        break
                if tracked is None:
                    tracked = self._unschedulable.get(key)
                if tracked is None:
                    for group, staged in self._gang_staging.items():
                        if key in staged:
                            tracked = staged[key]
                            staged_in = group
                            break
                if tracked is None:
                    # parked for a victim cover: keep the object fresh but
                    # stay parked — the preemptor's release/deadline owns
                    # when this gang re-enters the admission path
                    for parked in self._gang_parked.values():
                        if key in parked:
                            tracked = parked[key]
                            break
            if tracked is None:
                return False
            # status-only writes don't requeue (our own PodScheduled
            # condition would loop) — EXCEPT resourceClaimStatuses: the
            # resourceclaim controller's stamp resolves template claim
            # references, which gates schedulability exactly like spec
            spec_changed = (tracked.pod.spec != pod.spec
                            or tracked.pod.status.resource_claim_statuses
                            != pod.status.resource_claim_statuses)
            labels_changed = tracked.pod.metadata.labels != pod.metadata.labels
            tracked.pod = pod
            if (spec_changed or labels_changed) and staged_in is not None:
                # a spec or label change while staged (labels carry gang
                # membership): route the member back through _push_active so
                # it re-stages under its current group (or leaves staging if
                # no longer a member)
                staged = self._gang_staging.get(staged_in)
                if staged is not None:
                    staged.pop(key, None)
                    if not staged:
                        self._gang_staging.pop(staged_in, None)
                self._push_active(tracked)
                self._lock.notify()
                return True
            if spec_changed:
                if key in self._unschedulable:
                    self._unschedulable.pop(key)
                    remaining = self._backoff_remaining(tracked)
                    if remaining > 0:
                        self._backoff_push(self._clock.now() + remaining,
                                           tracked)
                    else:
                        self._push_active(tracked)
                        self._lock.notify()
                elif key in self._in_active:
                    # Re-sort: the heap key was computed at push time; a spec
                    # change (e.g. priority) must change pop order.
                    self._in_active.pop(key)
                    self._active = [(k, s, q) for k, s, q in self._active if q.key != key]
                    heapq.heapify(self._active)
                    self._push_active(tracked)
                    self._lock.notify()
            return True

    def delete(self, pod: Pod) -> None:
        self.delete_key(pod.key)

    def delete_key(self, key: str) -> None:
        with self._lock:
            self._unschedulable.pop(key, None)
            for group in list(self._gang_staging):
                staged = self._gang_staging[group]
                if staged.pop(key, None) is not None and not staged:
                    self._gang_staging.pop(group, None)
            for group in list(self._gang_parked):
                parked = self._gang_parked[group]
                if parked.pop(key, None) is not None and not parked:
                    self._gang_parked.pop(group, None)
            if key in self._in_active:
                self._in_active.pop(key)
                self._active = [(k, s, qp) for k, s, qp in self._active if qp.key != key]
                heapq.heapify(self._active)
            if key in self._backoff_keys:
                self._backoff = [(t, s, qp) for t, s, qp in self._backoff
                                 if qp.key != key]
                heapq.heapify(self._backoff)
                self._backoff_keys.pop(key, None)

    def clear(self) -> None:
        """Drop every queued pod across ALL tiers (crash-resync support:
        resync_from_store repopulates from a fresh LIST — a restarted
        scheduler has no memory of attempts or backoff)."""
        with self._lock:
            self._active.clear()
            self._backoff.clear()
            self._backoff_keys.clear()
            self._unschedulable.clear()
            self._in_active.clear()
            self._gang_staging.clear()
            self._gang_parked.clear()

    def contains(self, key: str) -> bool:
        """O(1) membership probe across every tier (active/backoff/
        unschedulable; gang staging is a small dict-of-dicts scan). The
        partitioned dispatch layer (ISSUE 12) calls this once per FOREIGN
        bound-pod event to clean up a stale local entry after losing a
        cross-partition race — it must never cost an O(queue) scan."""
        with self._lock:
            if (key in self._in_active or key in self._unschedulable
                    or key in self._backoff_keys):
                return True
            return (any(key in staged
                        for staged in self._gang_staging.values())
                    or any(key in parked
                           for parked in self._gang_parked.values()))

    def add_requeued(self, qps: List[QueuedPodInfo]) -> None:
        """Admit EXISTING QueuedPodInfos straight into the active tier,
        preserving their attempt counts and (crucially) submit_ts — the
        partitioned dispatch layer re-routes a pod that proved infeasible in
        one node shard to the next partition's queue through here. No
        backoff: the pod is not unschedulable, it was offered the wrong
        shard, and the hop count (PartitionRouter) bounds the re-routing so
        this cannot livelock."""
        if not qps:
            return
        with self._lock:
            for qp in qps:
                self._push_active(qp)
            self._lock.notify_all()

    def tracked_keys(self) -> List[str]:
        """Keys of every pod the queue knows, across all three tiers."""
        with self._lock:
            return (list(self._in_active)
                    + [qp.key for _, _, qp in self._backoff]
                    + list(self._unschedulable)
                    + [k for staged in self._gang_staging.values()
                       for k in staged]
                    + [k for parked in self._gang_parked.values()
                       for k in parked])

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    # -- introspection ---------------------------------------------------------

    def lengths(self) -> Tuple[int, int, int]:
        """(active, backoff, unschedulable); gang members waiting in staging
        or parked for a victim cover count as unschedulable — they are
        parked waiting, the same observable meaning."""
        with self._lock:
            staged = sum(len(s) for s in self._gang_staging.values())
            parked = sum(len(s) for s in self._gang_parked.values())
            return (len(self._active), len(self._backoff),
                    len(self._unschedulable) + staged + parked)

    def gang_staged_count(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._gang_staging.values())

    def gang_parked_count(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._gang_parked.values())

    def depths(self) -> Dict[str, int]:
        """Per-tier depth dict WITHOUT the O(queue) oldest-age scan
        telemetry() pays — the window-close probe (obs/timeseries.py) reads
        this every few seconds unthrottled, so it must stay O(tiers)."""
        with self._lock:
            return {"active": len(self._active),
                    "backoff": len(self._backoff),
                    "unschedulable": len(self._unschedulable),
                    "gang_staged": sum(len(s)
                                       for s in self._gang_staging.values()),
                    "gang_parked": sum(len(s)
                                       for s in self._gang_parked.values())}

    def telemetry(self) -> Dict[str, float]:
        """Queue depth by tier plus the age of the oldest pod still waiting
        anywhere (first-admission time, so a pod cycling through backoff
        keeps aging). One O(queue) scan per call — callers update gauges per
        PUMP, throttled (scheduler/batch.py), never per pod."""
        with self._lock:
            now = self._clock.now()
            staged = sum(len(m) for m in self._gang_staging.values())
            parked = sum(len(m) for m in self._gang_parked.values())
            waiting = itertools.chain(
                (qp for _, _, qp in self._active),
                (qp for _, _, qp in self._backoff),
                self._unschedulable.values(),
                (qp for m in self._gang_staging.values()
                 for qp in m.values()),
                (qp for m in self._gang_parked.values()
                 for qp in m.values()))
            oldest = min((qp.submit_ts or qp.timestamp for qp in waiting),
                         default=None)
            return {
                "active": len(self._active),
                "backoff": len(self._backoff),
                "unschedulable": len(self._unschedulable),
                "gang_staged": staged,
                "gang_parked": parked,
                "oldest_pending_age_s": (max(0.0, now - oldest)
                                         if oldest is not None else 0.0),
            }
