"""Partitioned scheduler: N solve pipelines over disjoint node shards.

ISSUE 12 / ROADMAP direction 3 — the first multi-pipeline configuration of
the whole system. N `BatchScheduler` pipelines each own a DISJOINT node
shard (hash or zone partition of the node set) against the ONE shared
store, pulling from a partition-aware dispatch layer:

  routing    Pending pods route by feasibility fingerprint — a cheap spec
             probe (spans_partitions): constraint-spanning pods (inter-pod
             affinity classes, gangs, topology-spread groups) are judged
             against the WHOLE cluster by definition, so they go straight
             to the global residual pass (a shard-limited pipeline could
             ACCEPT a placement that violates a required constraint whose
             witnesses live on another shard — only declines are
             recoverable); with the residual disabled they PIN to the
             designated partition instead (best-effort shard-local
             semantics). Everything else hashes over the live partitions.
             Bound pods route by their node's shard, so each pipeline's
             cache accounts exactly its own nodes — while gang-quorum
             accounting stays cluster-scoped (foreign-shard members still
             feed every pipeline's GangDirectory). CvxCluster (arxiv
             2605.01614) is the shape: the allocation problem decomposes
             into independently-solvable partitions plus a cheap
             reconciliation step.

  pipelines  Each pipeline runs its own ingest→tensorize→solve→assume→bind
             stages on its own thread, with its own cache/tensor snapshots,
             flight recorder, breaker, and bind worker — one partition's
             GIL-held host work overlaps another's GIL-free XLA solve and
             CDLL kernels, which is how this configuration exceeds one
             GIL's worth of throughput without new native code.

  re-route   A pod UNSCHEDULABLE in one shard is not unschedulable in the
             cluster: the reroute hook hands it to the next partition's
             active queue (hop-bounded), and when every shard has declined
             — or the pod spans partitions and the pinned shard declined —
             it falls through to the GLOBAL RESIDUAL PASS: a full-view
             pipeline rebuilt from a consistent LIST that runs between
             partition rounds (the propose-and-repair discipline of
             *Priority Matters*, arxiv 2511.08373: pack per-partition,
             repair the global constraints after).

  conflicts  Cross-partition races are absorbed OPTIMISTICALLY: pipelines
             assume into their private caches without coordination, and the
             store's bind_many is the arbiter — a per-pod "already bound"
             error is a FACT, not a fault (store.is_bind_conflict). The
             losing pipeline forgets its assume and drops the pod; the
             winner's commit is the pod's one true binding. Exactly-once
             binding therefore needs no cross-partition locking at all.

  failure    A partition is a failure domain: a hard-killed pipeline
             (chaos site `partition.dispatch`, or any FaultKill escaping
             its drive loop) is absorbed by the survivors — the router
             remaps the dead shard's slots, and each survivor
             resync_from_store()s under the new routing (the ISSUE 6 crash
             resync), re-adopting the dead partition's nodes and pods. Any
             of the dead pipeline's in-flight binds that still land are
             reconciled through the same conflict machinery.

LOCK DISCIPLINE (schedlint LK001 extension): the dispatch-layer locks —
`PartitionRouter._route_lock` and `PartitionedScheduler._dispatch_lock` —
are LEAF locks, ordered strictly AFTER the store's `_lock` → `_pods_lock`
chain: code holding either may touch only the router/coordinator's own
bookkeeping, NEVER call into the store, a cache, or a queue. (Routing
happens at ingest, where no store lock is held; a store call under a
dispatch lock would invert against every pipeline's commit path.)

`partitions=1` is byte-identical to a standalone BatchScheduler: no gates,
no hooks, no residual — pure delegation (pinned by tests/test_partition.py
across both watch_coalesce modes).
"""

from __future__ import annotations

import threading
import zlib
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..api import Pod
from ..chaos import faultinject as _chaos
from ..chaos.faultinject import FaultInjected, FaultKill
from ..store import APIStore
from .batch import BatchScheduler
from .queue import QueuedPodInfo

LABEL_ZONE = "topology.kubernetes.io/zone"


def _crc(name: str) -> int:
    return zlib.crc32(name.encode("utf-8", "surrogatepass"))


def spans_partitions(pod: Pod) -> bool:
    """The feasibility fingerprint's constraint probe: does placing this pod
    correctly require visibility beyond one node shard? Inter-pod
    (anti-)affinity counts other pods wherever they run, topology spread
    balances across ALL domains, and a gang's all-or-nothing quorum must be
    solved by ONE pipeline. Node-local predicates (node selector/affinity,
    taints, resources, ports, volumes) shard cleanly and return False."""
    spec = pod.spec
    if spec.topology_spread_constraints:
        return True
    a = spec.affinity
    if a is not None and (a.pod_affinity_required or a.pod_affinity_preferred
                          or a.pod_anti_affinity_required
                          or a.pod_anti_affinity_preferred):
        return True
    from ..api.podgroup import pod_group_key

    return bool(pod_group_key(pod))


class PartitionRouter:
    """Shared routing state of the dispatch layer. Thread-safe; every method
    is pure bookkeeping under the LEAF `_route_lock` (see the module
    docstring's lock discipline — no store/cache/queue call is ever made
    while it is held)."""

    def __init__(self, partitions: int, partition_by: str = "hash"):
        if partitions < 1:
            raise ValueError("partitions must be >= 1")
        if partition_by not in ("hash", "zone"):
            raise ValueError(f"unknown partition_by {partition_by!r} "
                             "(want 'hash' or 'zone')")
        self.n = partitions
        self.partition_by = partition_by
        # the dispatch/routing lock — LEAF (schedlint LK001 extension):
        # nothing store/cache/queue-shaped may be called while held
        self._route_lock = threading.Lock()
        # slot -> owning partition index; identity until a partition dies,
        # then the dead partition's slots remap round-robin over survivors
        self._slot_owner: List[int] = list(range(partitions))
        self._alive: List[bool] = [True] * partitions
        # zone mode: node/zone name -> slot, learned from node objects at
        # sync (and node events later); unknown names hash-fallback so a
        # node arriving before its zone is known still routes somewhere
        self._zone_slot: Dict[str, int] = {}
        self._node_slot: Dict[str, int] = {}
        self._next_zone_slot = 0
        # pod key -> (partition, hops): advisory re-route overrides. Safe to
        # lose or clear at any time — double-routing is absorbed by the bind
        # conflict machinery — so this map is pruned aggressively (cleared
        # at coordinator idle) instead of tracked precisely.
        self._overrides: Dict[str, Tuple[int, int]] = {}

    # -- nodes -----------------------------------------------------------------

    def observe_node(self, node) -> int:
        """Learn (zone mode) and return the owning partition of a Node
        OBJECT — the pipelines' node filters call this for every node event
        and LIST row, so the name->slot memo is populated before any bound
        pod on that node needs routing by name."""
        name = node.metadata.name
        if self.partition_by == "zone":
            zone = (node.metadata.labels or {}).get(LABEL_ZONE, "")
            with self._route_lock:
                if zone:
                    slot = self._zone_slot.get(zone)
                    if slot is None:
                        # round-robin assignment in discovery order keeps few
                        # zones balanced (a pure hash could collide all onto
                        # one slot)
                        slot = self._next_zone_slot % self.n
                        self._next_zone_slot += 1
                        self._zone_slot[zone] = slot
                else:
                    slot = _crc(name) % self.n
                self._node_slot[name] = slot
                return self._slot_owner[slot]
        with self._route_lock:
            return self._slot_owner[_crc(name) % self.n]

    def partition_of_node_name(self, name: str) -> int:
        with self._route_lock:
            slot = self._node_slot.get(name)
            if slot is None:
                slot = _crc(name) % self.n
            return self._slot_owner[slot]

    # -- pods ------------------------------------------------------------------

    def partition_of_pod(self, pod: Pod) -> int:
        """The dispatch decision for a PENDING pod: re-route override if one
        is active, else the pinned partition for constraint-spanning pods,
        else the feasibility-fingerprint hash over the slots."""
        key = pod.key
        spanning = spans_partitions(pod)
        with self._route_lock:
            ov = self._overrides.get(key)
            if ov is not None:
                return ov[0]
            if spanning:
                return self._slot_owner[0]  # the designated partition
            return self._slot_owner[_crc(key) % self.n]

    def next_hop(self, pod: Pod, frm: int) -> Optional[int]:
        """One re-route decision: the next live partition for a pod that
        partition `frm` declined, or None when the routing is exhausted (or
        the pod spans partitions — no other shard-limited pipeline can do
        better) and the pod must fall through to the global residual pass.
        Hop-bounded at the live-partition count so re-routing can never
        livelock."""
        if spans_partitions(pod):
            return None
        key = pod.key
        with self._route_lock:
            alive = [i for i, ok in enumerate(self._alive) if ok]
            if len(alive) <= 1:
                self._overrides.pop(key, None)
                return None
            hops = self._overrides.get(key, (frm, 0))[1] + 1
            if hops >= len(alive):
                self._overrides.pop(key, None)
                return None
            pos = alive.index(frm) if frm in alive else 0
            target = alive[(pos + 1) % len(alive)]
            self._overrides[key] = (target, hops)
            return target

    def designated(self) -> int:
        """The live owner of slot 0 — the partition whose gate parks
        spanning pods for the residual pass (exactly one parker), and the
        pin target when the residual is disabled."""
        with self._route_lock:
            return self._slot_owner[0]

    def forget(self, key: str) -> None:
        with self._route_lock:
            self._overrides.pop(key, None)

    def clear_overrides(self) -> None:
        with self._route_lock:
            self._overrides.clear()

    def override_count(self) -> int:
        with self._route_lock:
            return len(self._overrides)

    # -- partition lifecycle ---------------------------------------------------

    def live_partitions(self) -> List[int]:
        with self._route_lock:
            return [i for i, ok in enumerate(self._alive) if ok]

    def absorb(self, dead: int) -> List[int]:
        """Mark a partition dead and remap its slots round-robin over the
        survivors. Returns the survivor indices (callers resync them under
        the new routing). Overrides pointing at the corpse are dropped —
        those pods re-route by their home slot, now owned by a survivor."""
        with self._route_lock:
            self._alive[dead] = False
            alive = [i for i, ok in enumerate(self._alive) if ok]
            if not alive:
                return []
            rr = 0
            for slot, owner in enumerate(self._slot_owner):
                if owner == dead:
                    self._slot_owner[slot] = alive[rr % len(alive)]
                    rr += 1
            for key, (target, _hops) in list(self._overrides.items()):
                if target == dead:
                    del self._overrides[key]
            return alive


class PartitionedScheduler:
    """The coordinator: N BatchScheduler pipelines + the dispatch layer +
    the global residual pass. Mirrors the BatchScheduler driving surface
    (sync / run_until_idle / start / stop / flush_binds / sched_stats /
    resync_from_store) so benches and the control plane can swap it in.

    framework: a Framework for partitions=1, or (partitions>1) a ZERO-ARG
    FACTORY returning a fresh Framework per pipeline — plugins carry
    per-scheduler handles (recorders, preemption state), and sharing one
    instance across pipelines would cross-wire them."""

    MAX_IDLE_ROUNDS = 12  # reroute hops are partition-bounded; this is slack

    def __init__(self, store: APIStore, framework=None, partitions: int = 2,
                 partition_by: str = "hash", profiles=None,
                 residual: bool = True, concurrent: Optional[bool] = None,
                 **kw):
        import os

        self.store = store
        self.partitions = partitions
        self.router = PartitionRouter(partitions, partition_by)
        self._single = partitions == 1
        # concurrent drive (run_until_idle): one thread per pipeline so host
        # work overlaps GIL-free solves — the whole point of the mode — but
        # ONLY when the box has cores to overlap on. On a 1-core rig N
        # CPU-bound threads just thrash the GIL (measured ~25% overhead on
        # the 100k A/B), so the default degrades to round-robin sequential
        # drives: same dispatch/conflict/death semantics, no thrash.
        if concurrent is None:
            try:
                concurrent = len(os.sched_getaffinity(0)) > 1
            except AttributeError:  # platforms without affinity
                concurrent = (os.cpu_count() or 1) > 1
        self.concurrent_drive = bool(concurrent)
        if not self._single and not callable(framework):
            raise ValueError(
                "partitions > 1 needs a zero-arg framework FACTORY (each "
                "pipeline gets its own Framework; plugin handles are "
                "per-scheduler)")
        self._fw_factory = (framework if callable(framework)
                            else (lambda _fw=framework: _fw))
        self._profiles = profiles
        self._kw = dict(kw)
        # coordinator bookkeeping lock — LEAF like the router's (LK001
        # extension): guards the residual parking lot + death records only
        self._dispatch_lock = threading.Lock()
        self._residual_enabled = residual and not self._single
        self._residual: Optional[BatchScheduler] = None
        self._residual_keys: Set[str] = set()
        self._residual_qps: List[QueuedPodInfo] = []
        self._pending_dead: Set[int] = set()
        self._dead: Set[int] = set()
        self.dispatch_faults = 0  # absorbed partition.dispatch fail plans
        self.residual_passes = 0
        self.partitions_absorbed = 0
        self._sup_thread: Optional[threading.Thread] = None
        self._sup_stop = threading.Event()

        self.pipelines: List[BatchScheduler] = []
        for i in range(partitions):
            pipe = self._build_pipeline()
            if not self._single:
                pipe.partition_index = i
                pipe._node_filter = self._make_node_filter(i)
                pipe._pod_gate = self._make_pod_gate(i, pipe)
                pipe.reroute_hook = self._make_reroute_hook(i)
                pipe.conflict_sink = self._make_conflict_sink(i)
            self.pipelines.append(pipe)
        if not self._single:
            # each pipeline skips its PEERS' coalesced bind batches in O(1)
            # (disjoint shards — see serial.py _peer_bind_origins); the
            # residual's origin is excluded: its binds can land on any shard
            for pipe in self.pipelines:
                pipe._peer_bind_origins = frozenset(
                    p._bind_origin for p in self.pipelines if p is not pipe)

    def _build_pipeline(self) -> BatchScheduler:
        if self._profiles is not None:
            return BatchScheduler(self.store, profiles=self._profiles,
                                  **self._kw)
        return BatchScheduler(self.store, self._fw_factory(), **self._kw)

    # -- dispatch-layer closures (one set per pipeline) ------------------------

    def _make_node_filter(self, idx: int) -> Callable:
        router = self.router

        def node_filter(node) -> bool:
            # serial.py passes the Node OBJECT from every LIST row and node
            # event, so zone mode learns name->zone here; hash mode is a
            # pure crc. Chaos can perturb routing only at the coordinator's
            # drive loop, never here (this runs inside ingest).
            return router.observe_node(node) == idx

        return node_filter

    def _make_pod_gate(self, idx: int, pipe: BatchScheduler) -> Callable:
        router = self.router
        from ..store import DELETED

        def gate(etype: str, pod: Pod) -> bool:
            node = pod.spec.node_name
            if node or pod.is_terminal():
                mine = (router.partition_of_node_name(node) == idx if node
                        else router.partition_of_pod(pod) == idx)
                if not mine and pipe.queue.contains(pod.key):
                    # a pod WE still track went bound/terminal through
                    # another partition — the lost-race cleanup (O(1) probe
                    # per foreign event; delete only on a hit)
                    pipe.queue.delete_key(pod.key)
                return mine
            if self._residual_enabled and spans_partitions(pod):
                # constraint-spanning PENDING pods (inter-pod affinity,
                # topology spread, gangs) are judged against the WHOLE
                # cluster by definition — a shard-limited pipeline could
                # ACCEPT a placement that violates a required constraint
                # whose witnesses live on another shard (a wrong accept
                # is final; only declines fall through). They go straight
                # to the global residual pass, parked ONCE by the
                # designated partition's gate (dedup by key); with the
                # residual disabled they pin to the designated partition
                # instead — best-effort, shard-local semantics.
                if etype != DELETED and idx == router.designated():
                    self._park_residual(pod)
                return False
            return router.partition_of_pod(pod) == idx

        return gate

    def _make_reroute_hook(self, idx: int) -> Callable:
        def hook(qp: QueuedPodInfo, _status) -> bool:
            target = self.router.next_hop(qp.pod, idx)
            from ..server import metrics as m

            if target is None:
                # routing exhausted (or constraint-spanning): the global
                # residual pass owns the terminal verdict
                if not self._residual_enabled:
                    return False  # park locally like a standalone scheduler
                with self._dispatch_lock:
                    self._residual_keys.add(qp.pod.key)
                    self._residual_qps.append(qp)
                m.partition_reroutes_total.inc(partition=str(idx),
                                               target="residual")
                return True
            self.pipelines[target].queue.add_requeued([qp])
            m.partition_reroutes_total.inc(partition=str(idx),
                                           target=str(target))
            return True

        return hook

    def _make_conflict_sink(self, idx: int) -> Callable:
        def sink(qp: QueuedPodInfo, _msg: str) -> None:
            from ..server import metrics as m

            m.partition_conflicts_total.inc(partition=str(idx))
            self.router.forget(qp.pod.key)

        return sink

    # -- aggregate counters ----------------------------------------------------

    def _members(self) -> List[BatchScheduler]:
        out = [p for i, p in enumerate(self.pipelines) if i not in self._dead]
        if self._residual is not None:
            out.append(self._residual)
        return out

    @property
    def scheduled_count(self) -> int:
        return sum(p.scheduled_count for p in self._members())

    @property
    def failed_count(self) -> int:
        return sum(p.failed_count for p in self._members())

    @property
    def conflicts_total(self) -> int:
        return sum(p.partition_conflicts for p in self.pipelines)

    @property
    def reroutes_total(self) -> int:
        return sum(p.partition_reroutes for p in self.pipelines)

    def conservation_members(self) -> Tuple[List[BatchScheduler],
                                            Optional[BatchScheduler]]:
        """(live pipelines, residual-or-None) for the pod-conservation
        checker: pipeline caches are DISJOINT (double-accounting across two
        of them is a bug), the residual's cache is a deliberate full MIRROR
        (every bound pod appears there too) and is only checked internally."""
        return ([p for i, p in enumerate(self.pipelines)
                 if i not in self._dead], self._residual)

    # -- lifecycle -------------------------------------------------------------

    def sync(self) -> None:
        for i, pipe in enumerate(self.pipelines):
            if i not in self._dead:
                pipe.sync()

    def flush_binds(self) -> None:
        for p in self._members():
            p.flush_binds()

    def pump_events(self) -> None:
        for p in self._members():
            p.pump_events()

    def flush_queues(self) -> None:
        """Backoff/unschedulable maintenance across every member (what the
        chaos/bench harness drives between waves, mirroring the standalone
        loop's idle-path calls)."""
        for p in self._members():
            p.queue.flush_backoff_completed()
            p.queue.move_all_to_active_or_backoff()

    def attach_resource_sampler(self, sampler) -> None:
        """Forward an obs/resource.py ResourceSampler to every pipeline
        (ISSUE 13): each partition's windows grow resource columns and its
        sched/bind threads register under partition-labeled names
        (p0-sched, p1-bind, ...) so the per-thread CPU attribution can
        judge the partition A/B when the rig has real cores."""
        for p in self._members():
            p.attach_resource_sampler(sampler)

    def take_bind_failures(self) -> List:
        out: List = []
        for p in self._members():
            out.extend(p.take_bind_failures())
        return out

    def resync_from_store(self) -> Dict[str, int]:
        totals = {"nodes": 0, "bound": 0, "pending": 0, "dropped_assumes": 0}
        for p in self._members():
            counts = p.resync_from_store()
            for k in totals:
                totals[k] += counts.get(k, 0)
        return totals

    # -- driving ---------------------------------------------------------------

    def run_until_idle(self, max_cycles: int = 10_000) -> int:
        """Drive every live pipeline concurrently until the whole dispatch
        layer quiesces: pipelines drain their shards (overlapping solve and
        host work across threads), re-routed pods hop between rounds, dead
        partitions are absorbed, and parked residual pods get the global
        pass. Bounded like the standalone run_until_idle — pods in backoff
        stay there (the harness owns flush cadence)."""
        if self._single:
            return self.pipelines[0].run_until_idle(max_cycles)
        total = 0
        for _round in range(self.MAX_IDLE_ROUNDS):
            alive = [i for i in range(len(self.pipelines))
                     if i not in self._dead]
            if not alive:
                break
            cycles = [0] * len(self.pipelines)
            if self.concurrent_drive:
                threads = [
                    threading.Thread(target=self._drive_pipeline,
                                     args=(i, cycles, max_cycles),
                                     daemon=True)
                    for i in alive]
                for t in threads:
                    t.start()
                for idx, t in zip(alive, threads):
                    # per-thread CPU attribution (ISSUE 13): this round's
                    # drive thread IS the partition's scheduling thread;
                    # re-registration points the column at the live thread
                    sam = self.pipelines[idx].resource_sampler
                    if sam is not None:
                        sam.register_thread(f"p{idx}-sched", t)
                for t in threads:
                    t.join()
            else:
                # 1-core degradation: round-robin drives, identical
                # semantics (reroutes/conflicts/kills), no GIL thrash; the
                # bind workers still overlap their pipeline's solve
                for i in alive:
                    self._drive_pipeline(i, cycles, max_cycles)
            total += sum(cycles)
            with self._dispatch_lock:
                newly_dead = set(self._pending_dead)
                self._pending_dead.clear()
            if newly_dead:
                self._absorb_dead(newly_dead)
                continue  # survivors re-drive under the new routing
            self._run_residual_pass()
            if not self._work_remaining():
                break
        # advisory overrides are prunable at idle (double-routing is safe:
        # the conflict machinery absorbs it) — this bounds the map by the
        # in-flight re-routes instead of the run's history
        self.router.clear_overrides()
        return total

    def _drive_pipeline(self, i: int, cycles: List[int],
                        max_cycles: int) -> None:
        pipe = self.pipelines[i]
        n = 0
        try:
            while n < max_cycles:
                try:
                    if _chaos.ACTIVE is not None:
                        # the partition.dispatch chaos site: a fail plan is
                        # an absorbed dispatch hiccup (the cycle retries), a
                        # kill plan is THIS partition's hard death — the
                        # coordinator absorbs the shard (see _absorb_dead)
                        _chaos.ACTIVE.fire("partition.dispatch",
                                           key=f"partition-{i}")
                    if pipe.schedule_batch(timeout=0.0) == 0:
                        pipe.flush_binds()
                        pipe.pump_events()
                        pipe.sweep_expired_assumes()
                        if pipe.schedule_batch(timeout=0.0) == 0:
                            break
                    else:
                        pipe._drain_bind_results()
                    n += 1
                except FaultInjected:
                    self.dispatch_faults += 1
                    n += 1
            # the trailing flush sits INSIDE the kill domain too: a bind-
            # path kill plan (native.commit, store sites) firing here is
            # still this partition's hard death and must be absorbed
            pipe.flush_binds()
        except FaultKill:
            # hard partition death: no flush, no cleanup — exactly what a
            # crashed scheduler process leaves behind. In-flight bind
            # chunks may still land (committed RPCs); the survivors'
            # resync + conflict machinery reconcile either way.
            with self._dispatch_lock:
                self._pending_dead.add(i)
        cycles[i] = n

    def _park_residual(self, pod: Pod) -> None:
        """Hand a pending pod to the global residual pass (deduped by key:
        N events for one pod park it once — the pass re-LISTs anyway, the
        parking is the work signal + admission key)."""
        now = self.pipelines[0].clock.now()
        key = pod.key
        with self._dispatch_lock:
            if key in self._residual_keys:
                return
            self._residual_keys.add(key)
            self._residual_qps.append(QueuedPodInfo(pod=pod, timestamp=now))

    def _parked_count(self) -> int:
        with self._dispatch_lock:
            return len(self._residual_qps)

    def _work_remaining(self) -> bool:
        for i, pipe in enumerate(self.pipelines):
            if i in self._dead:
                continue
            if pipe.queue.lengths()[0] > 0:
                return True  # a re-route landed after that pipeline drained
        with self._dispatch_lock:
            return bool(self._residual_qps)

    # -- the global residual pass ----------------------------------------------

    def _ensure_residual(self) -> BatchScheduler:
        if self._residual is None:
            r = self._build_pipeline()
            r.partition_index = -1  # full view; labeled for observability
            r._pod_gate = self._residual_gate
            self._residual = r
        return self._residual

    def _residual_gate(self, _etype: str, pod: Pod) -> bool:
        if pod.spec.node_name or pod.is_terminal():
            return True  # the residual cache mirrors every node + bound pod
        with self._dispatch_lock:
            return pod.key in self._residual_keys

    def _run_residual_pass(self) -> int:
        """Schedule the parked residual pods against the FULL node set. Runs
        between partition rounds (a serialization point, so its assumes
        rarely race a live pipeline; when they do — background `start()`
        mode — the bind conflict machinery decides, like any cross-partition
        race). Rebuilds from a consistent LIST each pass: the residual
        pipeline holds no watch between passes, so its steady-state cost is
        zero when nothing falls through."""
        with self._dispatch_lock:
            parked = self._residual_qps
            self._residual_qps = []
        if not parked:
            return 0
        r = self._ensure_residual()
        self.residual_passes += 1
        # the LIST re-admits every parked key through _residual_gate; parked
        # QueuedPodInfos are superseded by the fresh LIST rows (attempts
        # reset — the residual is a fresh global verdict, like a restarted
        # scheduler), so the qps themselves are dropped here
        r.resync_from_store()
        handled = r.run_until_idle()
        r.flush_binds()
        if r._watch is not None:
            # no watch between passes: the next pass re-lists anyway, and an
            # idle subscription would just accumulate (then overflow) the
            # whole cluster's events
            r._watch.stop()
            r._watch = None
        # queue snapshot BEFORE the dispatch lock (leaf-lock discipline:
        # no queue/store/cache call may run while it is held)
        still = set(r.queue.tracked_keys())
        with self._dispatch_lock:
            # keys that bound (or went terminal) leave the residual set; a
            # pod the GLOBAL pass declared unschedulable stays parked in the
            # residual queue (its terminal verdict) until an event or the
            # next pass re-lists it
            self._residual_keys &= still | {
                qp.pod.key for qp in self._residual_qps}
        return handled

    # -- partition failure domains ---------------------------------------------

    def _absorb_dead(self, dead: Set[int]) -> None:
        """Survivors adopt a hard-killed partition's shard: remap the
        router, stop the corpse's machinery, then resync every survivor
        from the store under the new routing (bound pods and pending pods
        re-enter per the remapped slots — the ISSUE 6 crash-resync path,
        now cluster-shaped)."""
        from ..server import metrics as m

        for i in sorted(dead):
            if i in self._dead:
                continue  # already absorbed (idempotence)
            self._dead.add(i)
            self.partitions_absorbed += 1
            m.partition_deaths_total.inc(partition=str(i))
            self.router.absorb(i)
            corpse = self.pipelines[i]
            try:
                # a real crash takes the watch and workers with it; binds
                # already queued to the store may still land, which the
                # conflict machinery reconciles
                corpse.stop()
            except Exception:
                pass
        dead_origins = {self.pipelines[i]._bind_origin for i in self._dead}
        for j, pipe in enumerate(self.pipelines):
            if j not in self._dead:
                # the corpse's origin leaves the peer-skip set BEFORE the
                # resync: its in-flight binds that land after the survivor's
                # LIST are on nodes the survivor now OWNS and must be
                # ingested like any foreign bind
                pipe._peer_bind_origins = (pipe._peer_bind_origins
                                           - dead_origins)
                pipe.resync_from_store()

    def kill_partition(self, i: int) -> None:
        """Test/chaos surface: absorb partition i as if its drive thread
        had died hard (the chaos site does this in-band; this entry exists
        for harnesses that drive pipelines directly). Idempotent: a second
        kill of a corpse must not double-count the death or re-resync the
        survivors."""
        with self._dispatch_lock:
            self._pending_dead.discard(i)
            if i in self._dead:
                return
        self._absorb_dead({i})

    # -- background mode -------------------------------------------------------

    def start(self) -> None:
        if self._single:
            self.pipelines[0].start()
            return
        for i, pipe in enumerate(self.pipelines):
            if i not in self._dead:
                pipe.start()
        if self._sup_thread is not None:
            return
        self._sup_stop.clear()

        def supervise():
            while not self._sup_stop.is_set():
                for i, pipe in enumerate(self.pipelines):
                    if i in self._dead:
                        continue
                    t = pipe._thread
                    if t is not None and not t.is_alive():
                        # quiesce the SURVIVORS before the absorb: their
                        # resync_from_store must not race their own running
                        # loops (run_until_idle mode gets this for free —
                        # the drive threads are joined before absorb)
                        for j, other in enumerate(self.pipelines):
                            if j != i and j not in self._dead:
                                other.stop()
                        self._absorb_dead({i})
                        for j, other in enumerate(self.pipelines):
                            if j not in self._dead:
                                other.start()
                with self._dispatch_lock:
                    parked = bool(self._residual_qps)
                if parked:
                    self._run_residual_pass()
                self._sup_stop.wait(0.5)

        self._sup_thread = threading.Thread(target=supervise, daemon=True)
        self._sup_thread.start()

    def stop(self) -> None:
        self._sup_stop.set()
        if self._sup_thread is not None:
            self._sup_thread.join(timeout=2)
            self._sup_thread = None
        for p in self._members():
            p.stop()

    # -- observability ---------------------------------------------------------

    def sched_stats(self) -> Dict:
        """The coordinator's MERGED view: aggregate counters, the dispatch
        layer's routing/conflict/death totals, a merged stage table (totals
        summed; p99 is the per-partition max — a conservative tail), and
        one summary row per partition. Per-partition FULL stats stay on the
        pipelines' own registered sched_stats (each pipeline registers
        itself like any BatchScheduler, so /debug/schedstats and `ktl sched
        stats` render per-partition stage tables for free)."""
        if self._single:
            return self.pipelines[0].sched_stats()
        merged_stages: Dict[str, Dict] = {}
        rows = []
        for i, pipe in enumerate(self.pipelines):
            dead = i in self._dead
            rows.append({
                "index": i,
                "dead": dead,
                "nodes": 0 if dead else pipe.cache.node_count(),
                "scheduled": pipe.scheduled_count,
                "failed": pipe.failed_count,
                "conflicts": pipe.partition_conflicts,
                "reroutes": pipe.partition_reroutes,
                "breaker": pipe.breaker.state,
                "queue": dict(zip(("active", "backoff", "unschedulable"),
                                  pipe.queue.lengths())),
            })
            if dead:
                continue
            for stage, row in pipe.flightrec.stage_table().items():
                got = merged_stages.setdefault(stage, {
                    "total_ms": 0.0, "batches": 0, "p99_ms": None,
                    "overlapped": row.get("overlapped", False)})
                got["total_ms"] = round(got["total_ms"]
                                        + (row.get("total_ms") or 0.0), 3)
                got["batches"] += row.get("batches", 0)
                p99 = row.get("p99_ms")
                if p99 is not None:
                    got["p99_ms"] = max(got["p99_ms"] or 0.0, p99)
        return {
            "partitions": self.partitions,
            "partition_by": self.router.partition_by,
            "concurrent_drive": self.concurrent_drive,
            "live": len(self.router.live_partitions()),
            "scheduled": self.scheduled_count,
            "failed": self.failed_count,
            "conflicts": self.conflicts_total,
            "reroutes": self.reroutes_total,
            "dispatch_faults": self.dispatch_faults,
            "partitions_absorbed": self.partitions_absorbed,
            "residual": {
                "enabled": self._residual_enabled,
                "passes": self.residual_passes,
                "parked": self._parked_count(),
                "scheduled": (self._residual.scheduled_count
                              if self._residual is not None else 0),
            },
            "stages_merged": merged_stages,
            "rows": rows,
        }
