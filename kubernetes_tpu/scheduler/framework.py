"""Scheduler framework: plugin contract, statuses, CycleState, NodeInfo.

Re-provides the Scheduler Framework plugin API (reference:
pkg/scheduler/framework/interface.go — the 11 extension points PreEnqueue,
QueueSort, PreFilter, Filter, PostFilter, PreScore, Score(+Normalize), Reserve,
Permit, PreBind, Bind, PostBind), the Status/code vocabulary (interface.go:186-293),
CycleState (cycle_state.go:48), and NodeInfo/PodInfo (types.go:734/:412).

The serial implementations in scheduler/plugins are the *correctness oracle and
CPU fallback*; the TPU path (ops/) vectorizes the same semantics into
feasibility/cost tensors and is parity-tested against these.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..api import (
    Pod,
    Resource,
    compute_pod_resource_request,
)

MAX_NODE_SCORE = 100  # interface.go:255
MIN_NODE_SCORE = 0


class Code(enum.Enum):
    """Status codes (reference: interface.go:186)."""

    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5
    PENDING = 6


@dataclass
class Status:
    code: Code = Code.SUCCESS
    reasons: Tuple[str, ...] = ()
    plugin: str = ""

    def is_success(self) -> bool:
        return self.code == Code.SUCCESS

    def is_skip(self) -> bool:
        return self.code == Code.SKIP

    def is_rejected(self) -> bool:
        return self.code in (Code.UNSCHEDULABLE, Code.UNSCHEDULABLE_AND_UNRESOLVABLE, Code.PENDING)

    def message(self) -> str:
        return "; ".join(self.reasons)

    @staticmethod
    def success() -> "Status":
        return Status()

    @staticmethod
    def unschedulable(*reasons: str, plugin: str = "") -> "Status":
        return Status(Code.UNSCHEDULABLE, tuple(reasons), plugin)

    @staticmethod
    def unresolvable(*reasons: str, plugin: str = "") -> "Status":
        return Status(Code.UNSCHEDULABLE_AND_UNRESOLVABLE, tuple(reasons), plugin)

    @staticmethod
    def error(*reasons: str, plugin: str = "") -> "Status":
        return Status(Code.ERROR, tuple(reasons), plugin)

    @staticmethod
    def skip(plugin: str = "") -> "Status":
        return Status(Code.SKIP, (), plugin)


SUCCESS = Status.success()


class CycleState:
    """Per-scheduling-cycle typed KV store (reference: cycle_state.go:48)."""

    def __init__(self):
        self._data: Dict[str, Any] = {}
        self.skip_filter_plugins: Set[str] = set()
        self.skip_score_plugins: Set[str] = set()

    def write(self, key: str, value: Any) -> None:
        self._data[key] = value

    def read(self, key: str) -> Any:
        return self._data[key]

    def read_or_none(self, key: str) -> Any:
        return self._data.get(key)

    def clone(self) -> "CycleState":
        cs = CycleState()
        cs._data = {k: (v.clone() if hasattr(v, "clone") else v) for k, v in self._data.items()}
        cs.skip_filter_plugins = set(self.skip_filter_plugins)
        cs.skip_score_plugins = set(self.skip_score_plugins)
        return cs


@dataclass
class PreFilterResult:
    """Optional node-subset fast path (reference: interface.go:841)."""

    node_names: Optional[Set[str]] = None  # None = all nodes

    def merge(self, other: "PreFilterResult") -> "PreFilterResult":
        if self.node_names is None:
            return PreFilterResult(None if other.node_names is None else set(other.node_names))
        if other.node_names is None:
            return PreFilterResult(set(self.node_names))
        return PreFilterResult(self.node_names & other.node_names)

    def all_nodes(self) -> bool:
        return self.node_names is None


class PodInfo:
    """Pod + precomputed scheduling-relevant state (reference: types.go:412)."""

    __slots__ = (
        "pod",
        "request",
        "non_zero_request",
        "required_affinity_terms",
        "required_anti_affinity_terms",
        "preferred_affinity_terms",
        "preferred_anti_affinity_terms",
    )

    def __init__(self, pod: Pod):
        self.pod = pod
        # requests are pure functions of spec, and specs are immutable in
        # practice (every spec change parses a NEW Pod object; structural
        # clones share spec AND this cache via __dict__ copy) — memoizing
        # removes the dominant per-pod cost of cache adds at 100k-bind scale.
        # Consumers treat these Resource objects as read-only.
        cached = pod.__dict__.get("_req_cache")
        if cached is None:
            cached = (compute_pod_resource_request(pod),
                      compute_pod_resource_request(pod, non_zero=True))
            pod.__dict__["_req_cache"] = cached
        self.request, self.non_zero_request = cached
        aff = pod.spec.affinity
        self.required_affinity_terms = tuple(aff.pod_affinity_required) if aff else ()
        self.required_anti_affinity_terms = tuple(aff.pod_anti_affinity_required) if aff else ()
        self.preferred_affinity_terms = tuple(aff.pod_affinity_preferred) if aff else ()
        self.preferred_anti_affinity_terms = tuple(aff.pod_anti_affinity_preferred) if aff else ()


@dataclass
class ImageStateSummary:
    """reference: types.go ImageStateSummary {Size, NumNodes}."""

    size: int
    num_nodes: int


class NodeInfo:
    """Aggregated per-node scheduling state (reference: types.go:734).

    Generation increments on every mutation and drives incremental snapshotting
    (cache.go:186) — the same diff stream the TPU tensorizer consumes.
    """

    __slots__ = (
        "node",
        "pods",
        "pods_with_affinity",
        "pods_with_required_anti_affinity",
        "requested",
        "non_zero_requested",
        "allocatable",
        "used_ports",
        "image_states",
        "generation",
        "col_count",
    )

    def __init__(self, node=None):
        self.node = None
        self.pods: List[PodInfo] = []
        self.pods_with_affinity: List[PodInfo] = []
        self.pods_with_required_anti_affinity: List[PodInfo] = []
        self.requested = Resource()
        self.non_zero_requested = Resource()
        self.allocatable = Resource()
        self.used_ports: Set[Tuple[str, str, int]] = set()  # (hostIP, proto, port)
        self.image_states: Dict[str, ImageStateSummary] = {}
        self.generation = 0
        # Pods held as columnar cache rows (scheduler/cachecols.py) rather
        # than PodInfo objects. Their resources are already folded into
        # `requested`/`non_zero_requested` by the phase-2 scatter; this count
        # keeps pod-population checks (max_pods, tensorizer pod_count) exact
        # without materializing them. Rows are constraint-free by the
        # dispatch gate, so the affinity/port structures never owe entries.
        self.col_count = 0
        if node is not None:
            self.set_node(node)

    def set_node(self, node) -> None:
        self.node = node
        self.allocatable = Resource.from_resource_list(node.status.allocatable)
        # Per-node view of image states; the Cache overwrites num_nodes with the
        # cluster-wide spread count (cache.go createImageStateSummary).
        if node.status.images and not self.image_states:
            self.image_states = {
                nm: ImageStateSummary(size=img.size_bytes, num_nodes=1)
                for img in node.status.images
                for nm in img.names
            }

    def add_pod(self, pod_info: PodInfo) -> None:
        self.pods.append(pod_info)
        if pod_info.required_affinity_terms or pod_info.preferred_affinity_terms or \
           pod_info.required_anti_affinity_terms or pod_info.preferred_anti_affinity_terms:
            self.pods_with_affinity.append(pod_info)
        if pod_info.required_anti_affinity_terms:
            self.pods_with_required_anti_affinity.append(pod_info)
        self.requested.add(pod_info.request)
        self.non_zero_requested.add(pod_info.non_zero_request)
        for port in _host_ports(pod_info.pod):
            self.used_ports.add(port)

    def remove_pod(self, pod: Pod) -> bool:
        uid = pod.metadata.uid
        for i, pi in enumerate(self.pods):
            if pi.pod.metadata.uid == uid:
                self.pods.pop(i)
                self.pods_with_affinity = [p for p in self.pods_with_affinity if p.pod.metadata.uid != uid]
                self.pods_with_required_anti_affinity = [
                    p for p in self.pods_with_required_anti_affinity if p.pod.metadata.uid != uid
                ]
                self.requested.sub(pi.request)
                self.non_zero_requested.sub(pi.non_zero_request)
                for port in _host_ports(pi.pod):
                    self.used_ports.discard(port)
                return True
        return False

    def clone(self) -> "NodeInfo":
        ni = NodeInfo()
        ni.node = self.node
        ni.pods = list(self.pods)
        ni.pods_with_affinity = list(self.pods_with_affinity)
        ni.pods_with_required_anti_affinity = list(self.pods_with_required_anti_affinity)
        ni.requested = self.requested.clone()
        ni.non_zero_requested = self.non_zero_requested.clone()
        ni.allocatable = self.allocatable.clone()
        ni.used_ports = set(self.used_ports)
        ni.image_states = dict(self.image_states)
        ni.generation = self.generation
        ni.col_count = self.col_count
        return ni


def _host_ports(pod: Pod) -> Iterable[Tuple[str, str, int]]:
    for c in pod.spec.containers:
        for p in c.ports:
            if p.host_port > 0:
                yield (p.host_ip or "0.0.0.0", p.protocol or "TCP", p.host_port)


class Snapshot:
    """Immutable per-cycle view of cluster state (reference: backend/cache/snapshot.go:198).

    `changed_names`/`changed_from_gen` carry the incremental-diff provenance
    when the snapshot was derived via `from_prev`: the set of node names whose
    NodeInfo differs from the snapshot at cache generation `changed_from_gen`.
    Consumers holding that predecessor (TensorCache) can requantize exactly
    those rows instead of identity-walking the full node list. A full-built
    snapshot leaves them None (meaning: diff unknown, walk everything).
    """

    def __init__(self, node_infos: Optional[Dict[str, NodeInfo]] = None):
        self.node_info_map: Dict[str, NodeInfo] = node_infos or {}
        self.node_info_list: List[NodeInfo] = list(self.node_info_map.values())
        self._name_index: Dict[str, int] = {
            name: i for i, name in enumerate(self.node_info_map)
        }
        self.have_pods_with_affinity_list: List[NodeInfo] = [
            n for n in self.node_info_list if n.pods_with_affinity
        ]
        self.have_pods_with_required_anti_affinity_list: List[NodeInfo] = [
            n for n in self.node_info_list if n.pods_with_required_anti_affinity
        ]
        self.generation = 0
        self.changed_names: Optional[frozenset] = None
        self.changed_from_gen: Optional[int] = None

    @classmethod
    def from_prev(cls, prev: "Snapshot", changed: Dict[str, NodeInfo]) -> "Snapshot":
        """Derive a snapshot from `prev` with only `changed` nodes replaced.

        Only valid when the NODE SET is unchanged (same names, same order) —
        the cache's dirty-name tracking falls back to a full build on any
        node add/remove/promote. List positions are patched in place via the
        shared name index, so node ordering (and therefore every downstream
        tensor row order) is bit-identical to a full rebuild.
        """
        snap = cls.__new__(cls)
        snap.node_info_map = dict(prev.node_info_map)
        snap.node_info_map.update(changed)
        snap._name_index = prev._name_index  # same node set: shared, immutable
        lst = list(prev.node_info_list)
        affinity_dirty = False
        for name, ni in changed.items():
            old = prev.node_info_list[prev._name_index[name]]
            lst[prev._name_index[name]] = ni
            if (ni.pods_with_affinity or old.pods_with_affinity
                    or ni.pods_with_required_anti_affinity
                    or old.pods_with_required_anti_affinity):
                affinity_dirty = True
        snap.node_info_list = lst
        if affinity_dirty:
            snap.have_pods_with_affinity_list = [n for n in lst if n.pods_with_affinity]
            snap.have_pods_with_required_anti_affinity_list = [
                n for n in lst if n.pods_with_required_anti_affinity
            ]
        else:
            snap.have_pods_with_affinity_list = prev.have_pods_with_affinity_list
            snap.have_pods_with_required_anti_affinity_list = (
                prev.have_pods_with_required_anti_affinity_list
            )
        snap.generation = 0
        snap.changed_names = frozenset(changed)
        snap.changed_from_gen = prev.generation
        return snap

    def get(self, name: str) -> Optional[NodeInfo]:
        return self.node_info_map.get(name)

    def __len__(self) -> int:
        return len(self.node_info_list)


# ---------------------------------------------------------------------------
# Plugin base classes. A plugin implements any subset; the framework runtime
# dispatches by hasattr on these method names.
# ---------------------------------------------------------------------------


class ClusterEventWithHint:
    """reference: framework/interface.go ClusterEventWithHint — an event a
    plugin cares about plus an optional QueueingHintFn. The hint decides
    whether the event could make a pod this plugin rejected schedulable:
    hint(pod, event_obj) -> bool (True = Queue, False = Skip). hint=None means
    always Queue (the pre-hints behavior for that event)."""

    __slots__ = ("resource", "action", "hint")

    def __init__(self, resource: str, action: str, hint=None):
        self.resource = resource  # store kind: "pods", "nodes", storage kinds
        self.action = action  # "add" | "update" | "delete"
        self.hint = hint


class Plugin:
    name: str = "Plugin"

    # PreEnqueue(pod) -> Status
    # pre_filter(state, pod, snapshot) -> (PreFilterResult|None, Status)
    # filter(state, pod, node_info) -> Status
    # post_filter(state, pod, statuses) -> (nominated_node|None, Status)
    # pre_score(state, pod, nodes) -> Status
    # score(state, pod, node_info) -> (int, Status)
    # normalize_score(state, pod, scores: dict) -> Status
    # reserve/unreserve, permit, pre_bind, bind, post_bind
    # add_pod/remove_pod: PreFilterExtensions for incremental state updates

    def events_to_register(self):
        """EnqueueExtensions (interface.go:482): the cluster events that can
        make a pod rejected by this plugin schedulable. Default: none — a
        plugin that never rejects needs no events."""
        return ()


def default_normalize_score(max_priority: int, reverse: bool, scores: Dict[str, int]) -> None:
    """reference: plugins/helper/normalize_score.go DefaultNormalizeScore."""
    max_count = max(scores.values(), default=0)
    if max_count == 0:
        if reverse:
            for k in scores:
                scores[k] = max_priority
        return
    for k, v in scores.items():
        s = max_priority * v // max_count
        scores[k] = max_priority - s if reverse else s
