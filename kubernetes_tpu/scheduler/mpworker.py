"""Multi-process scheduler WORKER entry point (ISSUE 19).

This module is what a `scheduler/mpsched.py` worker process actually runs
— deliberately tiny and numpy-only: no jax, no Framework, no store. A
worker maps three shared-memory column groups read-only
(store/shm.py: the store's live pod columns plus the owner-built batch
and node shards), packs its shard's pending pods onto its shard's nodes,
and reports bind INTENTS — `(batch_row, node_row, rv_snapshot)` integer
triples — back over a bounded queue. Only ints ever cross the boundary
(schedlint MP001: no Pod/PodInfo pickling); the owner process re-validates
every rv snapshot against the live columns and commits through
`store.bind_many`, whose `is_bind_conflict` surfacing absorbs every
cross-process race — exactly-once binding needs zero new shared locks.

Solver: first-fit-decreasing by cpu over (cpu, mem) requests. Constrained
pods (affinity/topology/gang/anything beyond plain requests) never reach
a worker — the owner routes them to its thread-path residual pipeline
(scheduler/partition.py precedent), so FFD here is sound for what it
sees.

Clock contract: round spans are stamped with `time.perf_counter()`
(CLOCK_MONOTONIC on Linux — system-wide, so owner-side tracebuf tracks
`w{i}-sched` are comparable across processes) and `time.process_time()`
deltas carry each worker's genuine CPU burn for the `overlap_cpu_s`
judgment.
"""

from __future__ import annotations

import os
import time
from typing import List, Tuple

# intents per queue put: bounds a single message while letting a round
# stream results before it finishes
INTENT_CHUNK = 1024


def _solve_round(idx: int, pods_r, batch_r, nodes_r):
    """One round: pack my batch rows onto my node rows. Returns
    (intent_chunks, placed, unplaced_batch_rows)."""
    import numpy as np

    for r in (pods_r, batch_r, nodes_r):
        r.refresh()

    nb = batch_r.nrows
    ba = batch_r.arrays
    mine = np.nonzero(ba["worker"][:nb] == idx)[0]

    nn = nodes_r.nrows
    na = nodes_r.arrays
    my_nodes = np.nonzero(na["worker"][:nn] == idx)[0]
    free_cpu = (na["alloc_cpu"][my_nodes] - na["used_cpu"][my_nodes]).copy()
    free_mem = (na["alloc_mem"][my_nodes] - na["used_mem"][my_nodes]).copy()
    free_pods = (na["alloc_pods"][my_nodes]
                 - na["used_pods"][my_nodes]).copy()

    pods = pods_r.arrays
    pod_cap = pods_r.capacity
    store_row = ba["store_row"]
    req_cpu = ba["cpu"]
    req_mem = ba["mem"]

    # decreasing by cpu then mem — classic FFD ordering
    order = mine[np.lexsort((-req_mem[mine], -req_cpu[mine]))]

    intents: List[Tuple[int, int, int]] = []
    chunks: List[List[Tuple[int, int, int]]] = []
    unplaced: List[int] = []
    placed = 0
    for bi in order.tolist():
        sr = int(store_row[bi])
        if sr < 0 or sr >= pod_cap:
            continue
        rv = int(pods["row_rv"][sr])
        if rv < 0 or int(pods["node_id"][sr]) >= 0:
            continue  # removed / already bound — advisory skip, owner is truth
        c, m = int(req_cpu[bi]), int(req_mem[bi])
        cand = np.nonzero((free_cpu >= c) & (free_mem >= m)
                          & (free_pods >= 1))[0]
        if len(cand) == 0:
            unplaced.append(bi)
            continue
        slot = int(cand[0])
        free_cpu[slot] -= c
        free_mem[slot] -= m
        free_pods[slot] -= 1
        intents.append((bi, int(my_nodes[slot]), rv))
        placed += 1
        if len(intents) >= INTENT_CHUNK:
            chunks.append(intents)
            intents = []
    if intents:
        chunks.append(intents)
    return chunks, placed, unplaced


def worker_main(idx: int, store_base: str, batch_base: str, node_base: str,
                cmd_q, out_q) -> None:
    """Process entry: attach the three arenas read-only, serve rounds until
    told to stop. Protocol (ints and small tuples only — MP001):

      cmd_q <- ("round", rid)           solve the published batch/node state
      cmd_q <- ("stop",)                close mappings and exit
      out_q -> ("bind", idx, rid, [(batch_row, node_row, rv_snap), ...])
      out_q -> ("done", idx, rid, placed, unplaced_rows, t0, t1, cpu_s)
    """
    from ..store import shm as _shm

    pods_r = _shm.ShmArenaReader(store_base, _shm.POD_COLS_SCHEMA)
    batch_r = _shm.ShmArenaReader(batch_base, _shm.BATCH_COLS_SCHEMA)
    nodes_r = _shm.ShmArenaReader(node_base, _shm.NODE_COLS_SCHEMA)
    out_q.put(("ready", idx, os.getpid()))
    try:
        while True:
            cmd = cmd_q.get()
            if not cmd or cmd[0] == "stop":
                return
            if cmd[0] != "round":  # pragma: no cover - future-proofing
                continue
            rid = cmd[1]
            t0 = time.perf_counter()
            c0 = time.process_time()
            try:
                chunks, placed, unplaced = _solve_round(
                    idx, pods_r, batch_r, nodes_r)
            except Exception as exc:  # report, don't die silently
                out_q.put(("error", idx, rid, f"{type(exc).__name__}: {exc}"))
                continue
            for chunk in chunks:
                out_q.put(("bind", idx, rid, chunk))
            t1 = time.perf_counter()
            out_q.put(("done", idx, rid, placed, unplaced, t0, t1,
                       time.process_time() - c0))
    finally:
        pods_r.close()
        batch_r.close()
        nodes_r.close()
