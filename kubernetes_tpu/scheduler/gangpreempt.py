"""Gang-aware preemption — a batched victim cover that makes room for WHOLE
gangs (ISSUE 14, ROADMAP direction 4).

The gang subsystem (scheduler/gang.py) places all-or-nothing but never used
to *make room*: a gang that didn't fit parked forever even when
lower-priority victims existed, because per-pod preemption is useless to a
gang (evicting enough for ONE member strands the rest — and the victims —
for nothing). This module preempts at the gang's own granularity:

  cover      — when a staged gang's quorum is vetoed by the solver, select a
               min-cost victim set whose release fits the ENTIRE quorum on
               ONE ICI slice (the rank-aware-MPI / Tesserae placement unit:
               a gang split across slices pays DCN on every step). The
               per-slice eviction capacity curve is the gangcover kernel
               (models/gangcover.py cover_curve): caps[k] after evicting the
               first k victims of the slice's (priority asc, biggest-freed
               first) eviction order; the cover is the smallest k reaching
               the quorum, minimized across slices by (max victim priority,
               victim count, priority sum).
  veto       — if NO slice reaches the quorum even after every eligible
               victim, NOTHING is evicted: the same all-or-nothing
               discipline as placement, applied to eviction. A partial
               eviction that strands a half-placed gang (and its victims)
               is the failure mode tests/test_gangpreempt.py proves
               impossible, property-based.
  execute    — victims ride the EXISTING DefaultPreemption machinery:
               narration events + the batched native store.delete_pods path
               (PR 10), async on the preparation worker when the
               SchedulerAsyncPreemption gate is on. Deleted-then-replaced
               victims flow through the established evict→replace span
               links (PR 9) untouched.
  park/retry — the preempting gang PARKS in the queue's parked-gang tier
               (scheduler/queue.py) instead of cycling backoff: each
               victim's DELETED event checks it off, and the last one
               releases the gang to re-stage immediately — or the deadline
               sweep releases it anyway if deletions stall (a wedged victim
               must not strand the gang; it just falls back to the normal
               retry ladder).

Victim eligibility: priority below the gang's MINIMUM member priority, not
itself a gang member (evicting part of a placed gang would strand IT — the
same failure mode), not blocked by an exhausted PodDisruptionBudget, and on
a node the gang's class can use (an ineligible node's capacity can never
host a member). Everything here runs on the scheduling thread off the hot
path — a parked gang is by definition not making progress.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..api import compute_pod_resource_request
from ..api.podgroup import pod_group_key
from ..models.gangcover import COVER_MAX_VICTIMS, cover_curves, victim_order


def flatten_snapshot_victims(snapshot, dims):
    """Flatten every bound pod into dense victim arrays in ONE pass over the
    snapshot — shared by the batch preemption tier math
    (BatchScheduler._batch_preempt) and the gang victim cover (the
    direction-2b helper share). Returns (v_node [V] int64, v_prio [V] int64,
    v_req [V, R] int64 quantized requests, v_pods [V], node_victims: per-node
    victim index lists)."""
    from ..snapshot.tensorizer import _quantize

    n = len(snapshot.node_info_list)
    r = len(dims)
    v_node, v_prio, v_req, v_pods = [], [], [], []
    node_victims: List[List[int]] = [[] for _ in range(n)]
    for i, ni in enumerate(snapshot.node_info_list):
        for pi in ni.pods:
            p = pi.pod
            node_victims[i].append(len(v_pods))
            v_node.append(i)
            v_prio.append(p.spec.priority)
            v_req.append(_quantize(
                compute_pod_resource_request(p), dims, is_request=True))
            v_pods.append(p)
    if not v_pods:
        return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                np.zeros((0, r), np.int64), [], node_victims)
    return (np.array(v_node, np.int64), np.array(v_prio, np.int64),
            np.array(v_req, np.int64).reshape(len(v_pods), r),
            v_pods, node_victims)


def pdb_blocked_mask(v_pods, pdbs) -> np.ndarray:
    """Approximate PDB exhaustion per victim (the _batch_preempt criterion):
    True when the victim matches any budget with no disruptions left —
    excluded from the cover outright (a gang cover has no per-node reprieve
    pass to repair an over-evicted budget)."""
    blocked = np.zeros(len(v_pods), dtype=bool)
    if not pdbs:
        return blocked
    for vi, p in enumerate(v_pods):
        blocked[vi] = any(
            pd.metadata.namespace == p.metadata.namespace
            and pd.selector is not None
            and pd.selector.matches(p.metadata.labels)
            and pd.disruptions_allowed <= 0
            for pd in pdbs)
    return blocked


@dataclass
class _Cover:
    """One selected victim cover (or the veto that found none)."""

    slice_id: int = -1
    victims: List = field(default_factory=list)
    chosen: Optional[np.ndarray] = None  # ctx victim indices of `victims`
    cost: int = 0  # victim priority sum
    max_prio: int = 0
    considered: int = 0  # candidate victims examined across slices
    capped: bool = False  # COVER_MAX_VICTIMS truncated some slice's list
    # some slice fits the quorum with ZERO evictions (caps[0] >= need):
    # preemption must not fire at all — the next solve places there (free
    # room may also be a PRIOR cover's in-flight deletions, folded into
    # ctx by consume_cover)
    room_exists: bool = False


class GangPreemptor:
    """Owned by a BatchScheduler; try_preempt is called from the gang
    requeue path for solver-vetoed gangs, note_pod_deleted from the watch
    ingest, sweep from the idle loops. All three run on the scheduling
    thread; the lock covers the stats/waiting reads from sched_stats'
    HTTP handler threads."""

    PARK_TIMEOUT_S = 10.0  # deadline for victim deletions before fallback

    def __init__(self, sched):
        self.sched = sched
        self._lock = threading.Lock()
        # gang key -> outstanding victim keys; the parked-gang release gate
        self._waiting: Dict[str, Set[str]] = {}
        self._deadline: Dict[str, float] = {}
        self.totals = {
            "attempts": 0, "preempted": 0, "victims": 0, "cover_cost": 0,
            "slices_ripped": 0, "vetoed_partial": 0, "released": 0,
            "expired": 0, "victims_capped": 0}

    @property
    def has_waiting(self) -> bool:
        # unlocked truthiness probe: the per-DELETED-event fast-out
        return bool(self._waiting)

    # -- context (built lazily, once per batch with vetoed gangs) -------------

    def build_ctx(self, snapshot, cluster, sub, assignment,
                  need: np.ndarray) -> Dict:
        """Per-batch cover context: post-batch capacity (in-batch placements
        folded in — entries later rolled back at assume read as still
        placed, which only UNDER-counts room: the safe direction), the
        flattened victim arrays, slice ids (one pseudo-slice when the
        cluster carries no slice labels: the whole cluster is then the
        placement domain), and the per-gang residual quorum need."""
        from .gang import node_slice_ids

        used = cluster.used.astype(np.int64).copy()
        pod_count = cluster.pod_count.astype(np.int64).copy()
        if assignment is not None:
            a = np.asarray(assignment)
            placed = a >= 0
            if placed.any():
                np.add.at(used, a[placed], sub.req[placed])
                np.add.at(pod_count, a[placed], 1)
        slice_ids = node_slice_ids(cluster)
        if slice_ids is None:
            slice_ids = np.zeros(cluster.n, dtype=np.int64)
        v_node, v_prio, v_req, v_pods, _ = flatten_snapshot_victims(
            snapshot, cluster.resource_dims)
        try:
            pdbs, _ = self.sched.store.list("poddisruptionbudgets")
        except Exception:
            pdbs = []
        return {
            "snapshot": snapshot, "cluster": cluster, "sub": sub,
            "need": need,
            "free": np.maximum(cluster.alloc.astype(np.int64) - used, 0),
            "headroom": np.maximum(
                cluster.max_pods.astype(np.int64) - pod_count, 0),
            "slice_ids": np.asarray(slice_ids, dtype=np.int64),
            "victims": (v_node, v_prio, v_req, v_pods),
            "pdb_blocked": pdb_blocked_mask(v_pods, pdbs),
        }

    # -- cover selection ------------------------------------------------------

    def _select_cover(self, gid: int, need: int, prio: int,
                      ctx: Dict) -> _Cover:
        cluster = ctx["cluster"]
        sub = ctx["sub"]
        rows = np.nonzero(np.asarray(sub.gang_of_pod) == gid)[0]
        out = _Cover()
        if rows.size == 0:
            return out
        classes = np.unique(np.asarray(sub.class_of_pod)[rows])
        eligible = np.all(sub.tables.filter_ok[classes], axis=0)
        # conservative per-member request: the max over in-batch members
        # (mixed-request gangs are covered for their largest member)
        req = np.asarray(sub.req)[rows].astype(np.int64).max(axis=0)
        nz = req > 0
        v_node, v_prio, v_req, v_pods = ctx["victims"]
        if len(v_pods) == 0:
            return out
        # victim pool: below the gang's priority floor, never a gang member,
        # PDB-allowed, on an eligible node (ineligible capacity is useless)
        pool = ((v_prio < prio) & ~ctx["pdb_blocked"]
                & eligible[v_node])
        if pool.any():
            is_member = np.fromiter(
                (bool(pod_group_key(v_pods[i]))
                 for i in np.nonzero(pool)[0]), dtype=bool,
                count=int(pool.sum()))
            pool_idx = np.nonzero(pool)[0][~is_member]
        else:
            pool_idx = np.zeros(0, dtype=np.int64)
        slice_ids = ctx["slice_ids"]
        free, headroom = ctx["free"], ctx["headroom"]
        # "frees the most" normalization: victim request in units of the
        # gang request (scaled), summed over the gang's nonzero dims
        if nz.any() and pool_idx.size:
            freed_norm_all = (v_req[:, nz] * 1000
                              // np.maximum(req[nz], 1)).sum(axis=1)
        else:
            freed_norm_all = np.zeros(len(v_pods), dtype=np.int64)
        best: Optional[Tuple] = None
        for s in np.unique(slice_ids[slice_ids >= 0]).tolist():
            snodes = np.nonzero(slice_ids == s)[0]
            if not eligible[snodes].any():
                continue
            local = np.full(cluster.n, -1, dtype=np.int64)
            local[snodes] = np.arange(len(snodes))
            vsel = pool_idx[np.isin(v_node[pool_idx], snodes)]
            order = vsel[victim_order(v_prio[vsel], freed_norm_all[vsel])]
            if len(order) > COVER_MAX_VICTIMS:
                order = order[:COVER_MAX_VICTIMS]
                out.capped = True
            out.considered += len(order)
            caps = cover_curves(
                free[snodes], headroom[snodes], eligible[snodes],
                local[v_node[order]], v_req[order], req)
            ks = np.nonzero(caps >= need)[0]
            if ks.size == 0:
                continue
            if ks[0] == 0:
                # this slice already fits the quorum with no eviction: the
                # WHOLE attempt aborts — evicting on another slice when
                # free room exists would delete pods for nothing
                out.room_exists = True
                out.victims = []
                return out
            k = int(ks[0])
            chosen = order[:k]
            cand = (int(v_prio[chosen].max()), k, int(v_prio[chosen].sum()),
                    int(s), chosen)
            if best is None or cand[:4] < best[:4]:
                best = cand
        if best is not None:
            out.max_prio, _, out.cost, out.slice_id, chosen = best
            out.chosen = chosen
            out.victims = [v_pods[i] for i in chosen.tolist()]
        return out

    @staticmethod
    def consume_cover(ctx: Dict, cover: _Cover) -> None:
        """Fold a fired cover OUT of the shared per-batch context: the
        chosen victims leave the candidate pool and their room folds into
        free/headroom (their deletion is in flight). A second gang vetoed
        in the SAME batch then reasons against the post-eviction cluster —
        it either finds the freed room (room_exists: no double eviction,
        it places on a later solve) or proves its own DISJOINT cover,
        never double-counting a victim."""
        v_node, v_prio, v_req, v_pods = ctx["victims"]
        chosen = cover.chosen
        np.add.at(ctx["free"], v_node[chosen], v_req[chosen])
        np.add.at(ctx["headroom"], v_node[chosen], 1)
        keep = np.ones(len(v_pods), dtype=bool)
        keep[chosen] = False
        rows = np.nonzero(keep)[0]
        ctx["victims"] = (v_node[rows], v_prio[rows], v_req[rows],
                          [v_pods[i] for i in rows.tolist()])
        ctx["pdb_blocked"] = ctx["pdb_blocked"][rows]

    # -- entry point from the gang requeue path -------------------------------

    def try_preempt(self, gang_key: str, gid: int, members: List,
                    ctx: Dict) -> Optional[Dict]:
        """Attempt a victim cover for one solver-vetoed gang. Returns None
        when preemption does not apply (policy Never, no plugin, no
        candidates at all — the gang requeues normally, silently), a dict
        with "vetoed": True when candidates existed but NO single slice can
        be covered (narrated; zero evictions; normal requeue), or the cover
        stats dict after firing the eviction and PARKING the gang."""
        sched = self.sched
        need = int(ctx["need"][gid]) if gid < len(ctx["need"]) else 0
        if need <= 0 or gang_key in self._waiting:
            return None
        if any(m.pod.spec.preemption_policy == "Never" for m in members):
            return None
        fw = sched._fw(members[0].pod) or sched.framework
        plugin = sched._preemption_plugin(fw)
        if plugin is None:
            return None
        prio = min(m.pod.spec.priority for m in members)
        with self._lock:
            self.totals["attempts"] += 1
        cover = self._select_cover(gid, need, prio, ctx)
        if cover.capped:
            with self._lock:
                self.totals["victims_capped"] += 1
        if cover.room_exists:
            # free room (possibly a prior cover's in-flight deletions)
            # already fits the quorum: no eviction, no veto — the gang
            # requeues and places on a later solve
            return None
        if not cover.victims:
            if cover.considered == 0:
                return None  # nothing evictable: a plain capacity wait
            with self._lock:
                self.totals["vetoed_partial"] += 1
            sched.recorder.event(
                members[0].pod, "Warning", "GangPreemptionVetoed",
                f"gang {gang_key}: no victim set on any single slice frees "
                f"room for all {need} member(s) "
                f"({cover.considered} candidate victim(s) examined); "
                "partial eviction refused")
            return {"vetoed": True, "considered": cover.considered}
        from ..server import metrics as m

        k = len(cover.victims)
        slice_name = str(cover.slice_id)
        sched.recorder.event(
            members[0].pod, "Normal", "GangPreempting",
            f"gang {gang_key}: evicting {k} victim(s) on slice "
            f"{slice_name} (cover cost {cover.cost}) to fit all {need} "
            "member(s); gang parked awaiting victim termination")
        # the EXISTING DefaultPreemption execution machinery: narration +
        # batched store.delete_pods, on the preparation worker in async mode
        preemptor = f"gang/{gang_key}"
        node_label = f"slice {slice_name}"
        if plugin.async_preparation:
            plugin._ensure_prep_worker()
            plugin._prep_q.put((list(cover.victims), preemptor, node_label))
        else:
            plugin._narrate_victims(cover.victims, preemptor, node_label)
            plugin._delete_victims(cover.victims)
        with self._lock:
            self._waiting[gang_key] = {v.key for v in cover.victims}
            self._deadline[gang_key] = (sched.clock.now()
                                        + self.PARK_TIMEOUT_S)
            self.totals["preempted"] += 1
            self.totals["victims"] += k
            self.totals["cover_cost"] += cover.cost
            self.totals["slices_ripped"] += 1
        sched.queue.park_gang(gang_key, members)
        sched.preempt_victims_total += k
        m.gang_preempted_total.inc(reason="victim_cover")
        # later gangs vetoed in this SAME batch must reason against the
        # post-eviction pool/room, never double-count these victims
        self.consume_cover(ctx, cover)
        return {"victims": k, "slice": cover.slice_id, "cost": cover.cost,
                "considered": cover.considered}

    # -- release plumbing -----------------------------------------------------

    def note_pod_deleted(self, key: str) -> None:
        """A pod DELETED event reached the watch ingest: check it off every
        waiting cover; the gang whose last victim terminated releases to
        re-stage immediately. Callers fast-out on has_waiting, so the
        unlabeled 100% of deletes never takes the lock."""
        releases = []
        with self._lock:
            # every waiting cover that names this key (no early break:
            # distinct covers are disjoint by construction, but a release
            # must never depend on that invariant)
            for g, wait in self._waiting.items():
                if key in wait:
                    wait.discard(key)
                    if not wait:
                        releases.append(g)
        for g in releases:
            self._release(g, "released")

    def sweep(self, now: float) -> int:
        """Deadline fallback, run from the idle loops: a cover whose victim
        deletions stalled (wedged kubelet, chaos fault) releases its gang
        anyway — back to the normal retry ladder, never stranded parked."""
        with self._lock:
            expired = [g for g, d in self._deadline.items() if now >= d]
        for g in expired:
            self._release(g, "expired")
        return len(expired)

    def _release(self, gang_key: str, counter: str) -> None:
        with self._lock:
            self._waiting.pop(gang_key, None)
            self._deadline.pop(gang_key, None)
            self.totals[counter] += 1
        self.sched.queue.release_parked_gang(gang_key)

    def reset(self) -> None:
        """Crash resync: parked state was rebuilt from the store LIST (the
        queue re-admits every pending pod fresh), so in-flight cover
        tracking is meaningless — drop it."""
        with self._lock:
            self._waiting.clear()
            self._deadline.clear()

    def stats(self) -> Dict:
        with self._lock:
            out = dict(self.totals)
            out["waiting_gangs"] = len(self._waiting)
        return out
