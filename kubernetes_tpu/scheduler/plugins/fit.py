"""NodeResourcesFit + scoring strategies + BalancedAllocation.

reference: pkg/scheduler/framework/plugins/noderesources/{fit.go,
least_allocated.go:30, most_allocated.go:30, balanced_allocation.go:145-179,
resource_allocation.go}.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ...api import Resource
from ...api.resources import CPU, MEMORY, EPHEMERAL_STORAGE
from ..framework import (
    MAX_NODE_SCORE,
    CycleState,
    NodeInfo,
    Plugin,
    Status,
    SUCCESS,
)

_STATE_KEY = "PreFilterNodeResourcesFit"

DEFAULT_RESOURCES = ({"name": CPU, "weight": 1}, {"name": MEMORY, "weight": 1})


class NodeResourcesFit(Plugin):
    """PreFilter computes the pod request vector once (fit.go:230); Filter checks
    request <= allocatable - requested per resource incl. scalar resources and
    pod count (fit.go:499-580); Score applies the configured strategy."""

    name = "NodeResourcesFit"

    def __init__(self, strategy: str = "LeastAllocated", resources=DEFAULT_RESOURCES,
                 ignored_resources: Tuple[str, ...] = (), shape=None):
        self.strategy = strategy
        self.resources = tuple(resources)
        self.ignored_resources = set(ignored_resources)
        # RequestedToCapacityRatio piecewise-linear shape: [(utilization, score)]
        self.shape = shape or [(0, 0), (100, 10)]

    def events_to_register(self):
        """fit.go EventsToRegister: Node add/update (more capacity may fit the
        pod), assigned-Pod delete/update (resources freed)."""
        from ..framework import ClusterEventWithHint

        def node_could_fit(pod, node):
            # isSchedulableAfterNodeChange simplification: queue when the
            # request fits the node's full allocatable (optimistic — the
            # filter re-checks against live usage)
            from ...api import Resource, compute_pod_resource_request

            req = compute_pod_resource_request(pod)
            alloc = Resource.from_resource_list(node.status.allocatable)
            return (req.milli_cpu <= alloc.milli_cpu and req.memory <= alloc.memory
                    and all(alloc.scalar.get(k, 0) >= v for k, v in req.scalar.items()))

        def assigned_pod_freed(pod, event_pod):
            return bool(event_pod.spec.node_name)

        return (ClusterEventWithHint("nodes", "add", node_could_fit),
                ClusterEventWithHint("nodes", "update", node_could_fit),
                ClusterEventWithHint("pods", "delete", assigned_pod_freed),
                ClusterEventWithHint("pods", "update", assigned_pod_freed))

    # -- PreFilter -------------------------------------------------------------

    def pre_filter(self, state: CycleState, pod, snapshot):
        from ...api import compute_pod_resource_request

        state.write(_STATE_KEY, compute_pod_resource_request(pod))
        return None, SUCCESS

    # -- Filter ----------------------------------------------------------------

    def filter(self, state: CycleState, pod, node_info: NodeInfo) -> Status:
        req: Resource = state.read_or_none(_STATE_KEY)
        if req is None:
            from ...api import compute_pod_resource_request

            req = compute_pod_resource_request(pod)
        reasons = []
        alloc = node_info.allocatable
        used = node_info.requested
        if len(node_info.pods) + 1 > alloc.allowed_pod_number:
            reasons.append("Too many pods")
        if req.milli_cpu and req.milli_cpu > alloc.milli_cpu - used.milli_cpu:
            reasons.append("Insufficient cpu")
        if req.memory and req.memory > alloc.memory - used.memory:
            reasons.append("Insufficient memory")
        if req.ephemeral_storage and \
                req.ephemeral_storage > alloc.ephemeral_storage - used.ephemeral_storage:
            reasons.append("Insufficient ephemeral-storage")
        for name, v in req.scalar.items():
            if name in self.ignored_resources or v == 0:
                continue
            if v > alloc.scalar.get(name, 0) - used.scalar.get(name, 0):
                reasons.append(f"Insufficient {name}")
        if reasons:
            return Status.unschedulable(*reasons, plugin=self.name)
        return SUCCESS

    # -- Score -----------------------------------------------------------------

    def score(self, state: CycleState, pod, node_info: NodeInfo) -> Tuple[int, Status]:
        req: Resource = state.read_or_none(_STATE_KEY)
        if req is None:
            from ...api import compute_pod_resource_request

            req = compute_pod_resource_request(pod)
        # Fit strategies score on NonZeroRequested (resource_allocation.go:90-92,
        # useRequested=false), so best-effort pods still spread.
        requested, allocatable = _requested_allocatable(
            node_info, pod, self.resources, node_info.non_zero_requested, non_zero_pod=True
        )
        if self.strategy == "LeastAllocated":
            return _least_allocated(requested, allocatable, self.resources), SUCCESS
        if self.strategy == "MostAllocated":
            return _most_allocated(requested, allocatable, self.resources), SUCCESS
        if self.strategy == "RequestedToCapacityRatio":
            return _requested_to_capacity_ratio(requested, allocatable, self.resources, self.shape), SUCCESS
        return 0, Status.error(f"unknown strategy {self.strategy}", plugin=self.name)


class BalancedAllocation(Plugin):
    """score = (1 - std(utilization fractions)) * 100 with the 2-resource shortcut
    |f1-f2|/2 (balanced_allocation.go:145-179). Skips best-effort pods
    (PreScore returns Skip). Uses Requested (useRequested=true)."""

    name = "NodeResourcesBalancedAllocation"

    def __init__(self, resources=DEFAULT_RESOURCES):
        self.resources = tuple(resources)

    def pre_score(self, state: CycleState, pod, nodes) -> Status:
        from ...api import compute_pod_resource_request

        req = compute_pod_resource_request(pod)
        if all(req.get(r["name"]) == 0 for r in self.resources):
            return Status.skip(plugin=self.name)
        state.write("PreScoreBalanced", req)
        return SUCCESS

    def score(self, state: CycleState, pod, node_info: NodeInfo) -> Tuple[int, Status]:
        req = state.read_or_none("PreScoreBalanced")
        if req is None:
            from ...api import compute_pod_resource_request

            req = compute_pod_resource_request(pod)
        requested, allocatable = _requested_allocatable(
            node_info, pod, self.resources, node_info.requested, non_zero_pod=False, pod_request=req
        )
        fractions = []
        for r, a in zip(requested, allocatable):
            if a == 0:
                continue
            fractions.append(min(r / a, 1.0))
        if len(fractions) == 2:
            std = abs(fractions[0] - fractions[1]) / 2
        elif len(fractions) > 2:
            mean = sum(fractions) / len(fractions)
            std = math.sqrt(sum((f - mean) ** 2 for f in fractions) / len(fractions))
        else:
            std = 0.0
        return int((1 - std) * MAX_NODE_SCORE), SUCCESS


def _requested_allocatable(node_info: NodeInfo, pod, resources, node_requested: Resource,
                           non_zero_pod: bool, pod_request: Optional[Resource] = None):
    """Per-configured-resource (requested+podRequest, allocatable) vectors."""
    from ...api import compute_pod_resource_request

    if pod_request is None:
        pod_request = compute_pod_resource_request(pod, non_zero=non_zero_pod)
    req_vec, alloc_vec = [], []
    for spec in resources:
        name = spec["name"]
        req_vec.append(node_requested.get(name) + pod_request.get(name))
        alloc_vec.append(node_info.allocatable.get(name))
    return req_vec, alloc_vec


def _least_allocated(requested: List[int], allocatable: List[int], resources) -> int:
    score = weight_sum = 0
    for req, alloc, spec in zip(requested, allocatable, resources):
        if alloc == 0:
            continue
        w = spec.get("weight", 1)
        if req > alloc:
            rs = 0
        else:
            rs = (alloc - req) * MAX_NODE_SCORE // alloc
        score += rs * w
        weight_sum += w
    return score // weight_sum if weight_sum else 0


def _most_allocated(requested: List[int], allocatable: List[int], resources) -> int:
    score = weight_sum = 0
    for req, alloc, spec in zip(requested, allocatable, resources):
        if alloc == 0:
            continue
        w = spec.get("weight", 1)
        rs = min(req, alloc) * MAX_NODE_SCORE // alloc
        score += rs * w
        weight_sum += w
    return score // weight_sum if weight_sum else 0


def _requested_to_capacity_ratio(requested, allocatable, resources, shape) -> int:
    """Piecewise-linear on utilization% (requested_to_capacity_ratio.go:60);
    shape points (utilization 0-100, score 0-10), scores scaled to 0-100."""
    score = weight_sum = 0
    for req, alloc, spec in zip(requested, allocatable, resources):
        if alloc == 0:
            continue
        w = spec.get("weight", 1)
        util = min(req * 100 // alloc, 100)
        score += _interp(shape, util) * 10 * w
        weight_sum += w
    return score // weight_sum if weight_sum else 0


def _interp(shape, x: int) -> int:
    if x <= shape[0][0]:
        return shape[0][1]
    for (x0, y0), (x1, y1) in zip(shape, shape[1:]):
        if x <= x1:
            return int(y0 + (y1 - y0) * (x - x0) / (x1 - x0))
    return shape[-1][1]
