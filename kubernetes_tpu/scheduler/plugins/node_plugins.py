"""Small node-predicate/score plugins: NodeName, NodePorts, NodeUnschedulable,
NodeAffinity, TaintToleration, ImageLocality, SchedulingGates, PrioritySort.

reference: pkg/scheduler/framework/plugins/{nodename/node_name.go,
nodeports/node_ports.go, nodeunschedulable/node_unschedulable.go,
nodeaffinity/node_affinity.go, tainttoleration/taint_toleration.go,
imagelocality/image_locality.go, schedulinggates/scheduling_gates.go,
queuesort/priority_sort.go}.
"""

from __future__ import annotations

from typing import Dict, Tuple

from ...api import Toleration, find_matching_untolerated_taint
from ...api.types import TAINT_NO_EXECUTE, TAINT_NO_SCHEDULE, TAINT_PREFER_NO_SCHEDULE
from ..framework import (
    MAX_NODE_SCORE,
    CycleState,
    NodeInfo,
    Plugin,
    Status,
    SUCCESS,
    default_normalize_score,
)
from .helpers import node_matches_node_selector_and_affinity


class NodeName(Plugin):
    """Filter: pod.Spec.NodeName == node.Name (node_name.go)."""

    name = "NodeName"

    def events_to_register(self):
        from ..framework import ClusterEventWithHint

        def is_the_node(pod, node):
            return node.metadata.name == pod.spec.node_name

        return (ClusterEventWithHint("nodes", "add", is_the_node),)

    def filter(self, state, pod, node_info: NodeInfo) -> Status:
        if pod.spec.node_name and pod.spec.node_name != node_info.node.metadata.name:
            return Status.unschedulable("node(s) didn't match the requested node name",
                                        plugin=self.name)
        return SUCCESS


class NodePorts(Plugin):
    """Filter host-port conflicts (node_ports.go)."""

    name = "NodePorts"
    _KEY = "PreFilterNodePorts"

    def events_to_register(self):
        from ..framework import ClusterEventWithHint, _host_ports

        def freed_wanted_port(pod, event_pod):
            if not event_pod.spec.node_name:
                return False
            wanted = {(proto, port) for _, proto, port in _host_ports(pod)}
            return any((proto, port) in wanted
                       for _, proto, port in _host_ports(event_pod))

        return (ClusterEventWithHint("nodes", "add"),
                ClusterEventWithHint("pods", "delete", freed_wanted_port))

    def pre_filter(self, state: CycleState, pod, snapshot):
        from ..framework import _host_ports

        ports = list(_host_ports(pod))
        state.write(self._KEY, ports)
        if not ports:
            return None, Status.skip(plugin=self.name)
        return None, SUCCESS

    def filter(self, state: CycleState, pod, node_info: NodeInfo) -> Status:
        from ..framework import _host_ports

        ports = state.read_or_none(self._KEY)
        if ports is None:
            ports = list(_host_ports(pod))
        for ip, proto, port in ports:
            for uip, uproto, uport in node_info.used_ports:
                if port == uport and proto == uproto and (
                    ip == "0.0.0.0" or uip == "0.0.0.0" or ip == uip
                ):
                    return Status.unschedulable("node(s) didn't have free ports for the requested pod ports",
                                                plugin=self.name)
        return SUCCESS


class NodeUnschedulable(Plugin):
    """Filter spec.unschedulable, honoring the unschedulable taint toleration
    (node_unschedulable.go)."""

    name = "NodeUnschedulable"
    _UNSCHEDULABLE_TAINT_KEY = "node.kubernetes.io/unschedulable"

    def events_to_register(self):
        from ..framework import ClusterEventWithHint

        def now_schedulable(pod, node):
            return not node.spec.unschedulable

        return (ClusterEventWithHint("nodes", "add", now_schedulable),
                ClusterEventWithHint("nodes", "update", now_schedulable))

    def filter(self, state, pod, node_info: NodeInfo) -> Status:
        if not node_info.node.spec.unschedulable:
            return SUCCESS
        # Tolerating the synthesized unschedulable taint admits the pod
        # (node_unschedulable.go TolerationsTolerateTaint).
        from ...api import Taint

        fake = Taint(key=self._UNSCHEDULABLE_TAINT_KEY, effect=TAINT_NO_SCHEDULE)
        if any(t.tolerates(fake) for t in pod.spec.tolerations):
            return SUCCESS
        return Status.unresolvable("node(s) were unschedulable", plugin=self.name)


class NodeAffinity(Plugin):
    """Filter: nodeSelector AND required node affinity; Score: sum of matched
    preferred term weights, DefaultNormalizeScore (node_affinity.go)."""

    name = "NodeAffinity"

    def events_to_register(self):
        from ..framework import ClusterEventWithHint

        def node_matches(pod, node):
            return node_matches_node_selector_and_affinity(pod, node)

        return (ClusterEventWithHint("nodes", "add", node_matches),
                ClusterEventWithHint("nodes", "update", node_matches))

    def filter(self, state, pod, node_info: NodeInfo) -> Status:
        if not node_matches_node_selector_and_affinity(pod, node_info.node):
            return Status.unresolvable("node(s) didn't match Pod's node affinity/selector",
                                       plugin=self.name)
        return SUCCESS

    def score(self, state, pod, node_info: NodeInfo) -> Tuple[int, Status]:
        aff = pod.spec.affinity
        if not aff or not aff.node_affinity_preferred:
            return 0, SUCCESS
        total = 0
        for pref in aff.node_affinity_preferred:
            if pref.term.matches(node_info.node):
                total += pref.weight
        return total, SUCCESS

    def normalize_score(self, state, pod, scores: Dict[str, int]) -> Status:
        default_normalize_score(MAX_NODE_SCORE, False, scores)
        return SUCCESS


class TaintToleration(Plugin):
    """Filter NoSchedule/NoExecute taints; Score counts intolerable
    PreferNoSchedule taints, normalized reversed (taint_toleration.go)."""

    name = "TaintToleration"

    def events_to_register(self):
        from ..framework import ClusterEventWithHint

        def taints_tolerated(pod, node):
            return find_matching_untolerated_taint(
                node.spec.taints, pod.spec.tolerations,
                effects=(TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE)) is None

        return (ClusterEventWithHint("nodes", "add", taints_tolerated),
                ClusterEventWithHint("nodes", "update", taints_tolerated))

    def filter(self, state, pod, node_info: NodeInfo) -> Status:
        taint = find_matching_untolerated_taint(
            node_info.node.spec.taints, pod.spec.tolerations,
            effects=(TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE),
        )
        if taint is None:
            return SUCCESS
        return Status.unresolvable(
            f"node(s) had untolerated taint {{{taint.key}: {taint.value}}}", plugin=self.name
        )

    def pre_score(self, state: CycleState, pod, nodes) -> Status:
        # Tolerations with empty effect also cover PreferNoSchedule
        # (taint_toleration.go:133-141).
        tols = [t for t in pod.spec.tolerations if t.effect in ("", TAINT_PREFER_NO_SCHEDULE)]
        state.write("PreScoreTaintToleration", tols)
        return SUCCESS

    def score(self, state, pod, node_info: NodeInfo) -> Tuple[int, Status]:
        tols = state.read_or_none("PreScoreTaintToleration")
        if tols is None:
            tols = [t for t in pod.spec.tolerations if t.effect in ("", TAINT_PREFER_NO_SCHEDULE)]
        count = 0
        for taint in node_info.node.spec.taints:
            if taint.effect != TAINT_PREFER_NO_SCHEDULE:
                continue
            if not any(t.tolerates(taint) for t in tols):
                count += 1
        return count, SUCCESS

    def normalize_score(self, state, pod, scores: Dict[str, int]) -> Status:
        default_normalize_score(MAX_NODE_SCORE, True, scores)
        return SUCCESS


class ImageLocality(Plugin):
    """Score by image bytes already on node, scaled by image spread across nodes
    (image_locality.go:78-117)."""

    name = "ImageLocality"

    MIN_THRESHOLD = 23 * 1024 * 1024  # mb*23 (image_locality.go:36-40)
    MAX_CONTAINER_THRESHOLD = 1000 * 1024 * 1024

    def score(self, state, pod, node_info: NodeInfo) -> Tuple[int, Status]:
        total_nodes = state.read_or_none("TotalNodes") or 1
        sum_scores = 0
        for c in list(pod.spec.init_containers) + list(pod.spec.containers):
            img = _normalized_image_name(c.image)
            st = node_info.image_states.get(img)
            if st is not None:
                spread = st.num_nodes / total_nodes
                sum_scores += int(st.size * spread)
        num_containers = len(pod.spec.containers) + len(pod.spec.init_containers)
        max_threshold = self.MAX_CONTAINER_THRESHOLD * num_containers
        sum_scores = min(max(sum_scores, self.MIN_THRESHOLD), max_threshold)
        return MAX_NODE_SCORE * (sum_scores - self.MIN_THRESHOLD) // (max_threshold - self.MIN_THRESHOLD), SUCCESS


class SchedulingGates(Plugin):
    """PreEnqueue: hold gated pods out of the active queue (scheduling_gates.go)."""

    name = "SchedulingGates"

    def pre_enqueue(self, pod) -> Status:
        if pod.spec.scheduling_gates:
            gates = ", ".join(pod.spec.scheduling_gates)
            return Status.unresolvable(f"waiting for scheduling gates: {gates}", plugin=self.name)
        return SUCCESS


class PrioritySort(Plugin):
    """QueueSort: priority desc, then creation/queue timestamp asc (priority_sort.go)."""

    name = "PrioritySort"

    def less(self, pod_info_a, pod_info_b) -> bool:
        pa, pb = pod_info_a.pod.spec.priority, pod_info_b.pod.spec.priority
        if pa != pb:
            return pa > pb
        return pod_info_a.timestamp < pod_info_b.timestamp


def _normalized_image_name(name: str) -> str:
    if name.rfind(":") <= name.rfind("/"):
        name += ":latest"
    return name
