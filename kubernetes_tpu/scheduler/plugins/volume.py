"""Volume plugins: VolumeBinding, VolumeRestrictions, VolumeZone,
NodeVolumeLimits.

reference: pkg/scheduler/framework/plugins/{volumebinding/volume_binding.go,
volumerestrictions/volume_restrictions.go, volumezone/volume_zone.go,
nodevolumelimits/csi.go}. Semantics follow the reference's extension points:
VolumeBinding does PreFilter claim partitioning, Filter static-binding /
provisioning feasibility, Reserve assumes bindings, PreBind commits them;
VolumeRestrictions checks shared-disk conflicts and ReadWriteOncePod;
VolumeZone checks bound-PV zone/region labels against the node; NodeVolumeLimits
enforces CSINode attach limits.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ...api.storage import (
    BINDING_WAIT_FOR_FIRST_CONSUMER,
    CLAIM_BOUND,
    CSINode,
    PersistentVolume,
    PersistentVolumeClaim,
    READ_WRITE_ONCE_POD,
    StorageClass,
    VOLUME_BOUND,
)
from ...api.types import LABEL_REGION, LABEL_ZONE
from ..framework import (
    CycleState,
    NodeInfo,
    Plugin,
    Status,
    SUCCESS,
)

ERR_REASON_NOT_FOUND = "persistentvolumeclaim not found"
ERR_UNBOUND_IMMEDIATE = "pod has unbound immediate PersistentVolumeClaims"
ERR_NODE_CONFLICT = "node(s) had volume node affinity conflict"
ERR_BINDING = "node(s) didn't find available persistent volumes to bind"
ERR_ZONE_CONFLICT = "node(s) had no available volume zone"
ERR_DISK_CONFLICT = "node(s) had no available disk"
ERR_RWOP_CONFLICT = "pod uses a ReadWriteOncePod PVC already in use"
ERR_VOLUME_LIMIT = "node(s) exceed max volume count"


@dataclass
class VolumeLister:
    """Handle onto the storage objects the volume plugins consult (the
    reference reaches these through framework.Handle's SharedInformerFactory)."""

    pvcs: Dict[str, PersistentVolumeClaim] = field(default_factory=dict)  # "ns/name"
    pvs: Dict[str, PersistentVolume] = field(default_factory=dict)  # name
    classes: Dict[str, StorageClass] = field(default_factory=dict)  # name
    csinodes: Dict[str, CSINode] = field(default_factory=dict)  # node name

    def get_pvc(self, namespace: str, name: str) -> Optional[PersistentVolumeClaim]:
        return self.pvcs.get(f"{namespace}/{name}")

    def clear(self) -> None:
        """Drop all state ahead of a relist (informer cache replace)."""
        self.pvcs.clear()
        self.pvs.clear()
        self.classes.clear()
        self.csinodes.clear()

    def default_class(self) -> Optional[StorageClass]:
        for sc in self.classes.values():
            if sc.is_default:
                return sc
        return None

    def class_for(self, pvc: PersistentVolumeClaim) -> Optional[StorageClass]:
        name = pvc.spec.storage_class_name
        if name is None:
            return self.default_class()
        return self.classes.get(name)

    def _map_for(self, obj) -> Tuple[Dict, str]:
        if isinstance(obj, PersistentVolumeClaim):
            return self.pvcs, obj.key
        if isinstance(obj, PersistentVolume):
            return self.pvs, obj.metadata.name
        if isinstance(obj, StorageClass):
            return self.classes, obj.metadata.name
        if isinstance(obj, CSINode):
            return self.csinodes, obj.metadata.name
        raise TypeError(type(obj).__name__)

    def add(self, obj) -> None:
        m, key = self._map_for(obj)
        m[key] = obj

    def remove(self, obj) -> None:
        m, key = self._map_for(obj)
        m.pop(key, None)


def pod_pvc_names(pod) -> List[Tuple[str, bool]]:
    """(claim name, read_only) per PVC-backed volume; ephemeral volumes use the
    '<pod>-<volume>' generated claim name (volume_binding.go podVolumeClaims)."""
    out = []
    for v in pod.spec.volumes:
        if v.pvc_claim_name:
            out.append((v.pvc_claim_name, v.pvc_read_only))
        elif v.ephemeral:
            out.append((f"{pod.metadata.name}-{v.name}", False))
    return out


def pv_matches_node(pv: PersistentVolume, node) -> bool:
    if pv.spec.node_affinity is None:
        return True
    return pv.spec.node_affinity.matches(node)


@dataclass
class _PodVolumeState:
    bound: List[Tuple[PersistentVolumeClaim, PersistentVolume]]
    unbound: List[PersistentVolumeClaim]  # WaitForFirstConsumer, need binding


@dataclass
class _NodeBinding:
    static: List[Tuple[PersistentVolumeClaim, PersistentVolume]]
    provision: List[PersistentVolumeClaim]


class VolumeBinding(Plugin):
    """Topology-aware PV/PVC binding (volumebinding/volume_binding.go:603).

    PreFilter partitions the pod's claims; Filter checks each node can satisfy
    them (bound-PV affinity, matchable PVs, or provisionable class topology);
    Reserve picks concrete PVs per claim; PreBind writes the bindings through
    the lister (the in-memory stand-in for the API writes the reference's
    volume binder issues).
    """

    name = "VolumeBinding"
    STATE_KEY = "PreFilterVolumeBinding"
    BIND_KEY = "VolumeBindingReserved"

    def __init__(self, lister: Optional[VolumeLister] = None):
        self.lister = lister or VolumeLister()
        self._store = None

    def events_to_register(self):
        """volume_binding EventsToRegister: any PV/PVC/StorageClass/CSINode
        change can unblock a pending claim; assigned-pod deletes release
        ReadWriteOncePod claims and attach slots."""
        from ..framework import ClusterEventWithHint

        return (ClusterEventWithHint("persistentvolumes", "add"),
                ClusterEventWithHint("persistentvolumes", "update"),
                ClusterEventWithHint("persistentvolumeclaims", "add"),
                ClusterEventWithHint("persistentvolumeclaims", "update"),
                ClusterEventWithHint("storageclasses", "add"),
                ClusterEventWithHint("storageclasses", "update"),
                ClusterEventWithHint("csinodes", "add"),
                ClusterEventWithHint("csinodes", "update"),
                ClusterEventWithHint("nodes", "add"),
                ClusterEventWithHint("pods", "delete"))

    def set_handles(self, framework, store) -> None:
        """Persist PreBind's PVC/PV writes through the API store (the reference
        binder PATCHes the apiserver; serial.py calls this during wiring)."""
        self._store = store

    def _persist(self, kind: str, obj) -> None:
        """Write-through to the API store. Update-then-create covers objects
        the lister knows but the store hasn't seen yet; any other failure
        propagates so PreBind fails instead of silently diverging from the
        store."""
        if self._store is None:
            return
        from ...store import NotFoundError

        try:
            self._store.update(kind, obj, check_rv=False)
        except NotFoundError:
            self._store.create(kind, obj)

    def pre_filter(self, state: CycleState, pod, snapshot):
        claims = pod_pvc_names(pod)
        if not claims:
            state.write(self.STATE_KEY, _PodVolumeState([], []))
            return None, Status.skip(plugin=self.name)
        bound, unbound = [], []
        for claim_name, _ro in claims:
            pvc = self.lister.get_pvc(pod.metadata.namespace, claim_name)
            if pvc is None:
                return None, Status.unresolvable(
                    f'{ERR_REASON_NOT_FOUND}: "{claim_name}"', plugin=self.name)
            if pvc.is_bound():
                pv = self.lister.pvs.get(pvc.spec.volume_name)
                if pv is None:
                    return None, Status.unresolvable(
                        f'PersistentVolume "{pvc.spec.volume_name}" not found',
                        plugin=self.name)
                bound.append((pvc, pv))
                continue
            sc = self.lister.class_for(pvc)
            if sc is None or sc.volume_binding_mode != BINDING_WAIT_FOR_FIRST_CONSUMER:
                # Immediate-mode claims must be bound by the PV controller
                # before scheduling (volume_binding.go PreFilter).
                return None, Status.unresolvable(ERR_UNBOUND_IMMEDIATE, plugin=self.name)
            unbound.append(pvc)
        state.write(self.STATE_KEY, _PodVolumeState(bound, unbound))
        return None, SUCCESS

    def _find_matching_pv(self, pvc: PersistentVolumeClaim, node,
                          taken: set) -> Optional[PersistentVolume]:
        """Smallest available PV satisfying class/capacity/access/affinity
        (volume_binding.go findMatchingVolumes semantics)."""
        best = None
        # A claim without an explicit class resolves to the cluster default —
        # the PV must match the effective class either way.
        sc = self.lister.class_for(pvc)
        sc_name = pvc.spec.storage_class_name
        if sc_name is None:
            sc_name = sc.metadata.name if sc is not None else ""
        for pv in self.lister.pvs.values():
            if pv.metadata.name in taken or pv.spec.claim_ref or pv.phase == VOLUME_BOUND:
                continue
            if pv.spec.storage_class_name != sc_name:
                continue
            if pv.spec.capacity < pvc.spec.request:
                continue
            if not set(pvc.spec.access_modes) <= set(pv.spec.access_modes):
                continue
            if not pv_matches_node(pv, node):
                continue
            if best is None or pv.spec.capacity < best.spec.capacity:
                best = pv
        return best

    def _node_binding(self, state: CycleState, pod, node) -> Tuple[Optional[_NodeBinding], Status]:
        # Per-node result cached in CycleState: Filter computes it, Score and
        # Reserve reuse it (the reference caches PodVolumes the same way).
        cache_key = f"{self.STATE_KEY}/{node.metadata.name}"
        cached = state.read_or_none(cache_key)
        if cached is not None:
            return cached
        result = self._node_binding_uncached(state, pod, node)
        state.write(cache_key, result)
        return result

    def _node_binding_uncached(self, state: CycleState, pod, node) -> Tuple[Optional[_NodeBinding], Status]:
        vs: _PodVolumeState = state.read(self.STATE_KEY)
        for _pvc, pv in vs.bound:
            if not pv_matches_node(pv, node):
                return None, Status.unschedulable(ERR_NODE_CONFLICT, plugin=self.name)
        static, provision, taken = [], [], set()
        for pvc in vs.unbound:
            pv = self._find_matching_pv(pvc, node, taken)
            if pv is not None:
                taken.add(pv.metadata.name)
                static.append((pvc, pv))
                continue
            sc = self.lister.class_for(pvc)
            if sc is not None and sc.provisioner and (
                    sc.allowed_topologies is None or sc.allowed_topologies.matches(node)):
                provision.append(pvc)
                continue
            return None, Status.unschedulable(ERR_BINDING, plugin=self.name)
        return _NodeBinding(static, provision), SUCCESS

    def filter(self, state: CycleState, pod, node_info: NodeInfo) -> Status:
        _, st = self._node_binding(state, pod, node_info.node)
        return st

    def score(self, state: CycleState, pod, node_info: NodeInfo):
        """Prefer nodes where static binding wastes the least capacity
        (volume_binding.go scorer: utilization of the chosen PVs)."""
        binding, st = self._node_binding(state, pod, node_info.node)
        if not st.is_success() or binding is None or not binding.static:
            return 0, SUCCESS
        util = sum(min(pvc.spec.request / pv.spec.capacity, 1.0)
                   for pvc, pv in binding.static if pv.spec.capacity) / len(binding.static)
        return int(util * 100), SUCCESS

    def reserve(self, state: CycleState, pod, node_name: str) -> Status:
        snapshot = state.read_or_none("Snapshot")
        node_info = snapshot.get(node_name) if snapshot is not None else None
        if node_info is None:
            return Status.error(f"node {node_name} not in snapshot", plugin=self.name)
        binding, st = self._node_binding(state, pod, node_info.node)
        if not st.is_success():
            return st
        state.write(self.BIND_KEY, binding)
        return SUCCESS

    def unreserve(self, state: CycleState, pod, node_name: str) -> None:
        state.write(self.BIND_KEY, None)

    def pre_bind(self, state: CycleState, pod, node_name: str) -> Status:
        binding: Optional[_NodeBinding] = state.read_or_none(self.BIND_KEY)
        if binding is None:
            return SUCCESS
        try:
            return self._pre_bind(binding)
        except Exception as e:  # failed PVC/PV write must fail the bind
            return Status.error(f"binding volumes: {e}", plugin=self.name)

    def _pre_bind(self, binding: "_NodeBinding") -> Status:
        for pvc, pv in binding.static:
            pv.spec.claim_ref = pvc.key
            pv.phase = VOLUME_BOUND
            pvc.spec.volume_name = pv.metadata.name
            pvc.phase = CLAIM_BOUND
            self._persist("persistentvolumes", pv)
            self._persist("persistentvolumeclaims", pvc)
        for pvc in binding.provision:
            # Dynamic provisioning: materialize a PV on the spot (stand-in for
            # the external provisioner round-trip).
            sc = self.lister.class_for(pvc)
            name = f"pvc-{pvc.metadata.uid or pvc.metadata.name}"
            pv = PersistentVolume(metadata=type(pvc.metadata)(name=name))
            pv.spec.capacity = pvc.spec.request
            pv.spec.access_modes = list(pvc.spec.access_modes)
            pv.spec.storage_class_name = pvc.spec.storage_class_name or (
                sc.metadata.name if sc else "")
            pv.spec.claim_ref = pvc.key
            pv.phase = VOLUME_BOUND
            self.lister.pvs[name] = pv
            pvc.spec.volume_name = name
            pvc.phase = CLAIM_BOUND
            self._persist("persistentvolumes", pv)
            self._persist("persistentvolumeclaims", pvc)
        return SUCCESS


class VolumeRestrictions(Plugin):
    """Shared-disk conflicts + ReadWriteOncePod enforcement
    (volumerestrictions/volume_restrictions.go)."""

    name = "VolumeRestrictions"

    def __init__(self, lister: Optional[VolumeLister] = None):
        self.lister = lister or VolumeLister()

    def pre_filter(self, state: CycleState, pod, snapshot):
        # ReadWriteOncePod: the claim must not be in use by any other pod
        # anywhere in the cluster (volume_restrictions.go isRWOPConflict).
        rwop = set()
        for claim_name, _ro in pod_pvc_names(pod):
            pvc = self.lister.get_pvc(pod.metadata.namespace, claim_name)
            if pvc is not None and READ_WRITE_ONCE_POD in pvc.spec.access_modes:
                rwop.add(pvc.key)
        if rwop:
            for ni in snapshot.node_info_list:
                for pi in ni.pods:
                    other = pi.pod
                    if other.key == pod.key:
                        continue
                    for claim_name, _ro in pod_pvc_names(other):
                        if f"{other.metadata.namespace}/{claim_name}" in rwop:
                            return None, Status.unschedulable(
                                ERR_RWOP_CONFLICT, plugin=self.name)
        if not pod.spec.volumes:
            return None, Status.skip(plugin=self.name)
        return None, SUCCESS

    @staticmethod
    def _conflicts(v, existing) -> bool:
        """True when two volume sources collide (volume_restrictions.go
        isVolumeConflict): GCE PD / RBD / ISCSI allow sharing only when both
        sides are read-only; AWS EBS never shares."""
        if v.gce_pd and v.gce_pd == existing.gce_pd:
            if not (v.gce_read_only and existing.gce_read_only):
                return True
        if v.aws_ebs and v.aws_ebs == existing.aws_ebs:
            return True
        if v.rbd and v.rbd == existing.rbd:
            if not (v.rbd_read_only and existing.rbd_read_only):
                return True
        if v.iscsi and v.iscsi == existing.iscsi:
            if not (v.iscsi_read_only and existing.iscsi_read_only):
                return True
        return False

    def filter(self, state: CycleState, pod, node_info: NodeInfo) -> Status:
        for v in pod.spec.volumes:
            if not (v.gce_pd or v.aws_ebs or v.rbd or v.iscsi):
                continue
            for pi in node_info.pods:
                for ev in pi.pod.spec.volumes:
                    if self._conflicts(v, ev):
                        return Status.unschedulable(ERR_DISK_CONFLICT, plugin=self.name)
        return SUCCESS


class VolumeZone(Plugin):
    """Bound-PV zone/region labels must be satisfied by the node
    (volumezone/volume_zone.go)."""

    name = "VolumeZone"
    _TOPOLOGY_KEYS = (LABEL_ZONE, LABEL_REGION,
                      "failure-domain.beta.kubernetes.io/zone",
                      "failure-domain.beta.kubernetes.io/region")

    def __init__(self, lister: Optional[VolumeLister] = None):
        self.lister = lister or VolumeLister()

    def filter(self, state: CycleState, pod, node_info: NodeInfo) -> Status:
        node_labels = node_info.node.metadata.labels
        for claim_name, _ro in pod_pvc_names(pod):
            pvc = self.lister.get_pvc(pod.metadata.namespace, claim_name)
            if pvc is None or not pvc.spec.volume_name:
                continue  # unbound claims are VolumeBinding's problem
            pv = self.lister.pvs.get(pvc.spec.volume_name)
            if pv is None:
                continue
            for key in self._TOPOLOGY_KEYS:
                want = pv.metadata.labels.get(key)
                if want is None:
                    continue
                # PV zone labels may hold a __ separated set (volume_zone.go
                # uses LabelZonesToSet).
                if node_labels.get(key) not in want.split("__"):
                    return Status.unschedulable(ERR_ZONE_CONFLICT, plugin=self.name)
        return SUCCESS


class NodeVolumeLimits(Plugin):
    """CSI attachable-volume count limits (nodevolumelimits/csi.go)."""

    name = "NodeVolumeLimits"

    def __init__(self, lister: Optional[VolumeLister] = None):
        self.lister = lister or VolumeLister()

    def _csi_volumes(self, pod) -> Dict[str, set]:
        """driver -> {volume handles or pv names} the pod would attach."""
        out: Dict[str, set] = {}
        for claim_name, _ro in pod_pvc_names(pod):
            pvc = self.lister.get_pvc(pod.metadata.namespace, claim_name)
            if pvc is None:
                continue
            driver, handle = "", ""
            if pvc.spec.volume_name:
                pv = self.lister.pvs.get(pvc.spec.volume_name)
                if pv is not None and pv.spec.csi_driver:
                    driver = pv.spec.csi_driver
                    handle = pv.spec.volume_handle or pv.metadata.name
            else:
                sc = self.lister.class_for(pvc)
                if sc is not None:
                    driver, handle = sc.provisioner, pvc.key
            if driver:
                out.setdefault(driver, set()).add(handle)
        return out

    def filter(self, state: CycleState, pod, node_info: NodeInfo) -> Status:
        new = self._csi_volumes(pod)
        if not new:
            return SUCCESS
        csinode = self.lister.csinodes.get(node_info.node.metadata.name)
        if csinode is None:
            return SUCCESS  # no CSINode => no limits known (csi.go)
        existing: Dict[str, set] = {}
        for pi in node_info.pods:
            for driver, handles in self._csi_volumes(pi.pod).items():
                existing.setdefault(driver, set()).update(handles)
        for driver, handles in new.items():
            limit = csinode.drivers.get(driver)
            if limit is None:
                continue
            total = handles | existing.get(driver, set())
            if len(total) > limit:
                return Status.unschedulable(ERR_VOLUME_LIMIT, plugin=self.name)
        return SUCCESS
