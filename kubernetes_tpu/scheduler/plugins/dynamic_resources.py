"""DynamicResources plugin — DRA claims drive placement.

reference: pkg/scheduler/framework/plugins/dynamicresources/dynamicresources.go
(PreEnqueue :350, PreFilter :430, Filter :550, Reserve/Unreserve, PreBind) and
staging/src/k8s.io/dynamic-resource-allocation/structured (the allocator over
ResourceSlice pools). The widest plugin contract in the default set.

Flow preserved:
  PreEnqueue  — pods whose referenced ResourceClaims don't exist stay gated.
  PreFilter   — load the pod's claims; allocated claims pin candidate nodes;
                unallocated claims precompute per-request candidate devices.
  Filter      — a node passes iff every unallocated claim can be satisfied
                from the node's slice devices net of existing allocations +
                in-flight reservations, and every allocated claim is usable
                from this node.
  Reserve     — allocate devices on the chosen node in-memory (assume);
                Unreserve returns them.
  PreBind     — persist allocation + reservedFor to the store; failure
                unreserves (the transactional boundary the reference puts in
                PreBind so a crashed scheduler never leaks device claims).

The allocator is deliberately structural (attribute requirements, counts)
rather than CEL — same decision surface, bounded vocabulary.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from ...api.dra import AllocationResult, DeviceClass, ResourceClaim, ResourceSlice
from ..framework import CycleState, NodeInfo, Status, SUCCESS

_STATE_KEY = "DynamicResources"


class _PodClaimState:
    """Per-cycle state: the pod's claims, split by allocation status."""

    __slots__ = ("claims", "allocated", "pending")

    def __init__(self, claims):
        self.claims: List[ResourceClaim] = claims
        self.allocated = [c for c in claims if c.allocation is not None]
        self.pending = [c for c in claims if c.allocation is None]


class DynamicResources:
    name = "DynamicResources"

    def __init__(self, store=None):
        self.store = store
        # device key "node/driver-pool/device" -> claim key holding it,
        # covering both persisted allocations and in-flight reservations
        self._lock = threading.Lock()
        self._assumed: Dict[str, Dict[str, AllocationResult]] = {}  # claim -> alloc

    def set_handles(self, framework, store) -> None:
        self.store = store

    # -- listers ---------------------------------------------------------------

    @staticmethod
    def _claim_names(pod):
        """Resolved claim object names: direct spec references + generated
        claims recorded by the resourceclaim controller
        (status.resourceClaimStatuses); template refs without a recorded
        claim yet resolve to None (pod must wait)."""
        out = []
        for _ref, claim_name in pod.spec.resource_claims:
            out.append(claim_name)
        for ref, _tmpl in pod.spec.resource_claim_templates:
            out.append(pod.status.resource_claim_statuses.get(ref))
        return out

    @staticmethod
    def _has_claims(pod) -> bool:
        return bool(pod.spec.resource_claims
                    or pod.spec.resource_claim_templates)

    def _claims_for(self, pod) -> Optional[List[ResourceClaim]]:
        """None when a referenced claim is missing (or a template's claim
        has not been generated yet)."""
        if self.store is None or not self._has_claims(pod):
            return []
        out = []
        for claim_name in self._claim_names(pod):
            if not claim_name:
                return None
            try:
                out.append(self.store.get(
                    "resourceclaims", f"{pod.metadata.namespace}/{claim_name}"))
            except Exception:
                return None
        return out

    def _slices_by_node(self) -> Dict[str, List[ResourceSlice]]:
        if self.store is None:
            return {}
        slices, _ = self.store.list("resourceslices")
        by_node: Dict[str, List[ResourceSlice]] = {}
        for s in slices:
            by_node.setdefault(s.node_name, []).append(s)
        return by_node

    def _classes(self) -> Dict[str, DeviceClass]:
        if self.store is None:
            return {}
        classes, _ = self.store.list("deviceclasses")
        return {c.metadata.name: c for c in classes}

    def _in_use_devices(self) -> Set[str]:
        """Device keys held by persisted allocations + in-flight assumes."""
        used: Set[str] = set()
        if self.store is not None:
            claims, _ = self.store.list("resourceclaims")
            for c in claims:
                if c.allocation is not None:
                    for d in c.allocation.all_devices():
                        used.add(f"{c.allocation.node_name}/{d}")
        with self._lock:
            for alloc in self._assumed.values():
                for d in alloc.all_devices():
                    used.add(f"{alloc.node_name}/{d}")
        return used

    # -- extension points ------------------------------------------------------

    def pre_enqueue(self, pod) -> Status:
        """PreEnqueue (:350): a pod whose claims are absent can't schedule."""
        if not self._has_claims(pod):
            return SUCCESS
        if self._claims_for(pod) is None:
            return Status.unschedulable(
                "waiting for ResourceClaim(s) to be created", plugin=self.name)
        return SUCCESS

    def events_to_register(self):
        from ..framework import ClusterEventWithHint

        def claim_related(pod, claim) -> bool:
            """isSchedulableAfterClaimChange: the pod's own claim changing
            always matters; a FOREIGN claim matters when it just released its
            devices (allocation cleared) — those devices may now satisfy this
            pod's pending claims."""
            names = {cn for cn in DynamicResources._claim_names(pod) if cn}
            if (claim.metadata.name in names
                    and claim.metadata.namespace == pod.metadata.namespace):
                return True
            return claim.allocation is None

        return (ClusterEventWithHint("resourceclaims", "add", claim_related),
                ClusterEventWithHint("resourceclaims", "update", claim_related),
                # a deleted claim frees its devices even when it still carried
                # an allocation — always requeue on claim deletes
                ClusterEventWithHint("resourceclaims", "delete"),
                ClusterEventWithHint("resourceslices", "add"),
                ClusterEventWithHint("resourceslices", "update"),
                ClusterEventWithHint("deviceclasses", "add"))

    def pre_filter(self, state: CycleState, pod, snapshot):
        if not self._has_claims(pod):
            return None, Status.skip()
        claims = self._claims_for(pod)
        if claims is None:
            return None, Status.unschedulable(
                "pod's ResourceClaim(s) do not exist", plugin=self.name)
        st = _PodClaimState(claims)
        state.write(_STATE_KEY, st)
        if st.pending:
            # snapshot the allocator's inputs ONCE per cycle — Filter runs per
            # node and must not re-list the store each time (the reference
            # allocator preloads in PreFilter the same way)
            state.write(_STATE_KEY + "/ctx", (
                self._slices_by_node(), self._classes(), self._in_use_devices()))
        # an allocated claim pins the pod to its allocation node unless this
        # pod is already among reservedFor users on another (shared claims)
        from ..framework import PreFilterResult

        pinned = {c.allocation.node_name for c in st.allocated}
        if len(pinned) > 1:
            return None, Status.unschedulable(
                "claims are allocated on different nodes", plugin=self.name)
        if pinned:
            return PreFilterResult(node_names=pinned), SUCCESS
        return None, SUCCESS

    def filter(self, state: CycleState, pod, node_info: NodeInfo) -> Status:
        st: Optional[_PodClaimState] = state.read_or_none(_STATE_KEY)
        if st is None:
            return SUCCESS
        node_name = node_info.node.metadata.name
        for c in st.allocated:
            if c.allocation.node_name != node_name:
                return Status.unschedulable(
                    f"claim {c.metadata.name} is allocated on "
                    f"{c.allocation.node_name}", plugin=self.name)
        if st.pending:
            alloc = self._try_allocate(st.pending, node_name,
                                       ctx=state.read_or_none(_STATE_KEY + "/ctx"))
            if alloc is None:
                return Status.unschedulable(
                    "cannot allocate all claim devices on this node",
                    plugin=self.name)
        return SUCCESS

    def _try_allocate(self, claims: List[ResourceClaim], node_name: str,
                      ctx=None) -> Optional[Dict[str, AllocationResult]]:
        """The structured allocator: greedily satisfy every request of every
        claim from the node's free devices. Returns claim key -> allocation,
        or None (reference: structured.Allocator.Allocate). ctx, when given,
        is the cycle-invariant (slices_by_node, classes, in_use) snapshot."""
        if ctx is not None:
            slices_by_node, classes, in_use = ctx
        else:
            slices_by_node = self._slices_by_node()
            classes = self._classes()
            in_use = self._in_use_devices()
        slices = slices_by_node.get(node_name, [])
        if not slices:
            return None
        free = []  # (device key, Device)
        for s in slices:
            for d in s.devices:
                key = f"{node_name}/{d.name}"
                if key not in in_use:
                    free.append((key, d))
        out: Dict[str, AllocationResult] = {}
        taken: Set[str] = set()
        for c in claims:
            alloc = AllocationResult(node_name=node_name)
            for req in c.requests:
                cls = classes.get(req.device_class_name)
                if cls is None:
                    return None
                picked = []
                for key, d in free:
                    if key in taken:
                        continue
                    if not cls.matches(d):
                        continue
                    if not all(sel.matches(d.attributes) for sel in req.selectors):
                        continue
                    picked.append((key, d))
                    if len(picked) == req.count:
                        break
                if len(picked) < req.count:
                    return None
                for key, d in picked:
                    taken.add(key)
                alloc.devices[req.name] = [d.name for _k, d in picked]
            out[c.key] = alloc
        return out

    def reserve(self, state: CycleState, pod, node_name: str) -> Status:
        st: Optional[_PodClaimState] = state.read_or_none(_STATE_KEY)
        if st is None or not st.pending:
            return SUCCESS
        allocs = self._try_allocate(st.pending, node_name)
        if allocs is None:
            return Status.unschedulable(
                "claim devices were taken between Filter and Reserve",
                plugin=self.name)
        with self._lock:
            self._assumed.update(allocs)
        state.write(_STATE_KEY + "/reserved", allocs)
        return SUCCESS

    def unreserve(self, state: CycleState, pod, node_name: str) -> None:
        allocs = state.read_or_none(_STATE_KEY + "/reserved")
        if not allocs:
            return
        with self._lock:
            for claim_key in allocs:
                self._assumed.pop(claim_key, None)

    def pre_bind(self, state: CycleState, pod, node_name: str) -> Status:
        """Persist allocation + reservedFor; on write failure the framework
        unreserves (serial.py commit chain)."""
        st: Optional[_PodClaimState] = state.read_or_none(_STATE_KEY)
        if st is None:
            return SUCCESS
        allocs = state.read_or_none(_STATE_KEY + "/reserved") or {}
        try:
            for c in st.claims:
                alloc = allocs.get(c.key)
                if alloc is None and c.allocation is None:
                    continue

                def mutate(cur, _alloc=alloc):
                    if _alloc is not None:
                        cur.allocation = _alloc
                    if pod.metadata.name not in cur.reserved_for:
                        cur.reserved_for.append(pod.metadata.name)
                    return cur

                self.store.guaranteed_update("resourceclaims", c.key, mutate)
        except Exception as e:
            return Status.error(f"persisting claim allocation: {e}", plugin=self.name)
        finally:
            with self._lock:
                for claim_key in allocs:
                    self._assumed.pop(claim_key, None)
        return SUCCESS

    def deallocate(self, claim_key: str) -> None:
        """Free a claim's devices (pod deletion path / kubelet claim teardown —
        the controller side of the reference's claim lifecycle)."""
        def mutate(cur):
            cur.allocation = None
            cur.reserved_for = []
            return cur

        try:
            self.store.guaranteed_update("resourceclaims", claim_key, mutate)
        except Exception:
            pass
