"""InterPodAffinity — Filter (required (anti-)affinity incl. symmetry) and Score.

reference: pkg/scheduler/framework/plugins/interpodaffinity/{filtering.go,
scoring.go}. State = three topologyPair->count maps (filtering.go:44-50):
  existing_anti: existing pods' required anti-affinity terms matching the
    incoming pod (symmetry check);
  affinity / anti_affinity: existing pods matching the incoming pod's terms.
Filter rules (filtering.go:415):
  1. no existing pod's required anti-affinity is violated;
  2. incoming required affinity satisfied (with the first-pod-in-cluster
     exception, filtering.go satisfyPodAffinity);
  3. incoming required anti-affinity not violated.
Score (scoring.go): weighted per-(topologyKey,value) sums over preferred terms of
the incoming pod AND (symmetrically) of existing pods, incl. existing pods'
*required* affinity terms weighted by hard_pod_affinity_weight; normalized
(score-min)/(max-min)*100.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Tuple

from ..framework import (
    MAX_NODE_SCORE,
    CycleState,
    NodeInfo,
    Plugin,
    Status,
    SUCCESS,
)
from .helpers import effective_selector, term_matches_pod

_FILTER_KEY = "PreFilterInterPodAffinity"
_SCORE_KEY = "PreScoreInterPodAffinity"


class _FilterState:
    __slots__ = ("existing_anti", "affinity", "anti_affinity", "pod")

    def __init__(self, pod, existing_anti, affinity, anti_affinity):
        self.pod = pod
        self.existing_anti: Dict[Tuple[str, str], int] = existing_anti
        self.affinity: Dict[Tuple[str, str], int] = affinity
        self.anti_affinity: Dict[Tuple[str, str], int] = anti_affinity

    def clone(self):
        return _FilterState(self.pod, dict(self.existing_anti), dict(self.affinity),
                            dict(self.anti_affinity))


class InterPodAffinity(Plugin):
    name = "InterPodAffinity"

    def __init__(self, hard_pod_affinity_weight: int = 1,
                 ns_labels: Optional[Mapping[str, Mapping[str, str]]] = None):
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self._ns_labels = ns_labels or {}

    def set_namespace_labels(self, ns_labels: Mapping[str, Mapping[str, str]]) -> None:
        self._ns_labels = ns_labels

    def _has_constraints(self, pod) -> bool:
        aff = pod.spec.affinity
        return bool(aff and (aff.pod_affinity_required or aff.pod_anti_affinity_required))

    def events_to_register(self):
        """interpodaffinity EventsToRegister: Pod add/update/delete (a matching
        pod appearing satisfies affinity; a blocking pod leaving clears
        anti-affinity) and Node add/update (new topology domains)."""
        from ..framework import ClusterEventWithHint

        def pod_related(pod, event_pod):
            aff = pod.spec.affinity
            if aff is None:
                return True  # rejected via symmetry: any pod event may matter
            terms = (tuple(aff.pod_affinity_required)
                     + tuple(aff.pod_anti_affinity_required))
            if any(term_matches_pod(t, pod, event_pod, self._ns_labels)
                   for t in terms):
                return True
            # symmetric direction: the event pod's own terms may target us
            ev_aff = event_pod.spec.affinity
            return bool(ev_aff and (ev_aff.pod_affinity_required
                                    or ev_aff.pod_anti_affinity_required))

        return (ClusterEventWithHint("pods", "add", pod_related),
                ClusterEventWithHint("pods", "update", pod_related),
                ClusterEventWithHint("pods", "delete", pod_related),
                ClusterEventWithHint("nodes", "add"),
                ClusterEventWithHint("nodes", "update"))

    # -- Filter ----------------------------------------------------------------

    def pre_filter(self, state: CycleState, pod, snapshot):
        ns_labels = self._ns_labels
        existing_anti: Dict[Tuple[str, str], int] = {}
        affinity: Dict[Tuple[str, str], int] = {}
        anti_affinity: Dict[Tuple[str, str], int] = {}

        aff = pod.spec.affinity
        required = tuple(aff.pod_affinity_required) if aff else ()
        anti = tuple(aff.pod_anti_affinity_required) if aff else ()

        # Existing pods' required anti-affinity vs the incoming pod (symmetry).
        for ni in snapshot.have_pods_with_required_anti_affinity_list:
            node = ni.node
            for pi in ni.pods_with_required_anti_affinity:
                for term in pi.required_anti_affinity_terms:
                    val = node.metadata.labels.get(term.topology_key)
                    if val is None:
                        continue
                    if term_matches_pod(term, pi.pod, pod, ns_labels):
                        k = (term.topology_key, val)
                        existing_anti[k] = existing_anti.get(k, 0) + 1

        # Incoming pod's terms vs existing pods.
        if required or anti:
            for ni in snapshot.node_info_list:
                node = ni.node
                for pi in ni.pods:
                    for term in required:
                        val = node.metadata.labels.get(term.topology_key)
                        if val is not None and term_matches_pod(term, pod, pi.pod, ns_labels):
                            k = (term.topology_key, val)
                            affinity[k] = affinity.get(k, 0) + 1
                    for term in anti:
                        val = node.metadata.labels.get(term.topology_key)
                        if val is not None and term_matches_pod(term, pod, pi.pod, ns_labels):
                            k = (term.topology_key, val)
                            anti_affinity[k] = anti_affinity.get(k, 0) + 1

        if not existing_anti and not required and not anti:
            state.write(_FILTER_KEY, None)
            return None, Status.skip(plugin=self.name)
        state.write(_FILTER_KEY, _FilterState(pod, existing_anti, affinity, anti_affinity))
        return None, SUCCESS

    def filter(self, state: CycleState, pod, node_info: NodeInfo) -> Status:
        st: Optional[_FilterState] = state.read_or_none(_FILTER_KEY)
        if st is None:
            return SUCCESS
        node = node_info.node
        labels = node.metadata.labels

        # 1. existing pods' required anti-affinity (filtering.go satisfyExistingPodsAntiAffinity)
        for (tk, tv), cnt in st.existing_anti.items():
            if cnt > 0 and labels.get(tk) == tv:
                return Status.unschedulable(
                    "node(s) didn't satisfy existing pods anti-affinity rules", plugin=self.name
                )

        aff = pod.spec.affinity
        required = tuple(aff.pod_affinity_required) if aff else ()
        anti = tuple(aff.pod_anti_affinity_required) if aff else ()

        # 2. incoming required affinity (satisfyPodAffinity incl. first-pod rule)
        if required:
            pods_exist = True
            for term in required:
                val = labels.get(term.topology_key)
                if val is None:
                    return Status.unschedulable(
                        "node(s) didn't match pod affinity rules", plugin=self.name
                    )
                if st.affinity.get((term.topology_key, val), 0) <= 0:
                    pods_exist = False
            if not pods_exist:
                if not st.affinity and self._pod_matches_all_own_terms(pod, required):
                    pass  # first pod in a self-affine series
                else:
                    return Status.unschedulable(
                        "node(s) didn't match pod affinity rules", plugin=self.name
                    )

        # 3. incoming required anti-affinity (satisfyPodAntiAffinity)
        for term in anti:
            val = labels.get(term.topology_key)
            if val is not None and st.anti_affinity.get((term.topology_key, val), 0) > 0:
                return Status.unschedulable(
                    "node(s) didn't match pod anti-affinity rules", plugin=self.name
                )
        return SUCCESS

    def _pod_matches_all_own_terms(self, pod, terms) -> bool:
        return all(term_matches_pod(t, pod, pod, self._ns_labels) for t in terms)

    # PreFilterExtensions

    def add_pod(self, state: CycleState, pod, added_pod, node_info: NodeInfo) -> Status:
        self._update(state, pod, added_pod, node_info, +1)
        return SUCCESS

    def remove_pod(self, state: CycleState, pod, removed_pod, node_info: NodeInfo) -> Status:
        self._update(state, pod, removed_pod, node_info, -1)
        return SUCCESS

    def _update(self, state, pod, other, node_info, delta):
        st: Optional[_FilterState] = state.read_or_none(_FILTER_KEY)
        if st is None:
            return
        node = node_info.node
        labels = node.metadata.labels
        ns_labels = self._ns_labels
        other_aff = other.spec.affinity
        for term in (other_aff.pod_anti_affinity_required if other_aff else ()):
            val = labels.get(term.topology_key)
            if val is not None and term_matches_pod(term, other, pod, ns_labels):
                k = (term.topology_key, val)
                st.existing_anti[k] = st.existing_anti.get(k, 0) + delta
        aff = pod.spec.affinity
        for term in (aff.pod_affinity_required if aff else ()):
            val = labels.get(term.topology_key)
            if val is not None and term_matches_pod(term, pod, other, ns_labels):
                k = (term.topology_key, val)
                st.affinity[k] = st.affinity.get(k, 0) + delta
        for term in (aff.pod_anti_affinity_required if aff else ()):
            val = labels.get(term.topology_key)
            if val is not None and term_matches_pod(term, pod, other, ns_labels):
                k = (term.topology_key, val)
                st.anti_affinity[k] = st.anti_affinity.get(k, 0) + delta

    # -- Score -----------------------------------------------------------------

    def pre_score(self, state: CycleState, pod, filtered_nodes) -> Status:
        aff = pod.spec.affinity
        has_pref = bool(aff and (aff.pod_affinity_preferred or aff.pod_anti_affinity_preferred))
        has_constraints = has_pref
        # Symmetric scoring considers existing pods' terms even when the incoming
        # pod has none (scoring.go:127 PreScore early-exit only when the pod has
        # no affinity at all AND ignorePreferredTermsOfExistingPods).
        snapshot = state.read_or_none("Snapshot")
        all_nodes = snapshot.node_info_list if snapshot else filtered_nodes
        ns_labels = self._ns_labels

        score_map: Dict[Tuple[str, str], int] = {}

        def bump(topology_key: str, value: str, weight: int):
            k = (topology_key, value)
            score_map[k] = score_map.get(k, 0) + weight

        candidates = all_nodes if has_constraints else snapshot.have_pods_with_affinity_list if snapshot else all_nodes
        for ni in candidates:
            node = ni.node
            labels = node.metadata.labels
            pods = ni.pods if has_constraints else ni.pods_with_affinity
            for pi in pods:
                existing = pi.pod
                # incoming pod's preferred terms vs existing pod
                if aff:
                    for wt in aff.pod_affinity_preferred:
                        val = labels.get(wt.term.topology_key)
                        if val is not None and term_matches_pod(wt.term, pod, existing, ns_labels):
                            bump(wt.term.topology_key, val, wt.weight)
                    for wt in aff.pod_anti_affinity_preferred:
                        val = labels.get(wt.term.topology_key)
                        if val is not None and term_matches_pod(wt.term, pod, existing, ns_labels):
                            bump(wt.term.topology_key, val, -wt.weight)
                # existing pod's preferred terms vs incoming pod (symmetry)
                for wt in pi.preferred_affinity_terms:
                    val = labels.get(wt.term.topology_key)
                    if val is not None and term_matches_pod(wt.term, existing, pod, ns_labels):
                        bump(wt.term.topology_key, val, wt.weight)
                for wt in pi.preferred_anti_affinity_terms:
                    val = labels.get(wt.term.topology_key)
                    if val is not None and term_matches_pod(wt.term, existing, pod, ns_labels):
                        bump(wt.term.topology_key, val, -wt.weight)
                # existing pod's REQUIRED affinity terms, hard weight (symmetry)
                if self.hard_pod_affinity_weight > 0:
                    for term in pi.required_affinity_terms:
                        val = labels.get(term.topology_key)
                        if val is not None and term_matches_pod(term, existing, pod, ns_labels):
                            bump(term.topology_key, val, self.hard_pod_affinity_weight)

        if not score_map:
            state.write(_SCORE_KEY, None)
            return Status.skip(plugin=self.name)
        state.write(_SCORE_KEY, score_map)
        return SUCCESS

    def score(self, state: CycleState, pod, node_info: NodeInfo) -> Tuple[int, Status]:
        score_map = state.read_or_none(_SCORE_KEY)
        if not score_map:
            return 0, SUCCESS
        labels = node_info.node.metadata.labels
        total = 0
        for (tk, tv), w in score_map.items():
            if labels.get(tk) == tv:
                total += w
        return total, SUCCESS

    def normalize_score(self, state: CycleState, pod, scores: Dict[str, int]) -> Status:
        if not scores:
            return SUCCESS
        max_c = max(scores.values())
        min_c = min(scores.values())
        diff = max_c - min_c
        for k, v in scores.items():
            scores[k] = int(MAX_NODE_SCORE * (v - min_c) / diff) if diff > 0 else 0
        return SUCCESS
