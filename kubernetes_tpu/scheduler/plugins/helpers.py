"""Shared helpers for pod-affinity-style term matching.

reference: pkg/scheduler/framework/types.go AffinityTerm.Matches + GetAffinityTerms
(namespace defaulting), and the matchLabelKeys merge semantics of
podtopologyspread/common.go + interpodaffinity.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

from ...api import PodAffinityTerm, Selector
from ...api.labels import IN, Requirement


def term_namespaces_match(term: PodAffinityTerm, source_ns: str, target_ns: str,
                          ns_labels: Mapping[str, Mapping[str, str]]) -> bool:
    """Does `target_ns` fall in the term's namespace set?

    - If both `namespaces` and `namespaceSelector` are unset: defaults to the
      source pod's namespace.
    - `namespaceSelector` empty ({}) selects all namespaces; nil selects none.
    - The union of the explicit list and selector matches applies.
    """
    if term.namespaces:
        if target_ns in term.namespaces:
            return True
    if term.namespace_selector is not None:
        return term.namespace_selector.matches(ns_labels.get(target_ns, {}))
    if not term.namespaces:
        return target_ns == source_ns
    return False


def _merge_match_label_keys(sel: Optional[Selector], match_label_keys,
                            source_pod) -> Optional[Selector]:
    """matchLabelKeys merge shared by InterPodAffinity terms and PTS constraints:
    the source pod's value for each listed key is appended as an In requirement."""
    if not match_label_keys or sel is None:
        return sel
    extra = []
    for k in match_label_keys:
        if k in source_pod.metadata.labels:
            extra.append(Requirement(k, IN, (source_pod.metadata.labels[k],)))
    return Selector(sel.requirements + tuple(extra))


def effective_selector(term: PodAffinityTerm, source_pod) -> Optional[Selector]:
    """reference: interpodaffinity matchLabelKeys handling."""
    return _merge_match_label_keys(term.selector, term.match_label_keys, source_pod)


def term_matches_pod(term: PodAffinityTerm, source_pod, target_pod,
                     ns_labels: Mapping[str, Mapping[str, str]]) -> bool:
    """AffinityTerm.Matches: target pod's namespace in term namespaces AND labels
    match the (matchLabelKeys-merged) selector. A nil selector matches nothing."""
    if not term_namespaces_match(term, source_pod.metadata.namespace,
                                 target_pod.metadata.namespace, ns_labels):
        return False
    sel = effective_selector(term, source_pod)
    return sel is not None and sel.matches(target_pod.metadata.labels)


def pts_effective_selector(constraint, pod) -> Optional[Selector]:
    """PTS matchLabelKeys merge (reference: podtopologyspread/common.go)."""
    return _merge_match_label_keys(constraint.selector, constraint.match_label_keys, pod)


def count_pods_match_selector(pod_infos, selector: Optional[Selector], ns: str) -> int:
    """reference: podtopologyspread/common.go countPodsMatchSelector — counts
    non-terminating pods in `ns` matching selector."""
    if selector is None:
        return 0
    n = 0
    for pi in pod_infos:
        p = pi.pod
        if p.metadata.namespace == ns and p.metadata.deletion_timestamp is None \
                and selector.matches(p.metadata.labels):
            n += 1
    return n


def node_matches_node_selector_and_affinity(pod, node) -> bool:
    """Required node affinity = spec.nodeSelector AND
    affinity.nodeAffinity.required... (reference: component-helpers
    nodeaffinity.GetRequiredNodeAffinity)."""
    for k, v in pod.spec.node_selector.items():
        if node.metadata.labels.get(k) != v:
            return False
    aff = pod.spec.affinity
    if aff and aff.node_affinity_required is not None:
        if not aff.node_affinity_required.matches(node):
            return False
    return True
