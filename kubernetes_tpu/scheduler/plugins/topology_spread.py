"""PodTopologySpread — Filter (DoNotSchedule skew) and Score (ScheduleAnyway).

reference: pkg/scheduler/framework/plugins/podtopologyspread/{filtering.go,
scoring.go, common.go}. Semantics preserved:
  - PreFilter builds per-constraint TpValueToMatchNum over eligible nodes
    (honoring NodeAffinityPolicy/NodeTaintsPolicy), plus minMatchNum with
    MinDomains (filtering.go:55).
  - Filter: matchNum + selfMatch - minMatchNum <= maxSkew (filtering.go:340-355);
    nodes missing the topology key are UnschedulableAndUnresolvable.
  - Score: per-topology-value counts x log-normalizing weight (scoring.go),
    then the special maxScore+minScore-s normalization.
  - AddPod/RemovePod PreFilterExtensions keep counts incremental for preemption.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ...api import find_matching_untolerated_taint
from ...api.types import LABEL_HOSTNAME, TAINT_NO_SCHEDULE
from ..framework import (
    MAX_NODE_SCORE,
    CycleState,
    NodeInfo,
    Plugin,
    Status,
    SUCCESS,
)
from .helpers import (
    count_pods_match_selector,
    node_matches_node_selector_and_affinity,
    pts_effective_selector,
)

_FILTER_KEY = "PreFilterPodTopologySpread"
_SCORE_KEY = "PreScorePodTopologySpread"
_INVALID = -1


class _FilterState:
    def __init__(self, constraints, tp_counts, min_counts):
        # constraints: list of (constraint, effective_selector)
        self.constraints = constraints
        # tp_counts[i]: {topology_value: match_count}
        self.tp_counts = tp_counts
        # min_counts[i]: precomputed minMatchNum honoring MinDomains
        self.min_counts = min_counts

    def clone(self):
        return _FilterState(self.constraints, [dict(d) for d in self.tp_counts], list(self.min_counts))

    def recompute_min(self):
        out = []
        for i, (c, _sel) in enumerate(self.constraints):
            counts = self.tp_counts[i]
            m = min(counts.values(), default=0)
            if c.min_domains and c.min_domains > len(counts):
                m = 0
            out.append(m)
        self.min_counts = out


class PodTopologySpread(Plugin):
    name = "PodTopologySpread"

    def __init__(self, default_constraints=(), system_defaulted: bool = False):
        self.default_constraints = tuple(default_constraints)
        self.system_defaulted = system_defaulted

    def events_to_register(self):
        """podtopologyspread EventsToRegister: Pod add/update/delete of pods
        matching a constraint selector shift the skew; Node add/update can add
        topology domains."""
        from ..framework import ClusterEventWithHint

        def pod_counts(pod, event_pod):
            if event_pod.metadata.namespace != pod.metadata.namespace:
                return False
            for c in pod.spec.topology_spread_constraints:
                sel = pts_effective_selector(c, pod)
                if sel is not None and sel.matches(event_pod.metadata.labels):
                    return True
            return False

        return (ClusterEventWithHint("pods", "add", pod_counts),
                ClusterEventWithHint("pods", "update", pod_counts),
                ClusterEventWithHint("pods", "delete", pod_counts),
                ClusterEventWithHint("nodes", "add"),
                ClusterEventWithHint("nodes", "update"),
                # a domain disappearing can lower minMatchNum below the skew
                # bound (upstream registers Node Add|Delete)
                ClusterEventWithHint("nodes", "delete"))

    # -- Filter path -----------------------------------------------------------

    def pre_filter(self, state: CycleState, pod, snapshot):
        constraints = [
            (c, pts_effective_selector(c, pod))
            for c in pod.spec.topology_spread_constraints
            if c.when_unsatisfiable == "DoNotSchedule"
        ]
        if not constraints:
            state.write(_FILTER_KEY, None)
            return None, SUCCESS
        tp_counts: List[Dict[str, int]] = [dict() for _ in constraints]
        for ni in snapshot.node_info_list:
            node = ni.node
            # Inclusion policies are per-constraint (common.go
            # matchNodeInclusionPolicies): node eligibility for one constraint's
            # domains must not leak into another's.
            for i, (c, sel) in enumerate(constraints):
                if not self._constraint_node_eligible(pod, node, c):
                    continue
                val = node.metadata.labels.get(c.topology_key)
                if val is None:
                    continue
                cnt = count_pods_match_selector(ni.pods, sel, pod.metadata.namespace)
                tp_counts[i][val] = tp_counts[i].get(val, 0) + cnt
        st = _FilterState(constraints, tp_counts, [])
        st.recompute_min()
        state.write(_FILTER_KEY, st)
        return None, SUCCESS

    @staticmethod
    def _constraint_node_eligible(pod, node, c) -> bool:
        """Per-constraint node inclusion (common.go matchNodeInclusionPolicies)."""
        if c.node_affinity_policy == "Honor" and \
                not node_matches_node_selector_and_affinity(pod, node):
            return False
        if c.node_taints_policy == "Honor" and \
                find_matching_untolerated_taint(node.spec.taints, pod.spec.tolerations) is not None:
            return False
        return True

    def filter(self, state: CycleState, pod, node_info: NodeInfo) -> Status:
        st: Optional[_FilterState] = state.read_or_none(_FILTER_KEY)
        if st is None:
            return SUCCESS
        node = node_info.node
        for i, (c, sel) in enumerate(st.constraints):
            val = node.metadata.labels.get(c.topology_key)
            if val is None:
                return Status.unresolvable("node(s) didn't have the requested topology",
                                           plugin=self.name)
            self_match = 1 if (sel is not None and sel.matches(pod.metadata.labels)) else 0
            match_num = st.tp_counts[i].get(val, 0)
            skew = match_num + self_match - st.min_counts[i]
            if skew > c.max_skew:
                return Status.unschedulable(
                    "node(s) didn't match pod topology spread constraints",
                    plugin=self.name,
                )
        return SUCCESS

    # PreFilterExtensions (preemption dry-runs mutate counts incrementally)

    def add_pod(self, state: CycleState, pod_to_schedule, added_pod, node_info: NodeInfo) -> Status:
        self._update(state, pod_to_schedule, added_pod, node_info, +1)
        return SUCCESS

    def remove_pod(self, state: CycleState, pod_to_schedule, removed_pod, node_info: NodeInfo) -> Status:
        self._update(state, pod_to_schedule, removed_pod, node_info, -1)
        return SUCCESS

    def _update(self, state, pod, other_pod, node_info, delta):
        st: Optional[_FilterState] = state.read_or_none(_FILTER_KEY)
        if st is None:
            return
        node = node_info.node
        for i, (c, sel) in enumerate(st.constraints):
            if not self._constraint_node_eligible(pod, node, c):
                continue
            val = node.metadata.labels.get(c.topology_key)
            if val is None or sel is None:
                continue
            if other_pod.metadata.namespace == pod.metadata.namespace and \
                    sel.matches(other_pod.metadata.labels):
                st.tp_counts[i][val] = st.tp_counts[i].get(val, 0) + delta
        st.recompute_min()

    # -- Score path ------------------------------------------------------------

    def pre_score(self, state: CycleState, pod, filtered_nodes) -> Status:
        snapshot = state.read_or_none("Snapshot")
        all_nodes = snapshot.node_info_list if snapshot else filtered_nodes
        constraints = [
            (c, pts_effective_selector(c, pod))
            for c in pod.spec.topology_spread_constraints
            if c.when_unsatisfiable == "ScheduleAnyway"
        ]
        if not constraints:
            state.write(_SCORE_KEY, None)
            return Status.skip(plugin=self.name)
        require_all = True  # non-system-default constraints (scoring.go:121)

        # Domains from *filtered* nodes (initPreScoreState), counts over all nodes.
        ignored_nodes = set()
        tp_counts: List[Dict[str, int]] = [dict() for _ in constraints]
        topo_size = [0] * len(constraints)
        for ni in filtered_nodes:
            node = ni.node
            if require_all and any(c.topology_key not in node.metadata.labels for c, _ in constraints):
                ignored_nodes.add(node.metadata.name)
                continue
            for i, (c, _sel) in enumerate(constraints):
                if c.topology_key == LABEL_HOSTNAME:
                    continue
                val = node.metadata.labels.get(c.topology_key)
                if val is not None and val not in tp_counts[i]:
                    tp_counts[i][val] = 0
                    topo_size[i] += 1

        weights = []
        for i, (c, _sel) in enumerate(constraints):
            size = topo_size[i]
            if c.topology_key == LABEL_HOSTNAME:
                size = len(filtered_nodes) - len(ignored_nodes)
            weights.append(math.log(size + 2))

        for ni in all_nodes:
            node = ni.node
            if not node_matches_node_selector_and_affinity(pod, node):
                continue
            if require_all and any(c.topology_key not in node.metadata.labels for c, _ in constraints):
                continue
            for i, (c, sel) in enumerate(constraints):
                val = node.metadata.labels.get(c.topology_key)
                if val is None or val not in tp_counts[i]:
                    continue
                tp_counts[i][val] += count_pods_match_selector(ni.pods, sel, pod.metadata.namespace)

        state.write(_SCORE_KEY, {
            "constraints": constraints,
            "ignored": ignored_nodes,
            "tp_counts": tp_counts,
            "weights": weights,
        })
        return SUCCESS

    def score(self, state: CycleState, pod, node_info: NodeInfo) -> Tuple[int, Status]:
        s = state.read_or_none(_SCORE_KEY)
        if not s:
            return 0, SUCCESS
        node = node_info.node
        if node.metadata.name in s["ignored"]:
            return 0, SUCCESS
        score = 0.0
        for i, (c, sel) in enumerate(s["constraints"]):
            val = node.metadata.labels.get(c.topology_key)
            if val is None:
                continue
            if c.topology_key == LABEL_HOSTNAME:
                cnt = count_pods_match_selector(node_info.pods, sel, pod.metadata.namespace)
            else:
                cnt = s["tp_counts"][i].get(val, 0)
            score += cnt * s["weights"][i] + (c.max_skew - 1)
        return int(round(score)), SUCCESS

    def normalize_score(self, state: CycleState, pod, scores: Dict[str, int]) -> Status:
        s = state.read_or_none(_SCORE_KEY)
        if not s:
            return SUCCESS
        ignored = s["ignored"]
        valid = {k: v for k, v in scores.items() if k not in ignored}
        if not valid:
            for k in scores:
                scores[k] = 0
            return SUCCESS
        min_score = min(valid.values())
        max_score = max(valid.values())
        for k in scores:
            if k in ignored:
                scores[k] = 0
            elif max_score == 0:
                scores[k] = MAX_NODE_SCORE
            else:
                scores[k] = MAX_NODE_SCORE * (max_score + min_score - scores[k]) // max_score
        return SUCCESS
