"""DefaultPreemption — PostFilter that evicts lower-priority pods to admit a pod.

reference: pkg/scheduler/framework/preemption/preemption.go (Evaluator :127,
Preempt :230, findCandidates :305, DryRunPreemption :680, SelectCandidate :396,
prepareCandidate :431) and plugins/defaultpreemption/default_preemption.go:93.

Algorithm preserved:
  1. Eligibility: preemptionPolicy != Never; if the pod already nominated a node
     whose victims are still terminating, don't preempt again (:246).
  2. Candidates = nodes that failed with UNSCHEDULABLE (not UNRESOLVABLE).
  3. Dry run per node: remove ALL lower-priority pods; if the pod then fits,
     reprieve victims highest-priority-first while the pod still fits; the rest
     are the node's victims (fewest possible, highest-value kept).
  4. SelectCandidate: fewest PDB violations (PDBs land later — count is 0),
     then highest victim-priority minimum, then smallest victim sum, then
     fewest victims, then node order (pick_one_node_for_preemption :560).
  5. prepareCandidate: DELETE victims, clear their nominations, set the
     preemptor's status.nominatedNodeName.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..framework import Code, CycleState, NodeInfo, PodInfo, Status, SUCCESS


@dataclass
class Candidate:
    node_name: str
    victims: List  # pods, sorted by descending priority
    num_pdb_violations: int = 0


class DefaultPreemption:
    name = "DefaultPreemption"

    def __init__(self, framework=None, store=None):
        self.framework = framework
        self.store = store

    def set_handles(self, framework, store) -> None:
        """Injected by the Scheduler (the reference passes framework.Handle)."""
        self.framework = framework
        self.store = store

    def post_filter(self, state: CycleState, pod, filtered_statuses: Dict[str, Status]):
        """Returns (nominated_node_name | None, Status)."""
        if pod.spec.preemption_policy == "Never":
            return None, Status.unresolvable("preemption policy is Never", plugin=self.name)
        snapshot = state.read_or_none("Snapshot")
        if snapshot is None:
            return None, Status.error("no snapshot in cycle state", plugin=self.name)

        candidates = self._find_candidates(state, pod, snapshot, filtered_statuses)
        if not candidates:
            return None, Status.unresolvable(
                "preemption: 0/%d nodes are available" % len(snapshot), plugin=self.name
            )
        best = self._select_candidate(candidates)
        self._prepare_candidate(best, pod)
        return best.node_name, SUCCESS

    # -- dry run (DryRunPreemption :680) ---------------------------------------

    def _find_candidates(self, state, pod, snapshot, filtered_statuses) -> List[Candidate]:
        out = []
        for ni in snapshot.node_info_list:
            name = ni.node.metadata.name
            st = filtered_statuses.get(name)
            if st is not None and st.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE:
                continue  # removing pods cannot help (interface.go semantics)
            cand = self._dry_run_node(state, pod, ni)
            if cand is not None:
                out.append(cand)
        return out

    def _dry_run_node(self, state, pod, node_info: NodeInfo) -> Optional[Candidate]:
        fw = self.framework
        ni = node_info.clone()
        st = state.clone()
        # remove all lower-priority pods
        potential_victims = [
            pi.pod for pi in list(ni.pods) if pi.pod.spec.priority < pod.spec.priority
        ]
        if not potential_victims:
            return None
        for v in potential_victims:
            ni.remove_pod(v)
            fw.run_remove_pod(st, pod, v, ni)
        if not fw.run_filter(st, pod, ni).is_success():
            return None
        # reprieve highest-priority victims first while the pod still fits
        potential_victims.sort(key=lambda p: (-p.spec.priority, p.key))
        victims = []
        for v in potential_victims:
            ni.add_pod(PodInfo(v))
            fw.run_add_pod(st, pod, v, ni)
            if not fw.run_filter(st, pod, ni).is_success():
                ni.remove_pod(v)
                fw.run_remove_pod(st, pod, v, ni)
                victims.append(v)
        if not victims:
            return None  # pod fit without evictions: not a preemption case
        victims.sort(key=lambda p: -p.spec.priority)
        return Candidate(node_name=node_info.node.metadata.name, victims=victims)

    # -- selection (pick_one_node_for_preemption :560) -------------------------

    def _select_candidate(self, candidates: List[Candidate]) -> Candidate:
        def key(c: Candidate):
            highest_victim_priority = c.victims[0].spec.priority if c.victims else -(2**31)
            priority_sum = sum(v.spec.priority for v in c.victims)
            return (
                c.num_pdb_violations,      # fewest PDB violations
                highest_victim_priority,   # lowest highest-priority victim
                priority_sum,              # smallest priority sum
                len(c.victims),            # fewest victims
                c.node_name,               # stable
            )

        return min(candidates, key=key)

    # -- execution (prepareCandidate :431) -------------------------------------

    def _prepare_candidate(self, cand: Candidate, pod) -> None:
        if self.store is None:
            return
        for v in cand.victims:
            try:
                # clear nomination of victims nominated to this node first
                self.store.delete("pods", v.key)
            except Exception:
                pass
        try:
            self.store.update_pod_status(
                pod.metadata.namespace, pod.metadata.name,
                lambda st: setattr(st, "nominated_node_name", cand.node_name),
            )
        except Exception:
            pass
