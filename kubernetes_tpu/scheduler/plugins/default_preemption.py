"""DefaultPreemption — PostFilter that evicts lower-priority pods to admit a pod.

reference: pkg/scheduler/framework/preemption/preemption.go (Evaluator :127,
Preempt :230, findCandidates :305, DryRunPreemption :680, SelectCandidate :396,
prepareCandidate :431) and plugins/defaultpreemption/default_preemption.go:93.

Algorithm preserved:
  1. Eligibility: preemptionPolicy != Never; if the pod already nominated a node
     whose victims are still terminating, don't preempt again (:246).
  2. Candidates = nodes that failed with UNSCHEDULABLE (not UNRESOLVABLE).
  3. Dry run per node: remove ALL lower-priority pods; if the pod then fits,
     reprieve victims while the pod still fits — PDB-violating victims first
     (so they are most likely to be kept), then non-violating, each
     highest-priority-first (selectVictimsOnNode + filterPodsWithPDBViolation);
     reprieve failures among the violating set count as PDB violations.
  4. SelectCandidate: fewest PDB violations, then highest victim-priority
     minimum, then smallest victim sum, then fewest victims, then node order
     (pick_one_node_for_preemption :560).
  5. prepareCandidate[Async]: DELETE victims (async on a worker thread when
     async_preparation is on — prepareCandidateAsync :470), set the
     preemptor's status.nominatedNodeName synchronously.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..framework import Code, CycleState, NodeInfo, PodInfo, Status, SUCCESS


@dataclass
class Candidate:
    node_name: str
    victims: List  # pods, sorted by descending priority
    num_pdb_violations: int = 0


class DefaultPreemption:
    name = "DefaultPreemption"

    # candidate search caps (defaultpreemption config defaults:
    # minCandidateNodesPercentage 10, minCandidateNodesAbsolute 100)
    MIN_CANDIDATE_NODES_PERCENTAGE = 10
    MIN_CANDIDATE_NODES_ABSOLUTE = 100

    def __init__(self, framework=None, store=None,
                 async_preparation: Optional[bool] = None):
        from ...utils.featuregate import feature_gates

        self.framework = framework
        self.store = store
        # SchedulerAsyncPreemption: victim deletion off the scheduling thread.
        # Defaults from the feature gate (beta, on — registry.go:45-60).
        if async_preparation is None:
            async_preparation = feature_gates.enabled("SchedulerAsyncPreemption")
        self.async_preparation = async_preparation
        # one shared deletion worker (prepareCandidateAsync :470 runs one
        # goroutine per candidate; a queue bounds thread count under batches)
        self._prep_q = None  # queue.Queue, created lazily
        self._prep_thread: Optional[threading.Thread] = None
        # bulk-delete fallback warnings, one per exception type (see
        # _delete_victims: a silent fallback would hide a native regression)
        self._bulk_delete_warned: set = set()

    def set_handles(self, framework, store, recorder=None) -> None:
        """Injected by the Scheduler (the reference passes framework.Handle)."""
        self.framework = framework
        self.store = store
        if recorder is not None:
            self._recorder = recorder

    def _pdbs(self):
        if self.store is None:
            return []
        pdbs, _ = self.store.list("poddisruptionbudgets")
        return pdbs

    def post_filter(self, state: CycleState, pod, filtered_statuses: Dict[str, Status]):
        """Returns (nominated_node_name | None, Status)."""
        if pod.spec.preemption_policy == "Never":
            return None, Status.unresolvable("preemption policy is Never", plugin=self.name)
        snapshot = state.read_or_none("Snapshot")
        if snapshot is None:
            return None, Status.error("no snapshot in cycle state", plugin=self.name)

        candidates = self._find_candidates(state, pod, snapshot, filtered_statuses)
        if not candidates:
            return None, Status.unresolvable(
                "preemption: 0/%d nodes are available" % len(snapshot), plugin=self.name
            )
        best = self._select_candidate(candidates)
        self._prepare_candidate(best, pod)
        return best.node_name, SUCCESS

    # -- dry run (DryRunPreemption :680) ---------------------------------------

    def _find_candidates(self, state, pod, snapshot, filtered_statuses) -> List[Candidate]:
        pdbs = self._pdbs()
        # candidate cap (GetOffsetAndNumCandidates, preemption.go:595): dry-run
        # until enough candidates are found instead of sweeping every node
        n = len(snapshot.node_info_list)
        num_candidates = max(self.MIN_CANDIDATE_NODES_ABSOLUTE,
                             n * self.MIN_CANDIDATE_NODES_PERCENTAGE // 100)
        out = []
        for ni in snapshot.node_info_list:
            name = ni.node.metadata.name
            st = filtered_statuses.get(name)
            if st is not None and st.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE:
                continue  # removing pods cannot help (interface.go semantics)
            cand = self._dry_run_node(state, pod, ni, pdbs)
            if cand is not None:
                out.append(cand)
                if len(out) >= num_candidates:
                    break
        return out

    @staticmethod
    def _split_pdb_violating(victims, pdbs):
        """filterPodsWithPDBViolation (preemption.go): a victim violates when it
        matches a PDB with no disruption budget left; each non-violating match
        consumes one unit of that PDB's remaining allowance."""
        allowed = [p.disruptions_allowed for p in pdbs]
        violating, non_violating = [], []
        for v in victims:
            hits = [i for i, p in enumerate(pdbs)
                    if p.metadata.namespace == v.metadata.namespace
                    and p.selector is not None
                    and p.selector.matches(v.metadata.labels)]
            if any(allowed[i] <= 0 for i in hits):
                violating.append(v)
            else:
                for i in hits:
                    allowed[i] -= 1
                non_violating.append(v)
        return violating, non_violating

    def _dry_run_node(self, state, pod, node_info: NodeInfo, pdbs) -> Optional[Candidate]:
        fw = self.framework
        ni = node_info.clone()
        st = state.clone()
        # remove all lower-priority pods
        potential_victims = [
            pi.pod for pi in list(ni.pods) if pi.pod.spec.priority < pod.spec.priority
        ]
        if not potential_victims:
            return None
        for v in potential_victims:
            ni.remove_pod(v)
            fw.run_remove_pod(st, pod, v, ni)
        if not fw.run_filter(st, pod, ni).is_success():
            return None
        # reprieve while the pod still fits: PDB-violating victims first (most
        # likely to be KEPT), then non-violating; highest priority first within
        # each set (selectVictimsOnNode)
        potential_victims.sort(key=lambda p: (-p.spec.priority, p.key))
        violating, non_violating = self._split_pdb_violating(potential_victims, pdbs)
        victims = []
        num_violations = 0

        def reprieve(v) -> bool:
            ni.add_pod(PodInfo(v))
            fw.run_add_pod(st, pod, v, ni)
            if fw.run_filter(st, pod, ni).is_success():
                return True
            ni.remove_pod(v)
            fw.run_remove_pod(st, pod, v, ni)
            victims.append(v)
            return False

        for v in violating:
            if not reprieve(v):
                num_violations += 1
        for v in non_violating:
            reprieve(v)
        if not victims:
            return None  # pod fit without evictions: not a preemption case
        victims.sort(key=lambda p: -p.spec.priority)
        return Candidate(node_name=node_info.node.metadata.name, victims=victims,
                         num_pdb_violations=num_violations)

    # -- selection (pick_one_node_for_preemption :560) -------------------------

    def _select_candidate(self, candidates: List[Candidate]) -> Candidate:
        def key(c: Candidate):
            highest_victim_priority = c.victims[0].spec.priority if c.victims else -(2**31)
            priority_sum = sum(v.spec.priority for v in c.victims)
            return (
                c.num_pdb_violations,      # fewest PDB violations
                highest_victim_priority,   # lowest highest-priority victim
                priority_sum,              # smallest priority sum
                len(c.victims),            # fewest victims
                c.node_name,               # stable
            )

        return min(candidates, key=key)

    # -- execution (prepareCandidate :431 / prepareCandidateAsync :470) --------

    def _prepare_candidate(self, cand: Candidate, pod) -> None:
        if self.store is None:
            return
        # nomination is set synchronously either way — the next cycle's
        # nominated-node fast path depends on it (schedule_one.go:492)
        try:
            self.store.update_pod_status(
                pod.metadata.namespace, pod.metadata.name,
                lambda st: setattr(st, "nominated_node_name", cand.node_name),
            )
        except Exception:
            pass
        # async mode moves the WHOLE per-victim preparation — narration
        # events and DELETE writes — onto the worker (the reference's
        # prepareCandidateAsync runs everything after nomination in a
        # goroutine). Each recorder.event is a store write (~ms); paying
        # victims x that on the scheduling thread was why PreemptionAsync
        # benched no faster than the serial mode.
        if self.async_preparation:
            self._ensure_prep_worker()
            self._prep_q.put((list(cand.victims), pod.metadata.name,
                              cand.node_name))
        else:
            self._narrate_victims(cand.victims, pod.metadata.name,
                                  cand.node_name)
            self._delete_victims(cand.victims)

    def _narrate_victims(self, victims, preemptor_name: str,
                         node_name: str) -> None:
        """Victim narration (prepareCandidate's "Preempted" event) — uses the
        scheduler's recorder (shared clock/aggregation) when injected."""
        try:
            recorder = getattr(self, "_recorder", None)
            if recorder is None:
                from ...api.events import EventRecorder

                recorder = self._recorder = EventRecorder(
                    self.store, component="default-scheduler")
            for v in victims:
                recorder.event(
                    v, "Normal", "Preempted",
                    f"Preempted by pod {preemptor_name} on node {node_name}")
        except Exception:
            pass

    def _ensure_prep_worker(self) -> None:
        import queue as _q

        if self._prep_q is None:
            self._prep_q = _q.Queue()
        if self._prep_thread is None or not self._prep_thread.is_alive():
            self._prep_thread = threading.Thread(target=self._prep_loop, daemon=True)
            self._prep_thread.start()

    def _prep_loop(self) -> None:
        while True:
            victims, preemptor_name, node_name = self._prep_q.get()
            try:
                self._narrate_victims(victims, preemptor_name, node_name)
                self._delete_victims(victims)
            finally:
                self._prep_q.task_done()

    def _delete_victims(self, victims) -> None:
        # Batched victim deletion (ISSUE 11 satellite): one store critical
        # section + one coalesced DELETED batch through the same native
        # commit entry bind_many uses (store.delete_pods), instead of a
        # store.delete per victim — the per-victim lock/emit cycle was the
        # GIL-bound residual that kept PreemptionAsync at 1.37x of its async
        # baseline. Per-key misses come back as errors, matching the old
        # loop's per-victim exception swallowing. Store doubles without the
        # bulk surface (test fakes) keep the per-pod path.
        delete_pods = getattr(self.store, "delete_pods", None)
        if delete_pods is not None:
            try:
                delete_pods([v.key for v in victims])
                return
            except Exception as e:
                # fall through to the per-pod oracle — but NEVER silently: a
                # regressed bulk path would otherwise quietly degrade every
                # victim deletion to the slow per-pod loop (one warning per
                # failure type, not per victim set — no log storms)
                kind = type(e).__name__
                if kind not in self._bulk_delete_warned:
                    self._bulk_delete_warned.add(kind)
                    from ...utils.tracing import default_logger

                    default_logger.warning(
                        "delete_pods (bulk victim deletion) failed; falling "
                        "back to per-pod deletes", error=f"{kind}: {e}",
                        victims=len(victims))
        for v in victims:
            try:
                self.store.delete("pods", v.key)
            except Exception:
                pass

    def wait_for_preparation(self, timeout: float = 5.0) -> None:
        """Wait (bounded) for outstanding async victim deletions (test/quiesce
        hook); a hung store delete must not block the caller forever."""
        import time

        if self._prep_q is None:
            return
        deadline = time.monotonic() + timeout
        while self._prep_q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.005)
