"""Serial plugin implementations — the correctness oracle for the TPU path.

Default enabled set mirrors apis/config/v1/default_plugins.go:30-56.
"""

from .default_preemption import DefaultPreemption  # noqa: F401
from .fit import BalancedAllocation, NodeResourcesFit  # noqa: F401
from .interpod_affinity import InterPodAffinity  # noqa: F401
from .node_plugins import (  # noqa: F401
    ImageLocality,
    NodeAffinity,
    NodeName,
    NodePorts,
    NodeUnschedulable,
    PrioritySort,
    SchedulingGates,
    TaintToleration,
)
from .topology_spread import PodTopologySpread  # noqa: F401
from .volume import (  # noqa: F401
    NodeVolumeLimits,
    VolumeBinding,
    VolumeLister,
    VolumeRestrictions,
    VolumeZone,
)


def default_plugins(volume_lister=None):
    """Registry + default ordering (plugins/registry.go:64, default_plugins.go:30).
    DynamicResources joins the set behind its feature gate, exactly like the
    reference's registry (plugins/registry.go:45-60)."""
    from ...utils.featuregate import feature_gates

    vl = volume_lister if volume_lister is not None else VolumeLister()
    plugins = [
        PrioritySort(),
        SchedulingGates(),
        NodeUnschedulable(),
        NodeName(),
        TaintToleration(),
        NodeAffinity(),
        NodePorts(),
        NodeResourcesFit(),
        VolumeRestrictions(vl),
        NodeVolumeLimits(vl),
        VolumeBinding(vl),
        VolumeZone(vl),
        PodTopologySpread(),
        InterPodAffinity(),
        BalancedAllocation(),
        ImageLocality(),
        DefaultPreemption(),
    ]
    try:
        dra_on = feature_gates.enabled("DynamicResourceAllocation")
    except KeyError:
        dra_on = False
    if dra_on:
        from .dynamic_resources import DynamicResources

        plugins.insert(8, DynamicResources())
    return plugins
