"""Serial plugin implementations — the correctness oracle for the TPU path.

Default enabled set mirrors apis/config/v1/default_plugins.go:30-56 (minus the
volume plugins, which gate on a volume subsystem this build adds later).
"""

from .default_preemption import DefaultPreemption  # noqa: F401
from .fit import BalancedAllocation, NodeResourcesFit  # noqa: F401
from .interpod_affinity import InterPodAffinity  # noqa: F401
from .node_plugins import (  # noqa: F401
    ImageLocality,
    NodeAffinity,
    NodeName,
    NodePorts,
    NodeUnschedulable,
    PrioritySort,
    SchedulingGates,
    TaintToleration,
)
from .topology_spread import PodTopologySpread  # noqa: F401


def default_plugins():
    """Registry + default ordering (plugins/registry.go:64, default_plugins.go:30)."""
    return [
        PrioritySort(),
        SchedulingGates(),
        NodeUnschedulable(),
        NodeName(),
        TaintToleration(),
        NodeAffinity(),
        NodePorts(),
        NodeResourcesFit(),
        PodTopologySpread(),
        InterPodAffinity(),
        BalancedAllocation(),
        ImageLocality(),
        DefaultPreemption(),
    ]
