"""Serial plugin implementations — the correctness oracle for the TPU path.

Default enabled set mirrors apis/config/v1/default_plugins.go:30-56.
"""

from .default_preemption import DefaultPreemption  # noqa: F401
from .fit import BalancedAllocation, NodeResourcesFit  # noqa: F401
from .interpod_affinity import InterPodAffinity  # noqa: F401
from .node_plugins import (  # noqa: F401
    ImageLocality,
    NodeAffinity,
    NodeName,
    NodePorts,
    NodeUnschedulable,
    PrioritySort,
    SchedulingGates,
    TaintToleration,
)
from .topology_spread import PodTopologySpread  # noqa: F401
from .volume import (  # noqa: F401
    NodeVolumeLimits,
    VolumeBinding,
    VolumeLister,
    VolumeRestrictions,
    VolumeZone,
)


def default_plugins(volume_lister=None):
    """Registry + default ordering (plugins/registry.go:64, default_plugins.go:30)."""
    vl = volume_lister if volume_lister is not None else VolumeLister()
    return [
        PrioritySort(),
        SchedulingGates(),
        NodeUnschedulable(),
        NodeName(),
        TaintToleration(),
        NodeAffinity(),
        NodePorts(),
        NodeResourcesFit(),
        VolumeRestrictions(vl),
        NodeVolumeLimits(vl),
        VolumeBinding(vl),
        VolumeZone(vl),
        PodTopologySpread(),
        InterPodAffinity(),
        BalancedAllocation(),
        ImageLocality(),
        DefaultPreemption(),
    ]
