"""Scheduler ComponentConfig: versioned KubeSchedulerConfiguration with
defaulting, validation, and profile -> Framework construction.

reference: pkg/scheduler/apis/config/types.go (KubeSchedulerConfiguration :37,
Parallelism :49, PercentageOfNodesToScore :70, PodInitialBackoffSeconds :75,
KubeSchedulerProfile :100, Plugins :138) and v1 defaults
(apis/config/v1/default_plugins.go:30). Parses the same YAML/JSON shape a
`kubescheduler.config.k8s.io/v1` file has, so existing config files work.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

from ..api.types import DEFAULT_SCHEDULER_NAME
from .extender import ExtenderConfig, HTTPExtender
from .runtime import DEFAULT_WEIGHTS, Framework

# Extension points as named in config files (types.go Plugins struct fields).
EXTENSION_POINTS = (
    "preEnqueue", "queueSort", "preFilter", "filter", "postFilter",
    "preScore", "score", "reserve", "permit", "preBind", "bind", "postBind",
)

# config point name -> plugin method the runtime dispatches on
_POINT_TO_METHOD = {
    "preEnqueue": "pre_enqueue",
    "queueSort": "less",
    "preFilter": "pre_filter",
    "filter": "filter",
    "postFilter": "post_filter",
    "preScore": "pre_score",
    "score": "score",
    "reserve": "reserve",
    "permit": "permit",
    "preBind": "pre_bind",
    "bind": "bind",
    "postBind": "post_bind",
}


@dataclass
class PluginSet:
    """One extension point's enabled/disabled lists (types.go PluginSet)."""

    enabled: List[Tuple[str, int]] = field(default_factory=list)  # (name, weight)
    disabled: List[str] = field(default_factory=list)  # names or "*"

    @staticmethod
    def from_dict(d: Optional[Mapping]) -> "PluginSet":
        d = d or {}
        return PluginSet(
            enabled=[(e["name"], int(e.get("weight", 0) or 0))
                     for e in d.get("enabled") or []],
            disabled=[e["name"] if isinstance(e, Mapping) else e
                      for e in d.get("disabled") or []],
        )


@dataclass
class KubeSchedulerProfile:
    """types.go KubeSchedulerProfile :100."""

    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    percentage_of_nodes_to_score: Optional[int] = None
    plugins: Dict[str, PluginSet] = field(default_factory=dict)  # point -> set
    plugin_config: Dict[str, Dict] = field(default_factory=dict)  # plugin -> args

    @staticmethod
    def from_dict(d: Mapping) -> "KubeSchedulerProfile":
        return KubeSchedulerProfile(
            scheduler_name=d.get("schedulerName", DEFAULT_SCHEDULER_NAME),
            percentage_of_nodes_to_score=d.get("percentageOfNodesToScore"),
            plugins={point: PluginSet.from_dict((d.get("plugins") or {}).get(point))
                     for point in EXTENSION_POINTS
                     if point in (d.get("plugins") or {})},
            plugin_config={e["name"]: dict(e.get("args") or {})
                           for e in d.get("pluginConfig") or []},
        )


@dataclass
class KubeSchedulerConfiguration:
    """types.go KubeSchedulerConfiguration :37 (the scheduler-relevant subset)."""

    parallelism: int = 16
    percentage_of_nodes_to_score: int = 0  # 0 = adaptive (schedule_one.go:675)
    pod_initial_backoff_seconds: float = 1.0  # scheduler.go:252
    pod_max_backoff_seconds: float = 10.0  # scheduler.go:253
    profiles: List[KubeSchedulerProfile] = field(default_factory=list)
    extenders: List[ExtenderConfig] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Optional[Mapping]) -> "KubeSchedulerConfiguration":
        d = d or {}
        def opt(key, default, cast):
            v = d.get(key)
            return default if v is None else cast(v)

        cfg = KubeSchedulerConfiguration(
            parallelism=opt("parallelism", 16, int),
            percentage_of_nodes_to_score=opt("percentageOfNodesToScore", 0, int),
            pod_initial_backoff_seconds=opt("podInitialBackoffSeconds", 1.0, float),
            pod_max_backoff_seconds=opt("podMaxBackoffSeconds", 10.0, float),
            profiles=[KubeSchedulerProfile.from_dict(p) for p in d.get("profiles") or []],
            extenders=[ExtenderConfig.from_dict(e) for e in d.get("extenders") or []],
        )
        if not cfg.profiles:
            cfg.profiles = [KubeSchedulerProfile()]
        return cfg

    def validate(self) -> None:
        """apis/config/validation/validation.go ValidateKubeSchedulerConfiguration."""
        errs = []
        if self.parallelism <= 0:
            errs.append("parallelism must be greater than 0")
        if not 0 <= self.percentage_of_nodes_to_score <= 100:
            errs.append("percentageOfNodesToScore must be in [0, 100]")
        if self.pod_initial_backoff_seconds <= 0:
            errs.append("podInitialBackoffSeconds must be greater than 0")
        if self.pod_max_backoff_seconds < self.pod_initial_backoff_seconds:
            errs.append("podMaxBackoffSeconds must be >= podInitialBackoffSeconds")
        seen = set()
        for prof in self.profiles:
            if not prof.scheduler_name:
                errs.append("profile schedulerName is required")
            if prof.scheduler_name in seen:
                errs.append(f"duplicate profile schedulerName {prof.scheduler_name!r}")
            seen.add(prof.scheduler_name)
            unknown = set(prof.plugins) - set(EXTENSION_POINTS)
            if unknown:
                errs.append(f"unknown extension points {sorted(unknown)}")
            for point, ps in prof.plugins.items():
                for name, weight in ps.enabled:
                    if name != "*" and name not in plugin_registry():
                        errs.append(f"unknown plugin {name!r} at {point}")
                    if weight < 0:
                        errs.append(f"negative weight for {name!r}")
        for ext in self.extenders:
            if not ext.url_prefix:
                errs.append("extender urlPrefix is required")
            if ext.weight <= 0:
                errs.append("extender weight must be positive")
        if errs:
            raise ValueError("; ".join(errs))


def plugin_registry(volume_lister=None) -> Dict[str, object]:
    """Name -> constructed plugin instance (plugins/registry.go:64)."""
    from .plugins import (
        BalancedAllocation,
        DefaultPreemption,
        ImageLocality,
        InterPodAffinity,
        NodeAffinity,
        NodeName,
        NodePorts,
        NodeResourcesFit,
        NodeUnschedulable,
        NodeVolumeLimits,
        PodTopologySpread,
        PrioritySort,
        SchedulingGates,
        TaintToleration,
        VolumeBinding,
        VolumeLister,
        VolumeRestrictions,
        VolumeZone,
    )

    vl = volume_lister if volume_lister is not None else VolumeLister()
    return {
        "PrioritySort": PrioritySort(),
        "SchedulingGates": SchedulingGates(),
        "NodeUnschedulable": NodeUnschedulable(),
        "NodeName": NodeName(),
        "TaintToleration": TaintToleration(),
        "NodeAffinity": NodeAffinity(),
        "NodePorts": NodePorts(),
        "NodeResourcesFit": NodeResourcesFit(),
        "VolumeRestrictions": VolumeRestrictions(vl),
        "NodeVolumeLimits": NodeVolumeLimits(vl),
        "VolumeBinding": VolumeBinding(vl),
        "VolumeZone": VolumeZone(vl),
        "PodTopologySpread": PodTopologySpread(),
        "InterPodAffinity": InterPodAffinity(),
        "NodeResourcesBalancedAllocation": BalancedAllocation(),
        "ImageLocality": ImageLocality(),
        "DefaultPreemption": DefaultPreemption(),
    }


# Default plugin order (default_plugins.go:30); weights in runtime.DEFAULT_WEIGHTS.
DEFAULT_PLUGIN_ORDER = (
    "PrioritySort", "SchedulingGates", "NodeUnschedulable", "NodeName",
    "TaintToleration", "NodeAffinity", "NodePorts", "NodeResourcesFit",
    "VolumeRestrictions", "NodeVolumeLimits", "VolumeBinding", "VolumeZone",
    "PodTopologySpread", "InterPodAffinity", "NodeResourcesBalancedAllocation",
    "ImageLocality", "DefaultPreemption",
)


def build_framework(profile: KubeSchedulerProfile, volume_lister=None) -> Framework:
    """Default plugins +- the profile's per-point enabled/disabled deltas
    (v1/default_plugins.go mergePlugins semantics, name-keyed)."""
    registry = plugin_registry(volume_lister)
    order = [n for n in DEFAULT_PLUGIN_ORDER]
    weights = dict(DEFAULT_WEIGHTS)
    disabled_points: Set[Tuple[str, str]] = set()
    for point, ps in profile.plugins.items():
        method = _POINT_TO_METHOD[point]
        if "*" in ps.disabled:
            for name in order:
                if hasattr(registry[name], method):
                    disabled_points.add((name, method))
        else:
            for name in ps.disabled:
                disabled_points.add((name, method))
        for name, weight in ps.enabled:
            disabled_points.discard((name, method))
            if name not in order:
                order.append(name)
            if point == "score" and weight:
                weights[name] = weight
    plugins = [registry[n] for n in order if n in registry]
    fw = Framework(plugins, weights=weights, disabled_points=disabled_points)
    fw.profile_name = profile.scheduler_name
    fw.percentage_of_nodes_to_score = profile.percentage_of_nodes_to_score
    return fw


def build_profiles(
    config: KubeSchedulerConfiguration, volume_lister=None,
) -> Tuple[Dict[str, Framework], List[HTTPExtender]]:
    """profile.NewMap (profile/profile.go) + extender construction."""
    config.validate()
    profiles = {p.scheduler_name: build_framework(p, volume_lister)
                for p in config.profiles}
    extenders = [HTTPExtender(e) for e in config.extenders]
    return profiles, extenders
