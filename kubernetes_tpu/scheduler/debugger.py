"""Scheduler cache debugger: dump + cache-vs-store comparer.

reference: pkg/scheduler/backend/cache/debugger (debugger.go:32 — SIGUSR2
dumps the cache and queue; comparer.go diffs cached state against the
apiserver's). `install_signal_handler` wires the same SIGUSR2 behavior.
"""

from __future__ import annotations

import signal
from typing import Dict, List, Optional


def dump(scheduler) -> Dict:
    """Snapshot of cache + queue contents (dumper.go dumpNodes/dumpSchedulingQueue)."""
    snapshot = scheduler.cache.update_snapshot()
    nodes = {}
    for ni in snapshot.node_info_list:
        nodes[ni.node.metadata.name] = {
            "pods": sorted(pi.pod.key for pi in ni.pods),
            "requested": {"milliCPU": ni.requested.milli_cpu,
                          "memory": ni.requested.memory},
            "allocatable": {"milliCPU": ni.allocatable.milli_cpu,
                            "memory": ni.allocatable.memory},
        }
    active, backoff, unschedulable = scheduler.queue.lengths()
    return {
        "nodes": nodes,
        "queue": {"active": active, "backoff": backoff,
                  "unschedulable": unschedulable},
        "assumed": sorted(getattr(scheduler.cache, "_assumed", {})),
    }


def compare(scheduler) -> List[str]:
    """Cache-vs-store diff (comparer.go CompareNodes/ComparePods): returns
    human-readable discrepancy lines, empty when consistent."""
    problems: List[str] = []
    store_nodes, _ = scheduler.store.list("nodes")
    store_node_names = {n.metadata.name for n in store_nodes}
    snapshot = scheduler.cache.update_snapshot()
    cached_names = {ni.node.metadata.name for ni in snapshot.node_info_list}
    for name in sorted(store_node_names - cached_names):
        problems.append(f"node {name} in store but not in scheduler cache")
    for name in sorted(cached_names - store_node_names):
        problems.append(f"node {name} in scheduler cache but not in store")
    store_pods, _ = scheduler.store.list(
        "pods", lambda p: bool(p.spec.node_name) and not p.is_terminal())
    store_keys = {p.key for p in store_pods}
    cached_keys = set()
    for ni in snapshot.node_info_list:
        cached_keys.update(pi.pod.key for pi in ni.pods)
    # assumed pods are in the cache ahead of their Binding write landing in
    # the store — that window is healthy, not an inconsistency (comparer.go
    # filters assumed pods the same way)
    assumed = set(getattr(scheduler.cache, "_assumed", {}))
    for key in sorted(store_keys - cached_keys):
        problems.append(f"pod {key} bound in store but missing from cache")
    for key in sorted(cached_keys - store_keys - assumed):
        problems.append(f"pod {key} in cache but not bound in store")
    return problems


def install_signal_handler(scheduler, logger=None) -> None:
    """SIGUSR2 -> dump + compare to the structured log (debugger.go:71)."""
    from ..utils.tracing import default_logger

    log = logger or default_logger

    def handle(signum, frame):
        log.info("scheduler cache dump", dump=dump(scheduler))
        problems = compare(scheduler)
        if problems:
            log.warning("cache/store inconsistency", problems=problems)
        else:
            log.info("cache consistent with store")

    signal.signal(signal.SIGUSR2, handle)
