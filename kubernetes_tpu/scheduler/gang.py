"""Gang scheduling: all-or-nothing PodGroup placement for the batched solver.

A multi-host training job is a gang of ranks; placing half of it deadlocks the
cluster (the placed ranks hold capacity waiting for peers that never come —
the failure mode gang schedulers exist to prevent; Tesserae and the
rank-aware-MPI line in PAPERS.md both reason about whole jobs). Three pieces,
wired into the existing pipeline rather than a parallel one:

  directory   — GangDirectory mirrors PodGroup objects (min_member quorum) and
                the set of members already placed (assumed or bound), fed by
                the scheduler's ordinary watch ingest + assume/forget hooks.
  queue gate  — SchedulingQueue holds gang members in a staging area until the
                group reaches quorum, then admits the whole gang contiguously
                so ONE solver batch sees it together (scheduler/queue.py).
  batch veto  — after the device solve, gangs whose placed-count (in-batch +
                already-placed) misses min_member are stripped BEFORE any
                assume/bind and requeued as a unit with backoff; a gang that
                loses a member at assume time releases every already-assumed
                sibling through the existing Cache accounting
                (BatchScheduler.schedule_batch).

Topology packing: nodes advertise their TPU slice (ICI domain) via
LABEL_TPU_SLICE — the cluster-level analog of parallel/multislice
.slice_topology's device slice_index grouping. gang_slice_bonus computes a
per-(class, node) score bonus for the slice that best-fits the gang, so a
gang's ranks prefer to land inside one interconnect domain (per-step
collectives stay on ICI; only batch-level traffic crosses DCN).

Everything here is pay-for-what-you-use: with no PodGroup objects the
directory is inactive, the tensorizer threads no gang rows, the solvers
compile their gang-free variants, and the queue hooks cost one check per
admission batch.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..api import Pod
from ..api.podgroup import (LABEL_TPU_SLICE, LABEL_TPU_SLICE_INDEX,
                            pod_gang_rank, pod_group_key)

# Score bonus for nodes on a gang's chosen slice. Sized like one full plugin
# score (MAX_NODE_SCORE): it dominates the least-allocated/balanced deltas
# between near-equal nodes without overriding feasibility or hard plugin
# vetoes (which mask the score entirely). The waterfill sort key budgets for
# it explicitly (models/waterfill.py slot guard).
GANG_SLICE_BONUS = 100


class GangDirectory:
    """Authoritative gang state inside the scheduler: PodGroup quorums and the
    members already placed (assumed by us or observed bound). Mutated from
    the scheduling thread (watch ingest, assume) and the bind worker
    (bind-failure forgets) — a small lock keeps the two honest."""

    def __init__(self):
        self._lock = threading.Lock()
        self._min: Dict[str, int] = {}  # group key -> min_member
        self._placed: Dict[str, Set[str]] = {}  # group key -> placed pod keys

    # -- activity gate (the pay-for-what-you-use switch) -----------------------

    @property
    def active(self) -> bool:
        """True once any PodGroup exists; every per-pod gang code path is
        gated on this so gang-free clusters pay one attribute read."""
        return bool(self._min)

    # -- membership ------------------------------------------------------------

    @staticmethod
    def group_of(pod: Pod) -> Optional[str]:
        key = pod_group_key(pod)
        return key or None

    def min_member(self, group: str) -> Optional[int]:
        return self._min.get(group)

    def placed_count(self, group: str) -> int:
        got = self._placed.get(group)
        return len(got) if got else 0

    def quorum_ready(self, group: str, staged_count: int) -> Optional[bool]:
        """Queue-side gate: True admits a staged gang (staged + already-
        placed members reach min_member), False keeps it waiting, None means
        the group has NO PodGroup (deleted, or not created yet) — falsy for
        the wait path, but the queue's staleness sweep uses it to release
        long-stranded members as ordinary pods instead of parking them
        forever."""
        m = self._min.get(group)
        if m is None:
            return None
        return staged_count + self.placed_count(group) >= m

    # -- watch-fed state -------------------------------------------------------

    def observe_podgroup(self, etype: str, pg) -> None:
        from ..store import DELETED

        with self._lock:
            if etype == DELETED:
                self._min.pop(pg.key, None)
            else:
                self._min[pg.key] = max(1, pg.spec.min_member)

    def observe_pod(self, etype: str, pod: Pod) -> None:
        """Track placed members from the ordinary pod event stream: bound,
        non-terminal members count toward quorum; deletes/terminals free the
        slot. Unlabeled pods return on the first dict lookup."""
        group = pod_group_key(pod)
        if not group:
            return
        from ..store import DELETED

        with self._lock:
            if etype == DELETED or pod.is_terminal() or not pod.spec.node_name:
                got = self._placed.get(group)
                if got is not None:
                    got.discard(pod.key)
                    if not got:
                        self._placed.pop(group, None)
            else:
                self._placed.setdefault(group, set()).add(pod.key)

    def note_assumed(self, pod: Pod) -> None:
        """An accepted member was assumed by the batch scheduler (our own bind
        confirmations short-circuit the event stream, so assume time is when
        we learn about our own placements)."""
        group = pod_group_key(pod)
        if not group:
            return
        with self._lock:
            self._placed.setdefault(group, set()).add(pod.key)

    def note_forgotten(self, pod: Pod) -> None:
        """Assume rolled back (gang veto at assume, bind failure): the member
        no longer counts toward quorum."""
        group = pod_group_key(pod)
        if not group:
            return
        with self._lock:
            got = self._placed.get(group)
            if got is not None:
                got.discard(pod.key)
                if not got:
                    self._placed.pop(group, None)

    def note_expired_keys(self, keys) -> int:
        """Count expired assumes back OUT of the quorum (the leak
        scheduler_gang_quorum_expired_assumes measured, now consumed): the
        pod keys Cache.cleanup_expired_assumed_pods just dropped stop
        counting as placed, so a gang with expired assumed members
        re-evaluates its quorum against reality (and its members re-stage
        via the scheduler's expiry sweep) instead of silently
        under-counting. Returns how many placed entries were removed."""
        removed = 0
        with self._lock:
            for group in list(self._placed):
                got = self._placed[group]
                before = len(got)
                got.difference_update(keys)
                removed += before - len(got)
                if not got:
                    self._placed.pop(group, None)
        return removed

    def quorum_expired_count(self, contains) -> int:
        """How many placed members still counted toward some quorum are no
        longer known to the cache at all (their assume expired without a bind
        confirmation). The scheduler's sweep_expired_assumes consumes the
        leak via note_expired_keys; this gauge
        (scheduler_gang_quorum_expired_assumes) measures what remains
        between sweeps. `contains` is Cache.contains; called OUTSIDE our
        lock, stats-path only."""
        with self._lock:
            keys = [k for placed in self._placed.values() for k in placed]
        return sum(1 for k in keys if not contains(k))

    def reset(self) -> None:
        """Relist: state is rebuilt from the fresh LIST."""
        with self._lock:
            self._min.clear()
            self._placed.clear()

    # -- batch tensorization ---------------------------------------------------

    def batch_rows(self, pods: Sequence[Pod]
                   ) -> Tuple[Optional[np.ndarray], List[str],
                              Optional[np.ndarray]]:
        """Group-id rows for one solver batch: ([P] int32, -1 = not a gang
        member, else an index into the returned group-key list), plus the
        members' rank rows ([P] int32 from the positional rank label, -1
        absent; None when NO member carries a rank — the rank-alignment
        pass stays compiled out, ISSUE 14). Pods whose group has no PodGroup
        object (deleted between admission and solve) read -1 — without a
        quorum they schedule as ordinary pods. Returns (None, [], None)
        when the batch has no gang members at all."""
        rows = np.full(len(pods), -1, dtype=np.int32)
        ranks = np.full(len(pods), -1, dtype=np.int32)
        any_rank = False
        keys: List[str] = []
        idx: Dict[str, int] = {}
        known = self._min
        for i, pod in enumerate(pods):
            group = pod_group_key(pod)
            if not group or group not in known:
                continue
            gi = idx.get(group)
            if gi is None:
                gi = idx[group] = len(keys)
                keys.append(group)
            rows[i] = gi
            r = pod_gang_rank(pod)
            if r >= 0:
                ranks[i] = r
                any_rank = True
        if not keys:
            return None, [], None
        return rows, keys, (ranks if any_rank else None)


def gang_veto_mask(assignment: np.ndarray, gang_rows: np.ndarray,
                   need: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """The all-or-nothing decision for one solved batch (vectorized).

    assignment [K] — node index per pod row, -1 unplaced (the device solve's
    output for the gang's rows); gang_rows [K] — group id per row (-1 none);
    need [G] — members each group still needs placed (min_member minus
    already-placed), from the GangDirectory at veto time.

    Returns (veto [K] bool, satisfied [G] bool): veto marks every row of a
    gang whose in-batch placements miss its need — placed members included,
    so none of them bind; satisfied groups keep their placements (their
    unplaced extras fail individually, without preemption)."""
    g = len(need)
    member = gang_rows >= 0
    placed = member & (assignment >= 0)
    placed_per_group = np.bincount(gang_rows[placed], minlength=g)
    satisfied = placed_per_group >= np.maximum(need, 0)
    veto = member & ~satisfied[np.clip(gang_rows, 0, max(g - 1, 0))]
    return veto, satisfied


def node_slice_ids(cluster) -> Optional[np.ndarray]:
    """[N] int32 slice id per node from LABEL_TPU_SLICE (-1 = unlabeled), or
    None when no node carries the label (non-TPU or single-slice clusters:
    packing is moot). Dictionary-encoded through NodeColumns like every other
    topology key."""
    _vocab, ids = cluster.cols.val_ids(LABEL_TPU_SLICE)
    if (ids < 0).all():
        return None
    return ids


def node_slice_positions(cluster) -> Tuple[Optional[np.ndarray],
                                           Optional[np.ndarray]]:
    """(slice_ids [N], pos [N]) — each node's ICI ring position within its
    slice, for the rank-alignment pass (models/gangcover.py). Positions come
    from LABEL_TPU_SLICE_INDEX when every slice-labeled node carries a
    numeric value; otherwise (mixed or unlabeled) each node's enumeration
    order within its slice — deterministic either way, and exact when nodes
    are listed in ring order. (None, None) when no node has a slice label
    (single-ICI-domain clusters: adjacency is moot)."""
    slice_ids = node_slice_ids(cluster)
    if slice_ids is None:
        return None, None
    n = cluster.n
    vocab, idx_ids = cluster.cols.val_ids(LABEL_TPU_SLICE_INDEX)
    labeled = slice_ids >= 0
    pos = np.full(n, -1, dtype=np.int64)
    parsed = None
    if vocab:
        by_id = {}
        ok = True
        for val, vid in vocab.items():
            try:
                by_id[vid] = int(val)
            except ValueError:
                ok = False
                break
        if ok and bool((idx_ids[labeled] >= 0).all()):
            parsed = np.full(n, -1, dtype=np.int64)
            has = idx_ids >= 0
            parsed[has] = [by_id[v] for v in idx_ids[has].tolist()]
    if parsed is not None:
        pos = np.where(labeled, parsed, -1)
    else:
        # fallback: rank of the node within its slice, in node order
        order = np.argsort(slice_ids[labeled], kind="stable")
        rows = np.nonzero(labeled)[0][order]
        counts: Dict[int, int] = {}
        for i in rows.tolist():
            s = int(slice_ids[i])
            pos[i] = counts.get(s, 0)
            counts[s] = pos[i] + 1
    return slice_ids, pos


def ring_lengths(slice_ids: np.ndarray, pos: np.ndarray) -> Dict[int, int]:
    """Per-slice ICI ring length (max position + 1) — the adjacency
    metric's wrap-around modulus, shared by the scheduler's rank-align
    telemetry, the bench adjacency column, and tests (one definition: a
    position-semantics change lands everywhere at once)."""
    return {int(s): int(pos[slice_ids == s].max()) + 1
            for s in np.unique(slice_ids[slice_ids >= 0]).tolist()}


def gang_slice_bonus(cluster, class_of_pod: np.ndarray, req: np.ndarray,
                     filter_ok: np.ndarray, gang_rows: np.ndarray,
                     n_classes: int) -> Optional[np.ndarray]:
    """Per-(class, node) packing bonus: for each gang, pick the TPU slice that
    best fits the whole gang and award GANG_SLICE_BONUS to its nodes.

    Slice choice is best-fit packing over CURRENT feasible headroom: among
    slices whose member headroom covers the gang's in-batch size, the one
    with the least spare capacity (dense packing leaves big slices whole for
    big gangs); when none covers it, the roomiest slice (partial locality
    still beats scatter). Headroom uses the gang's own request vector against
    alloc-used and the class's static filter row — the same inputs the solver
    sees, so the bonus never points at nodes the gang can't use.

    Classes are gang-exclusive by construction: the gang label is part of
    pod_class_signature, so biasing a class's row never leaks onto non-gang
    pods. Returns [C, N] int32, or None when nodes carry no slice labels."""
    slice_ids = node_slice_ids(cluster)
    if slice_ids is None:
        return None
    n = cluster.n
    n_slices = int(slice_ids.max()) + 1
    alloc = cluster.alloc.astype(np.int64)
    used = cluster.used.astype(np.int64)
    free = np.maximum(alloc - used, 0)
    pod_headroom = np.maximum(
        cluster.max_pods.astype(np.int64) - cluster.pod_count.astype(np.int64), 0)
    bonus = np.zeros((n_classes, n), dtype=np.int32)

    # one representative row per (gang, class) pair present in the batch
    member_rows = np.nonzero(gang_rows >= 0)[0]
    gang_sizes = np.bincount(gang_rows[member_rows])
    seen = set()
    for i in member_rows.tolist():
        ci = int(class_of_pod[i])
        gi = int(gang_rows[i])
        if (gi, ci) in seen:
            continue
        seen.add((gi, ci))
        r = req[i].astype(np.int64)
        nz = r > 0
        if nz.any():
            cap = (free[:, nz] // r[nz]).min(axis=1)
        else:
            cap = np.full(n, 2**31 - 1, dtype=np.int64)
        cap = np.minimum(cap, pod_headroom)
        cap = np.where(filter_ok[ci] & (slice_ids >= 0), cap, 0)
        per_slice = np.bincount(slice_ids[slice_ids >= 0],
                                weights=cap[slice_ids >= 0],
                                minlength=n_slices).astype(np.int64)
        if per_slice.max(initial=0) <= 0:
            continue
        size = int(gang_sizes[gi])
        fits = per_slice >= size
        if fits.any():
            # best fit: least spare among covering slices, lowest id on ties
            spare = np.where(fits, per_slice - size, np.iinfo(np.int64).max)
            best = int(np.argmin(spare))
        else:
            best = int(np.argmax(per_slice))
        bonus[ci, slice_ids == best] = GANG_SLICE_BONUS
    if not seen:
        return None
    return bonus
