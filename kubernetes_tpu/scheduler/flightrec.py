"""Pipeline flight recorder: per-batch stage timing for the batched solver.

The north-star chase is steered by a stage table (ROADMAP.md): where do the
milliseconds of a 100k-pod schedule->bind->confirm window go? kube-scheduler
answers this with per-extension-point histograms and utiltrace steps
(schedule_one.go:411); placement-quality work (Tesserae, CvxCluster —
PAPERS.md) additionally needs per-decision attribution. Three pieces:

  StageClock     — cheap per-BATCH wall-clock marks (one perf_counter read per
                   stage boundary, never per pod; a 100k-pod batch pays ~10
                   reads total, so the <2% overhead budget holds by
                   construction).
  FlightRecorder — bounded ring of per-batch records: pod/node counts,
                   per-stage ms, outcome, gang veto/release counts,
                   preemption victims, unschedulable-reason attribution, and
                   the async bind failures drained from the bind worker.
                   Work that runs OUTSIDE a batch (self-bind confirm re-ingest
                   on a later pump, the overlapped bind worker, flush waits)
                   accumulates into per-stage "outside" buckets so the
                   aggregate stage table still sums to ~wall time.
  registry       — weak registry of live BatchSchedulers so the API server's
                   /debug/schedstats and `ktl sched stats` can read the stage
                   table of an in-process scheduler without new plumbing
                   (the configz register/snapshot pattern, utils/tracing.py).

The generic ring/stage machinery (bounded ring, per-stage totals +
windowed histograms, exact-while-complete p50/p99, self-time accounting)
lives in kubernetes_tpu/obs/recorder.py (ISSUE 9) — the reconcile-loop
recorder every controller inherits is built on the SAME base, so the whole
control plane shares one proven implementation. This module keeps the
scheduler-specific record schema and the outside-bucket stage table.

Everything is O(1) per batch and allocation-light; `enabled=False` skips the
ring-buffer append (placement parity with the recorder on is pinned by
tests/test_flightrec.py). bench.py consumes the recorder to emit the
machine-generated `stages` breakdown that replaced ROADMAP's hand-estimates.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable, Dict, List, Optional

from ..obs.recorder import (  # noqa: F401  (re-exported: public surface)
    STAGE_P_BUCKETS,
    RingRecorder,
    StageClock,
    nearest_rank as _nearest_rank,
)

# Serial-thread stages of one schedule_batch call, in pipeline order.
# "ingest" is the watch pump residual (decode + cache ingest) with the
# separately-attributed sub-stage (queue_add) subtracted out, so the serial
# stages stay disjoint and sum cleanly.
BATCH_STAGES = ("ingest", "pop", "tensorize", "build_pod_batch", "solve",
                "assume", "dispatch", "reject", "fallback")
# Stages accumulated outside the per-batch window: bulk queue admission
# (inside the pump), the bind worker's store.bind_many wall (overlapped with
# the next solve), and the scheduling thread's wait for in-flight binds
# (flush_binds). The old "confirm" stage is gone: the bind worker confirms
# its own assumes on the commit chunk, so self-bind events carry no work.
OUTSIDE_STAGES = ("queue_add", "bind", "bind_wait")
# Overlapped with the serial thread — excluded from "does the serial stage
# sum explain the wall clock" checks.
OVERLAPPED_STAGES = ("bind",)


class FlightRecorder(RingRecorder):
    """Bounded ring of per-batch trace records (last N batches)."""

    def __init__(self, capacity: int = RingRecorder.DEFAULT_CAPACITY,
                 enabled: bool = True):
        super().__init__(capacity=capacity, enabled=enabled)
        # async bind failures observed since the last record (attached to it)
        self._pending_bind_failures: List = []

    # -- ingest ----------------------------------------------------------------

    def note_bind_failures(self, failures: List) -> None:
        """Bind-worker failures surfaced at drain time; attached to the next
        batch record (take_bind_failures keeps its own drain semantics)."""
        if not self.enabled or not failures:
            return
        with self._lock:
            self._pending_bind_failures.extend(failures)
            del self._pending_bind_failures[:-200]  # bounded if batches stop

    def record(self, *, pods: int, nodes: int, outcome: str, solver: str,
               stages: Dict[str, float], total_s: float, scheduled: int = 0,
               unschedulable: int = 0, fallback: int = 0, preempted: int = 0,
               reasons: Optional[Dict[str, int]] = None,
               gang: Optional[Dict[str, int]] = None,
               repair: Optional[Dict] = None,
               solver_iterations: Optional[int] = None,
               breaker: Optional[str] = None,
               error: Optional[str] = None) -> Optional[Dict]:
        """Append one batch record (stage values in SECONDS; stored as ms).
        Returns the record, or None when disabled."""
        if not self.enabled:
            return None
        with self._lock:
            rec = {
                "pods": pods,
                "nodes": nodes,
                "outcome": outcome,
                "solver": solver,
                "total_ms": round(total_s * 1000, 3),
                "scheduled": scheduled,
                "unschedulable": unschedulable,
                "fallback": fallback,
                "preempted": preempted,
                "reasons": dict(reasons or {}),
                "gang": gang,
                # constraint propose-and-repair (ISSUE 8): the batch's
                # RepairStats dict when the repair path ran, else None
                "repair": repair,
                "solver_iterations": solver_iterations,
                # failure domains (ISSUE 6): non-closed breaker state and
                # the batch's handled pipeline error, when present
                "breaker": breaker,
                "error": error,
                "bind_failures": list(self._pending_bind_failures),
            }
            self._pending_bind_failures.clear()
            return self._append_record(rec, stages)

    # -- read side -------------------------------------------------------------

    def stage_table(self) -> Dict[str, Dict]:
        """Aggregate per-stage view across every batch since clear() plus the
        outside buckets (see RingRecorder.stage_table). The non-overlapped
        rows sum to ~the window's serial wall time — the machine-generated
        successor of ROADMAP's hand-maintained table."""
        return super().stage_table(
            order=list(BATCH_STAGES) + list(OUTSIDE_STAGES),
            overlapped=frozenset(OVERLAPPED_STAGES))

    def _clear_extra(self) -> None:
        self._pending_bind_failures.clear()


# -- live-scheduler registry (the configz pattern) ------------------------------

_registry_lock = threading.Lock()
_schedulers: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()


def register_scheduler(name: str, sched) -> None:
    """Register a live scheduler for /debug/schedstats. Weak: a stopped and
    collected scheduler drops out without an unregister call."""
    with _registry_lock:
        _schedulers[name] = sched


def schedstats_snapshot() -> Dict[str, Dict]:
    """{scheduler name: sched_stats()} over every live registered scheduler —
    what GET /debug/schedstats and `ktl sched stats` serve."""
    with _registry_lock:
        live = dict(_schedulers)
    out = {}
    for name, sched in live.items():
        stats: Callable = getattr(sched, "sched_stats", None)
        if stats is None:
            continue
        try:
            out[name] = stats()
        except Exception as e:  # a wedged scheduler must not 500 the endpoint
            out[name] = {"error": str(e)}
    return out


def timeseries_snapshot() -> Dict[str, Dict]:
    """{scheduler name: windowed time-series + resource summary} over every
    live registered scheduler — what GET /debug/timeseries and `ktl sched
    top` serve (obs/timeseries.py, ISSUE 13)."""
    with _registry_lock:
        live = dict(_schedulers)
    out = {}
    for name, sched in live.items():
        ts = getattr(sched, "timeseries", None)
        if ts is None:
            continue
        try:
            sampler = getattr(sched, "resource_sampler", None)
            out[name] = {
                "window_s": ts.window_s,
                "capacity": ts.capacity,
                "windows_closed": ts.windows_closed,
                "windows": ts.windows(),
                "resource": (sampler.summary()
                             if sampler is not None else None),
            }
        except Exception as e:  # same wedge-tolerance as schedstats
            out[name] = {"error": str(e)}
    return out


def schedtrace_snapshot() -> Dict[str, Dict]:
    """{scheduler name: podtrace snapshot} over every live registered
    scheduler — the sampled pod lifecycle spans GET /debug/schedtrace and
    `ktl sched trace` serve (scheduler/podtrace.py). Each snapshot carries
    the trace-buffer arm/drop counters (`tracebuf`) so a full trace ring is
    observable without exporting it (ISSUE 18)."""
    from ..obs import tracebuf

    with _registry_lock:
        live = dict(_schedulers)
    tb = tracebuf.status()
    out = {}
    for name, sched in live.items():
        tracer = getattr(sched, "podtrace", None)
        if tracer is None:
            continue
        try:
            out[name] = dict(tracer.snapshot(), tracebuf=tb)
        except Exception as e:  # same wedge-tolerance as schedstats
            out[name] = {"error": str(e)}
    return out


def _all_spans() -> List[Dict]:
    """Sampled spans pooled across every live registered scheduler (the
    partitioned scheduler registers one tracer per pipeline)."""
    with _registry_lock:
        live = dict(_schedulers)
    spans: List[Dict] = []
    for _name, sched in live.items():
        tracer = getattr(sched, "podtrace", None)
        if tracer is None:
            continue
        try:
            spans.extend(tracer.snapshot().get("spans") or [])
        except Exception:
            continue
    return spans


def trace_export() -> Dict:
    """The armed (or last-disarmed) trace buffer as Chrome trace-event JSON
    plus podtrace-derived evict→replace flow arrows — what GET /debug/trace
    and `ktl sched trace --export` serve (obs/tracebuf.py, ISSUE 18)."""
    from ..obs import tracebuf

    buf = tracebuf.current()
    if buf is None:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "error": "trace buffer never armed"}
    try:
        return buf.export(spans=_all_spans())
    except Exception as e:  # same wedge-tolerance as schedstats
        return {"traceEvents": [], "displayTimeUnit": "ms", "error": str(e)}


def critpath_snapshot() -> Dict[str, Dict]:
    """{scheduler name: critical-path analysis} over every live registered
    scheduler: podtrace spans decomposed into additive submit→bound
    components with the flight recorder's stage table supplying the
    build/solve split — what GET /debug/critpath and `ktl sched why` serve
    (obs/critpath.py, ISSUE 18)."""
    from ..obs import critpath

    with _registry_lock:
        live = dict(_schedulers)
    out = {}
    for name, sched in live.items():
        tracer = getattr(sched, "podtrace", None)
        if tracer is None:
            continue
        try:
            fr = getattr(sched, "flightrec", None)
            table = fr.stage_table() if fr is not None else None
            out[name] = critpath.analyze(
                tracer.snapshot().get("spans") or [], stage_table=table)
        except Exception as e:  # same wedge-tolerance as schedstats
            out[name] = {"error": str(e)}
    return out
