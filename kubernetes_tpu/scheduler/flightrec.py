"""Pipeline flight recorder: per-batch stage timing for the batched solver.

The north-star chase is steered by a stage table (ROADMAP.md): where do the
milliseconds of a 100k-pod schedule->bind->confirm window go? kube-scheduler
answers this with per-extension-point histograms and utiltrace steps
(schedule_one.go:411); placement-quality work (Tesserae, CvxCluster —
PAPERS.md) additionally needs per-decision attribution. Three pieces:

  StageClock     — cheap per-BATCH wall-clock marks (one perf_counter read per
                   stage boundary, never per pod; a 100k-pod batch pays ~10
                   reads total, so the <2% overhead budget holds by
                   construction).
  FlightRecorder — bounded ring of per-batch records: pod/node counts,
                   per-stage ms, outcome, gang veto/release counts,
                   preemption victims, unschedulable-reason attribution, and
                   the async bind failures drained from the bind worker.
                   Work that runs OUTSIDE a batch (self-bind confirm re-ingest
                   on a later pump, the overlapped bind worker, flush waits)
                   accumulates into per-stage "outside" buckets so the
                   aggregate stage table still sums to ~wall time.
  registry       — weak registry of live BatchSchedulers so the API server's
                   /debug/schedstats and `ktl sched stats` can read the stage
                   table of an in-process scheduler without new plumbing
                   (the configz register/snapshot pattern, utils/tracing.py).

Everything is O(1) per batch and allocation-light; `enabled=False` skips the
ring-buffer append (placement parity with the recorder on is pinned by
tests/test_flightrec.py). bench.py consumes the recorder to emit the
machine-generated `stages` breakdown that replaced ROADMAP's hand-estimates.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Callable, Dict, List, Optional

# Serial-thread stages of one schedule_batch call, in pipeline order.
# "ingest" is the watch pump residual (decode + cache ingest) with the
# separately-attributed sub-stage (queue_add) subtracted out, so the serial
# stages stay disjoint and sum cleanly.
BATCH_STAGES = ("ingest", "pop", "tensorize", "build_pod_batch", "solve",
                "assume", "dispatch", "reject", "fallback")
# Stages accumulated outside the per-batch window: bulk queue admission
# (inside the pump), the bind worker's store.bind_many wall (overlapped with
# the next solve), and the scheduling thread's wait for in-flight binds
# (flush_binds). The old "confirm" stage is gone: the bind worker confirms
# its own assumes on the commit chunk, so self-bind events carry no work.
OUTSIDE_STAGES = ("queue_add", "bind", "bind_wait")
# Overlapped with the serial thread — excluded from "does the serial stage
# sum explain the wall clock" checks.
OVERLAPPED_STAGES = ("bind",)

# Windowed per-stage latency buckets (ISSUE 7): log-spaced 0.2ms..~42s so
# the p50/p99 estimates survive ring eviction at bounded memory. The ~1.55x
# bucket ratio bounds the interpolation error well inside the headroom any
# sane SLO ceiling carries; batches still in the ring get EXACT nearest-rank
# percentiles instead (stage_table picks whichever source is lossless).
STAGE_P_BUCKETS = tuple(round(0.0002 * (1.55 ** i), 6) for i in range(28))


def _nearest_rank(sorted_vals: List[float], q: float) -> float:
    """Exact nearest-rank percentile over a complete sample."""
    import math

    return sorted_vals[min(len(sorted_vals) - 1,
                           max(0, math.ceil(q * len(sorted_vals)) - 1))]


class StageClock:
    """Per-batch stage boundary marks. mark(name) attributes the time since
    the previous boundary; skip() moves the boundary without attributing
    (work another accumulator already claimed)."""

    __slots__ = ("t0", "_last", "stages")

    def __init__(self):
        self.t0 = self._last = time.perf_counter()
        self.stages: Dict[str, float] = {}

    def mark(self, name: str) -> float:
        now = time.perf_counter()
        dt = now - self._last
        self.stages[name] = self.stages.get(name, 0.0) + dt
        self._last = now
        return dt

    def skip(self) -> None:
        self._last = time.perf_counter()

    def add(self, name: str, seconds: float) -> None:
        if seconds > 0:
            self.stages[name] = self.stages.get(name, 0.0) + seconds

    def sub(self, name: str, seconds: float) -> None:
        """Remove sub-stage time another bucket owns (floored at 0)."""
        if seconds > 0 and name in self.stages:
            self.stages[name] = max(0.0, self.stages[name] - seconds)

    def total(self) -> float:
        return time.perf_counter() - self.t0


class FlightRecorder:
    """Bounded ring of per-batch trace records (last N batches)."""

    DEFAULT_CAPACITY = 64

    def __init__(self, capacity: int = DEFAULT_CAPACITY, enabled: bool = True):
        self.capacity = capacity
        self.enabled = enabled
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=capacity)
        self._seq = 0
        # aggregate per-stage seconds since clear(), across ALL batches —
        # survives ring eviction so the stage table covers the full window
        self._stage_totals: Dict[str, float] = {}
        self._stage_batches: Dict[str, int] = {}
        # per-stage seconds accrued outside any batch (see OUTSIDE_STAGES)
        self._outside: Dict[str, float] = {}
        # per-stage latency histograms (ISSUE 7): one observation per batch
        # (or per outside-bucket call — a bind chunk, a flush wait), never
        # evicted with the ring, so stage_table's p50/p99 cover the whole
        # window. Built lazily per stage; metrics.Histogram carries its own
        # lock but every write here happens under self._lock anyway.
        self._stage_hist: Dict[str, object] = {}
        # async bind failures observed since the last record (attached to it)
        self._pending_bind_failures: List = []
        # instrumentation self-time: seconds spent building records,
        # observing histograms, and in the timing taps (queue_add / confirm
        # / bind wrappers note their own cost here). Everything measured
        # except the ~10 StageClock perf_counter reads per batch — bench
        # divides this by wall to bound the <2% overhead budget instead of
        # differencing two noisy runs.
        self._self_s = 0.0

    # -- ingest ----------------------------------------------------------------

    def _hist_observe(self, stage: str, seconds: float) -> None:
        """One per-stage latency observation (caller holds self._lock)."""
        h = self._stage_hist.get(stage)
        if h is None:
            from ..server.metrics import Histogram

            h = self._stage_hist[stage] = Histogram(
                stage, buckets=STAGE_P_BUCKETS)
        h.observe(seconds)

    def add_outside(self, stage: str, seconds: float) -> None:
        if not self.enabled or seconds <= 0:
            return
        with self._lock:
            self._outside[stage] = self._outside.get(stage, 0.0) + seconds
            self._hist_observe(stage, seconds)

    def outside_seconds(self, *stages: str) -> float:
        """Sum of the named outside buckets (the scheduler differences this
        around a pump to keep 'ingest' disjoint from its sub-stages)."""
        with self._lock:
            return sum(self._outside.get(s, 0.0) for s in stages)

    def note_bind_failures(self, failures: List) -> None:
        """Bind-worker failures surfaced at drain time; attached to the next
        batch record (take_bind_failures keeps its own drain semantics)."""
        if not self.enabled or not failures:
            return
        with self._lock:
            self._pending_bind_failures.extend(failures)
            del self._pending_bind_failures[:-200]  # bounded if batches stop

    def note_self_time(self, seconds: float) -> None:
        with self._lock:
            self._self_s += seconds

    def record(self, *, pods: int, nodes: int, outcome: str, solver: str,
               stages: Dict[str, float], total_s: float, scheduled: int = 0,
               unschedulable: int = 0, fallback: int = 0, preempted: int = 0,
               reasons: Optional[Dict[str, int]] = None,
               gang: Optional[Dict[str, int]] = None,
               repair: Optional[Dict] = None,
               solver_iterations: Optional[int] = None,
               breaker: Optional[str] = None,
               error: Optional[str] = None) -> Optional[Dict]:
        """Append one batch record (stage values in SECONDS; stored as ms).
        Returns the record, or None when disabled."""
        if not self.enabled:
            return None
        with self._lock:
            self._seq += 1
            rec = {
                "seq": self._seq,
                "ts": time.time(),
                "pods": pods,
                "nodes": nodes,
                "outcome": outcome,
                "solver": solver,
                "total_ms": round(total_s * 1000, 3),
                "stages": {k: round(v * 1000, 3) for k, v in stages.items()},
                "scheduled": scheduled,
                "unschedulable": unschedulable,
                "fallback": fallback,
                "preempted": preempted,
                "reasons": dict(reasons or {}),
                "gang": gang,
                # constraint propose-and-repair (ISSUE 8): the batch's
                # RepairStats dict when the repair path ran, else None
                "repair": repair,
                "solver_iterations": solver_iterations,
                # failure domains (ISSUE 6): non-closed breaker state and
                # the batch's handled pipeline error, when present
                "breaker": breaker,
                "error": error,
                "bind_failures": list(self._pending_bind_failures),
            }
            self._pending_bind_failures.clear()
            self._records.append(rec)
            for k, v in stages.items():
                self._stage_totals[k] = self._stage_totals.get(k, 0.0) + v
                self._stage_batches[k] = self._stage_batches.get(k, 0) + 1
                self._hist_observe(k, v)
            return rec

    # -- read side -------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def records(self) -> List[Dict]:
        with self._lock:
            return list(self._records)

    def last(self) -> Optional[Dict]:
        with self._lock:
            return self._records[-1] if self._records else None

    @property
    def self_seconds(self) -> float:
        with self._lock:
            return self._self_s

    def stage_table(self) -> Dict[str, Dict]:
        """Aggregate per-stage view across every batch since clear() plus the
        outside buckets: {stage: {total_ms, mean_ms, p50_ms, p99_ms, batches,
        overlapped}}. The non-overlapped rows sum to ~the window's serial
        wall time — the machine-generated successor of ROADMAP's
        hand-maintained table.

        Percentile source (ISSUE 7): nearest-rank over the per-batch ring
        while every observation is still in it (exact); once eviction or
        per-call outside observations outgrow the ring, the windowed stage
        histogram takes over (bucket-interpolated, error bounded by the
        STAGE_P_BUCKETS ratio)."""
        with self._lock:
            totals = dict(self._stage_totals)
            batches = dict(self._stage_batches)
            outside = dict(self._outside)
            hists = dict(self._stage_hist)
            ring_vals: Dict[str, List[float]] = {}
            for rec in self._records:
                for k, ms in rec["stages"].items():
                    ring_vals.setdefault(k, []).append(ms)

        def pcts(name):
            h = hists.get(name)
            n_obs = h._total if h is not None else 0
            vals = ring_vals.get(name)
            if vals and len(vals) == n_obs:
                vals = sorted(vals)
                return (round(_nearest_rank(vals, 0.50), 3),
                        round(_nearest_rank(vals, 0.99), 3))
            if h is None or n_obs == 0:
                return None, None
            return (round(h.quantile(0.50) * 1000, 3),
                    round(h.quantile(0.99) * 1000, 3))

        out: Dict[str, Dict] = {}
        for name in list(BATCH_STAGES) + list(OUTSIDE_STAGES):
            sec = totals.get(name, 0.0) + outside.get(name, 0.0)
            n = batches.get(name, 0)
            if sec == 0.0 and n == 0:
                continue
            p50, p99 = pcts(name)
            out[name] = {
                "total_ms": round(sec * 1000, 3),
                "mean_ms": round(sec * 1000 / n, 3) if n else None,
                "p50_ms": p50,
                "p99_ms": p99,
                "batches": n,
                "overlapped": name in OVERLAPPED_STAGES,
            }
        # anything recorded under a name this module doesn't know keeps
        # rendering (forward compatibility for new stages)
        for name in set(totals) | set(outside):
            if name not in out:
                sec = totals.get(name, 0.0) + outside.get(name, 0.0)
                p50, p99 = pcts(name)
                out[name] = {"total_ms": round(sec * 1000, 3),
                             "mean_ms": None,
                             "p50_ms": p50,
                             "p99_ms": p99,
                             "batches": batches.get(name, 0),
                             "overlapped": False}
        return out

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._stage_totals.clear()
            self._stage_batches.clear()
            self._outside.clear()
            self._stage_hist.clear()
            self._pending_bind_failures.clear()
            self._self_s = 0.0


# -- live-scheduler registry (the configz pattern) ------------------------------

_registry_lock = threading.Lock()
_schedulers: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()


def register_scheduler(name: str, sched) -> None:
    """Register a live scheduler for /debug/schedstats. Weak: a stopped and
    collected scheduler drops out without an unregister call."""
    with _registry_lock:
        _schedulers[name] = sched


def schedstats_snapshot() -> Dict[str, Dict]:
    """{scheduler name: sched_stats()} over every live registered scheduler —
    what GET /debug/schedstats and `ktl sched stats` serve."""
    with _registry_lock:
        live = dict(_schedulers)
    out = {}
    for name, sched in live.items():
        stats: Callable = getattr(sched, "sched_stats", None)
        if stats is None:
            continue
        try:
            out[name] = stats()
        except Exception as e:  # a wedged scheduler must not 500 the endpoint
            out[name] = {"error": str(e)}
    return out


def schedtrace_snapshot() -> Dict[str, Dict]:
    """{scheduler name: podtrace snapshot} over every live registered
    scheduler — the sampled pod lifecycle spans GET /debug/schedtrace and
    `ktl sched trace` serve (scheduler/podtrace.py)."""
    with _registry_lock:
        live = dict(_schedulers)
    out = {}
    for name, sched in live.items():
        tracer = getattr(sched, "podtrace", None)
        if tracer is None:
            continue
        try:
            out[name] = tracer.snapshot()
        except Exception as e:  # same wedge-tolerance as schedstats
            out[name] = {"error": str(e)}
    return out
