"""Framework runtime — executes plugins per extension point.

reference: pkg/scheduler/framework/runtime/framework.go (frameworkImpl):
RunPreFilterPlugins (merges PreFilterResults, records Skip set),
RunFilterPlugins (first rejection wins), RunScorePlugins :1112 (three passes:
score per node, NormalizeScore per plugin, apply weight), plus
Reserve/Permit/PreBind/Bind/PostBind chains.

The reference parallelizes the per-node passes over 16 goroutines
(parallelize/parallelism.go); serially that adds only overhead in CPython, so
the oracle runs them in a plain loop — the TPU path in ops/ is the real
parallel implementation.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .framework import (
    Code,
    CycleState,
    NodeInfo,
    Plugin,
    PreFilterResult,
    Snapshot,
    Status,
    SUCCESS,
)

# Default plugin weights (reference: apis/config/v1/default_plugins.go:30-56).
DEFAULT_WEIGHTS = {
    "TaintToleration": 3,
    "NodeAffinity": 2,
    "PodTopologySpread": 2,
    "InterPodAffinity": 2,
    "NodeResourcesFit": 1,
    "NodeResourcesBalancedAllocation": 1,
    "ImageLocality": 1,
}


class Framework:
    def __init__(self, plugins: Sequence[Plugin], weights: Optional[Dict[str, int]] = None,
                 disabled_points: Optional[set] = None):
        self.plugins = list(plugins)
        self.weights = dict(DEFAULT_WEIGHTS)
        if weights:
            self.weights.update(weights)
        # (plugin name, method name) pairs a profile disabled at one extension
        # point (apis/config/types.go PluginSet.Disabled)
        disabled = disabled_points or set()

        def at(method: str):
            return [p for p in self.plugins
                    if hasattr(p, method) and (p.name, method) not in disabled]

        self.pre_enqueue_plugins = at("pre_enqueue")
        self.pre_filter_plugins = at("pre_filter")
        self.filter_plugins = at("filter")
        self.post_filter_plugins = at("post_filter")
        self.pre_score_plugins = at("pre_score")
        self.score_plugins = at("score")
        self.reserve_plugins = at("reserve")
        self.permit_plugins = at("permit")
        self.pre_bind_plugins = at("pre_bind")
        self.bind_plugins = at("bind")
        self.post_bind_plugins = at("post_bind")
        self.queue_sort_plugin = next(iter(at("less")), None)

    # -- PreEnqueue ------------------------------------------------------------

    def run_pre_enqueue(self, pod) -> Status:
        for p in self.pre_enqueue_plugins:
            st = p.pre_enqueue(pod)
            if not st.is_success():
                return st
        return SUCCESS

    # -- PreFilter -------------------------------------------------------------

    def run_pre_filter(self, state: CycleState, pod, snapshot: Snapshot) -> Tuple[PreFilterResult, Status]:
        result = PreFilterResult(None)
        state.write("Snapshot", snapshot)
        state.write("TotalNodes", len(snapshot))
        for p in self.pre_filter_plugins:
            r, st = p.pre_filter(state, pod, snapshot)
            if st.is_skip():
                state.skip_filter_plugins.add(p.name)
                continue
            if not st.is_success():
                st.plugin = st.plugin or p.name
                return result, st
            if r is not None:
                result = result.merge(r)
                if r.node_names is not None and not r.node_names:
                    return result, Status.unresolvable(
                        "node(s) didn't satisfy plugin prefilter", plugin=p.name
                    )
        return result, SUCCESS

    # -- Filter ----------------------------------------------------------------

    def run_filter(self, state: CycleState, pod, node_info: NodeInfo) -> Status:
        for p in self.filter_plugins:
            if p.name in state.skip_filter_plugins:
                continue
            st = p.filter(state, pod, node_info)
            if not st.is_success():
                st.plugin = st.plugin or p.name
                return st
        return SUCCESS

    def run_filter_with_nominated_pods(self, state: CycleState, pod, node_info: NodeInfo,
                                       nominated_pods_for_node=()) -> Status:
        """Filters run twice when nominated pods exist: once assuming higher/equal
        priority nominated pods are running on the node, once without
        (runtime/framework.go:984 RunFilterPluginsWithNominatedPods)."""
        from .framework import PodInfo

        if nominated_pods_for_node:
            state_with = state.clone()
            ni = node_info.clone()
            for np in nominated_pods_for_node:
                pi = PodInfo(np)
                ni.add_pod(pi)
                self.run_add_pod(state_with, pod, np, ni)
            st = self.run_filter(state_with, pod, ni)
            if not st.is_success():
                return st
        return self.run_filter(state, pod, node_info)

    def run_add_pod(self, state: CycleState, pod, added_pod, node_info: NodeInfo) -> Status:
        for p in self.filter_plugins:
            if hasattr(p, "add_pod") and p.name not in state.skip_filter_plugins:
                st = p.add_pod(state, pod, added_pod, node_info)
                if not st.is_success():
                    return st
        return SUCCESS

    def run_remove_pod(self, state: CycleState, pod, removed_pod, node_info: NodeInfo) -> Status:
        for p in self.filter_plugins:
            if hasattr(p, "remove_pod") and p.name not in state.skip_filter_plugins:
                st = p.remove_pod(state, pod, removed_pod, node_info)
                if not st.is_success():
                    return st
        return SUCCESS

    # -- PostFilter ------------------------------------------------------------

    def run_post_filter(self, state: CycleState, pod, filtered_statuses) -> Tuple[Optional[str], Status]:
        for p in self.post_filter_plugins:
            nominated, st = p.post_filter(state, pod, filtered_statuses)
            if st.is_success() or st.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE:
                return nominated, st
        return None, Status.unschedulable("no postFilter plugin made the pod schedulable")

    # -- Score -----------------------------------------------------------------

    def run_pre_score(self, state: CycleState, pod, nodes: List[NodeInfo]) -> Status:
        for p in self.pre_score_plugins:
            st = p.pre_score(state, pod, nodes)
            if st.is_skip():
                state.skip_score_plugins.add(p.name)
                continue
            if not st.is_success():
                st.plugin = st.plugin or p.name
                return st
        return SUCCESS

    def run_score(self, state: CycleState, pod, nodes: List[NodeInfo]) -> Dict[str, int]:
        """Returns node name -> weighted total score (RunScorePlugins :1112)."""
        totals: Dict[str, int] = {ni.node.metadata.name: 0 for ni in nodes}
        for p in self.score_plugins:
            if p.name in state.skip_score_plugins:
                continue
            scores: Dict[str, int] = {}
            for ni in nodes:
                s, st = p.score(state, pod, ni)
                if not st.is_success():
                    raise RuntimeError(f"score plugin {p.name} failed: {st.message()}")
                scores[ni.node.metadata.name] = s
            if hasattr(p, "normalize_score"):
                p.normalize_score(state, pod, scores)
            w = self.weights.get(p.name, 1)
            for name, s in scores.items():
                totals[name] += s * w
        return totals

    # -- Reserve / Permit / Bind ----------------------------------------------

    def run_reserve(self, state: CycleState, pod, node_name: str) -> Status:
        for p in self.reserve_plugins:
            st = p.reserve(state, pod, node_name)
            if not st.is_success():
                for q in self.reserve_plugins:
                    if hasattr(q, "unreserve"):
                        q.unreserve(state, pod, node_name)
                return st
        return SUCCESS

    def run_unreserve(self, state: CycleState, pod, node_name: str) -> None:
        for p in self.reserve_plugins:
            if hasattr(p, "unreserve"):
                p.unreserve(state, pod, node_name)

    def run_permit(self, state: CycleState, pod, node_name: str) -> Status:
        for p in self.permit_plugins:
            st = p.permit(state, pod, node_name)
            if not st.is_success() and st.code != Code.WAIT:
                return st
        return SUCCESS

    def run_pre_bind(self, state: CycleState, pod, node_name: str) -> Status:
        for p in self.pre_bind_plugins:
            st = p.pre_bind(state, pod, node_name)
            if not st.is_success():
                return st
        return SUCCESS

    def run_bind(self, state: CycleState, pod, node_name: str) -> Status:
        for p in self.bind_plugins:
            st = p.bind(state, pod, node_name)
            if st.is_skip():
                continue
            return st
        return Status.error("no bind plugin handled the pod")

    def run_post_bind(self, state: CycleState, pod, node_name: str) -> None:
        for p in self.post_bind_plugins:
            p.post_bind(state, pod, node_name)
