"""Sampled pod lifecycle tracing: what a USER of the cluster experiences.

The flight recorder (scheduler/flightrec.py) explains where a batch's
milliseconds go; nothing in tree measured the submit->bound latency of an
individual pod, so a tail regression (a stalled bind chunk, a breaker
cooldown, a backoff-tier pile-up) was invisible while throughput held. Two
instruments, both built under the HP001 design constraint (instrumentation
is per BATCH/chunk, never per pod in a pod-scale loop):

  PodTracer.admitted   — reservoir-samples K pod keys per window at queue
                         admission using Algorithm L (Li 1994): the geometric
                         jump makes the per-batch cost O(samples taken), so a
                         100k-pod admission touches a handful of keys, not
                         100k random draws. The enqueue stamp is the batch's
                         shared admission timestamp (QueuedPodInfo.timestamp),
                         not a per-pod clock read.
  lifecycle stamps     — sampled pods are stamped at the pipeline edges
                         (enqueue, pop, solve, assume, dispatch, bind_commit,
                         bind_confirmed) with ONE shared timestamp per batch/
                         chunk. Unsampled pods pay one attribute read in the
                         settlement pass; per-pod stamping is legal ONLY
                         behind the sampled-set membership check (schedlint
                         HP001 enforces it in this file).
  latency histogram    — the aggregate submit->bound distribution covers ALL
                         pods, not just the sample: each committed bind chunk
                         bulk-observes (chunk commit stamp) - (admission batch
                         stamp) per pod — batch-boundary timestamps only, and
                         one histogram lock per chunk.

Every stamp tap is O(1) on the hot path (the PR 4 lazy-event idiom): it
records an op — the batch/chunk ref plus its shared timestamps — and the
per-pod settlement passes run at the next read surface with the recorded
stamps, identical whenever they happen, so the contended scheduling window
never pays a batch scan. Past a bounded pending cap the flush runs inline on
the recording thread and bills the recorder's <2% self-time budget
(stat_sink, asserted by bench.py); read-side settlement is rendering cost,
tracked separately as flush_seconds (published in snapshot()).

Everything is bounded: the reservoir holds K keys, completed spans live in a
ring, incomplete spans from rotated windows are capped and evicted oldest-
first (counted, never silent).

Consumers: `ktl sched trace` / GET /debug/schedtrace (span dump),
sched_stats()["latency"] / ["trace"], and the SLO gates in bench.py
(scheduler/slo.py) — the per-decision latency attribution placement-quality
work needs downstream (Tesserae, arxiv 2508.04953; CvxCluster, arxiv
2605.01614).
"""

from __future__ import annotations

import math
import random
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

# lifecycle edges, in pipeline order (bind_commit = store.bind_many returned,
# bind_confirmed = the cache assume-confirm settled on the same chunk).
# ISSUE 9 extends the span past the scheduler's horizon: watch_delivered =
# the bind MODIFIED event dequeued by the pod's kubelet watcher,
# kubelet_observed = the kubelet's syncLoop picked the pod up, running =
# the Running status write committed — the TRUE end-to-end latency a user
# of the cluster experiences, stamped via note_pod_event (O(1) miss for
# unsampled pods: one module-dict probe).
SPAN_STAGES = ("enqueue", "pop", "solve", "assume", "dispatch",
               "bind_commit", "bind_confirmed",
               "watch_delivered", "kubelet_observed", "running")

# -- post-edge key registry (module-level, like flightrec's configz pattern) ----
#
# Components OUTSIDE the scheduler (the hollow kubelet's syncLoop, a future
# real kubelet shim) stamp sampled spans through note_pod_event without
# holding a tracer reference. The registry maps sampled pod keys -> weak
# tracer refs; unsampled pods pay ONE falsy module check (empty dict) or one
# dict probe. Bounded: tracers register exactly their live + post-completion
# sampled keys and unregister on completion of the running edge, eviction,
# deletion, drop_live and clear; dead-tracer refs are pruned opportunistically.

_post_lock = threading.Lock()
_post_keys: Dict[str, "weakref.ref"] = {}


def _post_register(key: str, tracer: "PodTracer") -> None:
    with _post_lock:
        if len(_post_keys) > 4096:  # prune dead-tracer leftovers
            for k in [k for k, r in _post_keys.items() if r() is None]:
                _post_keys.pop(k, None)
        _post_keys[key] = weakref.ref(tracer)


def _post_unregister(key: str) -> None:
    with _post_lock:
        _post_keys.pop(key, None)


def _owner_link_key(ref: Dict) -> Optional[str]:
    """The identity an evict->replace link is keyed by: the owner's uid when
    set, else kind/name (this repo's workload builders often leave uid
    empty; the controller identity is what makes old and new pod siblings)."""
    uid = ref.get("uid")
    if uid:
        return uid
    kind, name = ref.get("kind"), ref.get("name")
    return f"{kind}/{name}" if kind and name else None


def note_pod_event(key: str, stage: str, ts: Optional[float] = None) -> None:
    """Stamp a post-scheduler lifecycle edge on a sampled pod's span (no-op
    for unsampled pods — the common case is one falsy check). Callers pass
    no timestamp; the owning tracer stamps with ITS clock so every edge of
    a span shares one clock."""
    if not _post_keys:
        return
    with _post_lock:
        ref = _post_keys.get(key)
    tracer = ref() if ref is not None else None
    if tracer is not None:
        tracer.stamp_post(key, stage, ts)


class PodSpan:
    """One sampled pod's lifecycle record. stamps maps stage -> absolute
    clock time (scheduler clock); re-pops overwrite, so the span always
    describes the attempt that finally bound (pops counts the retries).
    replaces/replaced_by link an evicted pod's span to its ReplicaSet
    replacement (causal chains under churn, ISSUE 9); deleted marks a span
    whose pod was evicted before it could complete."""

    __slots__ = ("key", "window", "stamps", "pops", "complete",
                 "replaces", "replaced_by", "deleted")

    def __init__(self, key: str, window: int):
        self.key = key
        self.window = window
        self.stamps: Dict[str, float] = {}
        self.pops = 0
        self.complete = False
        self.replaces: Optional[str] = None
        self.replaced_by: Optional[str] = None
        self.deleted = False

    def stamp(self, stage: str, ts: float) -> None:
        self.stamps[stage] = ts

    def render(self) -> Dict:
        t0 = self.stamps.get("enqueue")
        offsets = {}
        if t0 is not None:
            for stage in SPAN_STAGES:
                ts = self.stamps.get(stage)
                if ts is not None:
                    offsets[stage] = round((ts - t0) * 1000, 3)
        total = offsets.get("bind_confirmed")
        # t0 = absolute enqueue stamp (scheduler clock): the anchor the
        # trace exporter (obs/tracebuf.py) uses to place span-derived flow
        # arrows on the perf_counter timeline
        out = {"pod": self.key, "window": self.window, "pops": self.pops,
               "complete": self.complete, "t0": t0, "stamps_ms": offsets,
               "submit_to_bound_ms": total,
               "submit_to_running_ms": offsets.get("running")}
        if self.replaces is not None:
            out["replaces"] = self.replaces
        if self.replaced_by is not None:
            out["replaced_by"] = self.replaced_by
        if self.deleted:
            out["deleted"] = True
        return out


class PodTracer:
    """Reservoir-sampled lifecycle tracer + all-pods latency histogram."""

    DEFAULT_SAMPLE_K = 64
    DEFAULT_WINDOW_S = 30.0
    SPAN_RING = 512
    LIVE_CAP_FACTOR = 4  # incomplete spans kept across windows: K * this
    EVICTED_LINK_CAP = 64  # pending evict->replace links kept (oldest drop)
    # recorded-but-unsettled trace ops held for deferred processing; past
    # this the flush runs inline on the recording thread (bounded memory:
    # the deque holds refs to batch/chunk lists that are alive during the
    # batch anyway)
    PENDING_OPS_CAP = 64

    def __init__(self, clock=None, sample_k: int = DEFAULT_SAMPLE_K,
                 window_s: float = DEFAULT_WINDOW_S, enabled: bool = True,
                 rng_seed: Optional[int] = None, stat_sink=None):
        from ..server.metrics import E2E_LATENCY_BUCKETS, Histogram
        from ..utils import Clock

        self._clock = clock or Clock()
        self.sample_k = max(1, sample_k)
        self.window_s = window_s
        self.enabled = enabled
        self._rng = random.Random(rng_seed)
        self._lock = threading.Lock()
        # keys with a live (incomplete) span — THE stamp guard every per-pod
        # loop below checks before touching a span (schedlint HP001)
        self._sampled: set = set()
        self._live: Dict[str, PodSpan] = {}  # insertion-ordered: evict oldest
        self._done: deque = deque(maxlen=self.SPAN_RING)
        # bound spans still awaiting post-scheduler edges (watch_delivered /
        # kubelet_observed / running, ISSUE 9) — bounded by the done ring:
        # a span evicted from the ring leaves here too
        self._post_sampled: Dict[str, PodSpan] = {}
        # evict->replace causal links: owner identity -> FIFO of (evicted
        # key, its span) — one ReplicaSet drain evicts MANY siblings, each
        # owed a link to a replacement. Consumed oldest-first at admission;
        # bounded at EVICTED_LINK_CAP total links (oldest dropped).
        self._evicted_sampled: Dict[str, List[Tuple[str, PodSpan]]] = {}
        self._evicted_links = 0
        # Algorithm L reservoir state for the current window
        self._reservoir: List[str] = []
        self._w: Optional[float] = None
        self._skip = 0
        self._window_seq = 0
        self._window_start = self._clock.now()
        self.windows_rotated = 0
        self.evicted_incomplete = 0
        self._completed = 0
        # trace ops awaiting settlement (see the lifecycle-stamp taps);
        # appends and poplefts are atomic deque ops, so the recording
        # threads never contend on a lock for the O(1) taps. _flush_lock
        # serializes settlement (ops are order-dependent).
        self._ops: deque = deque()
        self._flush_lock = threading.Lock()
        self.flush_seconds = 0.0  # read-side settlement cost (rendering)
        # aggregate submit->bound latency over ALL pods (private histogram so
        # concurrent schedulers in one process don't blend; the process-wide
        # Prometheus series is fed alongside in chunk_bound)
        self.latency = Histogram("submit_to_bound_seconds",
                                 buckets=E2E_LATENCY_BUCKETS)
        # sampled keys present in the batch being scheduled right now
        # (scheduling thread only)
        self._batch_hits: Tuple = ()
        self.stat_sink = stat_sink  # FlightRecorder: self-time budget

    # -- sampling (queue admission) --------------------------------------------

    def _rand(self) -> float:
        return max(self._rng.random(), 1e-12)  # log() needs (0, 1]

    def _geom_skip(self) -> int:
        # items to pass over before the next reservoir replacement
        return int(math.log(self._rand()) / math.log(1.0 - self._w))

    def admitted(self, qps) -> None:
        """One call per admission batch (SchedulingQueue.add_batch / add).
        Samples this batch's slice of the admission stream into the
        candidate reservoir. Cost: O(samples taken) — Algorithm L's
        geometric jumps skip the rest of the batch untouched, and a sampled
        candidate costs only a slot write + set update (its PodSpan
        materializes lazily at first pop, so reservoir churn never allocates
        spans that immediately get replaced)."""
        if not self.enabled or not qps:
            return
        t0 = time.perf_counter()
        # settle pending ops FIRST: window rotation and candidate
        # displacement below read span.pops to decide which live spans
        # survive, so deferred pop stamps must land before sampling state
        # advances. Ops are empty in the bulk-ingest common case (admission
        # precedes the batch's pop), so this is a falsy check there.
        self._flush_ops(inline=True)
        with self._lock:
            now = qps[0].timestamp or self._clock.now()
            self._maybe_rotate(now)
            k = self.sample_k
            n = len(qps)
            res = self._reservoir  # slots hold QueuedPodInfo refs
            rng = self._rng.random
            log = math.log
            idx = 0
            filled = len(res)
            while idx < n and len(res) < k:
                res.append(qps[idx])
                idx += 1
            mutated = len(res) != filled
            if len(res) == k and self._w is None:
                self._w = math.exp(log(self._rand()) / k)
                self._skip = self._geom_skip()
            # jump phase, locals only: a replacement is one slot write plus
            # ~3 rng/log ops — the span bookkeeping for this call's
            # SURVIVORS happens once below, so within-call reservoir churn
            # allocates nothing
            w, skip, inv_k = self._w, self._skip, 1.0 / k
            while w is not None and idx + skip < n:
                idx += skip
                res[int(rng() * k)] = qps[idx]
                mutated = True
                idx += 1
                w *= (rng() or 1e-12) ** inv_k
                skip = int(log(rng() or 1e-12) / log(1.0 - w))
            if w is not None:
                skip -= n - idx
            self._w, self._skip = w, skip
            # the geometric jump skipped this whole slice (the per-pod
            # add() common case once the reservoir is warm): occupants are
            # unchanged, so reconciliation has nothing to do — skip the
            # O(K + live) scan
            if mutated:
                self._sync_candidates()
            # evict->replace causal chains (ISSUE 9): one falsy check in the
            # steady state; only while links are pending does admission pay
            # an owner-uid probe per pod
            if self._evicted_sampled:
                self._link_replacements(qps)
        sink = self.stat_sink
        if sink is not None:
            sink.note_self_time(time.perf_counter() - t0)

    def _sync_candidates(self) -> None:
        """Reconcile live spans with the reservoir's final occupants (caller
        holds self._lock): new candidates get a span (enqueue = their shared
        admission stamp) linked onto their QueuedPodInfo — the link every
        later stage reads instead of building keys and probing sets per pod;
        requeues reuse the same object so it survives retries. Displaced
        candidates that were never popped leave the sample; mid-flight spans
        keep their stamps coming and complete normally."""
        live = self._live
        current = set()
        for qp in self._reservoir:
            # a slot whose pod already bound (its span completed and left
            # the live set) is a SPENT sample: it keeps the slot — it is a
            # legitimately sampled stream item — but must not be re-issued
            # a fresh span that can never complete (admission waves after
            # binds would otherwise mint zombie incomplete spans)
            done = qp.trace_span
            if done is not None and done.complete:
                continue
            key = qp.pod.key
            current.add(key)
            span = live.get(key)
            if span is None:
                span = PodSpan(key, self._window_seq)
                span.stamp("enqueue", qp.submit_ts or qp.timestamp
                           or self._clock.now())
                live[key] = span
                self._sampled.add(key)
                _post_register(key, self)
            qp.trace_span = span
        for key in list(live):
            if key not in current and live[key].pops == 0:
                del live[key]
                self._sampled.discard(key)
                _post_unregister(key)
        # a pod that never binds must not leak spans forever: cap the live
        # set AFTER this window's additions, evicting oldest-first (counted,
        # never silent) — insertion order puts prior windows' stragglers up
        # front, so fresh candidates are the last to go
        cap = self.LIVE_CAP_FACTOR * self.sample_k
        while len(live) > cap:
            old = next(iter(live))
            live.pop(old)
            self._sampled.discard(old)
            _post_unregister(old)
            self.evicted_incomplete += 1

    def _maybe_rotate(self, now: float) -> None:
        if now - self._window_start < self.window_s:
            return
        self._window_start = now
        self._window_seq += 1
        self.windows_rotated += 1
        # un-materialized candidates from the old window lose their slot;
        # live spans keep tracing until they complete, bounded by the cap
        # in _sync_candidates
        self._reservoir = []
        self._sampled = set(self._live)
        self._w = None
        self._skip = 0

    # -- lifecycle stamps (O(1) taps, deferred settlement) ---------------------
    #
    # Every stamp tap records an op — (kind, payload, shared timestamp) — in
    # a FIFO and returns; the per-pod passes run in _flush_ops at the next
    # read surface with the RECORDED timestamps, so the rendered result is
    # byte-identical whenever settlement happens but the contended
    # scheduling window never pays a batch scan. Past PENDING_OPS_CAP the
    # flush runs inline on the recording thread and bills the recorder
    # budget; read-side settlement is rendering cost (tracked in
    # flush_seconds, published in snapshot()).

    def batch_popped(self, qps) -> None:
        """Once per popped batch: record the pop edge (shared timestamp)."""
        if not self.enabled or not qps:
            return
        self._ops.append(("pop", qps, self._clock.now()))
        if len(self._ops) > self.PENDING_OPS_CAP:
            self._flush_ops(inline=True)

    def batch_stage(self, stage: str) -> None:
        """Record one pipeline-stage edge for the current batch's sampled
        pods (resolved by the preceding pop op at settlement)."""
        if not self.enabled:
            return
        self._ops.append(("stage", stage, self._clock.now()))
        if len(self._ops) > self.PENDING_OPS_CAP:
            self._flush_ops(inline=True)

    def chunk_bound(self, items, t_commit: float, t_confirm: float,
                    errkeys=frozenset()) -> None:
        """Once per committed bind chunk (the bind worker thread, or the
        synchronous bind path): record the chunk with its ONE commit stamp.
        items are the bind triples (qp, node_name, assumed)."""
        if not self.enabled or not items:
            return
        self._ops.append(("chunk", (items, t_commit, t_confirm, errkeys),
                          0.0))
        if len(self._ops) > self.PENDING_OPS_CAP:
            self._flush_ops(inline=True)

    def _flush_ops(self, inline: bool = False) -> None:
        """Settle every deferred op in recording order (FIFO — a pop op
        establishes the batch hits its stage ops stamp). _flush_lock
        serializes flushers so order holds under concurrency; inline=True
        (cap overflow on a recording thread) bills the recorder budget,
        read-side settlement only accrues flush_seconds."""
        if not self._ops:
            return
        with self._flush_lock:
            # timer starts AFTER the lock: a flusher that blocked while a
            # peer drained the FIFO did no work, and must not re-bill the
            # peer's wall time to flush_seconds / the recorder budget
            t0 = time.perf_counter()
            while True:
                try:
                    kind, payload, ts = self._ops.popleft()
                except IndexError:
                    break
                if kind == "pop":
                    self._apply_pop(payload, ts)
                elif kind == "stage":
                    self._apply_stage(payload, ts)
                else:
                    self._apply_chunk(*payload)
            # accrued under _flush_lock: concurrent flushers (read surfaces
            # + cap overflows on recording threads) must not lose updates
            dt = time.perf_counter() - t0
            self.flush_seconds += dt
        if inline:
            sink = self.stat_sink
            if sink is not None:
                sink.note_self_time(dt)

    def _apply_pop(self, qps, now: float) -> None:
        """Find the sampled pods in a popped batch and stamp 'pop' with the
        batch's shared timestamp. The full-batch pass costs unsampled pods
        one attribute read each (the span was linked onto the QueuedPodInfo
        at sampling time); the membership check against the sampled set then
        guards only the <=K linked spans against staleness."""
        if not self._sampled:  # common case: one falsy check per batch
            self._batch_hits = ()
            return
        # C-speed pass: one attribute read per pod; only the <=K linked
        # spans reach the stamping loop below
        hits = [qp.trace_span for qp in qps if qp.trace_span is not None]
        kept = []
        if hits:
            with self._lock:
                sampled = self._sampled
                for sp in hits:
                    if sp.key in sampled:  # HP001 staleness guard
                        sp.stamp("pop", now)
                        sp.pops += 1
                        kept.append(sp.key)
        self._batch_hits = tuple(kept)

    def _apply_stage(self, stage: str, now: float) -> None:
        """Stamp one pipeline stage for the current batch's sampled pods —
        shared timestamp, O(hits) with hits <= K."""
        if not self._batch_hits:
            return
        with self._lock:
            for k in self._batch_hits:
                if k in self._sampled:  # HP001 guard (evicted mid-batch)
                    sp = self._live.get(k)
                    if sp is not None:
                        sp.stamp(stage, now)

    def _apply_chunk(self, items, t_commit: float, t_confirm: float,
                     errkeys) -> None:
        """Settle one committed bind chunk: bulk-observe submit->bound
        latency for EVERY successfully bound pod (shared commit stamp minus
        the shared admission stamp — submit_ts is always set,
        QueuedPodInfo.__post_init__), then stamp bind_commit/bind_confirmed
        for the sampled ones. Unsampled pods pay two attribute reads in
        C-speed listcomps."""
        if errkeys:
            vals = [t_commit - qp.submit_ts for qp, _node, _a in items
                    if qp.pod.key not in errkeys]
            spans = [qp.trace_span for qp, _node, _a in items
                     if qp.trace_span is not None
                     and qp.pod.key not in errkeys]
        else:
            vals = [t_commit - qp.submit_ts for qp, _node, _a in items]
            spans = [qp.trace_span for qp, _node, _a in items
                     if qp.trace_span is not None]
        if vals:
            # ONE bucket pass feeds both the private histogram and the
            # process-wide Prometheus series (identical E2E buckets)
            res = self.latency.bucket_counts(vals)
            self.latency.observe_counts(*res)
            from ..server import metrics as m

            m.pod_e2e_latency.observe_counts(*res)
        if spans:
            with self._lock:
                for sp in spans:
                    if sp.key in self._sampled:  # HP001 staleness guard
                        sp.stamp("bind_commit", t_commit)
                        sp.stamp("bind_confirmed", t_confirm)
                        self._complete(sp.key)

    def pod_bound(self, qp, now: float) -> None:
        """Serial-path bind (the per-pod fallback loop — inherently per pod,
        so a per-pod tap is the loop's own granularity): one latency
        observation plus the sampled stamps."""
        if not self.enabled:
            return
        # settle deferred pop/stage ops BEFORE completing: _complete()
        # removes the key from the sampled set, so a pending pop op settling
        # later would be staleness-guarded away and the finished span would
        # render with pops=0 and missing mid-pipeline stamps. Falsy check
        # after the first pod of the batch.
        self._flush_ops(inline=True)
        dt = now - (qp.submit_ts or qp.timestamp)
        self.latency.observe(dt)
        from ..server import metrics as m

        m.pod_e2e_latency.observe(dt)
        sp = qp.trace_span
        if sp is not None and sp.key in self._sampled:  # HP001 guard
            with self._lock:
                sp.stamp("bind_commit", now)
                sp.stamp("bind_confirmed", now)
                self._complete(sp.key)

    def _complete(self, key: str) -> None:
        """Caller holds self._lock."""
        sp = self._live.pop(key, None)
        if sp is None:
            return
        self._sampled.discard(key)
        sp.complete = True
        if len(self._done) == self._done.maxlen:
            # ring eviction: the evicted span's post-edge tracking ends too
            old = self._done[0]
            self._post_sampled.pop(old.key, None)
            _post_unregister(old.key)
        self._done.append(sp)
        self._completed += 1
        if "running" in sp.stamps:
            # the running edge already arrived (serial path + fast kubelet):
            # the kubelet taps are done with this key
            _post_unregister(key)
        # keep the span addressable until ring eviction: for the
        # watch_delivered / kubelet_observed / running stamps while they are
        # pending, and for the evict->replace link if this pod is later
        # evicted (ISSUE 9; bounded by the done ring)
        self._post_sampled[key] = sp

    def stamp_post(self, key: str, stage: str,
                   ts: Optional[float] = None) -> None:
        """Stamp a post-scheduler edge (watch_delivered / kubelet_observed /
        running) on a sampled span — live (bind still settling) or bound.
        Reached via note_pod_event; unsampled pods never get here."""
        if not self.enabled:
            return
        done = False
        with self._lock:
            sp = self._post_sampled.get(key) or self._live.get(key)
            if sp is None:
                return
            sp.stamp(stage, ts if ts is not None else self._clock.now())
            if stage == "running":
                # the kubelet is done with this span — but it STAYS in
                # _post_sampled until ring eviction, so a later eviction of
                # this pod can still find it for the evict->replace link
                done = True
        if done:
            _post_unregister(key)

    def note_deleted(self, pod) -> None:
        """A sampled pod was DELETED (evicted). A live span can never
        complete — close it out (kept in the ring, marked deleted); either
        way remember the owner uid so the ReplicaSet replacement's span
        links back to this one (causal chains under churn, ISSUE 9). O(1)
        for unsampled pods: two membership probes."""
        if not self.enabled:
            return
        key = pod.key
        if key not in self._sampled and key not in self._post_sampled:
            return
        # settle pending pop/stage ops first: the span's last stamps must
        # land before it leaves the live set (the pod_bound discipline)
        self._flush_ops(inline=True)
        meta = getattr(pod, "metadata", None)
        owner_uid = None
        for ref in (meta.owner_references if meta is not None else ()):
            owner_uid = _owner_link_key(ref)
            if owner_uid:
                break
        with self._lock:
            live_sp = self._live.pop(key, None)
            self._sampled.discard(key)
            span = self._post_sampled.pop(key, None) or live_sp
            if span is None:
                _post_unregister(key)
                return
            span.deleted = True
            if live_sp is not None:
                # an unbound evicted span joins the ring incomplete — the
                # chain must render even though the pod never bound
                if len(self._done) == self._done.maxlen:
                    old = self._done[0]
                    self._post_sampled.pop(old.key, None)
                    _post_unregister(old.key)
                self._done.append(live_sp)
            if owner_uid is not None:
                self._evicted_sampled.setdefault(owner_uid, []).append(
                    (key, span))
                self._evicted_links += 1
                while self._evicted_links > self.EVICTED_LINK_CAP:
                    oldest = next(iter(self._evicted_sampled))
                    lst = self._evicted_sampled[oldest]
                    lst.pop(0)
                    if not lst:
                        del self._evicted_sampled[oldest]
                    self._evicted_links -= 1
        _post_unregister(key)

    def _link_replacements(self, qps) -> None:
        """Adopt replacements of evicted sampled pods into the sample
        (caller holds self._lock; runs only while links are pending). A
        replacement is FORCE-sampled — causal chains are only useful when
        both ends exist, so it bypasses the reservoir lottery."""
        for qp in qps:
            meta = getattr(qp.pod, "metadata", None)
            if meta is None:
                continue
            for ref in meta.owner_references:
                link = _owner_link_key(ref)
                if link in self._evicted_sampled:
                    self._adopt_replacement(qp, link)
                    # one replacement consumes ONE link: a second owner ref
                    # with its own pending entry must not overwrite this
                    # span's `replaces` and starve the next real sibling
                    break
            if not self._evicted_sampled:
                return

    def _adopt_replacement(self, qp, uid: str) -> None:
        """Link one replacement (caller holds self._lock)."""
        lst = self._evicted_sampled[uid]
        old_key, old_span = lst.pop(0)  # oldest eviction claims the link
        if not lst:
            del self._evicted_sampled[uid]
        self._evicted_links -= 1
        key = qp.pod.key
        span = self._live.get(key)
        if span is None:
            span = PodSpan(key, self._window_seq)
            span.stamp("enqueue", qp.submit_ts or qp.timestamp
                       or self._clock.now())
            self._live[key] = span
            self._sampled.add(key)
            _post_register(key, self)
        qp.trace_span = span
        span.replaces = old_key
        old_span.replaced_by = key

    def drop_live(self) -> None:
        """Abandon every in-flight span (counted, never silent). Called on
        crash resync / relist: the rebuilt queue holds fresh QueuedPodInfos
        with no span links, so the old spans could never complete — exactly
        like the rest of the in-memory scheduler state a crash loses.
        Chunks that COMMITTED before the crash settle first: their binds
        are store facts the resync will re-observe."""
        self._flush_ops()
        with self._lock:
            self.evicted_incomplete += len(self._live)
            for key in self._live:
                _post_unregister(key)
            self._live.clear()
            self._sampled = set()
            self._reservoir = []
            self._w = None
            self._skip = 0
            self._batch_hits = ()
            # bound spans keep their post-edge tracking: their binds are
            # store facts the resync re-observes, so the kubelet's stamps
            # still land; pending evict->replace links die with the queue
            self._evicted_sampled.clear()
            self._evicted_links = 0

    # -- read side (every surface settles deferred chunks first) ---------------

    @property
    def live_incomplete(self) -> int:
        self._flush_ops()
        return len(self._live)

    @property
    def completed_total(self) -> int:
        self._flush_ops()
        return self._completed

    def latency_stats(self) -> Dict:
        """The aggregate submit->bound distribution: count/mean/p50/p99."""
        self._flush_ops()
        total_s, count = self.latency.snapshot()
        p50 = self.latency.quantile(0.50)
        p99 = self.latency.quantile(0.99)
        return {
            "count": count,
            "sum_s": round(total_s, 4),
            "mean_s": round(total_s / count, 6) if count else None,
            "p50_s": round(p50, 6) if p50 is not None else None,
            "p99_s": round(p99, 6) if p99 is not None else None,
        }

    def snapshot(self) -> Dict:
        """The /debug/schedtrace payload: config, window counters, the
        latency distribution, and every span (completed ring + live)."""
        self._flush_ops()
        with self._lock:
            spans = [sp.render() for sp in self._done]
            spans.extend(sp.render() for sp in self._live.values())
            live = len(self._live)
            post = len(self._post_sampled)
        return {
            "enabled": self.enabled,
            "sample_k": self.sample_k,
            "window_s": self.window_s,
            "windows_rotated": self.windows_rotated,
            "completed": self._completed,
            "live_incomplete": live,
            # bound spans still addressable for post edges / evict links
            # (bounded by the done ring)
            "post_sampled": post,
            "evicted_incomplete": self.evicted_incomplete,
            "flush_seconds": round(self.flush_seconds, 6),
            "latency": self.latency_stats(),
            "spans": spans,
        }

    def clear(self) -> None:
        from ..server.metrics import E2E_LATENCY_BUCKETS, Histogram

        with self._lock:
            for key in self._sampled:
                _post_unregister(key)
            for key in self._post_sampled:
                _post_unregister(key)
            self._sampled.clear()
            self._live.clear()
            self._done.clear()
            self._post_sampled.clear()
            self._evicted_sampled.clear()
            self._evicted_links = 0
            self._reservoir = []
            self._w = None
            self._skip = 0
            self._window_start = self._clock.now()
            self.windows_rotated = 0
            self.evicted_incomplete = 0
            self._completed = 0
            self._ops.clear()
            self.flush_seconds = 0.0
            self._batch_hits = ()
            self.latency = Histogram("submit_to_bound_seconds",
                                     buckets=E2E_LATENCY_BUCKETS)
