"""Multi-process scheduler (ISSUE 19): worker PROCESSES over shared-memory
column shards, with cross-process bind arbitration in the store process.

Every prior concurrency lever in tree shares ONE GIL. This module is the
first that does not: `MPScheduler` runs each solve pipeline in its own
process (its own interpreter lock), built from three pieces that already
exist —

  shared columns    the store's pod columns live in a store/shm.py arena
                    (`APIStore.enable_shm()`); the owner writes, workers
                    map the same bytes read-only (MU001 across processes).
  worker solve      scheduler/mpworker.py: numpy-only FFD over the
                    owner-built batch/node shards; bind INTENTS —
                    (batch_row, node_row, rv_snapshot) int triples — come
                    back over a bounded queue. No Pod ever crosses the
                    boundary (schedlint MP001).
  arbitration       the owner re-validates every intent's rv snapshot
                    against the LIVE columns, then commits through
                    `store.bind_many`, whose `is_bind_conflict` surfacing
                    absorbs any race — exactly-once binding with zero new
                    shared locks (the ISSUE 12 conflict contract, now
                    cross-process).

Work split: only PLAIN pods (cpu/mem requests and nothing else) go to
workers; anything constraint-shaped — node selector/affinity, inter-pod
terms, topology spread, gangs, gates, claims, host ports, PVCs — routes
to a thread-path residual BatchScheduler with full cluster visibility
(the scheduler/partition.py residual-pass precedent), which also delivers
the terminal verdict for pods FFD could not place. Tainted/unschedulable
nodes are excluded from the worker shards for the same reason.

Failure domain: a SIGKILLed worker is detected by the owner's collect
loop (the supervisor), its round re-offers to survivors, the slot is
respawned, and the estate is reconciled via `resync_from_store` — pod
conservation across a worker kill is proven by the `ChaosChurn_20k`
mp_worker_kill leg and tests/test_mpsched.py. The chaos site
`process.worker` (key="worker-<i>") injects fail/delay/kill per worker
per round; a kill plan SIGKILLs the REAL process.

Fallback matrix (every row runs the thread path, byte-identical to a
standalone BatchScheduler — pure delegation, the partitions=1 precedent):

  processes=1 / auto on a 1-core rig      thread path
  SCHED_PROCESSES=0                       thread path
  no /dev/shm, no numpy, dict-path store  thread path

Concurrency claims are judged ONLY by measured CPU overlap
(`overlap_cpu_s`, bench `_rig_info` honesty flags) — never wall clock.
"""

from __future__ import annotations

import itertools
import os
import queue as _queue
import signal
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Set, Tuple

from ..api.resources import Resource, compute_pod_resource_request
from ..chaos import faultinject as _chaos
from ..chaos.faultinject import FaultInjected, FaultKill
from ..obs import tracebuf as _tracebuf
from ..store.store import APIStore, is_bind_conflict
from .batch import BatchScheduler
from .flightrec import register_scheduler
from .partition import spans_partitions
from .queue import QueuedPodInfo

_mp_seq = itertools.count(1)

# pending-pod record fields (plain list for rate): store row, milli-cpu,
# mem bytes, reroute hops, preferred worker slot
_ROW, _CPU, _MEM, _HOPS, _SLOT = range(5)


def default_processes() -> int:
    """Auto process count: the rig's cores (capped), 1 on a 1-core box —
    mirroring PartitionedScheduler's concurrent-drive degradation."""
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover - platforms without affinity
        cores = os.cpu_count() or 1
    return min(cores, 8) if cores > 1 else 1


def pod_is_plain(pod) -> bool:
    """True when FFD over (cpu, mem, pod-slot) is a SOUND solver for this
    pod: no constraint that could make a resource-feasible node infeasible
    (tolerations only widen feasibility and untainted shards need none, so
    they stay plain). Everything else goes to the residual pipeline."""
    spec = pod.spec
    if (spec.node_selector or spec.affinity is not None
            or spec.scheduling_gates or spec.resource_claims):
        return False
    if spans_partitions(pod):  # inter-pod terms, topology spread, gangs
        return False
    for v in spec.volumes:
        if v.pvc_claim_name:
            return False
    for c in spec.containers:
        for p in c.ports:
            if p.host_port:
                return False
    return True


class _ShimQueue:
    """Conservation-checker face of the mp pending set
    (testing.py pod_conservation_report wants queue.tracked_keys())."""

    def __init__(self, sched: "MPScheduler"):
        self._sched = sched

    def tracked_keys(self) -> List[str]:
        return list(self._sched._pending)

    def lengths(self) -> Tuple[int, int, int]:
        return (len(self._sched._pending), 0, 0)

    def contains(self, key: str) -> bool:
        return key in self._sched._pending

    def flush_backoff_completed(self) -> None:
        pass

    def move_all_to_active_or_backoff(self) -> None:
        pass


class _ShimSnapshot:
    node_info_list: List[Any] = []


class _ShimCache:
    """Conservation-checker face of the mp path's (nonexistent) assume
    cache: the owner binds synchronously, so nothing is ever assumed."""

    def is_assumed(self, _key: str) -> bool:
        return False

    def update_snapshot(self) -> _ShimSnapshot:
        return _ShimSnapshot()


class _Worker:
    """Owner-side handle for one worker slot."""

    __slots__ = ("idx", "proc", "cmd_q", "pid", "state", "binds",
                 "conflicts", "restarts", "faults")

    def __init__(self, idx: int, proc, cmd_q):
        self.idx = idx
        self.proc = proc
        self.cmd_q = cmd_q
        self.pid = proc.pid
        self.state = "live"
        self.binds = 0
        self.conflicts = 0
        self.restarts = 0
        self.faults = 0

    def row(self) -> Dict[str, Any]:
        return {"index": self.idx, "pid": self.pid, "state": self.state,
                "binds": self.binds, "conflicts": self.conflicts,
                "restarts": self.restarts, "faults": self.faults}


class MPScheduler:
    """Owner/coordinator. Mirrors the BatchScheduler driving surface
    (sync / run_until_idle / flush_binds / resync_from_store / sched_stats
    / stop) so benches, tests, and the control plane can swap it in.

    processes: explicit >=2 forces the mp path even on a 1-core rig (the
    bench rung needs that to prove correctness there; the honesty flags
    record that overlap is not comparable). None = auto: SCHED_PROCESSES
    env, else cores. <=1, no shm, or a dict-path store all fall back to
    PURE DELEGATION to one thread-path BatchScheduler — byte-identical by
    construction, pinned by tests/test_mpsched.py."""

    MAX_ROUNDS = 64
    ROUND_DEADLINE_S = 60.0

    def __init__(self, store: APIStore, framework=None,
                 processes: Optional[int] = None, residual: bool = True,
                 **kw):
        self.store = store
        self._fw = framework
        self._kw = dict(kw)
        self._origin = f"mp{next(_mp_seq)}"
        configured = processes
        if configured is None:
            env = os.environ.get("SCHED_PROCESSES")
            configured = int(env) if env not in (None, "") \
                else default_processes()
        fallback = None
        if configured <= 1:
            fallback = "requested" if (processes is not None
                                       or os.environ.get("SCHED_PROCESSES")
                                       ) else "1-core-auto"
        else:
            from ..store import shm as _shm

            if not _shm.available():
                fallback = "no-shm"
            elif not store.columnar:
                fallback = "no-columnar-store"
        self.fallback = fallback
        self.processes = 1 if fallback else int(configured)
        self.mode = "thread" if fallback else "mp"
        self._inner: Optional[BatchScheduler] = None
        if self.mode == "thread":
            fw = framework() if callable(framework) else framework
            self._inner = BatchScheduler(store, fw, **kw)
            return
        # -- mp owner state (everything below is owner-process only) -------
        import multiprocessing as mp

        self._ctx = mp.get_context("spawn")
        self._out_q = None
        self._workers: List[_Worker] = []
        self._store_base: Optional[str] = None
        self._batch_arena = None
        self._node_arena = None
        self._residual_enabled = residual
        self._residual: Optional[BatchScheduler] = None
        self._residual_keys: Set[str] = set()
        self._residual_qps: List[QueuedPodInfo] = []
        # key -> [store_row, cpu_milli, mem_bytes, hops, slot]
        self._pending: Dict[str, List[int]] = {}
        self._req_cache: Dict[str, Tuple[int, int]] = {}
        self._node_names: List[str] = []
        self._node_acct: List[List[int]] = []  # [ac, am, ap, uc, um, up]
        self._node_rows: Dict[str, int] = {}
        self._round_keys: List[str] = []
        self._sampler = None
        self._stopped = False
        self.queue = _ShimQueue(self)
        self.cache = _ShimCache()
        self.rounds = 0
        self.stale_intents = 0
        self.bind_conflicts = 0
        self.dispatch_faults = 0
        self.worker_restarts = 0
        self.worker_cpu_s = 0.0
        self.residual_passes = 0
        self._bound_total = 0
        self._failed_binds = 0
        register_scheduler(self._origin, self)

    # -- thread-path delegation ------------------------------------------------

    def __getattr__(self, name: str):
        inner = self.__dict__.get("_inner")
        if inner is not None:
            return getattr(inner, name)
        raise AttributeError(name)

    @property
    def watch_coalesce(self):
        if self._inner is not None:
            return self._inner.watch_coalesce
        return None  # mp path: workers read columns, not watch events

    @watch_coalesce.setter
    def watch_coalesce(self, v) -> None:
        if self._inner is not None:
            self._inner.watch_coalesce = v

    # -- worker lifecycle ------------------------------------------------------

    def _ensure_workers(self) -> None:
        if self._workers or self._stopped:
            return
        from ..store import shm as _shm

        try:
            self._store_base = self.store.enable_shm()
            if self._store_base is None:  # pragma: no cover - init gates
                raise RuntimeError("mp mode needs the columnar store + shm")
            self._batch_arena = _shm.ShmArena(
                _shm.BATCH_COLS_SCHEMA, capacity=4096,
                base_name=_shm.fresh_base_name("batch"))
            self._node_arena = _shm.ShmArena(
                _shm.NODE_COLS_SCHEMA, capacity=1024,
                base_name=_shm.fresh_base_name("nodes"))
            self._out_q = self._ctx.Queue(maxsize=256)
            for i in range(self.processes):
                self._workers.append(self._spawn(i))
        except BaseException:
            # a failed bring-up (spawn refused, shm exhausted) must not
            # leak named segments: tear down whatever was created (MP002)
            self.stop()
            raise

    def _spawn(self, idx: int) -> _Worker:
        from .mpworker import worker_main

        cmd_q = self._ctx.Queue(maxsize=8)
        proc = self._ctx.Process(
            target=worker_main,
            args=(idx, self._store_base, self._batch_arena.base_name,
                  self._node_arena.base_name, cmd_q, self._out_q),
            daemon=True, name=f"mpsched-w{idx}")
        proc.start()
        return _Worker(idx, proc, cmd_q)

    def _handle_death(self, w: _Worker) -> None:
        """The supervisor half of the worker failure domain: reap the
        corpse, respawn the slot (cumulative counters carry over — restarts
        are honest), reconcile the estate from the store. Pods the dead
        worker was solving simply stay pending and re-offer to the
        survivors' next round."""
        w.state = "dead"
        try:
            w.proc.join(timeout=0.2)
        except Exception:  # pragma: no cover - join on a corpse
            pass
        nw = self._spawn(w.idx)
        nw.binds, nw.conflicts, nw.faults = w.binds, w.conflicts, w.faults
        nw.restarts = w.restarts + 1
        self._workers[w.idx] = nw
        self.worker_restarts += 1
        self.resync_from_store()

    # -- estate (nodes + pending pods) -----------------------------------------

    def _pod_req(self, key: str, pod) -> Tuple[int, int]:
        got = self._req_cache.get(key)
        if got is None:
            r = compute_pod_resource_request(pod)
            got = (r.milli_cpu, r.memory)
            self._req_cache[key] = got
        return got

    def _refresh_estate(self) -> Dict[str, int]:
        """Full re-scan of the store's columns: eligible nodes with their
        live usage, and the pending split (plain -> worker shards,
        constrained -> residual parking). The mp path's resync — O(rows),
        run at sync, between run_until_idle calls, and after a death."""
        names: List[str] = []
        acct: List[List[int]] = []
        rows: Dict[str, int] = {}
        for node in self.store.list("nodes")[0]:
            if node.spec.unschedulable or node.spec.taints:
                continue
            alloc = Resource.from_resource_list(node.status.allocatable)
            rows[node.metadata.name] = len(names)
            names.append(node.metadata.name)
            acct.append([alloc.milli_cpu, alloc.memory,
                         alloc.allowed_pod_number or 110, 0, 0, 0])
        self._node_names, self._node_acct, self._node_rows = (
            names, acct, rows)
        pending: Dict[str, List[int]] = {}
        view = self.store.pod_columns()
        n_bound = 0
        for i in range(view.n):
            key = view.keys[i]
            if key is None or view.row_rv[i] < 0:
                continue
            pod = view.base[i]
            nid = int(view.node_id[i])
            if nid >= 0:
                row = rows.get(view.node_names[nid])
                if row is not None:
                    c, m = self._pod_req(key, pod)
                    a = acct[row]
                    a[3] += c
                    a[4] += m
                    a[5] += 1
                n_bound += 1
                continue
            if pod.is_terminal() or key in self._residual_keys:
                continue
            if pod_is_plain(pod):
                c, m = self._pod_req(key, pod)
                old = self._pending.get(key)
                slot = old[_SLOT] if old else \
                    zlib.crc32(key.encode()) % self.processes
                pending[key] = [i, c, m, 0, slot]
            else:
                self._park_residual(pod)
        self._pending = pending
        return {"nodes": len(names), "bound": n_bound,
                "pending": len(pending), "dropped_assumes": 0}

    # -- driving ---------------------------------------------------------------

    def sync(self) -> None:
        if self._inner is not None:
            self._inner.sync()
            return
        self._ensure_workers()
        self._refresh_estate()

    def resync_from_store(self) -> Dict[str, int]:
        if self._inner is not None:
            return self._inner.resync_from_store()
        totals = self._refresh_estate()
        if self._residual is not None:
            for k, v in self._residual.resync_from_store().items():
                totals[k] = totals.get(k, 0) + v
        return totals

    def run_until_idle(self, max_cycles: int = 10_000) -> int:
        if self._inner is not None:
            return self._inner.run_until_idle(max_cycles)
        self._ensure_workers()
        if not self._pending and not self._residual_qps:
            self._refresh_estate()
        rounds = 0
        for _ in range(min(self.MAX_ROUNDS, max_cycles)):
            if not self._pending:
                break
            placed, parked, deaths = self._round()
            rounds += 1
            if placed == 0 and parked == 0 and deaths == 0:
                # no worker could place anything and nothing re-routed:
                # the rest gets the global residual verdict
                for key in list(self._pending):
                    self._park_residual_key(key)
                break
        self._run_residual_pass()
        return rounds

    def _round(self) -> Tuple[int, int, int]:
        """One dispatch/collect/arbitrate cycle across the live workers."""
        live = [w for w in self._workers if w.state == "live"]
        if not live:
            for key in list(self._pending):
                self._park_residual_key(key)
            return 0, len(self._residual_qps), 0
        rid = self.rounds
        self.rounds += 1
        live_idx = [w.idx for w in live]
        self._publish_round(live_idx)
        dispatched: Set[int] = set()
        for w in live:
            if _chaos.ACTIVE is not None:
                try:
                    _chaos.ACTIVE.fire("process.worker",
                                       key=f"worker-{w.idx}")
                except FaultInjected:
                    w.faults += 1
                    self.dispatch_faults += 1
                    continue  # skipped round: its pods re-offer next time
                except FaultKill:
                    # a kill plan kills the REAL process — the supervisor
                    # path below must detect and recover it
                    try:
                        os.kill(w.proc.pid, signal.SIGKILL)
                    except OSError:  # pragma: no cover - already gone
                        pass
                    continue
            try:
                w.cmd_q.put(("round", rid), timeout=1.0)
                dispatched.add(w.idx)
            except _queue.Full:  # pragma: no cover - wedged worker
                pass
        placed, parked = self._collect(rid, dispatched)
        deaths = 0
        for w in list(self._workers):
            if w.state == "live" and not w.proc.is_alive():
                deaths += 1
                self._handle_death(w)
        return placed, parked, deaths

    def _publish_round(self, live_idx: List[int]) -> None:
        """Write this round's batch + node shards into the arenas. Worker
        assignment: each pending pod's preferred slot, folded onto the live
        workers; nodes round-robin over the live workers."""
        nlive = len(live_idx)
        entries = list(self._pending.items())
        ba = self._batch_arena
        if len(entries) > ba.capacity:
            ba.grow(len(entries))
        arrs = ba.arrays
        self._round_keys = []
        for i, (key, ent) in enumerate(entries):
            arrs["store_row"][i] = ent[_ROW]
            arrs["cpu"][i] = ent[_CPU]
            arrs["mem"][i] = ent[_MEM]
            arrs["worker"][i] = live_idx[ent[_SLOT] % nlive]
            self._round_keys.append(key)
        ba.publish(len(entries))
        na = self._node_arena
        if len(self._node_acct) > na.capacity:
            na.grow(len(self._node_acct))
        narrs = na.arrays
        for j, a in enumerate(self._node_acct):
            narrs["alloc_cpu"][j] = a[0]
            narrs["alloc_mem"][j] = a[1]
            narrs["alloc_pods"][j] = a[2]
            narrs["used_cpu"][j] = a[3]
            narrs["used_mem"][j] = a[4]
            narrs["used_pods"][j] = a[5]
            narrs["worker"][j] = live_idx[j % nlive]
        na.publish(len(self._node_acct))

    def _collect(self, rid: int, dispatched: Set[int]) -> Tuple[int, int]:
        """Drain worker results for one round, arbitrating bind intents as
        they arrive. Returns (placed, parked)."""
        placed = 0
        parked = 0
        done: Set[int] = set()
        deadline = time.monotonic() + self.ROUND_DEADLINE_S
        by_idx = {w.idx: w for w in self._workers}
        while dispatched - done:
            try:
                msg = self._out_q.get(timeout=0.2)
            except _queue.Empty:
                for idx in list(dispatched - done):
                    w = by_idx[idx]
                    if not w.proc.is_alive():
                        dispatched.discard(idx)  # death handled by caller
                if time.monotonic() > deadline:  # pragma: no cover - wedge
                    for idx in dispatched - done:
                        by_idx[idx].proc.kill()
                    break
                continue
            kind = msg[0]
            if kind == "ready":
                continue
            idx, mrid = msg[1], msg[2]
            if mrid != rid:
                continue  # stale message from a pre-respawn round
            w = by_idx[idx]
            if kind == "bind":
                placed += self._arbitrate(w, msg[3])
            elif kind == "error":
                w.faults += 1
                self.dispatch_faults += 1
                done.add(idx)
            elif kind == "done":
                _idx, _rid, _placed, unplaced, t0, t1, cpu_s = msg[1:]
                self.worker_cpu_s += cpu_s
                if _tracebuf.ACTIVE is not None:
                    _tracebuf.ACTIVE.note_span(
                        f"w{idx}-sched", f"round-{rid}", t0, t1,
                        cat="sched",
                        args={"pid": w.pid, "offered": _placed,
                              "cpu_ms": round(cpu_s * 1e3, 3)})
                parked += self._reroute_unplaced(unplaced)
                done.add(idx)
        return placed, parked

    def _arbitrate(self, w: _Worker, chunk) -> int:
        """Cross-process bind arbitration: re-validate each intent's rv
        snapshot against the LIVE columns (a changed row raced — stale,
        re-offered next round), then commit survivors through bind_many.
        Conflicts surface per-pod via is_bind_conflict and mean the pod IS
        bound (by someone) — it leaves the pending set either way."""
        view = self.store.pod_columns()
        batch: List[Tuple[str, str, str]] = []
        keys: List[str] = []
        reqs: List[Tuple[str, int, int, int]] = []
        nkeys = len(self._round_keys)
        for bi, node_row, rv_snap in chunk:
            if bi >= nkeys:
                continue
            key = self._round_keys[bi]
            ent = self._pending.get(key)
            if ent is None:
                continue  # already resolved this round
            row = ent[_ROW]
            if (row >= view.n or view.keys[row] != key
                    or int(view.row_rv[row]) != rv_snap
                    or int(view.node_id[row]) >= 0):
                self.stale_intents += 1
                continue
            ns, name = key.split("/", 1)
            batch.append((ns, name, self._node_names[node_row]))
            keys.append(key)
            reqs.append((key, ent[_CPU], ent[_MEM], node_row))
        if not batch:
            return 0
        bound, errors = self.store.bind_many(batch, origin=self._origin)
        failed = {key for key, _msg in errors}
        for key, msg in errors:
            if is_bind_conflict(msg):
                w.conflicts += 1
                self.bind_conflicts += 1
            else:
                self._failed_binds += 1
            self._pending.pop(key, None)
        for key, c, m, node_row in reqs:
            if key in failed:
                continue
            a = self._node_acct[node_row]
            a[3] += c
            a[4] += m
            a[5] += 1
            self._pending.pop(key, None)
        w.binds += bound
        self._bound_total += bound
        return bound

    def _reroute_unplaced(self, unplaced) -> int:
        """Shard-local unschedulability hops to the next worker; once every
        live worker has declined, the global residual pass owns the
        terminal verdict (the partition reroute contract)."""
        live = sum(1 for w in self._workers if w.state == "live")
        parked = 0
        nkeys = len(self._round_keys)
        for bi in unplaced:
            if bi >= nkeys:
                continue
            key = self._round_keys[bi]
            ent = self._pending.get(key)
            if ent is None:
                continue
            ent[_HOPS] += 1
            if ent[_HOPS] >= max(live, 1):
                self._park_residual_key(key)
                parked += 1
            else:
                ent[_SLOT] += 1
        return parked

    # -- the global residual pass (partition.py precedent) ---------------------

    def _ensure_residual(self) -> BatchScheduler:
        if self._residual is None:
            fw = self._fw() if callable(self._fw) else self._fw
            r = BatchScheduler(self.store, fw, **self._kw)
            r.partition_index = -1
            r._pod_gate = self._residual_gate
            if self._sampler is not None:
                r.attach_resource_sampler(self._sampler)
            self._residual = r
        return self._residual

    def _residual_gate(self, _etype: str, pod) -> bool:
        if pod.spec.node_name or pod.is_terminal():
            return True  # the residual cache mirrors every bound pod
        return pod.key in self._residual_keys

    def _park_residual(self, pod) -> None:
        key = pod.key
        if key in self._residual_keys:
            return
        self._residual_keys.add(key)
        self._residual_qps.append(QueuedPodInfo(pod=pod))

    def _park_residual_key(self, key: str) -> None:
        ent = self._pending.pop(key, None)
        if ent is None or key in self._residual_keys:
            return
        view = self.store.pod_columns()
        row = ent[_ROW]
        if row < view.n and view.keys[row] == key:
            self._park_residual(view.base[row])

    def _run_residual_pass(self) -> int:
        if self._inner is not None or not self._residual_enabled:
            return 0
        parked = self._residual_qps
        self._residual_qps = []
        if not parked:
            return 0
        r = self._ensure_residual()
        self.residual_passes += 1
        r.resync_from_store()
        handled = r.run_until_idle()
        r.flush_binds()
        if r._watch is not None:
            r._watch.stop()
            r._watch = None
        still = set(r.queue.tracked_keys())
        self._residual_keys &= still | {
            qp.pod.key for qp in self._residual_qps}
        # residual binds shift the estate under the workers — refresh usage
        self._refresh_estate()
        return handled

    # -- BatchScheduler-surface compatibility ----------------------------------

    def flush_binds(self) -> None:
        if self._inner is not None:
            self._inner.flush_binds()
        elif self._residual is not None:
            self._residual.flush_binds()

    def pump_events(self) -> None:
        if self._inner is not None:
            self._inner.pump_events()

    def sweep_expired_assumes(self) -> int:
        if self._inner is not None:
            return self._inner.sweep_expired_assumes()
        return 0

    def flush_queues(self) -> None:
        if self._inner is not None:
            self._inner.queue.flush_backoff_completed()
            self._inner.queue.move_all_to_active_or_backoff()
        elif self._residual is not None:
            self._residual.queue.flush_backoff_completed()
            self._residual.queue.move_all_to_active_or_backoff()

    def take_bind_failures(self) -> List:
        if self._inner is not None:
            return self._inner.take_bind_failures()
        return (self._residual.take_bind_failures()
                if self._residual is not None else [])

    def attach_resource_sampler(self, sampler) -> None:
        if self._inner is not None:
            self._inner.attach_resource_sampler(sampler)
            return
        self._sampler = sampler
        if self._residual is not None:
            self._residual.attach_resource_sampler(sampler)

    def conservation_members(self):
        if self._inner is not None:
            return [self._inner], None
        return [self], self._residual

    @property
    def scheduled_count(self) -> int:
        if self._inner is not None:
            return self._inner.scheduled_count
        return self._bound_total + (self._residual.scheduled_count
                                    if self._residual is not None else 0)

    @property
    def failed_count(self) -> int:
        if self._inner is not None:
            return self._inner.failed_count
        return self._failed_binds + (self._residual.failed_count
                                     if self._residual is not None else 0)

    def start(self) -> None:
        if self._inner is not None:
            self._inner.start()
            return
        self._ensure_workers()

    def stop(self) -> None:
        """Tear everything down unlink-clean: workers stopped (then
        killed), queues drained, both owner arenas AND the store's pod
        arena closed+unlinked — `/dev/shm` must hold zero ktpu-* segments
        afterwards (schedlint MP002; asserted by the MultiProcess rung and
        tests/test_mpsched.py)."""
        if self._inner is not None:
            self._inner.stop()
            return
        if self._stopped:
            return
        self._stopped = True
        try:
            for w in self._workers:
                if w.state == "live":
                    try:
                        w.cmd_q.put_nowait(("stop",))
                    except _queue.Full:
                        pass
            for w in self._workers:
                w.proc.join(timeout=2.0)
                if w.proc.is_alive():
                    w.proc.kill()
                    w.proc.join(timeout=2.0)
                w.state = "stopped"
            for w in self._workers:
                w.cmd_q.cancel_join_thread()
                w.cmd_q.close()
            if self._out_q is not None:
                self._out_q.cancel_join_thread()
                self._out_q.close()
            if self._residual is not None:
                self._residual.stop()
        finally:
            if self._batch_arena is not None:
                self._batch_arena.close()
            if self._node_arena is not None:
                self._node_arena.close()
            self.store.shm_close()

    # -- observability ---------------------------------------------------------

    def sched_stats(self) -> Dict:
        if self._inner is not None:
            st = dict(self._inner.sched_stats())
            st["processes"] = {
                "mode": "thread", "configured": self.processes,
                "fallback": self.fallback, "workers": [],
            }
            return st
        return {
            "scheduled": self.scheduled_count,
            "failed": self.failed_count,
            "queue": {"active": len(self._pending), "backoff": 0,
                      "unschedulable": 0},
            "processes": {
                "mode": "mp",
                "configured": self.processes,
                "fallback": None,
                "rounds": self.rounds,
                "stale_intents": self.stale_intents,
                "bind_conflicts": self.bind_conflicts,
                "dispatch_faults": self.dispatch_faults,
                "worker_restarts": self.worker_restarts,
                "worker_cpu_s": round(self.worker_cpu_s, 4),
                "workers": [w.row() for w in self._workers],
                "residual": {
                    "enabled": self._residual_enabled,
                    "passes": self.residual_passes,
                    "parked": len(self._residual_qps),
                    "scheduled": (self._residual.scheduled_count
                                  if self._residual is not None else 0),
                },
            },
        }
