"""Scheduler extender: the HTTP webhook alternative to in-process plugins.

Wire types mirror staging/src/k8s.io/kube-scheduler/extender/v1/types.go
(ExtenderArgs :73, ExtenderFilterResult :88, ExtenderBindingArgs :106,
HostPriority :124); the client mirrors pkg/scheduler/extender.go (HTTPExtender
:43) and its call sites in schedule_one.go (findNodesThatPassExtenders :703,
prioritize merge :798-856, extendersBinding :981).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import Pod
from ..api.serialize import pod_to_dict

MAX_EXTENDER_PRIORITY = 10  # extender/v1/types.go MaxExtenderPriority
MAX_NODE_SCORE = 100  # framework/interface.go:255


# -- wire types. JSON tags follow extender/v1/types.go exactly: "pod",
# "nodenames", "failedNodes", "failedAndUnresolvable", "error" — a stock Go
# extender must be able to decode/encode these bodies. Parsing also accepts
# Go-field casing for tolerance.


def _get(d: Dict, *keys, default=None):
    for k in keys:
        if k in d:
            return d[k]
    return default


def extender_args(pod: Pod, node_names: Sequence[str]) -> Dict:
    """ExtenderArgs in node-cache-capable form (nodenames, not full nodes)."""
    return {"pod": pod_to_dict(pod), "nodenames": list(node_names)}


@dataclass
class FilterResult:
    """Parsed ExtenderFilterResult."""

    node_names: List[str] = field(default_factory=list)
    failed_nodes: Dict[str, str] = field(default_factory=dict)
    failed_and_unresolvable: Dict[str, str] = field(default_factory=dict)
    error: str = ""

    @staticmethod
    def from_dict(d: Dict) -> "FilterResult":
        names = _get(d, "nodenames", "NodeNames", "nodeNames")
        nodes = _get(d, "nodes", "Nodes")
        if names is None and nodes:
            names = [n["metadata"]["name"] for n in (nodes.get("items") or [])]
        return FilterResult(
            node_names=list(names or []),
            failed_nodes=dict(_get(d, "failedNodes", "FailedNodes") or {}),
            failed_and_unresolvable=dict(
                _get(d, "failedAndUnresolvable", "FailedAndUnresolvableNodes") or {}),
            error=_get(d, "error", "Error") or "",
        )


@dataclass
class ExtenderConfig:
    """KubeSchedulerConfiguration .extenders[] entry
    (apis/config/types.go Extender)."""

    url_prefix: str = ""
    filter_verb: str = "filter"
    prioritize_verb: str = "prioritize"
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: int = 1
    ignorable: bool = False  # scheduling proceeds if the extender is down
    node_cache_capable: bool = True
    managed_resources: List[str] = field(default_factory=list)
    timeout_seconds: float = 5.0

    @staticmethod
    def from_dict(d: Dict) -> "ExtenderConfig":
        return ExtenderConfig(
            url_prefix=d.get("urlPrefix", ""),
            filter_verb=d.get("filterVerb", ""),
            prioritize_verb=d.get("prioritizeVerb", ""),
            bind_verb=d.get("bindVerb", ""),
            preempt_verb=d.get("preemptVerb", ""),
            weight=int(d.get("weight", 1) or 1),
            ignorable=bool(d.get("ignorable", False)),
            node_cache_capable=bool(d.get("nodeCacheCapable", True)),
            managed_resources=[r["name"] if isinstance(r, dict) else r
                               for r in d.get("managedResources") or []],
            timeout_seconds=float(d.get("httpTimeout", 5.0) or 5.0),
        )


class ExtenderError(Exception):
    pass


class HTTPExtender:
    """POSTs ExtenderArgs JSON to urlPrefix/<verb> (extender.go:43 send())."""

    def __init__(self, config: ExtenderConfig):
        self.config = config

    @property
    def name(self) -> str:
        return self.config.url_prefix

    @property
    def weight(self) -> int:
        return self.config.weight

    @property
    def is_binder(self) -> bool:
        return bool(self.config.bind_verb)

    @property
    def is_ignorable(self) -> bool:
        return self.config.ignorable

    def is_interested(self, pod: Pod) -> bool:
        """extender.go IsInterested: no managed resources = all pods; else only
        pods requesting one of them."""
        if not self.config.managed_resources:
            return True
        managed = set(self.config.managed_resources)
        for c in list(pod.spec.containers) + list(pod.spec.init_containers):
            for section in ("requests", "limits"):
                if managed & set((c.resources.get(section) or {}).keys()):
                    return True
        return False

    def _post(self, verb: str, payload: Dict) -> Dict:
        url = f"{self.config.url_prefix.rstrip('/')}/{verb}"
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.config.timeout_seconds) as resp:
                return json.loads(resp.read().decode())
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
            raise ExtenderError(f"extender {url}: {e}") from e

    def filter(self, pod: Pod, node_names: Sequence[str]) -> FilterResult:
        if not self.config.filter_verb:
            return FilterResult(node_names=list(node_names))
        result = FilterResult.from_dict(
            self._post(self.config.filter_verb, extender_args(pod, node_names)))
        if result.error:
            raise ExtenderError(result.error)
        return result

    def prioritize(self, pod: Pod, node_names: Sequence[str]) -> Dict[str, int]:
        """Returns host -> raw score (0..MaxExtenderPriority). The wire body is
        a bare HostPriorityList JSON array (extender/v1/types.go:124)."""
        if not self.config.prioritize_verb:
            return {}
        out = self._post(self.config.prioritize_verb, extender_args(pod, node_names))
        priorities = out if isinstance(out, list) else (
            _get(out or {}, "hostPriorityList") or [])
        return {_get(e, "host", "Host"): int(_get(e, "score", "Score", default=0) or 0)
                for e in priorities}

    def bind(self, pod: Pod, node_name: str) -> None:
        payload = {"podName": pod.metadata.name,
                   "podNamespace": pod.metadata.namespace,
                   "podUID": pod.metadata.uid,
                   "node": node_name}
        out = self._post(self.config.bind_verb, payload)
        err = _get(out or {}, "error", "Error")
        if err:
            raise ExtenderError(err)


def find_nodes_that_pass_extenders(
    extenders: Sequence[HTTPExtender], pod: Pod, feasible: List[str],
    failed_nodes: Dict[str, object],
) -> Tuple[List[str], Optional[str]]:
    """schedule_one.go findNodesThatPassExtenders :703 — sequential filtering;
    an ignorable extender's failure is skipped, otherwise it aborts the cycle.
    Mutates failed_nodes with per-node extender rejections (message strings)."""
    for ext in extenders:
        if not feasible:
            break
        if not ext.is_interested(pod):
            continue
        try:
            result = ext.filter(pod, feasible)
        except ExtenderError as e:
            if ext.is_ignorable:
                continue
            return feasible, str(e)
        for name, msg in result.failed_nodes.items():
            failed_nodes.setdefault(name, f"extender: {msg}")
        for name, msg in result.failed_and_unresolvable.items():
            failed_nodes[name] = f"extender (unresolvable): {msg}"
        feasible = [n for n in feasible if n in set(result.node_names)]
    return feasible, None


def merge_extender_priorities(
    extenders: Sequence[HTTPExtender], pod: Pod, node_names: Sequence[str],
    totals: Dict[str, int],
) -> None:
    """schedule_one.go :798-856 — extender score x weight, rescaled from the
    0..10 extender range onto the 0..100 plugin range
    (MaxNodeScore/MaxExtenderPriority), added onto the plugin totals. Extender
    failures during Prioritize are tolerated (score 0)."""
    rescale = MAX_NODE_SCORE // MAX_EXTENDER_PRIORITY
    for ext in extenders:
        if not ext.is_interested(pod) or not ext.config.prioritize_verb:
            continue
        try:
            scores = ext.prioritize(pod, node_names)
        except ExtenderError:
            continue
        for name, score in scores.items():
            if name in totals:
                totals[name] += score * ext.weight * rescale
